"""Load-driven gang autoscaler: scale before you shed.

The reference job's only answer to load is an *operator-performed*
Flink savepoint-and-rescale (PAPER.md lineage §0); the PR-5 degradation
ladder automated the opposite response — destroying work (tighter cuts,
truncated top-K, paused ingest) under pressure. Every piece of elastic
capacity already exists — ``ShardedRescaleStore`` restores an N-shard
checkpoint onto M shards, the ``GangSupervisor`` relaunches whole gangs
from epoch-committed generations, and incremental checkpoints made the
commit at a rescale seam cheap — this module connects them: sustained
SHED_* pressure *grows* the gang, sustained idle *shrinks* it, and the
ladder only sheds once capacity is exhausted.

The loop (``--autoscale on``; timeline in docs/ARCHITECTURE.md
"Elastic capacity"):

1. **Signal** — every fired window, each worker's :class:`AutoscaleTap`
   exchanges one packed int over the watchdog-guarded allgather: its
   local idle bit (window wall under a quarter of
   ``--degrade-window-wall-s``) and its drain-readiness bit, alongside
   the :class:`~.degrade.DegradationController`'s already-gang-maxed
   overloaded bit. The gang-wide signal (pressure = any overloaded,
   idle = all idle) plus the running consecutive-window counters land
   in a per-worker ``pressure.p<i>`` beacon in the gang dir — the same
   channel the heartbeat files ride.
2. **Decision** — the supervisor polls the beacons and feeds a
   :class:`ScalePolicy` (per-window signals in → target topology out).
   The default :class:`LadderScalePolicy` mirrors the degradation
   ladder's hysteresis: asymmetric consecutive-window counters
   (``--autoscale-trip-windows`` overloaded grows, the larger
   ``--autoscale-clear-windows`` idle shrinks), a cooldown after every
   rescale, and hard ``--autoscale-min/max-workers`` bounds.
3. **Drain** — a decision becomes a ``RESCALE`` request beacon in the
   gang dir. Workers see it at a window boundary, vote it gang-wide
   (all workers must have read it — the drain window is identical on
   every host by construction), checkpoint under the epoch-commit
   protocol, journal an AUTOSCALE record, and exit with
   :data:`RESCALE_EXIT` — a *voluntary* code the supervisor never
   counts against ``--restart-on-failure`` and never feeds the
   crash-loop breaker.
4. **Relaunch** — the supervisor respawns the gang at M workers; the
   topology-aware restore vote (``gang.agree_restore_topology``) finds
   the newest generation committed by the *writing* topology, merges
   the N per-process blobs into the canonical global key space
   (``state/store.merge_mh_cells``) and ``rebucket_cells`` lands it on
   M shards — the run resumes bit-identically.

Degradation precedence is explicit: while the gang is below
``--autoscale-max-workers``, the controller's escalation is held
(``hold_escalation``) so sustained pressure triggers a rescale attempt
*before* the ladder may leave NORMAL; at max capacity (or with
``--autoscale off``) the ladder behaves exactly as before.

Chaos sites: ``rescale_drain`` fires in the worker between the drain
commit and the voluntary exit; ``rescale_relaunch`` fires in the
supervisor between the drain verdict and the relaunch — together they
bracket the rescale seam the recovery tests crash inside.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from typing import Callable, List, Optional

from ..observability.registry import REGISTRY

LOG = logging.getLogger("tpu_cooccurrence.autoscale")

#: Voluntary rescale exit code: the whole gang drained a checkpoint at
#: a window boundary and is asking to be relaunched at a new topology.
#: NOT a failure — the gang supervisor relaunches without consuming the
#: ``--restart-on-failure`` budget and without tripping the crash-loop
#: breaker. Distinct from the permanent codes (2, 78), the collective
#: watchdog's 75 and the timeout's 124.
RESCALE_EXIT = 86

#: Rescale-request beacon filename inside the gang dir: the supervisor
#: writes it (atomic rename), workers read it at window boundaries and
#: drain once the whole gang has seen it.
REQUEST_NAME = "RESCALE"

#: Worker pressure-beacon filename pattern inside the gang dir.
_BEACON_FMT = "pressure.p{pid}"

#: Autoscale gauges (CANONICAL_METRICS): the topology in force, the
#: rescales performed so far, and the last gang-wide load signal.
TARGET_WORKERS_GAUGE = "cooc_gang_target_workers"
RESCALES_GAUGE = "cooc_gang_rescales_total"
LEVEL_GAUGE = "cooc_autoscale_level"


class RescaleDrain(Exception):
    """Raised by the job at the drain boundary: the drain checkpoint is
    committed and this worker must exit :data:`RESCALE_EXIT`."""

    def __init__(self, request: dict, window: int) -> None:
        super().__init__(
            f"gang rescale drain at window {window}: "
            f"{request.get('from')} -> {request.get('to')} workers")
        self.request = request
        self.window = window


def beacon_path(gang_dir: str, process_id: int) -> str:
    return os.path.join(gang_dir, _BEACON_FMT.format(pid=process_id))


def request_path(gang_dir: str) -> str:
    return os.path.join(gang_dir, REQUEST_NAME)


def read_json(path: str) -> Optional[dict]:
    """Best-effort read of a beacon/request file; ``None`` when missing
    or torn (the writer replaces atomically, so a parse failure is a
    transient race, not corruption)."""
    try:
        with open(path, encoding="utf-8") as f:
            out = json.load(f)
        return out if isinstance(out, dict) else None
    except (OSError, ValueError):
        return None


def write_json(path: str, payload: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, sort_keys=True)
    os.replace(tmp, path)


# -- the policy interface ----------------------------------------------


@dataclasses.dataclass
class ScaleDecision:
    """One policy verdict: rescale the gang to ``target`` workers."""

    target: int
    trigger: str       # "pressure" | "idle"
    window: int        # fired-window ordinal the decision observed
    cooldown: int      # policy cooldown windows armed by this decision

    @property
    def decision(self) -> str:
        return "grow" if self.trigger == "pressure" else "shrink"


class ScalePolicy:
    """Per-window signals in → target topology out.

    ``decide`` is fed once per *new* beacon window with the gang-wide
    bits and the worker-computed consecutive-run counters; it returns a
    :class:`ScaleDecision` or ``None``. ``rescaled`` notifies the
    policy that a decision was applied (the gang relaunched at
    ``workers``). Implementations must be registered: the cooclint
    ``scale-policy-registry`` rule requires every subclass to carry a
    ``tests/`` reference and a row in the ARCHITECTURE scale-policy
    table.
    """

    def decide(self, window: int, overloaded: bool, idle: bool,
               bad_run: int, idle_run: int,
               workers: int) -> Optional[ScaleDecision]:
        raise NotImplementedError

    def rescaled(self, workers: int) -> None:
        """A decision was applied; the gang now runs ``workers``."""


class LadderScalePolicy(ScalePolicy):
    """Default policy: the degradation ladder's hysteresis, pointed at
    capacity instead of fidelity.

    * ``trip_windows`` consecutive gang-overloaded windows grow the
      gang by ``factor`` (clamped to ``max_workers``).
    * ``clear_windows`` consecutive gang-idle windows shrink it by
      ``factor`` (clamped to ``min_workers``) — asymmetric on purpose,
      exactly like the ladder: grow fast, shrink slow, never flap.
    * Every decision arms a ``cooldown_windows`` refractory period so
      the post-rescale warm-up (restore, recompiles, catch-up windows)
      can never read as a fresh signal — and the run counters
      accumulated DURING the cooldown never count as evidence either:
      a decision needs its full trip/clear run observed on
      post-cooldown windows, so a warm-up that outlasts the cooldown
      cannot cascade a second rescale on one fresh window.
    """

    def __init__(self, max_workers: int, min_workers: int = 2,
                 trip_windows: int = 3, clear_windows: int = 8,
                 cooldown_windows: int = 8, factor: int = 2) -> None:
        if min_workers < 2:
            raise ValueError(
                f"min_workers must be >= 2 (a gang of one is "
                f"--restart-on-failure), got {min_workers}")
        if max_workers < min_workers:
            raise ValueError(
                f"max_workers ({max_workers}) must be >= min_workers "
                f"({min_workers})")
        if trip_windows < 1 or clear_windows < 1:
            raise ValueError("trip/clear window counts must be >= 1")
        if cooldown_windows < 0:
            raise ValueError(
                f"cooldown_windows must be >= 0, got {cooldown_windows}")
        if factor < 2:
            raise ValueError(f"factor must be >= 2, got {factor}")
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.trip_windows = trip_windows
        self.clear_windows = clear_windows
        self.cooldown_windows = cooldown_windows
        self.factor = factor
        self._last_window = -1
        self._cooldown = 0
        # Windows observed since the last cooldown expired: a run
        # counter only counts as evidence up to this (see class doc).
        self._fresh = 0

    def decide(self, window: int, overloaded: bool, idle: bool,
               bad_run: int, idle_run: int,
               workers: int) -> Optional[ScaleDecision]:
        if window <= self._last_window:
            return None  # already observed (beacons are re-read per poll)
        self._last_window = window
        if self._cooldown > 0:
            self._cooldown -= 1
            self._fresh = 0
            return None
        self._fresh += 1
        # min(run, fresh): bad_run >= trip proves the last `trip`
        # windows were consecutively overloaded; fresh >= trip proves
        # they were all observed AFTER the cooldown — together, the
        # evidence is entirely post-warm-up.
        bad_run = min(bad_run, self._fresh)
        idle_run = min(idle_run, self._fresh)
        if bad_run >= self.trip_windows and workers < self.max_workers:
            target = min(self.max_workers, workers * self.factor)
            trigger = "pressure"
        elif idle_run >= self.clear_windows and workers > self.min_workers:
            target = max(self.min_workers, workers // self.factor)
            trigger = "idle"
        else:
            return None
        self._cooldown = self.cooldown_windows
        return ScaleDecision(target=target, trigger=trigger,
                             window=window,
                             cooldown=self.cooldown_windows)

    def rescaled(self, workers: int) -> None:
        # The cooldown armed at decision time keeps ticking over the
        # relaunched gang's windows; nothing else carries over (the
        # worker-side run counters reset with the worker processes).
        pass


# -- the worker-side tap -----------------------------------------------


class AutoscaleTap:
    """Worker-side autoscale plumbing: one gang vote per fired window,
    one pressure beacon write, and the drain trigger.

    ``exchange`` (injectable for tests) allgathers one packed int per
    process and returns the per-process values; default is the
    watchdog-guarded ``parallel/distributed.guarded_allgather``. Bits:
    1 = overloaded (already gang-maxed by the degradation controller's
    own vote; OR-ing is idempotent), 2 = locally idle (AND-ed: the gang
    is idle only when every worker is), 4 = rescale request seen
    (AND-ed: the gang drains only at a window where *every* worker has
    read the request — the drain boundary is therefore identical on
    every host, which is what lets the epoch-commit barrier inside the
    drain checkpoint line up).
    """

    def __init__(self, gang_dir: str, process_id: int,
                 num_processes: int, idle_wall_s: float,
                 exchange: Optional[Callable[[int], List[int]]] = None
                 ) -> None:
        if idle_wall_s <= 0:
            raise ValueError(
                f"idle_wall_s must be positive, got {idle_wall_s}")
        self.gang_dir = gang_dir
        self.process_id = process_id
        self.num_processes = num_processes
        self.idle_wall_s = idle_wall_s
        self.exchange = exchange
        self.bad_run = 0
        self.idle_run = 0
        #: The request dict once the gang voted to drain (job reads it
        #: at the window boundary and raises :class:`RescaleDrain`).
        self.drain: Optional[dict] = None
        REGISTRY.gauge(
            TARGET_WORKERS_GAUGE,
            help="gang worker count this process was launched at "
                 "(the autoscaler's topology in force)").set(num_processes)
        self._gauge_level = REGISTRY.gauge(
            LEVEL_GAUGE,
            help="last gang-wide autoscale signal "
                 "(-1=idle 0=neutral 1=pressure)")
        self._gauge_level.set(0)

    def _exchange(self, value: int) -> List[int]:
        if self.exchange is not None:
            return self.exchange(value)
        import numpy as np

        from ..parallel.distributed import guarded_allgather

        return [int(v) for v in np.asarray(
            guarded_allgather(np.asarray([value], dtype=np.int64))
        ).reshape(-1)]

    def observe(self, window: int, wall_seconds: float,
                overloaded: bool) -> bool:
        """Feed one fired window; returns True when the gang voted to
        drain at this boundary (:attr:`drain` then holds the request)."""
        idle_local = (not overloaded) and wall_seconds <= self.idle_wall_s
        req = read_json(request_path(self.gang_dir))
        ready = (req is not None
                 and int(req.get("to", 0)) >= 2
                 and int(req.get("to", 0)) != self.num_processes)
        packed = (int(bool(overloaded))
                  | (int(idle_local) << 1)
                  | (int(ready) << 2))
        votes = self._exchange(packed)
        gang_over = any(v & 1 for v in votes)
        gang_idle = all(v & 2 for v in votes) and not gang_over
        gang_ready = bool(votes) and all(v & 4 for v in votes)
        self.bad_run = self.bad_run + 1 if gang_over else 0
        self.idle_run = self.idle_run + 1 if gang_idle else 0
        self._gauge_level.set(1 if gang_over else (-1 if gang_idle else 0))
        try:
            write_json(beacon_path(self.gang_dir, self.process_id), {
                "window": window,
                "overloaded": int(gang_over),
                "idle": int(gang_idle),
                "bad_run": self.bad_run,
                "idle_run": self.idle_run,
                "wall_unix": round(time.time(), 3),
            })
        except OSError as exc:
            # Pressure reporting must never kill the worker it reports
            # on; a stale beacon just delays the supervisor's decision.
            LOG.warning("pressure beacon write failed: %s", exc)
        if gang_ready:
            self.drain = req
            return True
        return False
