"""Graceful-degradation plane: adaptive load shedding + scorer breaker.

The reference inherits Flink's backpressure for free; this standalone
build previously had exactly two answers to overload — stall (and get
killed by the PR-3 watchdog) or die. This module is the third answer:
*degrade*. A process-global :class:`DegradationController` watches the
per-window health signals the observability plane already produces
(window wall time, staging-ring saturation/stall, journal staleness)
— plus, when the serving plane is up, QUERY_PRESSURE (a ``/recommend``
over its latency SLO) — and steps through explicit levels::

    NORMAL -> SHED_SAMPLING -> SHED_K -> PAUSE_INGEST

Each level trades result fidelity for liveness using the paper's own
knobs: the Schelter-style per-item/per-user frequency cuts (PAPER.md
§0) are a *principled* shedding lever — tightening them drops exactly
the highest-frequency tail interactions the cuts were designed to
bound — and the emitted top-K width is the result-side equivalent.
``PAUSE_INGEST`` is the last resort: bounded-delay admission control at
the source (each admit may be delayed at most ``pause_ms``; never an
unbounded stall, so a paused job cannot deadlock itself).

**Hysteresis.** Escalation needs ``trip_windows`` *consecutive*
overloaded windows; de-escalation needs ``clear_windows`` consecutive
healthy ones, and both move exactly one level per decision — the
journal therefore shows monotone, step-wise transitions, never
flapping (``tests/test_degrade.py`` pins this).

**Parity.** Every effective-cut/top-K function is the identity at
``NORMAL``: a run whose controller never leaves ``NORMAL`` is
bit-identical to a run without the controller (parity-tested at
pipeline depths 0 and 2).

**Multi-host lockstep** (ISSUE 10): on multi-controller runs the job
wires :attr:`DegradationController.exchange` to the watchdog-guarded
``allgather_max`` — every observed window exchanges each host's local
overloaded bit and the gang-wide max drives the ladder, so all hosts
apply the identical transition sequence at the identical window
ordinal and the replicated/partitioned sampling state never diverges.
The admission-side wall-clock staleness escalation is disabled in this
mode (it is per-host-nondeterministic); chaos-proven in
``tests/test_gang_chaos.py``.

Zero-cost-when-off contract (same as :mod:`.faults`): hot paths guard
with ``if degrade.CONTROLLER is not None`` — one module-attribute load
and a pointer compare. Arming is explicit (:func:`install`, done by
``CooccurrenceJob.__init__`` under ``--degrade``).

This module stays stdlib-only at import time (the cooclint
``degrade-registry`` rule and ``observability/http.py`` read it without
pulling numpy/jax); the breaker's host fallback imports lazily.
"""

from __future__ import annotations

import enum
import logging
import threading
import time
from typing import Callable, List, Optional, Tuple

from ..observability.registry import REGISTRY
from . import faults

LOG = logging.getLogger("tpu_cooccurrence.degrade")


class DegradationLevel(enum.IntEnum):
    """Explicit degradation ladder; higher = more load shed."""

    NORMAL = 0
    SHED_SAMPLING = 1
    SHED_K = 2
    PAUSE_INGEST = 3


#: Level -> one-line transition rule (the operator-facing contract,
#: mirrored in docs/ARCHITECTURE.md "Backpressure & degradation").
#: The cooclint ``degrade-registry`` rule AST-checks that every
#: :class:`DegradationLevel` member has an entry here, an event token in
#: :data:`LEVEL_EVENTS`, and a mention in the ARCHITECTURE level table —
#: a new level cannot land undocumented or unjournaled.
TRANSITION_RULES = {
    "NORMAL": "entered after clear_windows consecutive healthy windows "
              "at SHED_SAMPLING; all cuts and top-K at configured values",
    "SHED_SAMPLING": "entered after trip_windows consecutive overloaded "
                     "windows at NORMAL (or clear_windows healthy at "
                     "SHED_K); item/user cuts tighten by shed_factor",
    "SHED_K": "entered after trip_windows consecutive overloaded windows "
              "at SHED_SAMPLING (or clear_windows healthy at "
              "PAUSE_INGEST); cuts tighten by shed_factor^2 and emitted "
              "top-K shrinks by shed_factor",
    "PAUSE_INGEST": "entered after trip_windows consecutive overloaded "
                    "windows at SHED_K (ingest-side staleness — no "
                    "window completed for stale_after_s while lines "
                    "keep arriving — also climbs toward here, one "
                    "level per stale period); each source admit is "
                    "delayed up to pause_ms",
}

#: Level -> journal event token, emitted in the window record
#: (``degrade_events``) of the window whose observation applied the
#: transition into that level. Explicit literals (not ``f"...{name}"``)
#: so the degrade-registry rule can see every member's event statically.
LEVEL_EVENTS = {
    "NORMAL": "degrade/enter_normal",
    "SHED_SAMPLING": "degrade/enter_shed_sampling",
    "SHED_K": "degrade/enter_shed_k",
    "PAUSE_INGEST": "degrade/enter_pause_ingest",
}


class DegradationController:
    """Level state machine over per-window health signals.

    Thread contract: :meth:`observe_window` runs on whichever thread
    records windows (caller serially, scorer worker pipelined);
    :meth:`admit` runs on the ingest thread; the cut/top-K readers run
    on the sampling thread. All state transitions happen under one
    internal leaf lock, and every public reader is either locked or a
    single int read (atomic under the GIL).
    """

    def __init__(self, window_wall_s: float = 1.0, trip_windows: int = 3,
                 clear_windows: int = 8, shed_factor: int = 2,
                 pause_ms: int = 200, stale_after_s: float = 30.0) -> None:
        if window_wall_s <= 0 or stale_after_s <= 0:
            raise ValueError("degrade thresholds must be positive")
        if trip_windows < 1 or clear_windows < 1:
            raise ValueError("trip/clear window counts must be >= 1")
        if shed_factor < 2:
            raise ValueError(f"shed_factor must be >= 2, got {shed_factor}")
        if pause_ms < 0:
            raise ValueError(f"pause_ms must be >= 0, got {pause_ms}")
        self.window_wall_s = window_wall_s
        self.trip_windows = trip_windows
        self.clear_windows = clear_windows
        self.shed_factor = shed_factor
        self.pause_s = pause_ms / 1000.0
        self.stale_after_s = stale_after_s
        self._level = DegradationLevel.NORMAL
        self._bad = 0
        self._good = 0
        self._queue_pressure = False
        self._query_pressure = False
        # Transition event tokens not yet drained into a journal record.
        # Observe-side transitions drain in the same observe_window call;
        # admission-side (stale-ingest) escalations drain through
        # ``journal_event`` IMMEDIATELY when the job attached one —
        # in exactly the stalled-scorer scenario this path exists for,
        # no further window may ever be observed, so waiting for one
        # would lose the forensic record. Without a hook they ride the
        # next observed window's record.
        self._pending_events: List[str] = []
        # Optional durable event sink (job wires its journal here):
        # called with each transition token outside the controller lock.
        self.journal_event: Optional[Callable[[str], None]] = None
        # Multi-host worst-signal vote (job wires
        # parallel/distributed.allgather_max here): every observed
        # window's local overloaded bit is exchanged and the gang-wide
        # MAX drives the ladder, so every host applies the identical
        # transition sequence at the identical window ordinal and
        # sampling stays in lockstep. None = single-process (local
        # signals only). With an exchange attached the admission-side
        # wall-clock staleness escalation is disabled — it is
        # per-host-nondeterministic and would desynchronize the vote.
        self.exchange: Optional[Callable[[int], int]] = None
        # Scale-before-shed precedence (robustness/autoscale.py): while
        # True, the ladder may never ESCALATE — sustained pressure is
        # the autoscaler's rescale trigger first, and only once the
        # gang is at --autoscale-max-workers (the job then leaves this
        # False) may the same signal start destroying work. Static per
        # attempt (derived from config on every host identically), so
        # the multi-host transition lockstep is preserved.
        # De-escalation is never held: relieving pressure is always
        # allowed.
        self.hold_escalation = False
        # The gang-wide overloaded bit of the last observed window
        # (post-exchange on multi-host runs) — the autoscale tap's
        # pressure input. Written under the leaf lock, read by the
        # window-record thread right after observe_window returns.
        self.last_overloaded = False
        self._transitions = 0
        # Staleness baseline before any window completes: controller
        # construction time — a scorer that wedges on its very FIRST
        # dispatch must still trip the stale gate (construction-to-now
        # covers warm-up, so set stale_after_s above worst-case cold
        # compile time on slow targets).
        self._started_monotonic = time.monotonic()
        self._last_window_monotonic: Optional[float] = None
        self._last_stale_escalation = 0.0
        self._lock = threading.Lock()
        self._gauge_level = REGISTRY.gauge(
            "cooc_degradation_level",
            help="current degradation level (0=NORMAL 1=SHED_SAMPLING "
                 "2=SHED_K 3=PAUSE_INGEST)")
        self._gauge_shed = REGISTRY.gauge(
            "cooc_shed_events_total",
            help="windows processed under a degraded level plus "
                 "admission pauses applied")
        self._gauge_level.set(int(self._level))

    # -- level state machine ---------------------------------------------

    @property
    def level(self) -> DegradationLevel:
        return self._level

    def _transition(self, new: DegradationLevel) -> None:
        """Apply one level change (lock held); the event token is queued
        for the next journal record (:attr:`_pending_events`)."""
        self._transitions += 1
        if faults.PLAN is not None:
            faults.PLAN.fire("degrade_step", seq=self._transitions)
        old, self._level = self._level, new
        self._bad = 0
        self._good = 0
        self._gauge_level.set(int(new))
        event = LEVEL_EVENTS[new.name]
        self._pending_events.append(event)
        LOG.warning("degradation level %s -> %s (%s): %s",
                    old.name, new.name, event, TRANSITION_RULES[new.name])

    def observe_window(self, wall_seconds: float, ring_depth: int = 0,
                       ring_capacity: int = 0, stall_seconds: float = 0.0
                       ) -> "Tuple[int, List[str]]":
        """Feed one completed window's health signals.

        Returns ``(level, events)`` for the window's journal record:
        the level in force after this observation and every transition
        event token applied since the last observation — including
        admission-side (stale-ingest) escalations, drained here so no
        transition ever misses the journal.
        """
        with self._lock:
            overloaded = (
                wall_seconds > self.window_wall_s
                or (ring_capacity > 0 and ring_depth >= ring_capacity)
                or stall_seconds > self.window_wall_s / 4
                or self._queue_pressure
                or self._query_pressure)
            self._queue_pressure = False
            self._query_pressure = False
            self._last_window_monotonic = time.monotonic()
        if self.exchange is not None:
            # Outside the lock: the vote is a collective and must not
            # hold the leaf lock against the ingest thread's admit()
            # while peers rendezvous. Called once per observed window —
            # windows are deterministic, so the collective order is in
            # lockstep across hosts.
            overloaded = bool(self.exchange(int(overloaded)))
        with self._lock:
            self.last_overloaded = bool(overloaded)
            if overloaded:
                self._bad += 1
                self._good = 0
            else:
                self._good += 1
                self._bad = 0
            if (self._bad >= self.trip_windows
                    and self._level < DegradationLevel.PAUSE_INGEST
                    and not self.hold_escalation):
                self._transition(DegradationLevel(self._level + 1))
            elif (self._good >= self.clear_windows
                    and self._level > DegradationLevel.NORMAL):
                self._transition(DegradationLevel(self._level - 1))
            if self._level > DegradationLevel.NORMAL:
                self._gauge_shed.add(1)
            events, self._pending_events = self._pending_events, []
            return int(self._level), events

    def overloaded_bit(self) -> bool:
        """Post-exchange gang-max overload bit of the most recent
        observed window, read under the leaf lock (the observer thread
        writes it; the autoscale vote reads it)."""
        with self._lock:
            return bool(self.last_overloaded)

    def note_queue_wait(self, seconds: float) -> None:
        """Producer-side pipeline backpressure signal: a submit that
        blocked this long marks the *next* observed window overloaded
        (the wait is attributed to the window whose slot it waited for).
        """
        if seconds > self.window_wall_s / 4:
            with self._lock:
                self._queue_pressure = True

    def note_query_pressure(self) -> None:
        """QUERY_PRESSURE signal from the serving plane: a /recommend
        exceeded its latency SLO (``--serve-query-slo-s``), so the next
        observed window counts as overloaded and the ladder sheds
        *ingest* (tighter cuts, narrower top-K, admission pause) —
        queries are never shed; the direction is structural, there is no
        query-shedding lever in this controller. Called from HTTP
        handler threads; one flag write under the leaf lock.
        """
        with self._lock:
            self._query_pressure = True
        REGISTRY.gauge(
            "cooc_query_pressure_events_total",
            help="queries that exceeded --serve-query-slo-s and "
                 "signaled the degradation plane").add(1)

    # -- admission control (ingest thread) -------------------------------

    def admit(self) -> float:
        """Source-side admission gate; returns the delay applied.

        At ``PAUSE_INGEST`` each call sleeps ``pause_ms`` — *bounded*
        admission delay, so a paused job throttles intake without ever
        deadlocking against a scorer that needs ingest to progress.
        Below ``PAUSE_INGEST`` the gate also carries the journal-
        staleness signal: if windows have stopped completing for
        ``stale_after_s`` while ingest keeps arriving, escalate one
        level (rate-limited to one escalation per stale period).
        """
        if self._level >= DegradationLevel.PAUSE_INGEST:
            with self._lock:
                self._gauge_shed.add(1)
            if self.pause_s > 0:
                time.sleep(self.pause_s)
            return self.pause_s
        if self.exchange is not None:
            # Multi-host: the wall-clock staleness escalation is
            # per-host-nondeterministic; only the exchanged per-window
            # vote may move the ladder, or hosts would desynchronize.
            return 0.0
        pending: List[str] = []
        with self._lock:
            # Before the first window completes, staleness is measured
            # from construction — a first-dispatch wedge escalates too.
            last = self._last_window_monotonic or self._started_monotonic
            now = time.monotonic()
            if (now - last > self.stale_after_s
                    and now - self._last_stale_escalation > self.stale_after_s
                    and self._level < DegradationLevel.PAUSE_INGEST):
                self._last_stale_escalation = now
                self._transition(DegradationLevel(self._level + 1))
                if self.journal_event is not None:
                    # Journal NOW: in the stalled-scorer scenario this
                    # escalation responds to, the next observe_window
                    # (the other drain point) may never come.
                    pending, self._pending_events = self._pending_events, []
        for event in pending:  # outside the lock: the sink does file I/O
            self.journal_event(event)
        return 0.0

    # -- shedding knobs (identity at NORMAL — the parity contract) -------

    def _cut_divisor(self) -> int:
        if self._level >= DegradationLevel.SHED_K:
            return self.shed_factor * self.shed_factor
        if self._level >= DegradationLevel.SHED_SAMPLING:
            return self.shed_factor
        return 1

    def effective_item_cut(self, base: int) -> int:
        """Per-item frequency cut in force (fMax; never below 1)."""
        return max(1, base // self._cut_divisor())

    def effective_user_cut(self, base: int) -> int:
        """Per-user cut in force (kMax; sliding-mode per-window cap)."""
        return max(1, base // self._cut_divisor())

    def effective_top_k(self, base: int) -> int:
        """Emitted top-K width in force (never below 1)."""
        if self._level >= DegradationLevel.SHED_K:
            return max(1, base // self.shed_factor)
        return base


class ScorerCircuitBreaker:
    """Availability wrapper around a device scorer.

    ``threshold`` consecutive ``process_window`` failures open the
    breaker; while open, windows are scored on a host oracle fallback
    (the exact float64 rescorer, ``state/rescorer.HostRescorer`` — the
    ``--backend oracle`` engine) so the run *completes* instead of
    dying. After ``probe_after_windows`` windows open, the next window
    is a half-open probe against the primary: success closes the
    breaker, failure re-opens it. Any individual primary failure —
    tripped or not — routes that window to the fallback, so no window's
    pairs are ever dropped.

    Documented fidelity trade (the SMASH-style precision-for-liveness
    swap): the fallback starts from empty co-occurrence state at the
    first failure, and windows scored while open never reach the
    primary's device state — scores after a trip are degraded, not
    wrong-shaped, and a checkpoint taken while open snapshots the
    primary's (stale) state. ``breaker_state`` rides every journal
    record so the trip is visible in forensics.
    """

    _STATE_CODES = {"closed": 0, "half_open": 1, "open": 2}

    def __init__(self, primary, top_k: int, counters=None,
                 threshold: int = 3, probe_after_windows: int = 8) -> None:
        if threshold < 1:
            raise ValueError(f"breaker threshold must be >= 1, "
                             f"got {threshold}")
        if probe_after_windows < 1:
            raise ValueError(f"probe_after_windows must be >= 1, "
                             f"got {probe_after_windows}")
        self.primary = primary
        self.top_k = top_k
        self.counters = counters
        self.threshold = threshold
        self.probe_after_windows = probe_after_windows
        self.breaker_state = "closed"
        self.trips = 0
        self.last_dispatched_rows = 0
        self._failures = 0
        self._windows = 0
        self._opened_at_window = 0
        self._fallback = None
        # Items whose LAST scoring happened on the fallback (dense-id
        # space): rows the primary's final flush must not overwrite.
        # Primary successes reclaim their dispatched items, so a
        # transient blip — or a recovered breaker — does not leave the
        # fallback's single-window rows shadowing fresher primary state.
        self._fallback_owned: set = set()
        self._gauge_state = REGISTRY.gauge(
            "cooc_scorer_breaker_state",
            help="scorer circuit breaker state "
                 "(0=closed 1=half-open 2=open)")
        self._gauge_trips = REGISTRY.gauge(
            "cooc_scorer_breaker_trips_total",
            help="times the scorer breaker opened onto the host fallback")
        self._gauge_state.set(0)

    # Pipeline staging consults this before folding; the fallback
    # (HostRescorer) accepts aggregated deltas, so the wrapper simply
    # mirrors the primary's preference.
    @property
    def accepts_aggregated(self) -> bool:
        return getattr(self.primary, "accepts_aggregated", False)

    def __getattr__(self, name):
        # Checkpoint hooks, capacity knobs, defer_results, … — everything
        # not owned by the breaker delegates to the primary scorer.
        return getattr(object.__getattribute__(self, "primary"), name)

    def _set_state(self, state: str) -> None:
        self.breaker_state = state
        self._gauge_state.set(self._STATE_CODES[state])

    def _ensure_fallback(self):
        if self._fallback is None:
            from ..state.rescorer import HostRescorer

            self._fallback = HostRescorer(self.top_k, self.counters)
        return self._fallback

    def _mirror_dispatch_path(self, fused) -> None:
        """Keep the journal's ``fused`` field honest through the
        wrapper: once the primary exposes ``last_dispatch_fused``, the
        breaker shadows it per window — a fallback-scored window is
        never a fused dispatch, whatever the primary's stale flag says.
        Backends without the flag stay without it (the field remains
        absent from their journal records)."""
        if getattr(self.primary, "last_dispatch_fused", None) is not None:
            self.last_dispatch_fused = fused

    def _fallback_process(self, ts, pairs):
        out = self._ensure_fallback().process_window(ts, pairs)
        self._fallback_owned.update(item for item, _ in out)
        self.last_dispatched_rows = len(out)
        self._mirror_dispatch_path(False)
        return out

    def process_window(self, ts, pairs):
        self._windows += 1
        if self.breaker_state == "open":
            if self._windows - self._opened_at_window >= self.probe_after_windows:
                self._set_state("half_open")
                LOG.warning("scorer breaker half-open: probing the "
                            "primary scorer at window %d", self._windows)
            else:
                return self._fallback_process(ts, pairs)
        try:
            out = self.primary.process_window(ts, pairs)
        except Exception as exc:
            self._failures += 1
            probe_failed = self.breaker_state == "half_open"
            LOG.error("primary scorer dispatch failed (%d consecutive): "
                      "%s: %s", self._failures, type(exc).__name__, exc)
            if probe_failed or self._failures >= self.threshold:
                self.trips += 1
                self._gauge_trips.add(1)
                self._opened_at_window = self._windows
                self._set_state("open")
                LOG.error("scorer breaker OPEN (trip %d): scoring on the "
                          "host oracle fallback", self.trips)
            return self._fallback_process(ts, pairs)
        self._failures = 0
        if self.breaker_state != "closed":
            self._set_state("closed")
            LOG.warning("scorer breaker closed: primary scorer recovered "
                        "at window %d", self._windows)
        if self._fallback_owned and len(pairs):
            # The primary just re-scored these items: its state is the
            # fresher one again, so the final flush may emit them.
            self._fallback_owned.difference_update(
                int(i) for i in set(pairs.src.tolist()))
        self.last_dispatched_rows = getattr(
            self.primary, "last_dispatched_rows", len(out))
        self._mirror_dispatch_path(
            getattr(self.primary, "last_dispatch_fused", False))
        return out

    def flush(self):
        """Drain the primary's result pipeline (the fallback scores
        synchronously — it never holds results in flight), keeping the
        fallback's rows authoritative.

        The last scorer of an item owns its row: items whose most
        recent scoring happened on the fallback (``_fallback_owned`` —
        primary successes reclaim their dispatched items) are filtered
        out of the primary's flush, which for deferred-results backends
        is the WHOLE run's table, absorbed last — so the final
        absorption cannot overwrite fresher fallback rows with stale
        device state, while items the primary re-scored after recovery
        flow through normally. A primary whose flush
        fails while the breaker is open costs its unflushed results —
        for deferred-results backends that is every primary-scored
        window still in the device table (they live on the broken
        device; nothing host-side can recover them) — never the
        fallback's rows, which were absorbed as they were scored."""
        primary_flush = getattr(self.primary, "flush", None)
        if primary_flush is None:
            return []
        try:
            out = primary_flush()
        except Exception as exc:
            if self.breaker_state != "open":
                raise
            LOG.error(
                "primary scorer flush failed while breaker open — "
                "dropping its unflushed results (for deferred-results "
                "backends: every primary-scored window; fallback-scored "
                "rows are already absorbed): %s", exc)
            return []
        owned = self._fallback_owned  # dense ids, same space as rows
        if not owned or not len(out):
            return out
        from ..state.results import TopKBatch

        if isinstance(out, TopKBatch):
            import numpy as np

            keep = np.array([int(r) not in owned
                             for r in out.rows.tolist()], dtype=bool)
            return TopKBatch(out.rows[keep], out.idx[keep], out.vals[keep])
        return [(item, top) for item, top in out if item not in owned]


#: The installed controller; ``None`` = degradation plane off (the
#: hot-path guard, same shape as ``faults.PLAN``).
CONTROLLER: Optional[DegradationController] = None


def install(controller: DegradationController) -> DegradationController:
    """Install ``controller`` as the process-wide degradation plane."""
    global CONTROLLER
    CONTROLLER = controller
    return controller


def uninstall(controller: Optional[DegradationController] = None) -> None:
    """Remove the installed controller (job teardown / tests). With an
    argument, only uninstalls if that instance is still the one
    installed — a stale job's teardown cannot evict its successor's."""
    global CONTROLLER
    if controller is None or CONTROLLER is controller:
        CONTROLLER = None
