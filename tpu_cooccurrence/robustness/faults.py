"""Deterministic fault-injection plane.

Recovery code that is never exercised is recovery code that does not
work. The reference gets its failure coverage for free from Flink's own
test matrix; this standalone build injects its own: named **sites**
threaded through the hot path fire a configured fault *exactly once per
spec*, at a deterministic point (a window ordinal), in one of four
**kinds** — so every failure domain the recovery loop claims to survive
(``supervisor.py`` restarts, ``state/checkpoint.py`` generation
fallback, the hang watchdog) has a test that actually kills the process
there (``tests/test_chaos.py``).

Spec grammar (CLI ``--inject-fault``, repeatable)::

    site[@proc][:window_seq][:kind[:arg]]

* ``site`` — a key of :data:`SITES` (the registered injection points).
* ``proc`` — optional process qualifier (multi-host chaos): the spec
  arms only in the process whose ``--process-id`` matches (a plan armed
  without a process id is process 0). ``ckpt_commit@1:5:crash`` kills
  exactly worker 1, at exactly the gang's generation-5 commit — the
  deterministic peer-death injection the gang-recovery tests are built
  on. Omitted = fires in whichever process hits the site first (every
  process, for replicated sites — each keeps its own fired marker).
* ``window_seq`` — optional integer: trigger on the first hit whose
  sequence number is >= this (sites inside the window loop pass the
  fired-window ordinal; ``source_read`` passes the file-open ordinal).
  Omitted = first hit.
* ``kind`` — one of :data:`KINDS`, default ``crash``:
    - ``crash``      — SIGKILL the process (uncatchable hard death);
    - ``exception``  — raise :class:`InjectedFault` (clean-ish failure
      that unwinds through normal error handling);
    - ``delay_ms``   — sleep ``arg`` milliseconds (a hang, for the
      supervisor watchdog); ``arg`` is required;
    - ``torn_write`` — tear the file the site is mid-writing (whole-file
      writers: truncate to half and complete the pending rename with the
      torn bytes; appenders: leave a newline-less partial record), then
      SIGKILL: the torn-media crash that defeats a naive restore.

Exactly-once across restarts: a supervised child is respawned with the
same argv, so the same specs re-arm on every attempt. With
``--fault-state-dir`` each spec persists a ``fault<i>.fired`` marker
*before* executing (the marker must survive the SIGKILL that follows),
and already-marked specs arm spent — one injection per spec per
directory, however many attempts the supervisor makes.

Zero-cost-when-off contract: every site guards with
``if faults.PLAN is not None`` — one module-attribute load and a
pointer compare on the hot path, nothing else. Arming is explicit
(:func:`arm`, called by the CLI after config parse).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal
import threading
import time
from typing import List, Optional, Sequence

LOG = logging.getLogger("tpu_cooccurrence.faults")

#: Registered injection sites: name -> where it fires. The static
#: consistency test (``tests/test_faults.py``) greps the repo for every
#: referenced site name and asserts membership here, so a site cannot
#: drift out of this table silently.
SITES = {
    "source_read": "io/source.py — opening the next input file "
                   "(seq = 1-based file-open ordinal)",
    "window_fire": "job.py — a window just fired, before sampling "
                   "(seq = fired-window ordinal)",
    "scorer_dispatch": "job.py / pipeline.py — immediately before "
                       "scorer.process_window (seq = window ordinal)",
    "checkpoint_pre_write": "state/checkpoint.py — before the snapshot "
                            "tmp file is written",
    "checkpoint_post_write": "state/checkpoint.py — snapshot fully "
                             "written, before the atomic rename",
    "journal_append": "observability/journal.py — before appending a "
                      "window record",
    "parse_record": "io/parse.py — before parsing a buffered line batch "
                    "(seq = 1-based batch ordinal)",
    "degrade_step": "robustness/degrade.py — a degradation-level "
                    "transition is about to apply (seq = 1-based "
                    "transition ordinal)",
    "scorer_breaker": "ops/device_scorer.py / state/sparse_scorer.py — "
                      "inside process_window before device dispatch "
                      "(seq = 1-based scorer-window ordinal; the "
                      "exception kind is the breaker's trip input)",
    "barrier_enter": "parallel/distributed.py — entering a guarded "
                     "collective/barrier (seq = 1-based per-process "
                     "collective ordinal)",
    "ckpt_commit": "state/checkpoint.py — generation file renamed into "
                   "place, before the directory fsync / gang epoch "
                   "commit (seq = generation number); a crash here "
                   "leaves a durable per-host file with no EPOCH marker",
    "peer_heartbeat": "robustness/gang.py — the heartbeat writer is "
                      "about to touch this process's liveness file "
                      "(seq = 1-based beat ordinal; delay_ms simulates "
                      "a silently wedged peer)",
    "rescale_drain": "job.py — the autoscale drain checkpoint is "
                     "committed and the worker is about to take its "
                     "voluntary rescale exit (seq = fired-window "
                     "ordinal of the drain boundary); a crash here "
                     "dies INSIDE the rescale seam, after the commit "
                     "and before the relaunch",
    "rescale_relaunch": "robustness/gang.py — the gang supervisor saw "
                        "the whole gang drain voluntarily and is about "
                        "to relaunch it at the new topology (seq = "
                        "1-based rescale ordinal)",
    "offset_commit": "state/checkpoint.py — the ingest offset section "
                     "is in the committed generation and the state is "
                     "durable, before the gang epoch commit (seq = "
                     "generation number); a crash here must replay "
                     "the wire and the state from the SAME boundary",
    "partition_reassign": "state/checkpoint.py — the rescaled restore "
                          "merged the per-writer offset sections and "
                          "is re-deriving partition ownership at the "
                          "new topology (seq = restored generation)",
}

KINDS = ("crash", "exception", "delay_ms", "torn_write")


class InjectedFault(RuntimeError):
    """The ``exception`` fault kind: a deliberate, attributable failure."""


class UnknownFaultSiteError(ValueError):
    """``--inject-fault`` named a site not registered in :data:`SITES`.

    A distinct subclass so the CLI can map it to exit code 2 (already
    classified permanent by the supervisor's ``PERMANENT_EXIT_CODES``):
    a chaos-test argv with a typo'd site must stop the run immediately,
    not burn the restart budget re-spawning a child that can never arm.
    The message carries the registered-site list for the operator.
    """


def _die() -> None:
    """Hard process death (SIGKILL self: uncatchable, like the OOM
    killer). A module function so unit tests can monkeypatch it."""
    os.kill(os.getpid(), signal.SIGKILL)


@dataclasses.dataclass
class FaultSpec:
    """One parsed ``--inject-fault`` spec."""

    site: str
    window_seq: Optional[int]
    kind: str
    arg: Optional[int]
    index: int  # position in the plan (the persistence-marker key)
    proc: Optional[int] = None  # process qualifier (site@proc); None =
    # unqualified, fires in any process
    fired: bool = False

    @classmethod
    def parse(cls, raw: str, index: int) -> "FaultSpec":
        parts = raw.split(":")
        site, sep, proc_s = parts[0].partition("@")
        proc: Optional[int] = None
        if sep:
            if not _is_int(proc_s) or int(proc_s) < 0:
                raise ValueError(
                    f"process qualifier must be a non-negative integer "
                    f"in --inject-fault {raw!r}")
            proc = int(proc_s)
        if site not in SITES:
            raise UnknownFaultSiteError(
                f"unknown fault site {site!r} in --inject-fault {raw!r}; "
                f"registered sites: {', '.join(sorted(SITES))}")
        rest = parts[1:]
        window_seq: Optional[int] = None
        if rest and _is_int(rest[0]):
            window_seq = int(rest[0])
            if window_seq < 1:
                raise ValueError(
                    f"window_seq must be >= 1 in --inject-fault {raw!r}")
            rest = rest[1:]
        kind = rest[0] if rest else "crash"
        if kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} in --inject-fault {raw!r}; "
                f"kinds: {', '.join(KINDS)}")
        rest = rest[1:]
        arg: Optional[int] = None
        if rest:
            if kind != "delay_ms":
                raise ValueError(
                    f"fault kind {kind!r} takes no argument "
                    f"(--inject-fault {raw!r})")
            if not _is_int(rest[0]) or len(rest) > 1:
                raise ValueError(
                    f"delay_ms needs one integer argument "
                    f"(--inject-fault {raw!r})")
            arg = int(rest[0])
            if arg < 0:
                raise ValueError(
                    f"delay_ms must be non-negative "
                    f"(--inject-fault {raw!r})")
        elif kind == "delay_ms":
            raise ValueError(
                f"delay_ms needs an argument, e.g. "
                f"{site}:delay_ms:5000 (--inject-fault {raw!r})")
        return cls(site=site, window_seq=window_seq, kind=kind, arg=arg,
                   index=index, proc=proc)


def _is_int(s: str) -> bool:
    try:
        int(s)
        return True
    except ValueError:
        return False


class FaultPlan:
    """The armed set of fault specs. Sites call :meth:`fire`; each spec
    triggers at most once (persisted across restarts via ``state_dir``).

    ``process_id`` qualifies ``site@proc`` specs: a spec whose ``proc``
    does not match this plan's process never fires here (the gang's
    shared ``--fault-state-dir`` keys markers per process, so two
    processes firing the same unqualified spec stay independent)."""

    def __init__(self, specs: List[FaultSpec],
                 state_dir: Optional[str] = None,
                 process_id: Optional[int] = None) -> None:
        self.specs = specs
        self.state_dir = state_dir
        self.process_id = process_id
        self._lock = threading.Lock()
        if state_dir:
            os.makedirs(state_dir, exist_ok=True)
            for spec in self.specs:
                if os.path.exists(self._marker(spec)):
                    spec.fired = True

    @classmethod
    def parse(cls, raw_specs: Sequence[str],
              state_dir: Optional[str] = None,
              process_id: Optional[int] = None) -> "FaultPlan":
        return cls([FaultSpec.parse(raw, i)
                    for i, raw in enumerate(raw_specs)], state_dir,
                   process_id)

    def _marker(self, spec: FaultSpec) -> str:
        # Gang runs share one state dir: markers are per (spec, process)
        # so each process's exactly-once is tracked independently.
        part = (f".p{self.process_id}" if self.process_id is not None
                else "")
        return os.path.join(self.state_dir,
                            f"fault{spec.index}{part}.fired")

    def fire(self, site: str, seq: int = 0, path: Optional[str] = None,
             rename_to: Optional[str] = None) -> None:
        """Trigger any armed spec matching ``site`` at ``seq``.

        ``path``/``rename_to`` give ``torn_write`` its target: the file
        the site is mid-writing, and the final name a pending atomic
        rename would commit it to.
        """
        for spec in self.specs:
            if spec.fired or spec.site != site:
                continue
            if (spec.proc is not None
                    and spec.proc != (self.process_id or 0)):
                continue
            if spec.window_seq is not None and seq < spec.window_seq:
                continue
            with self._lock:
                if spec.fired:  # lost the race to another thread
                    continue
                spec.fired = True
                if self.state_dir:
                    # Persist BEFORE executing: the kinds that kill the
                    # process must not re-fire on the supervised restart.
                    with open(self._marker(spec), "w") as f:
                        f.write(f"{spec.site}:{seq}:{spec.kind}\n")
                        f.flush()
                        os.fsync(f.fileno())
            self._execute(spec, seq, path, rename_to)

    def _execute(self, spec: FaultSpec, seq: int, path: Optional[str],
                 rename_to: Optional[str]) -> None:
        LOG.warning("injecting fault: site=%s seq=%d kind=%s arg=%s",
                    spec.site, seq, spec.kind, spec.arg)
        if spec.kind == "crash":
            _die()
        elif spec.kind == "exception":
            raise InjectedFault(
                f"injected fault at {spec.site} (seq={seq})")
        elif spec.kind == "delay_ms":
            time.sleep(spec.arg / 1000.0)
        elif spec.kind == "torn_write":
            if rename_to is not None and path is not None \
                    and os.path.exists(path):
                # Whole-file writers (checkpoint snapshots): truncate the
                # staged file to half and commit the torn bytes where the
                # good file would have landed — the media-corruption shape
                # the digest-verified restore must survive.
                os.truncate(path, os.path.getsize(path) // 2)
                os.replace(path, rename_to)
            elif path is not None:
                # Appenders (the journal): leave a torn, newline-less
                # partial record at the tail — the SIGKILL-mid-write
                # shape readers and the next attempt's seal must absorb.
                with open(path, "a") as f:
                    f.write('{"torn": tru')
                    f.flush()
            _die()


#: The armed plan; ``None`` = injection off (the hot-path guard).
PLAN: Optional[FaultPlan] = None


def arm(raw_specs: Sequence[str],
        state_dir: Optional[str] = None,
        process_id: Optional[int] = None) -> FaultPlan:
    """Parse and arm ``raw_specs`` as the process-wide plan.

    ``process_id`` (a multi-host run's ``--process-id``) resolves
    ``site@proc`` qualifiers; ``None`` arms as process 0."""
    global PLAN
    PLAN = FaultPlan.parse(raw_specs, state_dir, process_id)
    return PLAN


def disarm() -> None:
    """Drop the armed plan (tests)."""
    global PLAN
    PLAN = None
