"""Pipelined window execution: overlap host sampling with device scoring.

The serial job pays ``host_time + device_time`` per window: it samples a
window on the caller thread, then runs the scorer's host work (fold, slot
allocation, COO packing) and device dispatch before sampling the next one.
The reference overlaps its operators across Flink task slots
(``FlinkCooccurrences.java:89-167``); this module is the TPU build's
equivalent — a bounded-depth producer/consumer pipeline:

* the **caller thread** (producer) keeps running windowing + cuts + pair
  generation for window ``N+1`` — including the per-cell fold when the
  backend accepts pre-aggregated deltas (:class:`~.ops.aggregate.AggregatedPairs`) — and applies the feedback
  edge (item-cut reject decrements) *before* firing the next window, so
  the sampled stream is bit-identical to the serial path's;
* the **scorer worker thread** (consumer) runs the backend's
  ``process_window`` for window ``N`` — host-side index/packing plus the
  already-jitted, donated-buffer device dispatch — and absorbs the
  previous window's materialized top-K into ``LatestResults`` one step
  behind the device frontier (the scorers' existing one-window result
  pipeline / deferred table, unchanged). With ``--serve-port`` the same
  absorption step folds the rows into the serving build buffer and
  swaps the next read-optimized snapshot in (``serving/snapshot.py`` —
  single-writer by this thread contract, zero-lock for query readers).

Nothing in the steady state forces ``block_until_ready``: the worker's
dispatches return as soon as the transfer is enqueued, and synchronization
happens only where results are consumed (``state/results.py``
materialization) or a checkpoint fires (:meth:`PipelineDriver.barrier`).

**Staging ring.** Staged windows ride a ring of ``depth + 1``
pre-allocated, reusable host buffers (the packed fold output the worker
hands to the scorer): one slot per queue position plus one for whichever
side is actively packing or scoring. Reuse keeps the slot pages hot
across windows and bounds staging memory: when every slot is in flight
the producer blocks in ``stage`` until the worker recycles one — the
memory-bound form of backpressure, one window ahead of the queue-bound
form in ``submit``. A slot is recycled only after the
worker's ``process_window`` for it returns — by then every staged byte
has been copied into the scorer's own packed upload buffers, so the
device never holds a reference into the ring (true page-pinning is not
reachable from NumPy; warm, bounded, reused pages are the practical
equivalent on this runtime).

**Ordering and shutdown.** The queue is FIFO and the worker is single:
windows are scored in exactly the serial order, and
:meth:`PipelineDriver.close` processes everything already submitted
before joining the thread — a mid-stream shutdown drops or double-applies
nothing (``tests/test_pipeline_driver.py``). A worker failure is latched
and re-raised on the caller thread at the next ``submit``/``barrier``/
``close``; the worker keeps draining (and recycling) queued slots so the
producer can never deadlock against a dead consumer.

Parity argument (exact, not approximate): sampling state (item cut,
reservoirs, RNG draws) lives entirely on the producer and is touched in
the same order as the serial path; the scorer sees the identical
``(ts, pairs)`` sequence through a FIFO; the fold the producer performs
for ``accepts_aggregated`` backends is the same
``aggregate_window_coo`` call the scorer would have made, byte for byte.
``tests/test_pipeline_driver.py`` pins serial-vs-pipelined equality of
top-K tables and counters on a seeded Zipfian stream.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Optional

import numpy as np

from .observability import WindowStats, clock
from .observability.registry import REGISTRY
from .ops.aggregate import AggregatedPairs
from .robustness import degrade, faults

#: Queue sentinel: process everything already enqueued, then exit.
_SHUTDOWN = object()


class PipelineError(RuntimeError):
    """A scorer-worker failure, re-raised on the caller thread."""


@dataclasses.dataclass
class StagedWindow:
    """One sampled window handed from the producer to the scorer worker."""

    ts: int
    payload: object          # PairDeltaBatch | AggregatedPairs
    events: int              # window event count (observability)
    raw_pairs: int           # pre-fold pair count (stats parity w/ serial)
    sample_seconds: float    # producer-side stage time for this window
    slot: Optional["_StagingSlot"] = None  # ring slot backing the payload
    seq: int = 0             # fired-window ordinal (journal record id)
    stall_seconds: float = 0.0  # producer wait for a free ring slot
    admit_seconds: float = 0.0  # admission-cut share of sample_seconds
                                # (the journal's ingest-admission span)


class _StagingSlot:
    """One ring slot: growable pinned-size buffers for a folded window."""

    __slots__ = ("key", "delta", "src", "dst")

    def __init__(self) -> None:
        self.key = np.empty(0, np.int64)
        self.delta = np.empty(0, np.int64)
        self.src = np.empty(0, np.int32)
        self.dst = np.empty(0, np.int32)

    def pack(self, src, dst, delta, key) -> AggregatedPairs:
        m = len(key)
        if m > len(self.key):
            cap = max(1 << 12, 1 << (m - 1).bit_length())
            self.key = np.empty(cap, np.int64)
            self.delta = np.empty(cap, np.int64)
            self.src = np.empty(cap, np.int32)
            self.dst = np.empty(cap, np.int32)
        self.key[:m] = key
        self.delta[:m] = delta
        self.src[:m] = src
        self.dst[:m] = dst
        return AggregatedPairs(self.src[:m], self.dst[:m], self.delta[:m],
                               self.key[:m])


class StagingRing:
    """Bounded pool of :class:`_StagingSlot`; ``stage`` blocks when every
    slot is in flight (the memory-bound form of backpressure)."""

    def __init__(self, depth: int) -> None:
        self._free: "queue.Queue[_StagingSlot]" = queue.Queue()
        # depth queue positions + 1 for the side actively packing/scoring:
        # the producer can block here (memory-bound backpressure) but the
        # worker's release always unblocks it — no deadlock.
        for _ in range(depth + 1):
            self._free.put(_StagingSlot())
        # Producer-side stall acquiring the last slot (single producer,
        # so a plain attribute is race-free); ~0 while the scorer keeps
        # up, the full scorer-lag once the ring is the bottleneck.
        self.last_stall_seconds = 0.0

    def stage(self, pairs) -> "tuple[AggregatedPairs, _StagingSlot]":
        """Fold one window's raw pair deltas and pack them into a slot."""
        with clock() as wait:
            slot = self._free.get()
        self.last_stall_seconds = wait.seconds
        agg = AggregatedPairs.fold(pairs.src, pairs.dst, pairs.delta)
        return slot.pack(agg.src, agg.dst, agg.delta, agg.key), slot

    def release(self, slot: _StagingSlot) -> None:
        self._free.put(slot)


class PipelineDriver:
    """Depth-bounded scorer pipeline owned by a :class:`~.job.CooccurrenceJob`.

    ``depth`` bounds how many sampled-but-unscored windows may be in
    flight (`queue` positions); the producer blocks on ``submit`` beyond
    that — backpressure, not unbounded buffering. Depth 1 already
    overlaps one window of sampling with one window of scoring; depth 2
    additionally rides out jitter between the two stages' per-window
    costs (the classic double buffer).
    """

    def __init__(self, job, depth: int) -> None:
        if depth < 1:
            raise ValueError(f"pipeline depth must be >= 1, got {depth}")
        self.job = job
        self.depth = depth
        self.ring = StagingRing(depth)
        self._queue: "queue.Queue[object]" = queue.Queue(maxsize=depth)
        self._worker: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self.windows_processed = 0
        self.scorer_busy_seconds = 0.0
        # Cumulative producer block time in submit (queue-bound
        # backpressure; the ring-bound form is StagingRing stall).
        self.queue_wait_seconds = 0.0
        self._hist_queue_wait = REGISTRY.histogram(
            "cooc_pipeline_queue_wait_seconds",
            help="producer block time submitting a window (backpressure)")
        self._gauge_ring_depth = REGISTRY.gauge(
            "cooc_pipeline_ring_depth",
            help="staged windows in flight after the last submit")

    # -- producer side ---------------------------------------------------

    def submit(self, staged: StagedWindow) -> None:
        """Enqueue one sampled window (blocks at ``depth`` in flight)."""
        self._raise_if_failed()
        self._ensure_worker()
        with clock() as wait:
            self._queue.put(staged)
        self.queue_wait_seconds += wait.seconds
        self._hist_queue_wait.observe(wait.seconds)
        self._gauge_ring_depth.set(self._queue.qsize())
        if degrade.CONTROLLER is not None:
            # Queue-bound backpressure signal for the degradation plane:
            # a long submit block means the scorer is the bottleneck.
            degrade.CONTROLLER.note_queue_wait(wait.seconds)

    def barrier(self) -> None:
        """Block until every submitted window is scored and absorbed.

        The synchronization point checkpoints (and the end-of-stream
        flush) require: after it, the scorer and ``LatestResults`` hold
        exactly the serial path's state for the submitted prefix.
        """
        if self._worker is not None:
            self._queue.join()
        self._raise_if_failed()

    def close(self) -> None:
        """Ordered shutdown: drain everything submitted, then join."""
        self._shutdown_worker()
        self._raise_if_failed()

    def _shutdown_worker(self) -> None:
        """Drain the queue, stop the worker, join it. Idempotent."""
        if self._worker is not None and self._worker.is_alive():
            self._queue.put(_SHUTDOWN)
            self._worker.join()
        self._worker = None

    def _raise_if_failed(self) -> None:
        if self._error is not None:
            # Tear the worker down BEFORE surfacing the error: a caller
            # that catches PipelineError and discards the job must not
            # leak a parked daemon thread (pinning the job, the scorer
            # and its device buffers). The worker keeps draining after a
            # latched failure, so the shutdown sentinel is reached.
            self._shutdown_worker()
            raise PipelineError(
                "pipeline scorer worker failed; the job cannot continue "
                f"({type(self._error).__name__}: {self._error})"
            ) from self._error

    def _ensure_worker(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._run, name="cooc-pipeline-scorer", daemon=True)
            self._worker.start()

    # -- worker side -----------------------------------------------------

    def _run(self) -> None:
        while True:
            item = self._queue.get()
            if item is _SHUTDOWN:
                self._queue.task_done()
                return
            try:
                if self._error is None:
                    self._process(item)
            except BaseException as exc:  # latched; re-raised on caller
                self._error = exc
            finally:
                # Recycle even on failure: the producer may be blocked in
                # ring.stage() and must never deadlock on a dead worker.
                if item.slot is not None:
                    self.ring.release(item.slot)
                self._queue.task_done()

    def _process(self, item: StagedWindow) -> None:
        job = self.job
        # Windows still queued behind this one — the journal's per-window
        # ring-depth (how far the producer ran ahead of the scorer).
        ring_depth = self._queue.qsize()
        if faults.PLAN is not None:
            faults.PLAN.fire("scorer_dispatch", seq=item.seq)
        with clock() as score_clock:
            window_out = job.scorer.process_window(item.ts, item.payload)
        self.scorer_busy_seconds += score_clock.seconds
        job._record_window(WindowStats(
            timestamp=item.ts, events=item.events, pairs=item.raw_pairs,
            rows_scored=getattr(job.scorer, "last_dispatched_rows",
                                len(window_out)),
            sample_seconds=item.sample_seconds,
            score_seconds=score_clock.seconds),
            seq=item.seq, ring_depth=ring_depth,
            stall_seconds=item.stall_seconds,
            admit_seconds=item.admit_seconds)
        job._absorb(window_out)
        self.windows_processed += 1
