"""Run configuration.

Mirrors every flag of the reference CLI (reference:
``Configuration.java:56-199``) plus TPU-framework extensions (backend
selection, device-matrix sizing, sharding, sliding windows, checkpointing).

Defaults match the reference exactly: item cut 500, user cut 500, top-k 10,
window unit milliseconds, buffer timeout 100 ms, seed from the clock
(``Configuration.java:151-182``).
"""

from __future__ import annotations

import argparse
import dataclasses
import enum
import sys
import time
from typing import List, Optional, Sequence

from . import tuning


class WindowUnit(enum.Enum):
    """Time unit for window sizes (reference: ``Configuration.java:157-179``)."""

    MILLISECONDS = 1
    SECONDS = 1_000
    MINUTES = 60_000
    HOURS = 3_600_000
    DAYS = 86_400_000

    @property
    def millis(self) -> int:
        return self.value

    @classmethod
    def parse(cls, s: str) -> "WindowUnit":
        try:
            return cls[s.upper()]
        except KeyError:
            raise ValueError(f"Unrecognized window unit {s}") from None


class Backend(enum.Enum):
    """Execution backend for the scoring/aggregation path.

    ``ORACLE`` is the pure-Python/NumPy reference implementation (float64,
    dict-based state) used as the correctness oracle; ``DEVICE`` is the
    JAX/XLA path (CPU or TPU depending on available devices); ``SHARDED``
    is the multi-chip ``shard_map`` path over a device mesh.
    """

    ORACLE = "oracle"
    DEVICE = "device"
    SHARDED = "sharded"
    HYBRID = "hybrid"  # RETIRED (round 3): alias for SPARSE, which beat it
    # 2.2x on its flagship config and covers the same vocab range;
    # checkpoints are interchangeable so old flags/state keep working
    SPARSE = "sparse"  # device-resident sparse slab, host index (big vocab,
    # minimal host<->device transfer — see state/sparse_scorer.py)


def _parse_seed(value: str) -> int:
    """Parse a decimal or ``0x``-prefixed hex seed (``Configuration.java:211-220``)."""
    if value.startswith("0x") or value.startswith("0X"):
        return int(value[2:], 16)
    return int(value)


@dataclasses.dataclass
class Config:
    """Configuration of a co-occurrence run.

    Reference parity (``Configuration.java``):
      input, skip_cuts, item_cut (fMax), user_cut (kMax), top_k,
      window_size/window_unit, seed (hex-capable), buffer_timeout.
    """

    input: Optional[str] = None
    skip_cuts: bool = False
    item_cut: int = 500
    user_cut: int = 500
    top_k: int = 10
    window_size: int = 0
    window_unit: WindowUnit = WindowUnit.MILLISECONDS
    seed: Optional[int] = None
    buffer_timeout: int = 100  # ms a parsed line may wait in a partial
    # batch when tailing continuously (reference: record flush bound,
    # FlinkCooccurrences.java:46); no-op in process-once runs
    source_format: str = "files"  # ingest source shape: "files" = the
    # reference's file-monitor tail (io/source.py); "partitioned" = the
    # append-only partitioned log (io/partitioned.py: part-* files,
    # Kafka shape without the dependency) whose per-partition offsets
    # commit atomically with the checkpoint under the epoch protocol —
    # exactly-once from the wire up
    ingest_partitions: int = 0  # expected part-* file count with
    # --source-format partitioned: pins the partition/offset contract up
    # front (a drifted directory fails fast, like a Kafka topic changing
    # partition count under a consumer group); 0 = derive from the
    # directory at first listing

    # --- TPU-framework extensions (no reference analogue) ---
    backend: Backend = Backend.DEVICE
    num_items: int = 0  # dense device vocab capacity; 0 = derive from the
    # data (the device backend doubles its C on vocab growth; the sharded
    # backend doubles-with-reshard the same way, except multi-host runs,
    # which still need an explicit capacity agreed across processes)
    num_shards: int = 1  # item-axis shards over the device mesh
    window_slide: Optional[int] = None  # sliding windows; None = tumbling
    max_pairs_per_step: int = 1 << 20  # COO padding bucket (recompile guard)
    # (--sample-workers was RETIRED in round 3 and fully removed in PR 8:
    # passing it now raises a clear "retired" error in from_args —
    # --partition-sampling is the ingest scale-out axis.)
    checkpoint_dir: Optional[str] = None
    checkpoint_every_windows: int = 0  # 0 = disabled
    checkpoint_retain: int = 3  # generation-numbered checkpoints kept
    # (state.<gen>.npz; restore falls back to the newest generation that
    # verifies its digest, quarantining corrupt ones as *.corrupt).
    # Chain-aware under --checkpoint-incremental: a base or intermediate
    # delta a retained generation still chains through is never deleted.
    checkpoint_incremental: bool = False  # dirty-row incremental
    # generations (state/delta.py): a full base plus per-generation
    # delta.<gen>.bin files holding only rows touched since the previous
    # committed generation, coded with the PR-7 delta+zigzag+varint
    # primitives — commit bytes scale with per-generation churn, not
    # vocab. Restore replays base + deltas into byte-identical state.
    # Sparse backends only (the canonical rows_key/rows_cnt blob is the
    # delta's domain); the same files are the consumable delta log
    # (state/delta.read_delta_stream) future read replicas tail.
    checkpoint_compact_ratio: float = tuning.default("checkpoint_compact_ratio")  # ratio trigger: once the
    # delta chain's bytes exceed this fraction of the base's, the next
    # checkpoint rewrites a fresh full base (bounds restore replay) and
    # the old chain ages out under --checkpoint-retain
    restart_on_failure: int = 0  # supervisor: respawn the job up to N
    # times on abnormal exit, resuming from --checkpoint-dir when set
    # (the reference delegates this to Flink's restart strategies,
    # SURVEY §5); 0 = no supervision
    restart_delay_ms: int = 1000  # fixed delay between restart attempts
    # (the analogue of Flink's fixed-delay restart strategy)
    restart_backoff_base_ms: int = 0  # >0 switches restart delays to
    # exponential backoff with decorrelated jitter, starting here
    restart_backoff_max_ms: int = 30000  # backoff delay cap
    crash_loop_threshold: int = 3  # failures within the sliding window
    # that open the crash-loop breaker (step back one checkpoint
    # generation, then give up on a re-trip); 0 = breaker off
    crash_loop_window_s: float = 60.0  # breaker sliding-window seconds
    watchdog_stale_after_s: float = 0.0  # supervisor hang watchdog: kill
    # a child whose --journal has not grown for this many seconds (the
    # /healthz "no window fired" liveness signal); 0 = off
    degrade: bool = False  # graceful-degradation controller
    # (robustness/degrade.py): watch per-window health signals and step
    # NORMAL -> SHED_SAMPLING -> SHED_K -> PAUSE_INGEST, tightening the
    # paper's frequency cuts / emitted top-K and finally applying
    # bounded admission delay at the source; off = today's behavior
    degrade_window_wall_s: float = 1.0  # a window slower than this
    # wall-clock (sample+score) counts as overloaded
    degrade_trip_windows: int = 3  # consecutive overloaded windows that
    # escalate one level (hysteresis: escalation is never single-sample)
    degrade_clear_windows: int = 8  # consecutive healthy windows that
    # de-escalate one level (asymmetric on purpose: recover slower than
    # you shed, so the level cannot flap)
    degrade_shed_factor: int = 2  # cut/top-K divisor per shedding level
    degrade_pause_ms: int = 200  # bounded per-admit delay at PAUSE_INGEST
    # (a throttle, never an unbounded stall — no self-deadlock)
    degrade_stale_after_s: float = 30.0  # ingest-side staleness signal:
    # no window completed for this long while lines keep arriving
    # escalates one level (rate-limited to one step per stale period)
    quarantine_file: Optional[str] = None  # poison-input dead-letter
    # JSONL (robustness/quarantine.py): malformed lines divert here with
    # path:lineno provenance instead of crashing the job; None = off
    # (a malformed line raises, with the same provenance in the error)
    max_quarantine_rate: float = 0.01  # quarantine breaker: abort (exit
    # 2, permanent) once more than this fraction of input lines has
    # been quarantined — a systematically wrong input must not
    # "succeed" on its crumbs
    max_quarantine_bytes: int = 0  # dead-letter size cap: the active
    # file rolls over to .1/.2/... at this size, oldest backup beyond
    # the keep window deleted — a week-long stream cannot grow the
    # dead-letter JSONL unboundedly. 0 = unbounded (today's behavior)
    scorer_breaker_threshold: int = 0  # scorer circuit breaker
    # (robustness/degrade.py): N consecutive process_window failures
    # open the breaker onto the exact host-oracle fallback scorer, so a
    # failing device dispatch degrades the run instead of killing it;
    # 0 = off (single-process device/sparse backends only)
    scorer_breaker_probe_windows: int = 8  # windows the breaker stays
    # open before a half-open probe retries the primary scorer
    inject_fault: Optional[List[str]] = None  # fault-injection specs
    # (robustness/faults.py): site[:window_seq][:kind[:arg]], each fires
    # exactly once; None/[] = injection off (zero hot-path cost)
    fault_state_dir: Optional[str] = None  # markers making injected
    # faults fire once per RUN (across supervised restarts), not once
    # per attempt
    profile_dir: Optional[str] = None  # XLA profiler trace output (TensorBoard)
    journal: Optional[str] = None  # run-journal JSONL path: one flushed
    # record per fired window (observability/journal.py flight recorder);
    # a supervised crash leaves its tail intact and the supervisor quotes
    # it in the restart log. None = off
    metrics_port: Optional[int] = None  # live scrape endpoint
    # (observability/http.py): /metrics Prometheus text + /healthz
    # staleness probe on 127.0.0.1; 0 = ephemeral port (logged at
    # startup); None = off
    healthz_stale_after_s: float = 300.0  # /healthz turns 503 once no
    # window has fired for this many wall seconds
    serve_port: Optional[int] = None  # online serving plane
    # (serving/): /recommend beside /metrics + /healthz on
    # 127.0.0.1:PORT, backed by double-buffered zero-lock snapshots of
    # the per-item top-K table swapped at window boundaries; 0 =
    # ephemeral port (logged at startup); None = off
    serve_history: int = 50  # per-user recent-history ring length the
    # blend multiplies against the co-occurrence rows (bounded memory:
    # 4 B x users x length)
    serve_stale_after_s: float = 0.0  # /healthz turns 503 once the
    # published snapshot is older than this many seconds (load-balancer
    # drain signal for a wedged job); 0 = off
    serve_query_slo_s: float = 0.25  # query-latency SLO: a /recommend
    # slower than this raises the degradation plane's QUERY_PRESSURE
    # signal, shedding INGEST (tighter cuts, pause) before query latency
    # degrades — never the reverse; 0 = signal off
    score_ladder: Optional[int] = None  # sparse score-bucket ladder base
    # (power of two >= 2); None = env TPU_COOC_SCORE_LADDER or 4. Coarser
    # = fewer dispatches, more padding — the high-latency-link lever.
    fixed_score: str = tuning.default("fixed_score")  # sparse fixed-shape scoring: auto|on|off
    # (auto = on for real TPUs when results are deferred; constant
    # per-bucket rectangles -> one compile + one dispatch per bucket)
    pallas: str = "auto"  # fused score/top-K kernel: auto|on|off (auto = on
    # for int16 counts on a real TPU where it wins 247x, off otherwise —
    # measured, see ops/device_scorer.pallas_auto)
    fused_window: str = "off"  # one-dispatch fused window path.
    # device backend (tumbling mode): the sampler uplinks baskets (star
    # ops) and expansion + count scatter + row sums + LLR + top-K run
    # as ONE program per shape bucket
    # (ops/pallas_score.pallas_expand_baskets +
    # ops/device_scorer._fused_window_*). sparse backend
    # (single-process, deferred results): packed-wire decode + slab
    # update scatter + device registry sync + rescore + results-table
    # scatter run as ONE program per shape bucket
    # (state/sparse_scorer._fused_sparse_window_*); relocation /
    # promotion / spill-re-promotion windows route chained per window.
    # auto = on-chip only — the CPU fallback stays on the chained
    # scatter+score path

    count_dtype: str = tuning.default("count_dtype")  # dense C cell dtype; int16 halves HBM
    # (reference-style short counts incl. its wraparound, doubles the
    # dense/sharded vocab ceiling)
    cell_dtype: str = tuning.default("cell_dtype")  # sparse slab cnt cell dtype: auto|int32|
    # int16|int8 (state/wire.py). Narrow cells stay EXACT — a row is
    # promoted to the wide int32 side-table before any cell could
    # saturate — unlike the dense --count-dtype, which wraps like the
    # reference's Java shorts. auto = int16 on the single-process sparse
    # backend, int32 elsewhere.
    spill_threshold_windows: int = tuning.default("spill_threshold_windows")  # tiered elastic state
    # (state/store.TieredSlabStore): rows untouched for this many fired
    # windows spill from the HBM slab to a host-side packed arena
    # (index keys really freed, capacity reused by hot rows) and
    # re-promote exactly on next touch, batched into the window's
    # existing uplink. 0 = tiering off (every row device-resident for
    # the whole run). Bit-identical output and checkpoints either way.
    spill_target_hbm_frac: float = tuning.default("spill_target_hbm_frac")  # spilling engages only while
    # live slab cells exceed this fraction of the allocated device slab
    # capacity (0.0 = spill every eligible cold row unconditionally;
    # 1.0 = only under a full slab)
    wire_format: str = tuning.default("wire_format")  # sparse per-window uplink encoding:
    # auto|raw|packed. packed = per-section sorted delta + zigzag +
    # bit-pack of the update buffer, decoded on device by a jit prologue
    # (state/wire.py) — fewer uplink bytes at bit-identical results; an
    # explicit TPU_COOC_UPLOAD_CHUNKS/_CHUNK_KB split request pins the
    # raw chunked path. Also selects the checkpoint blob codec
    # (raw = pre-codec layout, else delta+varint). auto = packed on the
    # single-process sparse backend, raw elsewhere.
    pipeline_depth: int = tuning.default("pipeline_depth")  # pipelined execution: the caller thread
    # samples window N+1 while a worker thread runs the scorer for
    # window N (pipeline.py). 0 = serial (today's behavior); 1 =
    # single-window overlap; 2 = double-buffered (absorbs stage jitter).
    # Bit-identical output to serial at every depth (parity-tested).
    development_mode: bool = False  # invariant checks (FlinkCooccurrences.java:34)
    emit_updates: bool = False  # stream every window's updated top-K rows
    # to stdout as they materialize (the consumable form of the
    # reference's continuous sink emission); off = final state only
    process_continuously: bool = False  # PROCESS_ONCE vs PROCESS_CONTINUOUSLY
    # Multi-host (multi-controller JAX): run one process per host, each
    # consuming the same input stream; state shards over all hosts' chips
    # and each process emits the rows its chips own (parallel/distributed.py).
    coordinator: Optional[str] = None  # host:port of process 0
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    run_id: Optional[str] = None  # tracing correlation id stamped on
    # every journal record (observability/journal.py); None = inherit
    # TPU_COOC_RUN_ID from a supervising parent, else mint fresh. Set
    # explicitly to join separately launched processes (e.g. a writer
    # and a standalone replica) into one cooc-trace timeline
    gang_workers: int = 0  # gang supervision (robustness/gang.py): this
    # process becomes the gang supervisor — it launches N workers with
    # the multi-controller identity flags filled in (fresh local
    # coordinator port per attempt), monitors exits + heartbeat files,
    # and gang-kills + gang-restarts the WHOLE set on any failure (JAX
    # collectives cannot survive peer loss); --restart-on-failure is
    # the gang's restart budget. 0 = off
    gang_heartbeat_s: float = 5.0  # worker heartbeat write interval
    autoscale: str = "off"  # load-driven gang autoscaler
    # (robustness/autoscale.py, gang runs only): sustained SHED_*
    # pressure grows the gang, sustained idle shrinks it — workers
    # drain a checkpoint at a gang-voted window boundary and exit
    # voluntarily, the supervisor relaunches at the new size, and the
    # topology-aware restore vote re-buckets N-shard state onto M
    # (scale before you shed; the degradation ladder only sheds once
    # the gang is at --autoscale-max-workers). off = today's behavior
    autoscale_min_workers: int = 2  # scale-down floor (a gang needs 2)
    autoscale_max_workers: int = 0  # scale-up ceiling; REQUIRED (> 0)
    # with --autoscale on — the operator owns the capacity budget
    autoscale_trip_windows: int = tuning.default("autoscale_trip_windows")  # consecutive gang-overloaded
    # windows that trigger a scale-up (hysteresis mirrors the ladder)
    autoscale_clear_windows: int = tuning.default("autoscale_clear_windows")  # consecutive gang-idle windows
    # that trigger a scale-down (asymmetric: grow fast, shrink slow)
    autoscale_cooldown_windows: int = tuning.default("autoscale_cooldown_windows")  # observed windows ignored
    # after every rescale decision (restore + recompile warm-up must
    # not read as a fresh signal)
    gang_stale_after_s: float = 60.0  # heartbeat age past which a peer
    # counts as dead: the gang supervisor restarts the gang, /healthz
    # 503s ("peer_stale") so a load balancer drains first; 0 = off
    collective_timeout_s: float = tuning.default("collective_timeout_s")  # collective-entry watchdog
    # (parallel/distributed.py): a guarded collective blocked this long
    # means a peer is gone — exit 75 for the gang supervisor to restart
    # the whole gang, instead of hanging forever; 0 = off
    partition_sampling: bool = False  # split host-side sampling across
    # processes by user (u % P) — the reservoir in tumbling mode, basket
    # expansion in sliding mode (cuts stay replicated) — and allgather
    # pair deltas per window: the reference's keyed-parallel ingest
    # scaling (sampling/multihost.py); off = every process samples the
    # full stream (replicated host state)

    def __post_init__(self):
        if self.seed is None:
            self.seed = time.time_ns()  # reference: System.nanoTime()
        if self.top_k <= 0:
            raise ValueError(f"{self.top_k} is <= 0")
        if self.source_format not in ("files", "partitioned"):
            raise ValueError(
                f"--source-format must be 'files' or 'partitioned', got "
                f"{self.source_format!r}")
        if self.ingest_partitions < 0:
            raise ValueError(
                f"--ingest-partitions must be >= 0, got "
                f"{self.ingest_partitions}")
        if self.ingest_partitions and self.source_format != "partitioned":
            raise ValueError(
                "--ingest-partitions only applies to --source-format "
                "partitioned (the files source has no partition "
                "contract to pin)")
        if self.restart_on_failure > 0 and self.process_continuously:
            raise ValueError(
                "--restart-on-failure buffers each attempt's stdout until "
                "it exits cleanly; a --process-continuously job never "
                "exits, so the combination would stream nothing and grow "
                "without bound — supervise continuous jobs externally "
                "(systemd/k8s) instead")
        if self.restart_on_failure > 0 and self.coordinator is not None:
            raise ValueError(
                "--restart-on-failure supervises one process; in a "
                "multi-host run a respawned child would re-join the "
                "coordinator while surviving peers are blocked "
                "mid-collective — use --gang-workers (the gang "
                "supervisor restarts all processes together) or "
                "supervise externally")
        multihost = (self.coordinator, self.num_processes, self.process_id)
        if any(v is not None for v in multihost):
            if any(v is None for v in multihost):
                raise ValueError(
                    "multi-host needs all of --coordinator, --num-processes "
                    "and --process-id (or none of them)")
            if not (0 <= self.process_id < self.num_processes):
                raise ValueError(
                    f"--process-id {self.process_id} out of range for "
                    f"--num-processes {self.num_processes}")
        if self.partition_sampling:
            if self.coordinator is None and not self.gang_workers:
                raise ValueError(
                    "--partition-sampling is a multi-host mode — it needs "
                    "--coordinator/--num-processes/--process-id (or "
                    "--gang-workers, which assigns them)")
        if self.gang_workers:
            if self.gang_workers < 2:
                raise ValueError(
                    f"--gang-workers needs >= 2 workers (a gang of one "
                    f"is --restart-on-failure), got {self.gang_workers}")
            if self.coordinator is not None or self.process_id is not None \
                    or self.num_processes is not None:
                raise ValueError(
                    "--gang-workers assigns --coordinator/--num-processes"
                    "/--process-id to its workers itself — do not pass "
                    "them to the supervisor")
            if self.process_continuously:
                raise ValueError(
                    "--gang-workers buffers each worker's stdout until "
                    "the gang exits cleanly; a --process-continuously "
                    "job never exits — supervise continuous gangs "
                    "externally (restart all processes together)")
            if self.serve_port is not None:
                raise ValueError(
                    "--serve-port is single-process only; gang workers "
                    "hold partial top-K tables — serve reads from a "
                    "replica fleet instead (cooc-replica --state-dir "
                    "<checkpoint dir>, with --checkpoint-incremental "
                    "on the ingest job)")
            backend_multihost = (
                self.backend == Backend.SHARDED
                or (self.backend in (Backend.SPARSE, Backend.HYBRID)
                    and self.num_shards > 1))
            if not backend_multihost:
                raise ValueError(
                    "--gang-workers runs a multi-controller job: use "
                    "--backend sharded, or sparse with --num-shards > 1 "
                    "(other backends would run one full independent job "
                    "per worker and clobber the shared checkpoint dir)")
        if self.gang_heartbeat_s <= 0:
            raise ValueError(
                f"--gang-heartbeat-s must be positive, got "
                f"{self.gang_heartbeat_s}")
        if self.autoscale not in ("off", "on"):
            raise ValueError(
                f"--autoscale must be off|on, got {self.autoscale!r}")
        if (self.autoscale_trip_windows < 1
                or self.autoscale_clear_windows < 1):
            raise ValueError(
                "--autoscale-trip-windows and --autoscale-clear-windows "
                "must be >= 1")
        if self.autoscale_cooldown_windows < 0:
            raise ValueError(
                f"--autoscale-cooldown-windows must be >= 0, got "
                f"{self.autoscale_cooldown_windows}")
        if self.autoscale == "on":
            if not self.gang_workers and self.coordinator is None:
                raise ValueError(
                    "--autoscale on is gang machinery — it needs "
                    "--gang-workers (the supervisor owns relaunching at "
                    "a new topology)")
            if not self.degrade:
                raise ValueError(
                    "--autoscale on reads the degradation plane's "
                    "per-window pressure signal — it needs --degrade")
            if not self.checkpoint_dir:
                raise ValueError(
                    "--autoscale on drains a checkpoint at every "
                    "rescale boundary — it needs --checkpoint-dir")
            if self.backend not in (Backend.SPARSE, Backend.HYBRID):
                raise ValueError(
                    "--autoscale on needs --backend sparse (the N->M "
                    "rescale restore re-buckets the sparse slab's "
                    "global key space; the dense sharded matrix has no "
                    "rescale-on-restore path)")
            if self.partition_sampling:
                raise ValueError(
                    "--autoscale on cannot run with "
                    "--partition-sampling: the per-process reservoir "
                    "partition (u %% P) changes shape at a rescale and "
                    "has no redistribution path")
            if self.autoscale_min_workers < 2:
                raise ValueError(
                    f"--autoscale-min-workers must be >= 2 (a gang of "
                    f"one is --restart-on-failure), got "
                    f"{self.autoscale_min_workers}")
            if self.autoscale_max_workers < self.autoscale_min_workers:
                raise ValueError(
                    "--autoscale on needs --autoscale-max-workers >= "
                    f"--autoscale-min-workers (got "
                    f"{self.autoscale_max_workers} < "
                    f"{self.autoscale_min_workers}) — the operator "
                    "owns the capacity ceiling")
            launch = (self.gang_workers
                      if self.gang_workers else (self.num_processes or 0))
            if launch and not (self.autoscale_min_workers <= launch
                               <= self.autoscale_max_workers):
                raise ValueError(
                    f"the launch topology ({launch} workers) must sit "
                    f"inside [--autoscale-min-workers, "
                    f"--autoscale-max-workers] = "
                    f"[{self.autoscale_min_workers}, "
                    f"{self.autoscale_max_workers}]")
        if self.gang_stale_after_s < 0:
            raise ValueError(
                f"--gang-stale-after-s must be >= 0, got "
                f"{self.gang_stale_after_s}")
        if self.collective_timeout_s < 0:
            raise ValueError(
                f"--collective-timeout-s must be >= 0, got "
                f"{self.collective_timeout_s}")
        if self.inject_fault is None:
            self.inject_fault = []
        if self.inject_fault:
            # Fail fast on a bad spec (unknown site/kind, missing
            # delay arg) — at config time, not mid-run at first fire.
            from .robustness.faults import FaultPlan

            FaultPlan.parse(self.inject_fault)
            if self.restart_on_failure > 0 and not self.fault_state_dir:
                raise ValueError(
                    "--inject-fault under --restart-on-failure needs "
                    "--fault-state-dir: without persisted fired-markers "
                    "every respawned attempt re-injects the same faults "
                    "and the run can only exhaust its restarts")
        if self.checkpoint_retain < 1:
            raise ValueError(
                f"--checkpoint-retain must be >= 1, got "
                f"{self.checkpoint_retain}")
        if self.checkpoint_compact_ratio <= 0:
            raise ValueError(
                f"--checkpoint-compact-ratio must be > 0, got "
                f"{self.checkpoint_compact_ratio}")
        if self.checkpoint_incremental:
            if self.backend not in (Backend.SPARSE, Backend.HYBRID):
                # The delta records' domain is the canonical sparse
                # rows_key/rows_cnt blob; dense C matrices have no
                # dirty-row representation to replay.
                raise ValueError(
                    "--checkpoint-incremental needs a sparse-family "
                    "backend (--backend sparse, any shard count); got "
                    f"--backend {self.backend.value}")
            if self.scorer_breaker_threshold > 0:
                # A tripped breaker scores on the host fallback: rows it
                # rescored never reach the store's dirty log, so a delta
                # written mid-trip would silently miss them.
                raise ValueError(
                    "--checkpoint-incremental cannot run with "
                    "--scorer-breaker-threshold: fallback-scored rows "
                    "bypass the dirty-row log — disable one of the two")
        if self.restart_backoff_base_ms < 0 or self.restart_backoff_max_ms < 0:
            raise ValueError("restart backoff values must be >= 0")
        if (self.restart_backoff_base_ms
                and self.restart_backoff_max_ms < self.restart_backoff_base_ms):
            raise ValueError(
                "--restart-backoff-max-ms must be >= "
                "--restart-backoff-base-ms")
        if self.watchdog_stale_after_s < 0:
            raise ValueError(
                f"--watchdog-stale-after-s must be >= 0, got "
                f"{self.watchdog_stale_after_s}")
        if self.watchdog_stale_after_s > 0:
            if self.restart_on_failure <= 0 and not self.gang_workers:
                raise ValueError(
                    "--watchdog-stale-after-s is supervisor machinery — "
                    "it needs --restart-on-failure (or --gang-workers)")
            if not self.journal:
                raise ValueError(
                    "--watchdog-stale-after-s watches the run journal "
                    "for liveness — it needs --journal")
        if self.metrics_port is not None and not (
                0 <= self.metrics_port <= 65535):
            raise ValueError(
                f"--metrics-port must be 0..65535, got {self.metrics_port}")
        if self.serve_port is not None:
            if not (0 <= self.serve_port <= 65535):
                raise ValueError(
                    f"--serve-port must be 0..65535, got {self.serve_port}")
            if (self.metrics_port is not None
                    and self.metrics_port == self.serve_port):
                raise ValueError(
                    "--serve-port already serves /metrics and /healthz; "
                    "binding --metrics-port to the same port would fail "
                    "at startup — drop one (or use distinct ports)")
            if self.coordinator is not None or self.partition_sampling:
                # Each multi-host process materializes only the rows its
                # chips own; a per-process snapshot would silently serve
                # a partial catalog as if it were the whole table.
                raise ValueError(
                    "--serve-port is single-process only (a multi-host "
                    "process holds a partial top-K table) — serve reads "
                    "from a replica fleet instead (cooc-replica "
                    "--state-dir <checkpoint dir>, with "
                    "--checkpoint-incremental on the ingest job)")
        if self.serve_history < 1:
            raise ValueError(
                f"--serve-history must be >= 1, got {self.serve_history}")
        if self.serve_stale_after_s < 0:
            raise ValueError(
                f"--serve-stale-after-s must be >= 0, got "
                f"{self.serve_stale_after_s}")
        if self.serve_query_slo_s < 0:
            raise ValueError(
                f"--serve-query-slo-s must be >= 0, got "
                f"{self.serve_query_slo_s}")
        if self.healthz_stale_after_s <= 0:
            raise ValueError(
                f"--healthz-stale-after-s must be positive, got "
                f"{self.healthz_stale_after_s}")
        if self.degrade_window_wall_s <= 0:
            raise ValueError(
                f"--degrade-window-wall-s must be positive, got "
                f"{self.degrade_window_wall_s}")
        if self.degrade_trip_windows < 1 or self.degrade_clear_windows < 1:
            raise ValueError(
                "--degrade-trip-windows and --degrade-clear-windows "
                "must be >= 1")
        if self.degrade_shed_factor < 2:
            raise ValueError(
                f"--degrade-shed-factor must be >= 2, got "
                f"{self.degrade_shed_factor}")
        if self.degrade_pause_ms < 0:
            raise ValueError(
                f"--degrade-pause-ms must be >= 0, got "
                f"{self.degrade_pause_ms}")
        if self.degrade_stale_after_s <= 0:
            raise ValueError(
                f"--degrade-stale-after-s must be positive, got "
                f"{self.degrade_stale_after_s}")
        if (self.degrade and self.pipeline_depth > 0
                and (self.coordinator is not None or self.gang_workers)):
            # Multi-host --degrade stays in lockstep through a
            # per-window worst-signal allgather on the window-record
            # thread (robustness/degrade.py exchange); at depth 0 that
            # thread IS the sampling thread, so the level every host
            # samples under is deterministic. Pipelined, the sampling
            # thread would read the level mid-flight while the scorer
            # worker votes — hosts could sample the same window under
            # different cuts and diverge the pair streams.
            raise ValueError(
                "--degrade on multi-host runs needs --pipeline-depth 0 "
                "(the per-window shed vote is only in lockstep with "
                "sampling on the serial path)")
        if not (0.0 < self.max_quarantine_rate <= 1.0):
            raise ValueError(
                f"--max-quarantine-rate must be in (0, 1], got "
                f"{self.max_quarantine_rate}")
        if self.max_quarantine_bytes < 0:
            raise ValueError(
                f"--max-quarantine-bytes must be >= 0, got "
                f"{self.max_quarantine_bytes}")
        if self.scorer_breaker_threshold < 0:
            raise ValueError(
                f"--scorer-breaker-threshold must be >= 0, got "
                f"{self.scorer_breaker_threshold}")
        if self.scorer_breaker_probe_windows < 1:
            raise ValueError(
                f"--scorer-breaker-probe-windows must be >= 1, got "
                f"{self.scorer_breaker_probe_windows}")
        if self.scorer_breaker_threshold > 0:
            if self.backend == Backend.ORACLE:
                raise ValueError(
                    "--scorer-breaker-threshold: the oracle backend IS "
                    "the breaker's fallback — there is nothing to break "
                    "over")
            if (self.backend == Backend.SHARDED or self.num_shards > 1
                    or self.coordinator is not None):
                raise ValueError(
                    "--scorer-breaker-threshold is single-process "
                    "device/sparse only (a per-process host fallback "
                    "cannot substitute for a mesh collective)")
        if self.cell_dtype not in ("auto", "int32", "int16", "int8"):
            raise ValueError(
                f"--cell-dtype must be auto|int32|int16|int8, got "
                f"{self.cell_dtype!r}")
        if self.wire_format not in ("auto", "raw", "packed"):
            raise ValueError(
                f"--wire-format must be auto|raw|packed, got "
                f"{self.wire_format!r}")
        sparse_single = (self.backend in (Backend.SPARSE, Backend.HYBRID)
                         and self.num_shards == 1
                         and self.coordinator is None)
        # The sharded-sparse mesh (single controller) carries the wide
        # side-table and the packed uplink too; only multi-controller
        # runs are excluded (per-process snapshots have no wide blocks,
        # and every worker would re-encode the same replicated window).
        sparse_local = (sparse_single
                        or (self.backend == Backend.SPARSE
                            and self.coordinator is None))
        if self.cell_dtype in ("int16", "int8") and not sparse_local:
            # 'auto' degrades gracefully; an explicit narrow request the
            # backend cannot honor must fail loudly (same rule as
            # --fused-window on).
            raise ValueError(
                f"--cell-dtype {self.cell_dtype} is --backend sparse "
                f"without --coordinator only (multi-controller "
                f"per-process snapshots carry no wide side-table "
                f"blocks)")
        if self.wire_format == "packed" and not (
                sparse_local or self.backend == Backend.SPARSE):
            raise ValueError(
                "--wire-format packed applies to the sparse backend's "
                "update uplink (other backends ship raw COO or basket "
                "formats)")
        if self.spill_threshold_windows < 0:
            raise ValueError(
                f"--spill-threshold-windows must be >= 0, got "
                f"{self.spill_threshold_windows}")
        if not (0.0 <= self.spill_target_hbm_frac <= 1.0):
            raise ValueError(
                f"--spill-target-hbm-frac must be in [0, 1], got "
                f"{self.spill_target_hbm_frac}")
        if self.spill_threshold_windows > 0 and not sparse_single:
            # Same single-process-sparse scoping rule as --cell-dtype:
            # the spill arena and promotion extras are per-process slab
            # state (the sharded backend's elastic axis is
            # rescale-on-restore instead).
            raise ValueError(
                "--spill-threshold-windows is single-process --backend "
                "sparse only (the spill arena is per-process slab "
                "state; sharded runs rescale via --num-shards at "
                "restore instead)")
        if self.fused_window not in ("auto", "on", "off"):
            raise ValueError(
                f"--fused-window must be auto|on|off, got "
                f"{self.fused_window!r}")
        if self.fused_window == "on":
            # 'auto' may ride along anywhere (it only engages where a
            # fused-capable backend resolves it); a forced 'on' that
            # cannot engage must fail loudly, not silently run chained.
            if self.backend == Backend.DEVICE:
                if self.window_slide is not None:
                    raise ValueError(
                        "--fused-window on with --backend device applies "
                        "to tumbling reservoir sampling; sliding windows "
                        "stay on the chained path")
                if self.partition_sampling or self.coordinator is not None:
                    raise ValueError(
                        "--fused-window on is single-process only (the "
                        "partitioned sampler allgathers expanded COO)")
            elif self.backend in (Backend.SPARSE, Backend.HYBRID):
                if self.backend == Backend.HYBRID and not sparse_single:
                    raise ValueError(
                        "--fused-window on with --backend hybrid is "
                        "single-process only")
                if self.emit_updates:
                    raise ValueError(
                        "--fused-window on with --backend sparse needs "
                        "deferred results (drop --emit-updates): the "
                        "fused program scatters top-K into the "
                        "device-resident table, never downlinks per "
                        "window")
            else:
                raise ValueError(
                    f"--fused-window on is --backend device or sparse "
                    f"only (got {self.backend.value}); other backends "
                    f"stay on the chained path")
        if self.pipeline_depth not in (0, 1, 2):
            raise ValueError(
                f"--pipeline-depth must be 0, 1 or 2, got "
                f"{self.pipeline_depth}")
        if self.pipeline_depth > 0 and self.partition_sampling:
            # Multi-controller collectives must be issued in the same
            # order on every process; the partitioned sampler's
            # per-window allgather runs on the sampling thread, which
            # would race the scorer worker's dispatches. Plain
            # multi-host pipelining is fine: every collective (scorer
            # dispatch, degrade-off, epoch barrier behind
            # pipeline.barrier()) issues from one thread in window
            # order.
            raise ValueError(
                "--pipeline-depth > 0 is incompatible with "
                "--partition-sampling (the partitioned sampler's "
                "allgather on the sampling thread would race the "
                "scorer worker's collectives)")

    @property
    def window_millis(self) -> int:
        return self.window_size * self.window_unit.millis

    @property
    def slide_millis(self) -> Optional[int]:
        if self.window_slide is None:
            return None
        return self.window_slide * self.window_unit.millis

    def log_configuration(self, logger) -> None:
        """Echo the config at startup (reference: ``Configuration.java:272-282``)."""
        logger.info("input\t%s", self.input)
        logger.info("skip cuts\t%s", self.skip_cuts)
        logger.info("item cut (fMax)\t%s", self.item_cut)
        logger.info("user cut (kMax)\t%s", self.user_cut)
        logger.info("topK\t%s", self.top_k)
        logger.info("windowSize\t%s", self.window_size)
        logger.info("windowUnit\t%s", self.window_unit.name)
        logger.info("seed\t%s", self.seed)
        logger.info("buffer timeout\t%s", self.buffer_timeout)
        logger.info("backend\t%s", self.backend.value)
        logger.info("numItems\t%s", self.num_items)
        logger.info("numShards\t%s", self.num_shards)

    @classmethod
    def from_args(cls, argv: Optional[Sequence[str]] = None) -> "Config":
        """CLI parsing mirroring the reference flags (``Configuration.java:56-199``)."""
        p = argparse.ArgumentParser(
            prog="tpu-cooccurrence",
            description="TPU-native streaming item-item co-occurrence (LLR) recommender",
            # No prefix abbreviations: the supervisor strips its own flags
            # from the child argv by exact name, and an abbreviated
            # `--restart-on` would survive the strip and recurse into a
            # nested supervisor (also matches commons-cli, which has no
            # abbreviation).
            allow_abbrev=False,
        )
        p.add_argument("-i", "--input", required=True,
                       help="Input file/directory to consume (expected format 'user,item,timestamp')")
        p.add_argument("--source-format", choices=("files", "partitioned"),
                       default="files", dest="source_format",
                       help="Ingest source shape: 'files' tails the "
                            "input in modification-time order; "
                            "'partitioned' consumes an append-only "
                            "partitioned log (part-* files) whose "
                            "per-partition offsets commit atomically "
                            "with the checkpoint (default: files)")
        p.add_argument("--ingest-partitions", type=int, default=0,
                       dest="ingest_partitions",
                       help="Expected part-* partition count with "
                            "--source-format partitioned; a directory "
                            "with a different count fails fast "
                            "(0 = derive from the directory)")
        p.add_argument("-sc", "--skip-cuts", action="store_true", dest="skip_cuts",
                       help="Skip the interaction cuts")
        p.add_argument("-ic", "--item-cut", type=int, default=500, dest="item_cut",
                       help="Item interaction cut (default: 500)")
        p.add_argument("-uc", "--user-cut", type=int, default=500, dest="user_cut",
                       help="User interaction cut (default: 500)")
        p.add_argument("-k", "--top-k", type=int, default=10, dest="top_k",
                       help="Top K (default: 10)")
        p.add_argument("-ws", "--window-size", type=int, required=True, dest="window_size",
                       help="Window size")
        p.add_argument("-wu", "--window-unit", type=WindowUnit.parse,
                       default=WindowUnit.MILLISECONDS, dest="window_unit",
                       help="TimeUnit for the window (default: milliseconds)")
        p.add_argument("-s", "--seed", type=_parse_seed, default=None,
                       help="Seed for random number generator (decimal or 0x-hex)")
        p.add_argument("-bt", "--buffer-timeout", type=int, default=100, dest="buffer_timeout",
                       help="Buffer timeout (default: 100ms)")
        # Extensions
        p.add_argument("--backend", type=Backend, choices=list(Backend),
                       default=Backend.DEVICE)
        p.add_argument("--num-items", type=int, default=0, dest="num_items",
                       help="Dense item-vocabulary capacity on device "
                            "(0 = derive from data; device backend only — "
                            "sharded requires an explicit capacity)")
        p.add_argument("--num-shards", type=int, default=1, dest="num_shards",
                       help="Item-axis shards over the device mesh")
        p.add_argument("--window-slide", type=int, default=None, dest="window_slide",
                       help="Slide (same unit as window) for sliding windows")
        p.add_argument("--profile-dir", default=None, dest="profile_dir",
                       help="Write a jax.profiler trace for TensorBoard")
        p.add_argument("--journal", default=None, dest="journal",
                       help="Append one JSONL record per fired window to "
                            "this path (flight recorder; survives crashes "
                            "and is quoted by the supervisor's restart log)")
        p.add_argument("--metrics-port", type=int, default=None,
                       dest="metrics_port",
                       help="Serve Prometheus /metrics and /healthz on "
                            "127.0.0.1:PORT (0 = ephemeral, logged at "
                            "startup; omit to disable)")
        p.add_argument("--healthz-stale-after-s", type=float, default=300.0,
                       dest="healthz_stale_after_s",
                       help="/healthz reports 503 once no window has fired "
                            "for this many seconds (default: 300)")
        p.add_argument("--serve-port", type=int, default=None,
                       dest="serve_port",
                       help="Serve /recommend (plus /metrics and /healthz) "
                            "on 127.0.0.1:PORT from zero-lock double-"
                            "buffered top-K snapshots swapped at window "
                            "boundaries (0 = ephemeral, logged at "
                            "startup; omit to disable)")
        p.add_argument("--serve-history", type=int, default=50,
                       dest="serve_history",
                       help="Per-user recent-history ring length the "
                            "/recommend blend uses (default: 50)")
        p.add_argument("--serve-stale-after-s", type=float, default=0.0,
                       dest="serve_stale_after_s",
                       help="/healthz reports 503 once the serving "
                            "snapshot is older than this many seconds, so "
                            "load balancers can drain a wedged job "
                            "(default: 0 = off)")
        p.add_argument("--serve-query-slo-s", type=float, default=0.25,
                       dest="serve_query_slo_s",
                       help="Query-latency SLO: a /recommend slower than "
                            "this raises QUERY_PRESSURE so the "
                            "degradation plane sheds ingest before query "
                            "latency degrades (default: 0.25; 0 = off)")
        p.add_argument("--pallas", choices=["auto", "on", "off"],
                       default="auto",
                       help="Fused Pallas score/top-K kernel (auto: on for "
                            "int16 counts on TPU, off otherwise — measured)")
        p.add_argument("--fused-window", choices=["auto", "on", "off"],
                       default="off", dest="fused_window",
                       help="One-dispatch fused window path. device: ship "
                            "baskets, run expansion + count update + LLR "
                            "+ top-K as one program per shape bucket. "
                            "sparse (single-process, deferred results): "
                            "packed-wire decode + slab update + registry "
                            "sync + rescore as one program; relocation/"
                            "promotion/spill windows route chained. "
                            "(auto: on-chip only — the CPU fallback "
                            "stays on the chained path)")
        p.add_argument("--count-dtype",
                       choices=list(tuning.get("count_dtype").choices),
                       default=tuning.default("count_dtype"),
                       dest="count_dtype",
                       help="Dense count-matrix cell dtype (int16 halves "
                            "device memory; counts then wrap like the "
                            "reference's Java shorts)")
        p.add_argument("--cell-dtype",
                       choices=list(tuning.get("cell_dtype").choices),
                       default=tuning.default("cell_dtype"),
                       dest="cell_dtype",
                       help="Sparse slab cell dtype — EXACT narrow "
                            "counts: rows promote to a wide int32 "
                            "side-table before saturation (auto: int16 "
                            "on the single-process sparse backend)")
        p.add_argument("--spill-threshold-windows", type=int,
                       default=tuning.default("spill_threshold_windows"),
                       dest="spill_threshold_windows",
                       help="Tiered elastic state (sparse backend): "
                            "spill rows untouched for this many windows "
                            "from the HBM slab to a host-side arena, "
                            "re-promoting exactly on touch (0 = off; "
                            "output and checkpoints stay bit-identical)")
        p.add_argument("--spill-target-hbm-frac", type=float,
                       default=tuning.default("spill_target_hbm_frac"),
                       dest="spill_target_hbm_frac",
                       help="Spill cold rows only while live slab cells "
                            "exceed this fraction of the allocated "
                            "device slab capacity (0.0 = spill every "
                            "eligible row; default: 0.5)")
        p.add_argument("--wire-format",
                       choices=list(tuning.get("wire_format").choices),
                       default=tuning.default("wire_format"),
                       dest="wire_format",
                       help="Sparse per-window uplink + checkpoint blob "
                            "encoding: packed = sorted delta + zigzag + "
                            "bit-pack, decoded on device, bit-identical "
                            "results (auto: packed on the single-process "
                            "sparse backend)")
        p.add_argument("--score-ladder", type=int, default=None,
                       dest="score_ladder",
                       help="Sparse-backend score-bucket ladder base "
                            "(power of two >= 2; default 4 or env "
                            "TPU_COOC_SCORE_LADDER). Coarser = fewer "
                            "dispatches, more padding")
        p.add_argument("--fixed-score",
                       choices=list(tuning.get("fixed_score").choices),
                       default=tuning.default("fixed_score"),
                       dest="fixed_score",
                       help="Sparse-backend fixed-shape scoring (constant "
                            "per-bucket rectangles; auto = on for real "
                            "TPUs when results are deferred)")
        p.add_argument("--pipeline-depth", type=int, choices=[0, 1, 2],
                       default=tuning.default("pipeline_depth"),
                       dest="pipeline_depth",
                       help="Overlap host sampling with device scoring: "
                            "sample window N+1 while the scorer runs "
                            "window N on a worker thread (0 = serial, "
                            "2 = double-buffered; output is bit-identical "
                            "at every depth)")
        p.add_argument("--checkpoint-dir", default=None, dest="checkpoint_dir")
        p.add_argument("--checkpoint-every-windows", type=int, default=0,
                       dest="checkpoint_every_windows")
        p.add_argument("--checkpoint-retain", type=int, default=3,
                       dest="checkpoint_retain",
                       help="Generation-numbered checkpoints to keep "
                            "(restore falls back to the newest one that "
                            "verifies; chain-aware: a base or delta some "
                            "retained generation chains through is never "
                            "deleted; default: 3)")
        p.add_argument("--checkpoint-incremental", action="store_true",
                       dest="checkpoint_incremental",
                       help="Dirty-row incremental checkpoint generations "
                            "(sparse backends): a full base plus per-"
                            "generation delta.<gen>.bin files holding only "
                            "rows touched since the previous generation — "
                            "commit bytes scale with churn, not vocab; "
                            "restore replays base + deltas bit-identically")
        p.add_argument("--checkpoint-compact-ratio", type=float,
                       default=tuning.default("checkpoint_compact_ratio"),
                       dest="checkpoint_compact_ratio",
                       help="Rewrite a fresh full base once the delta "
                            "chain's bytes exceed this fraction of the "
                            "base's (bounds restore replay; default: 0.5)")
        p.add_argument("--restart-on-failure", type=int, default=0,
                       dest="restart_on_failure",
                       help="Supervise the run: respawn the job up to N "
                            "times on abnormal exit, resuming from "
                            "--checkpoint-dir when set (Flink restart-"
                            "strategy analogue)")
        p.add_argument("--restart-delay-ms", type=int, default=1000,
                       dest="restart_delay_ms",
                       help="Fixed delay between restart attempts")
        p.add_argument("--restart-backoff-base-ms", type=int, default=0,
                       dest="restart_backoff_base_ms",
                       help="Enable exponential restart backoff with "
                            "decorrelated jitter, starting at this delay "
                            "(0 = fixed --restart-delay-ms)")
        p.add_argument("--restart-backoff-max-ms", type=int, default=30000,
                       dest="restart_backoff_max_ms",
                       help="Backoff delay cap (default: 30000)")
        p.add_argument("--crash-loop-threshold", type=int, default=3,
                       dest="crash_loop_threshold",
                       help="Failures within --crash-loop-window-s that "
                            "open the crash-loop breaker: step back one "
                            "checkpoint generation, then give up on a "
                            "re-trip (0 = breaker off; default: 3)")
        p.add_argument("--crash-loop-window-s", type=float, default=60.0,
                       dest="crash_loop_window_s",
                       help="Crash-loop breaker sliding window seconds "
                            "(default: 60)")
        p.add_argument("--watchdog-stale-after-s", type=float, default=0.0,
                       dest="watchdog_stale_after_s",
                       help="Supervisor hang watchdog: SIGTERM/SIGKILL a "
                            "child whose --journal has not grown for this "
                            "many seconds and count a failed attempt "
                            "(0 = off; needs --restart-on-failure and "
                            "--journal)")
        p.add_argument("--degrade", action="store_true", dest="degrade",
                       help="Enable the graceful-degradation controller: "
                            "shed load (tighter cuts, narrower top-K, "
                            "bounded admission delay) under sustained "
                            "overload instead of stalling or dying")
        p.add_argument("--degrade-window-wall-s", type=float, default=1.0,
                       dest="degrade_window_wall_s",
                       help="Per-window wall-time threshold above which a "
                            "window counts as overloaded (default: 1.0)")
        p.add_argument("--degrade-trip-windows", type=int, default=3,
                       dest="degrade_trip_windows",
                       help="Consecutive overloaded windows that escalate "
                            "one degradation level (default: 3)")
        p.add_argument("--degrade-clear-windows", type=int, default=8,
                       dest="degrade_clear_windows",
                       help="Consecutive healthy windows that de-escalate "
                            "one level (default: 8)")
        p.add_argument("--degrade-shed-factor", type=int, default=2,
                       dest="degrade_shed_factor",
                       help="Cut/top-K divisor applied per shedding level "
                            "(default: 2)")
        p.add_argument("--degrade-pause-ms", type=int, default=200,
                       dest="degrade_pause_ms",
                       help="Bounded per-admit source delay at "
                            "PAUSE_INGEST (default: 200)")
        p.add_argument("--degrade-stale-after-s", type=float, default=30.0,
                       dest="degrade_stale_after_s",
                       help="Escalate one level when no window has "
                            "completed for this long while ingest "
                            "continues (default: 30)")
        p.add_argument("--gang-workers", type=int, default=0,
                       dest="gang_workers",
                       help="Gang supervision: launch N multi-controller "
                            "workers (coordinator flags assigned per "
                            "attempt), monitor heartbeats, and gang-kill "
                            "+ gang-restart the whole set from the last "
                            "committed epoch on any failure "
                            "(--restart-on-failure = restart budget)")
        p.add_argument("--gang-heartbeat-s", type=float, default=5.0,
                       dest="gang_heartbeat_s",
                       help="Worker heartbeat-file write interval "
                            "(default: 5)")
        p.add_argument("--autoscale", choices=["off", "on"],
                       default="off",
                       help="Load-driven gang autoscaler: sustained "
                            "pressure grows the gang, sustained idle "
                            "shrinks it — workers drain a checkpoint "
                            "at a gang-voted window boundary and the "
                            "supervisor relaunches at the new size, "
                            "re-bucketing N-shard state onto M; the "
                            "degradation ladder only sheds once the "
                            "gang is at --autoscale-max-workers "
                            "(needs --gang-workers, --degrade and "
                            "--checkpoint-dir; default: off)")
        p.add_argument("--autoscale-min-workers", type=int, default=2,
                       dest="autoscale_min_workers",
                       help="Scale-down floor (default: 2 — the gang "
                            "minimum)")
        p.add_argument("--autoscale-max-workers", type=int, default=0,
                       dest="autoscale_max_workers",
                       help="Scale-up ceiling; required with "
                            "--autoscale on (the operator owns the "
                            "capacity budget)")
        p.add_argument("--autoscale-trip-windows", type=int,
                       default=tuning.default("autoscale_trip_windows"),
                       dest="autoscale_trip_windows",
                       help="Consecutive gang-overloaded windows that "
                            "trigger a scale-up (default: 3)")
        p.add_argument("--autoscale-clear-windows", type=int,
                       default=tuning.default("autoscale_clear_windows"),
                       dest="autoscale_clear_windows",
                       help="Consecutive gang-idle windows that "
                            "trigger a scale-down (asymmetric on "
                            "purpose; default: 8)")
        p.add_argument("--autoscale-cooldown-windows", type=int,
                       default=tuning.default("autoscale_cooldown_windows"),
                       dest="autoscale_cooldown_windows",
                       help="Windows ignored by the scale policy after "
                            "every rescale decision (default: 8)")
        p.add_argument("--gang-stale-after-s", type=float, default=60.0,
                       dest="gang_stale_after_s",
                       help="Heartbeat age past which a gang peer counts "
                            "as dead: the supervisor restarts the gang, "
                            "/healthz 503s 'peer_stale' (default: 60; "
                            "0 = off)")
        p.add_argument("--collective-timeout-s", type=float,
                       default=tuning.default("collective_timeout_s"),
                       dest="collective_timeout_s",
                       help="Collective-entry watchdog: a guarded "
                            "collective blocked this long exits 75 (a "
                            "gang peer is gone; the gang supervisor "
                            "restarts the whole set) instead of hanging "
                            "forever (default: 0 = off)")
        p.add_argument("--quarantine-file", default=None,
                       dest="quarantine_file",
                       help="Divert malformed input lines to this "
                            "dead-letter JSONL (path:lineno provenance + "
                            "raw line) instead of crashing the job")
        p.add_argument("--max-quarantine-rate", type=float, default=0.01,
                       dest="max_quarantine_rate",
                       help="Abort (exit 2, permanent) once more than "
                            "this fraction of input lines has been "
                            "quarantined (default: 0.01)")
        p.add_argument("--max-quarantine-bytes", type=int, default=0,
                       dest="max_quarantine_bytes",
                       help="Roll the dead-letter file over to .1/.2/... "
                            "at this size (oldest backup beyond the keep "
                            "window deleted) so a long stream cannot "
                            "grow it unboundedly (default: 0 = "
                            "unbounded)")
        p.add_argument("--scorer-breaker-threshold", type=int, default=0,
                       dest="scorer_breaker_threshold",
                       help="Scorer circuit breaker: consecutive dispatch "
                            "failures that open onto the host-oracle "
                            "fallback scorer (0 = off; single-process "
                            "device/sparse backends)")
        p.add_argument("--scorer-breaker-probe-windows", type=int,
                       default=8, dest="scorer_breaker_probe_windows",
                       help="Windows the scorer breaker stays open before "
                            "a half-open probe retries the primary "
                            "(default: 8)")
        p.add_argument("--inject-fault", action="append", default=None,
                       dest="inject_fault",
                       metavar="SITE[@PROC][:SEQ][:KIND[:ARG]]",
                       help="Fault injection (repeatable): fire KIND "
                            "(crash|exception|delay_ms|torn_write; default "
                            "crash) once at the named site, optionally at "
                            "window ordinal SEQ and only in process PROC "
                            "(multi-host chaos) — e.g. "
                            "--inject-fault checkpoint_post_write:3:"
                            "torn_write, or ckpt_commit@1:5:crash to kill "
                            "exactly worker 1 at the generation-5 commit "
                            "(sites: robustness/faults.py)")
        p.add_argument("--fault-state-dir", default=None,
                       dest="fault_state_dir",
                       help="Directory persisting fired-fault markers so "
                            "each --inject-fault spec fires once per run, "
                            "across supervised restarts")
        p.add_argument("--emit-updates", action="store_true",
                       dest="emit_updates",
                       help="Stream each window's updated top-K rows to "
                            "stdout as they materialize (instead of one "
                            "final dump)")
        p.add_argument("--development-mode", action="store_true", dest="development_mode")
        p.add_argument("--process-continuously", action="store_true",
                       dest="process_continuously")
        p.add_argument("--partition-sampling", action="store_true",
                       dest="partition_sampling",
                       help="Multi-host: partition host-side sampling "
                            "across processes by user (u %% P; reservoir "
                            "in tumbling mode, basket expansion in sliding "
                            "mode) and allgather pair deltas per window "
                            "instead of replicating all host sampling on "
                            "every process")
        p.add_argument("--coordinator", default=None,
                       help="Multi-host: host:port of process 0")
        p.add_argument("--num-processes", type=int, default=None,
                       dest="num_processes", help="Multi-host: process count")
        p.add_argument("--process-id", type=int, default=None,
                       dest="process_id", help="Multi-host: this process's id")
        p.add_argument("--run-id", default=None, dest="run_id",
                       help="Tracing: correlation id stamped on every "
                            "journal record (default: inherit "
                            "TPU_COOC_RUN_ID from a supervising parent, "
                            "else mint fresh); set explicitly to join "
                            "separately launched processes into one "
                            "cooc-trace timeline")
        raw = list(argv) if argv is not None else sys.argv[1:]
        if any(
                a == "--sample-workers" or a.startswith("--sample-workers=")
                for a in raw):
            # Fully retired (PR 8; ignored since round 3): fail with the
            # reason and the replacement, not argparse's bare
            # "unrecognized arguments".
            raise ValueError(
                "--sample-workers is retired: thread-partitioned host "
                "sampling measured ~0.9x serial (GIL-bound) and was "
                "removed; the serial native sampler always runs — use "
                "--partition-sampling for multi-process ingest scale-out")
        ns = p.parse_args(argv)
        return cls(**vars(ns))

    def __str__(self) -> str:
        return (
            f"Config{{input={self.input}, skipCuts={self.skip_cuts}, "
            f"fMax={self.item_cut}, kMax={self.user_cut}, topK={self.top_k}, "
            f"windowSize={self.window_size}, windowUnit={self.window_unit.name}, "
            f"seed=0x{self.seed:x}, bufferTimeout={self.buffer_timeout}, "
            f"backend={self.backend.value}}}"
        )


def main(argv: Optional[Sequence[str]] = None) -> None:
    """Standalone config smoke test (reference: ``Configuration.java:299-302``)."""
    print(Config.from_args(argv))


if __name__ == "__main__":
    main()
