"""Counter registry mirroring the reference's Flink accumulators.

The reference uses named Flink accumulators as its metric system and dumps
them at job end (``FlinkCooccurrences.java:181``). Counter names are kept
byte-identical so runs are comparable:

  - ``ItemInteractionCounterLateElements``       (ItemInteractionCounterTwoInputStreamOperator.java:66)
  - ``UserInteractionCounterLateElements``       (UserInteractionCounterOneInputStreamOperator.java:111)
  - ``UserInteractionCounterObservedCooccurrences`` (:112)
  - ``UserInteractionCounterFeedbackQueues``     (:109)
  - ``ItemRowRescorerRescoredItems``             (ItemRowRescorerTwoInputStreamOperator.java:60)
  - ``RowSumProcessWindowRowSum``                (RowSumAggregator.java:50)
  - ``SplitReaderNumSplits``                     (ContinuousFileMonitoringFunction.java:277)

plus development-mode-only counters (``FlinkCooccurrences.java:34`` gating).
Of the dev-mode set, ``...FeedbackElements`` and ``...ReceivedElements`` are
wired; the buffered-elements balance counters have no analogue here because
the batch engine has no cross-operator buffers to balance (their invariant —
every buffered element is eventually processed — holds structurally).
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict


class Counters:
    """A flat named-counter registry (Flink accumulator analogue).

    Increments are locked: in pipelined execution (``pipeline.py``) the
    sampling thread and the scorer worker update the same registry, and a
    Python ``dict[k] += v`` is a read-modify-write the GIL does not make
    atomic. The lock is per-window-scale traffic (a handful of adds per
    fire), not per-event — uncontended cost is noise.
    """

    def __init__(self) -> None:
        self._counters: Dict[str, int] = defaultdict(int)
        self._lock = threading.Lock()

    def add(self, name: str, delta: int = 1) -> None:
        with self._lock:
            self._counters[name] += delta

    def get(self, name: str) -> int:
        # Locked like every other accessor: the bench-summary and
        # flush-balance paths read counters the scorer worker may be
        # mid-`add`-ing in pipelined mode, and an unlocked dict read
        # interleaving with a defaultdict __missing__ insertion is
        # exactly the torn-read shape the PR-2 races taught us to ban
        # (cooclint rule `lock-discipline` now enforces the class's
        # outside view; this closes the inside one).
        with self._lock:
            return self._counters.get(name, 0)

    def as_dict(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def merge(self, other: "Counters") -> None:
        # Snapshot `other` under ITS lock first (as_dict), then fold under
        # ours — never hold both at once, so two registries merging toward
        # each other cannot deadlock, and `other` mid-add can't be seen
        # half-applied.
        snap = other.as_dict()
        with self._lock:
            for name, value in snap.items():
                self._counters[name] += value

    def snapshot_and_diff(self, prev: Dict[str, int]
                          ) -> "tuple[Dict[str, int], Dict[str, int]]":
        """One locked snapshot plus its delta against ``prev`` (a previous
        snapshot). The journal's per-window counter deltas: taking the
        snapshot and computing the diff from the same locked view means a
        concurrent ``add`` lands entirely in this window's delta or
        entirely in the next — never split or double-counted."""
        with self._lock:
            snap = dict(self._counters)
        diff = {name: value - prev.get(name, 0)
                for name, value in snap.items()
                if value != prev.get(name, 0)}
        return snap, diff

    def replace_all(self, values: Dict[str, int]) -> None:
        """Overwrite all counters (checkpoint restore)."""
        with self._lock:
            self._counters.clear()
            self._counters.update(values)

    def __repr__(self) -> str:
        with self._lock:
            inner = ", ".join(
                f"{k}={v}" for k, v in sorted(self._counters.items()))
        return f"{{{inner}}}"


# Canonical counter names (kept identical to the reference accumulators).
ITEM_LATE_ELEMENTS = "ItemInteractionCounterLateElements"
ITEM_FEEDBACK_ELEMENTS = "ItemInteractionCounterFeedbackElements"  # dev-mode
USER_LATE_ELEMENTS = "UserInteractionCounterLateElements"
OBSERVED_COOCCURRENCES = "UserInteractionCounterObservedCooccurrences"
FEEDBACK_QUEUES = "UserInteractionCounterFeedbackQueues"
USER_RECEIVED_ELEMENTS = "UserInteractionCounterReceivedElements"  # dev-mode
USER_BUFFERED_ELEMENTS = "UserInteractionCounterBufferedElements"  # dev-mode
USER_ROW_SUMS = "UserInteractionCounterRowSums"  # dev-mode
RESCORED_ITEMS = "ItemRowRescorerRescoredItems"
RESCORER_BUFFERED_ITEM_ROWS = "ItemRowRescorerBufferedItemRows"  # dev-mode
RESCORER_BUFFERED_ROW_SUM_UPDATES = "ItemRowRescorerBufferedRowSumUpdates"  # dev-mode
ROW_SUM_PROCESS_WINDOW = "RowSumProcessWindowRowSum"
SPLIT_READER_NUM_SPLITS = "SplitReaderNumSplits"

#: The reference's always-on accumulator set (the non-dev-mode names in
#: the module docstring). The /metrics exposition emits every one of
#: these even at zero, so a scraper sees a stable series set from the
#: first scrape — absent-until-first-increment would read as a broken
#: series to alerting rules.
CANONICAL_COUNTERS = (
    ITEM_LATE_ELEMENTS,
    USER_LATE_ELEMENTS,
    OBSERVED_COOCCURRENCES,
    FEEDBACK_QUEUES,
    RESCORED_ITEMS,
    ROW_SUM_PROCESS_WINDOW,
    SPLIT_READER_NUM_SPLITS,
)
