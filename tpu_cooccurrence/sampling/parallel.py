"""User-partitioned parallel reservoir sampling (host-side scale-out).

The reference scales its hot loop with keyed data parallelism: the user
operator runs P subtasks, each owning the users that hash to it
(``FlinkCooccurrences.java:70,108``). This is the host analogue: W worker
threads, worker ``w`` owning dense users with ``u % W == w``, each with an
independent :class:`UserReservoirSampler` over *part-local* compact ids
(``u // W`` — dense within the part, so per-part state arrays hold only
their share of users).

Bit-identical to the serial sampler by construction:

  * reservoir state is strictly per-user, and the stable partition mask
    preserves each user's arrival order;
  * the draw RNG hashes ``(seed, global user id, per-user draw index)``
    (``sampling/rng.py``) — order- and partition-independent — so every
    accept/replace/reject decision is the same as serial (the wrapper
    passes the global ids for hashing, part-local ids for state);
  * pair-delta blocks are concatenated in worker order; consumers fold
    them per cell (``ops/aggregate.py``), so block order is immaterial.

Threads, not processes: the ctypes C++ pair expansion releases the GIL
and per-user state stays in place — no serialization, no IPC, and
checkpoints reassemble the exact serial layout (a serial checkpoint
restores into any worker count and back).

Measured reality (this machine, benchmark config 4's 1M-event Zipfian
stream): the sampling pipeline is NOT thread-scalable today — per-window
work is dominated by small GIL-holding NumPy kernels (grouped ranks,
uniques, fancy indexing), so 4 workers run at ~0.9x serial speed. The
host-side wins that actually landed are serial: vectorized vocab mapping
(``state/vocab.py``) and int32 reservoir storage, together ~1.6x. This
module stays because it is semantically free (bit-identical, tested) and
becomes the scale-out seam the moment the GIL-holding fraction shrinks
(free-threaded CPython, or expansion-dominated workloads).
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Tuple

import numpy as np

from ..metrics import Counters
from .reservoir import PairDeltaBatch, UserReservoirSampler


def scatter_part_state(part: UserReservoirSampler, p: int, P: int,
                       n_users: int, hist, hist_len, total, draws) -> None:
    """Write one part's reservoir arrays into the serial global-dense-id
    layout (user ``u`` lives at part ``u % P``, local row ``u // P``) —
    shared by the thread- and process-partitioned samplers so their
    checkpoints stay interchangeable with the serial sampler's."""
    n_local = (n_users - p + P - 1) // P
    if n_local <= 0:
        return
    # The vocab can be ahead of the sampler (unfired buffered windows);
    # size the part up before slicing.
    part._ensure_rows(n_local - 1)
    hist[p::P, : part.hist.shape[1]] = part.hist[:n_local]
    hist_len[p::P] = part.hist_len[:n_local]
    total[p::P] = part.total[:n_local]
    draws[p::P] = part.draws[:n_local]


def restore_part_state(part: UserReservoirSampler, st: dict, p: int,
                       P: int, n_users: int) -> None:
    """Inverse of :func:`scatter_part_state` for one part."""
    n_local = (n_users - p + P - 1) // P
    if n_local <= 0:
        return
    part.restore_state(
        {k: st[k][p::P] for k in ("hist", "hist_len", "total", "draws")},
        n_local)


class PartitionedReservoirSampler:
    """W user-partitioned reservoir samplers fired concurrently."""

    def __init__(self, user_cut: int, seed: int, skip_cuts: bool,
                 workers: int, capacity: int = 1024,
                 counters: Optional[Counters] = None) -> None:
        if workers < 2:
            raise ValueError("use UserReservoirSampler for a single worker")
        self.workers = workers
        self.counters = counters if counters is not None else Counters()
        # Each part gets private counters, merged after every fire — the
        # shared registry is a plain dict and must not see racing adds.
        self.parts = [
            UserReservoirSampler(user_cut, seed, skip_cuts,
                                 capacity=max(capacity // workers, 16),
                                 counters=Counters())
            for _ in range(workers)
        ]
        self._pool = ThreadPoolExecutor(max_workers=workers,
                                        thread_name_prefix="sampler")

    def _fire_part(self, part: int, users: np.ndarray, items: np.ndarray,
                   sampled: np.ndarray) -> Tuple[PairDeltaBatch, np.ndarray]:
        local = users // self.workers
        return self.parts[part].fire(local, items, sampled, rng_users=users)

    def fire(self, users: np.ndarray, items: np.ndarray,
             sampled: np.ndarray) -> Tuple[PairDeltaBatch, np.ndarray]:
        part_of = users % self.workers
        futures = []
        for p in range(self.workers):
            mask = part_of == p  # stable: preserves per-user arrival order
            futures.append(self._pool.submit(
                self._fire_part, p, users[mask], items[mask], sampled[mask]))
        blocks: List[PairDeltaBatch] = []
        feedback: List[np.ndarray] = []
        for p, fut in enumerate(futures):
            pairs, fb = fut.result()
            blocks.append(pairs)
            feedback.append(fb)
            self.counters.merge(self.parts[p].counters)
            self.parts[p].counters.replace_all({})
        return (PairDeltaBatch.concat(blocks), np.concatenate(feedback))

    # -- checkpoint -------------------------------------------------------
    # Serial (global dense-id) layout on disk: global user u lives at part
    # u % W, local row u // W — so checkpoints are interchangeable across
    # worker counts (including the serial sampler's).

    def checkpoint_state(self, n_users: int) -> dict:
        cols = max((p.hist.shape[1] for p in self.parts), default=0)
        hist = np.zeros((n_users, cols), dtype=np.int32)
        hist_len = np.zeros(n_users, dtype=np.int64)
        total = np.zeros(n_users, dtype=np.int64)
        draws = np.zeros(n_users, dtype=np.int64)
        for p, part in enumerate(self.parts):
            scatter_part_state(part, p, self.workers, n_users,
                               hist, hist_len, total, draws)
        return {"hist": hist, "hist_len": hist_len, "total": total,
                "draws": draws}

    def restore_state(self, st: dict, n_users: int) -> None:
        for p, part in enumerate(self.parts):
            restore_part_state(part, st, p, self.workers, n_users)
