"""Process-partitioned reservoir sampling: scale ingest across hosts.

The reference scales its sampling hot loop with keyed data parallelism —
P subtasks, each owning the users that hash to it, exchanging results
through Flink's shuffle (``FlinkCooccurrences.java:70,108``). Without
this, a multi-controller run of this framework replicates ALL host-side
sampling on every process (each host consumes the whole stream), so
host-bound workloads gain nothing from more hosts.

``--partition-sampling`` restores the reference's scaling model at the
process level: process ``p`` of ``P`` runs the user reservoir only for
users with ``u % P == p`` (1/P of the expansion work), then the emitted
pair-delta blocks, rejection feedback, and counter deltas are packed into
ONE vector and exchanged per window (a lengths gather + a payload gather
— two collective rounds) — the TPU-native shuffle, riding the same
gloo/DCN fabric as the collectives. Item cuts stay
replicated (they are global per-item ranks over the window, vectorized
and cheap; partitioning them would change semantics).

Bit-identical to serial by the same argument as the thread-partitioned
sampler (``sampling/parallel.py``): reservoir state is strictly per-user,
the partition mask preserves each user's arrival order, and the draw RNG
hashes ``(seed, global user id, per-user draw index)`` — partition- and
order-independent. Block concatenation in process order is deterministic,
and every consumer folds blocks per cell, so inter-block order is
immaterial to scores.

Checkpoints: each process snapshots only its own users' reservoir state
(the others are zeros in the fixed global layout) plus a
``sampler_part = [process_index, process_count]`` marker; restore
validates the layout matches and the generic restore path refuses to
feed a partitioned snapshot to a non-partitioned sampler.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..metrics import Counters
from .reservoir import PairDeltaBatch, UserReservoirSampler

# Fixed exchange order for counter deltas (names resolved lazily to avoid
# hard-coding the metric strings here).
_XCHG_COUNTERS = None


def _counter_names() -> List[str]:
    global _XCHG_COUNTERS
    if _XCHG_COUNTERS is None:
        from .. import metrics

        _XCHG_COUNTERS = sorted(
            v for k, v in vars(metrics).items()
            if k.isupper() and isinstance(v, str))
    return _XCHG_COUNTERS


def _allgather_ragged(vec: np.ndarray) -> List[np.ndarray]:
    """Gather a per-process int64 vector from every process.

    ``process_allgather`` needs equal shapes, so lengths go first and the
    payload is padded to the global max — two collective rounds total,
    which is why callers pack everything they exchange into ONE vector.
    """
    from jax.experimental import multihost_utils

    lens = multihost_utils.process_allgather(
        np.asarray([len(vec)], dtype=np.int64))  # [P, 1]
    m = max(int(lens.max()), 1)
    padded = np.zeros(m, vec.dtype)
    padded[: len(vec)] = vec
    gathered = multihost_utils.process_allgather(padded)  # [P, m]
    return [gathered[p][: int(lens[p, 0])]
            for p in range(gathered.shape[0])]


class ProcessPartitionedSampler:
    """User-partitioned reservoir across multi-controller processes."""

    process_partition = True  # checkpoint-format marker (see module doc)

    def __init__(self, user_cut: int, seed: int, skip_cuts: bool,
                 capacity: int = 1024,
                 counters: Optional[Counters] = None) -> None:
        import jax

        self.pid = jax.process_index()
        self.nproc = jax.process_count()
        self.counters = counters if counters is not None else Counters()
        # Local part over part-local compact ids (u // P), like the
        # thread-partitioned sampler; private counters, exchanged+merged
        # after every fire so every process sees the global totals.
        self.part = UserReservoirSampler(
            user_cut, seed, skip_cuts,
            capacity=max(capacity // self.nproc, 16), counters=Counters())

    def fire(self, users: np.ndarray, items: np.ndarray,
             sampled: np.ndarray) -> Tuple[PairDeltaBatch, np.ndarray]:
        mine = (users % self.nproc) == self.pid
        pairs, feedback = self.part.fire(
            users[mine] // self.nproc, items[mine], sampled[mine],
            rng_users=users[mine])
        if self.nproc == 1:
            self.counters.merge(self.part.counters)
            self.part.counters.replace_all({})
            return pairs, feedback

        # ONE exchange payload (2 collective rounds: lengths, then data):
        # header [n_pairs, n_fb] | counter deltas [C] | src | dst | delta
        # | feedback.
        names = _counter_names()
        n, nf = len(pairs), len(feedback)
        vec = np.concatenate([
            np.asarray([n, nf], dtype=np.int64),
            np.asarray([self.part.counters.get(x) for x in names],
                       dtype=np.int64),
            pairs.src, pairs.dst, pairs.delta.astype(np.int64),
            feedback.astype(np.int64),
        ])
        self.part.counters.replace_all({})

        blocks, fb_l = [], []
        totals = np.zeros(len(names), dtype=np.int64)
        for v in _allgather_ragged(vec):
            pn, pf = int(v[0]), int(v[1])
            body = v[2 + len(names):]
            totals += v[2: 2 + len(names)]
            blocks.append(PairDeltaBatch(
                body[:pn], body[pn: 2 * pn],
                body[2 * pn: 3 * pn].astype(np.int32)))
            fb_l.append(body[3 * pn: 3 * pn + pf])
        for name, value in zip(names, totals.tolist()):
            if value:
                self.counters.add(name, value)
        return PairDeltaBatch.concat(blocks), np.concatenate(fb_l)

    # -- checkpoint (fixed global layout; local rows only) ----------------

    def checkpoint_state(self, n_users: int) -> dict:
        from .parallel import scatter_part_state

        hist = np.zeros((n_users, self.part.hist.shape[1]), dtype=np.int32)
        hist_len = np.zeros(n_users, dtype=np.int64)
        total = np.zeros(n_users, dtype=np.int64)
        draws = np.zeros(n_users, dtype=np.int64)
        scatter_part_state(self.part, self.pid, self.nproc, n_users,
                           hist, hist_len, total, draws)
        return {"hist": hist, "hist_len": hist_len, "total": total,
                "draws": draws,
                "sampler_part": np.asarray([self.pid, self.nproc],
                                           dtype=np.int64)}

    def restore_state(self, st: dict, n_users: int) -> None:
        from .parallel import restore_part_state

        part_info = st.get("sampler_part")
        if part_info is not None:
            pid, nproc = int(part_info[0]), int(part_info[1])
            if (pid, nproc) != (self.pid, self.nproc):
                raise ValueError(
                    f"sampler checkpoint is partition {pid}/{nproc} but "
                    f"this process is {self.pid}/{self.nproc} — restore "
                    f"under the writing run's layout")
        restore_part_state(self.part, st, self.pid, self.nproc, n_users)
