"""Process-partitioned reservoir sampling: scale ingest across hosts.

The reference scales its sampling hot loop with keyed data parallelism —
P subtasks, each owning the users that hash to it, exchanging results
through Flink's shuffle (``FlinkCooccurrences.java:70,108``). Without
this, a multi-controller run of this framework replicates ALL host-side
sampling on every process (each host consumes the whole stream), so
host-bound workloads gain nothing from more hosts.

``--partition-sampling`` restores the reference's scaling model at the
process level: process ``p`` of ``P`` runs the user reservoir only for
users with ``u % P == p`` (1/P of the expansion work), then the emitted
pair-delta blocks, rejection feedback, and counter deltas are packed into
ONE vector and exchanged per window (a lengths gather + a payload gather
— two collective rounds) — the TPU-native shuffle, riding the same
gloo/DCN fabric as the collectives. Item cuts stay
replicated (they are global per-item ranks over the window, vectorized
and cheap; partitioning them would change semantics).

Bit-identical to serial: reservoir state is strictly per-user, the
partition mask preserves each user's arrival order, and the draw RNG
hashes ``(seed, global user id, per-user draw index)`` — partition- and
order-independent. Block concatenation in process order is deterministic,
and every consumer folds blocks per cell, so inter-block order is
immaterial to scores. (A thread-partitioned variant of the same scheme,
``sampling/parallel.py``, was removed in round 3: measured ~0.9x serial
on this image — the per-window work is dominated by small GIL-holding
NumPy kernels, and the native serial kernels had already taken the wins.)

Checkpoints: each process snapshots only its own users' reservoir state
(the others are zeros in the fixed global layout) plus a
``sampler_part = [process_index, process_count]`` marker; restore
validates the layout matches and the generic restore path refuses to
feed a partitioned snapshot to a non-partitioned sampler.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..metrics import Counters
from .reservoir import PairDeltaBatch, UserReservoirSampler


def scatter_part_state(part: UserReservoirSampler, p: int, P: int,
                       n_users: int, hist, hist_len, total, draws) -> None:
    """Write one part's reservoir arrays into the serial global-dense-id
    layout (user ``u`` lives at part ``u % P``, local row ``u // P``), so
    partitioned checkpoints stay interchangeable with the serial
    sampler's."""
    n_local = (n_users - p + P - 1) // P
    if n_local <= 0:
        return
    # The vocab can be ahead of the sampler (unfired buffered windows);
    # size the part up before slicing.
    part._ensure_rows(n_local - 1)
    # clean_hist: zero the unspecified cells (np.empty growth) so merged
    # checkpoints stay deterministic, like the serial sampler's.
    hist[p::P, : part.hist.shape[1]] = part.clean_hist(n_local)
    hist_len[p::P] = part.hist_len[:n_local]
    total[p::P] = part.total[:n_local]
    draws[p::P] = part.draws[:n_local]


def restore_part_state(part: UserReservoirSampler, st: dict, p: int,
                       P: int, n_users: int) -> None:
    """Inverse of :func:`scatter_part_state` for one part."""
    n_local = (n_users - p + P - 1) // P
    if n_local <= 0:
        return
    part.restore_state(
        {k: st[k][p::P] for k in ("hist", "hist_len", "total", "draws")},
        n_local)

# Fixed exchange order for counter deltas (names resolved lazily to avoid
# hard-coding the metric strings here).
_XCHG_COUNTERS = None


def _counter_names() -> List[str]:
    global _XCHG_COUNTERS
    if _XCHG_COUNTERS is None:
        from .. import metrics

        _XCHG_COUNTERS = sorted(
            v for k, v in vars(metrics).items()
            if k.isupper() and isinstance(v, str))
    return _XCHG_COUNTERS


def _allgather_ragged(vec: np.ndarray) -> List[np.ndarray]:
    """Gather a per-process int64 vector from every process.

    The allgather needs equal shapes, so lengths go first and the
    payload is padded to the global max — two collective rounds total,
    which is why callers pack everything they exchange into ONE vector.
    Rides :func:`~tpu_cooccurrence.parallel.distributed
    .guarded_allgather` so a dead peer trips the collective-entry
    watchdog (supervised exit) instead of wedging the sampler forever.
    """
    from ..parallel.distributed import guarded_allgather

    lens = guarded_allgather(
        np.asarray([len(vec)], dtype=np.int64))  # [P, 1]
    m = max(int(lens.max()), 1)
    padded = np.zeros(m, vec.dtype)
    padded[: len(vec)] = vec
    gathered = guarded_allgather(padded)  # [P, m]
    return [gathered[p][: int(lens[p, 0])]
            for p in range(gathered.shape[0])]


def _exchange(counters: Counters, part_counters: Counters,
              sections: List[np.ndarray]) -> List[List[np.ndarray]]:
    """Pack ``sections`` + this process's counter deltas into one vector,
    allgather it (2 collective rounds), merge the counter totals into
    ``counters``, and return each process's unpacked sections.

    The single wire format keeps every partitioned sampler's exchange
    protocol identical: header = section lengths, then counter deltas,
    then the section payloads, all int64.
    """
    names = _counter_names()
    k = len(sections)
    vec = np.concatenate(
        [np.asarray([len(sec) for sec in sections], dtype=np.int64),
         np.asarray([part_counters.get(x) for x in names], dtype=np.int64)]
        + [sec.astype(np.int64, copy=False) for sec in sections])
    part_counters.replace_all({})

    per_process: List[List[np.ndarray]] = []
    totals = np.zeros(len(names), dtype=np.int64)
    for v in _allgather_ragged(vec):
        lens = v[:k]
        totals += v[k: k + len(names)]
        body = v[k + len(names):]
        out, lo = [], 0
        for ln in lens.tolist():
            out.append(body[lo: lo + ln])
            lo += ln
        per_process.append(out)
    for name, value in zip(names, totals.tolist()):
        if value:
            counters.add(name, value)
    return per_process


class ProcessPartitionedSampler:
    """User-partitioned reservoir across multi-controller processes."""

    process_partition = True  # checkpoint-format marker (see module doc)

    def __init__(self, user_cut: int, seed: int, skip_cuts: bool,
                 capacity: int = 1024,
                 counters: Optional[Counters] = None) -> None:
        import jax

        self.pid = jax.process_index()
        self.nproc = jax.process_count()
        self.counters = counters if counters is not None else Counters()
        # Local part over part-local compact ids (u // P), like the
        # thread-partitioned sampler; private counters, exchanged+merged
        # after every fire so every process sees the global totals.
        self.part = UserReservoirSampler(
            user_cut, seed, skip_cuts,
            capacity=max(capacity // self.nproc, 16), counters=Counters())

    def fire(self, users: np.ndarray, items: np.ndarray,
             sampled: np.ndarray) -> Tuple[PairDeltaBatch, np.ndarray]:
        mine = (users % self.nproc) == self.pid
        pairs, feedback = self.part.fire(
            users[mine] // self.nproc, items[mine], sampled[mine],
            rng_users=users[mine])
        if self.nproc == 1:
            self.counters.merge(self.part.counters)
            self.part.counters.replace_all({})
            return pairs, feedback

        per_process = _exchange(
            self.counters, self.part.counters,
            [pairs.src, pairs.dst, pairs.delta, feedback])
        blocks = [PairDeltaBatch(src, dst, delta.astype(np.int32))
                  for src, dst, delta, _ in per_process]
        fb = np.concatenate([sec[3] for sec in per_process])
        return PairDeltaBatch.concat(blocks), fb

    # -- checkpoint (fixed global layout; local rows only) ----------------

    def checkpoint_state(self, n_users: int) -> dict:
        hist = np.zeros((n_users, self.part.hist.shape[1]), dtype=np.int32)
        hist_len = np.zeros(n_users, dtype=np.int64)
        total = np.zeros(n_users, dtype=np.int64)
        draws = np.zeros(n_users, dtype=np.int64)
        scatter_part_state(self.part, self.pid, self.nproc, n_users,
                           hist, hist_len, total, draws)
        return {"hist": hist, "hist_len": hist_len, "total": total,
                "draws": draws,
                "sampler_part": np.asarray([self.pid, self.nproc],
                                           dtype=np.int64)}

    def restore_state(self, st: dict, n_users: int) -> None:
        part_info = st.get("sampler_part")
        if part_info is not None:
            pid, nproc = int(part_info[0]), int(part_info[1])
            if (pid, nproc) != (self.pid, self.nproc):
                raise ValueError(
                    f"sampler checkpoint is partition {pid}/{nproc} but "
                    f"this process is {self.pid}/{self.nproc} — restore "
                    f"under the writing run's layout")
        restore_part_state(self.part, st, self.pid, self.nproc, n_users)


class ProcessPartitionedSlidingSampler:
    """Sliding-mode ingest scaling: per-window basket expansion split by
    user across processes.

    The sliding sampler is stateless, so partitioning is simpler than the
    reservoir's: the per-window cuts stay replicated (the ITEM cut is a
    rank over ALL of the window's arrivals — partitioning it by user
    would change semantics — and both cuts are O(n) counting passes),
    then each process expands only its users' baskets (the O(pairs) hot
    part) with cuts disabled, and the blocks + counter deltas ride the
    same packed allgather as the reservoir path.
    """

    process_partition = True  # stateless: nothing to checkpoint, but the
    # marker keeps restore-path expectations uniform

    def __init__(self, item_cut: int, user_cut: int, skip_cuts: bool,
                 counters: Optional[Counters] = None) -> None:
        import jax

        from .sliding import SlidingBasketSampler

        self.pid = jax.process_index()
        self.nproc = jax.process_count()
        self.item_cut = item_cut
        self.user_cut = user_cut
        self.skip_cuts = skip_cuts
        self.counters = counters if counters is not None else Counters()
        # Cuts are applied here (replicated) — the expander never cuts.
        self.expand = SlidingBasketSampler(item_cut, user_cut,
                                           skip_cuts=True,
                                           counters=Counters())
        from ..native import SlidingScratch

        self._cut_scratch = SlidingScratch()

    def _cut(self, users: np.ndarray, items: np.ndarray):
        """Replicated grouped-rank cuts: one native O(n) counting pass
        when the library is available, argsort grouped_rank otherwise."""
        from ..native import sliding_cut_mask

        keep = sliding_cut_mask(users, items, self.item_cut,
                                self.user_cut, self._cut_scratch)
        if keep is None:
            from .item_cut import grouped_rank

            keep = ((grouped_rank(items) < self.item_cut)
                    & (grouped_rank(users) < self.user_cut))
        return users[keep], items[keep]

    def fire(self, users: np.ndarray, items: np.ndarray) -> PairDeltaBatch:
        if len(users) and not self.skip_cuts:
            users, items = self._cut(users, items)
        mine = (users % self.nproc) == self.pid
        pairs = self.expand.fire(users[mine], items[mine])
        if self.nproc == 1:
            self.counters.merge(self.expand.counters)
            self.expand.counters.replace_all({})
            return pairs

        # Sliding deltas are always +1 — ship only (src, dst) and rebuild
        # the ones vector locally (a third of the exchange payload saved).
        per_process = _exchange(self.counters, self.expand.counters,
                                [pairs.src, pairs.dst])
        return PairDeltaBatch.concat(
            [PairDeltaBatch(src, dst, np.ones(len(src), dtype=np.int32))
             for src, dst in per_process])
