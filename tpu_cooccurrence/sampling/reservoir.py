"""Vectorized per-user reservoir sampling with eviction deltas.

Replaces the reference's keyed user-counter operator — the algorithmic core
(``UserInteractionCounterOneInputStreamOperator.java:145-257``) — with a
batch formulation that emits NumPy COO pair-delta blocks per window instead
of record-at-a-time tuples.

Key vectorization facts (proved against the reference semantics; tested
directly in ``tests/test_sampler_equivalence.py`` and end-to-end in
``tests/test_pipeline.py``):

  1. Within a window, a user's reservoir length never decreases, so *all
     appends precede all draws*: the first ``kMax - len_before`` sampled
     interactions append, the rest draw. Append targets are distinct slots,
     so all appends can be written first and each append's pair partners are
     then exactly ``history[:slot]`` of the post-write array.
  2. The reservoir denominator counts *every* interaction (sampled or not):
     ``total_at_event = total_before + rank_within_window + 1``
     (reference :158 increments before the ``sample`` check).
  3. Row-sum deltas are exactly the per-source segment-sum of pair deltas
     (append: ``(item, size)`` + ``(other, +1)`` each, :183-192; replace:
     ``+/-(kMax-1)`` with partner sums cancelling, :218-236), so they are
     not emitted separately — the scorer derives them.
  4. ``observedCooccurrences`` counts only append-path emissions
     (``2 * size``, :195); the replace path does not touch it.

Draws use the order-independent ``(seed, user, draw_index)`` hash RNG
(``sampling/rng.py``); the draw index is a per-user monotone counter.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

from ..metrics import Counters, OBSERVED_COOCCURRENCES
from .item_cut import grouped_rank
from .rng import reservoir_draw


@dataclasses.dataclass
class PairDeltaBatch:
    """COO pair deltas for one window: ``C[src, dst] += delta``."""

    src: np.ndarray  # int64
    dst: np.ndarray  # int64
    delta: np.ndarray  # int32

    @staticmethod
    def concat(batches: List["PairDeltaBatch"]) -> "PairDeltaBatch":
        if not batches:
            z = np.zeros(0, dtype=np.int64)
            return PairDeltaBatch(z, z, np.zeros(0, dtype=np.int32))
        return PairDeltaBatch(
            np.concatenate([b.src for b in batches]),
            np.concatenate([b.dst for b in batches]),
            np.concatenate([b.delta for b in batches]),
        )

    def __len__(self) -> int:
        return len(self.src)


def _ragged_arange(sizes: np.ndarray) -> np.ndarray:
    """``[0..s0), [0..s1), ...`` concatenated."""
    total = int(sizes.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64)
    ends = np.cumsum(sizes)
    starts = ends - sizes
    return np.arange(total, dtype=np.int64) - np.repeat(starts, sizes)


class UserReservoirSampler:
    """Reservoir state over dense user ids, with 2D history storage.

    In sampled mode histories are bounded by ``kMax`` → a flat
    ``[capacity, kMax]`` int64 array. In skip-cuts mode histories are
    unbounded → the column dimension grows by doubling.
    """

    def __init__(self, user_cut: int, seed: int, skip_cuts: bool,
                 capacity: int = 1024, counters: Optional[Counters] = None) -> None:
        self.user_cut = user_cut
        self.seed = seed
        self.skip_cuts = skip_cuts
        self.counters = counters if counters is not None else Counters()
        init_cols = 8 if skip_cuts else user_cut
        # int32 storage: histories hold dense item ids (< 2^31 by the
        # job's vocab mapping); at 100k+ users x kMax columns the growth
        # memcpys and cache footprint are the sampler's dominant cost.
        self.hist = np.zeros((capacity, init_cols), dtype=np.int32)
        self.hist_len = np.zeros(capacity, dtype=np.int64)
        self.total = np.zeros(capacity, dtype=np.int64)
        self.draws = np.zeros(capacity, dtype=np.int64)

    # -- storage growth --------------------------------------------------

    def _ensure_rows(self, max_user: int) -> None:
        # ``hist`` grows with np.empty, NOT np.zeros: zeroing the grown
        # region is a 100+ MB memset at benchmark user counts (measured
        # 0.19 s of a 0.44 s host window pass — the single biggest host
        # cost), and cells at column >= hist_len[u] are never read (the
        # append path writes slot then reads [0, slot); the draw path
        # reads [0, kMax) of full reservoirs). Contract: hist content
        # beyond each row's hist_len is UNSPECIFIED. The count vectors
        # stay zero-initialized — their zeros are semantic.
        if max_user >= self.hist.shape[0]:
            # Pow-2 target, not max_user+1: with uniform user ids the
            # first window's max lands a hair under the true user count,
            # and an exact-fit growth forces a second full-array copy one
            # window later (measured: 200 MB of memcpy on config 4).
            new_rows = max(2 * self.hist.shape[0],
                           1 << int(max_user + 1).bit_length())
            for name in ("hist_len", "total", "draws"):
                old = getattr(self, name)
                grown = np.zeros(new_rows, dtype=old.dtype)
                grown[: len(old)] = old
                setattr(self, name, grown)
            grown = np.empty((new_rows, self.hist.shape[1]),
                             dtype=self.hist.dtype)
            grown[: self.hist.shape[0]] = self.hist
            self.hist = grown

    def _ensure_cols(self, max_len: int) -> None:
        if max_len > self.hist.shape[1]:
            new_cols = max(2 * self.hist.shape[1], max_len)
            grown = np.empty((self.hist.shape[0], new_cols),
                             dtype=self.hist.dtype)
            grown[:, : self.hist.shape[1]] = self.hist
            self.hist = grown

    # -- the window fire -------------------------------------------------

    def fire(
        self,
        users: np.ndarray,
        items: np.ndarray,
        sampled: np.ndarray,
        rng_users: Optional[np.ndarray] = None,
    ) -> Tuple[PairDeltaBatch, np.ndarray]:
        """Process one window's tagged interactions (arrival order).

        Returns ``(pair_deltas, feedback_items)`` where ``feedback_items``
        are the rejected interactions' items (each implies a ``-1`` item-cut
        decrement, reference :246-248).

        ``rng_users`` (default: ``users``) supplies the ids hashed by the
        draw RNG. The partitioned sampler indexes state by *part-local*
        compact ids but must draw with the *global* dense ids so its
        decisions are bit-identical to the serial sampler's.
        """
        if rng_users is None:
            rng_users = users
        if len(users) == 0:
            return PairDeltaBatch.concat([]), np.zeros(0, dtype=np.int64)
        self._ensure_rows(int(users.max()))

        # Reservoir denominators (fact 2): per-event totals.
        rank_all = grouped_rank(users)
        total_at_event = self.total[users] + rank_all + 1
        np.add.at(self.total, users, 1)

        if not np.any(sampled):
            return PairDeltaBatch.concat([]), np.zeros(0, dtype=np.int64)

        s_users = users[sampled]
        s_items = items[sampled]
        s_rng = rng_users[sampled]
        s_total = total_at_event[sampled]
        s_rank = grouped_rank(s_users)  # rank among *sampled* events per user

        len_before = self.hist_len[s_users]
        if self.skip_cuts:
            is_append = np.ones(len(s_users), dtype=bool)
        else:
            is_append = (len_before + s_rank) < self.user_cut

        blocks: List[PairDeltaBatch] = []

        # ---- Append path (vectorized; fact 1) ----
        a_users = s_users[is_append]
        a_items = s_items[is_append]
        a_slot = (len_before + s_rank)[is_append]  # the slot each append writes
        if len(a_users):
            self._ensure_cols(int(a_slot.max()) + 1)
            # Write all appends first; partners of event e are hist[u, :slot_e],
            # which equals the state at e's processing time (earlier appends of
            # the same user occupy earlier slots; other users don't interfere).
            self.hist[a_users, a_slot] = a_items
            # Unbuffered scatter-add: exact with duplicate users, and
            # ~6x cheaper than the np.unique sort it replaces.
            np.add.at(self.hist_len, a_users, 1)

            sizes = a_slot  # number of partners per append event
            total_partners = int(sizes.sum())
            if total_partners > 0:
                # Hot path: native C++ expansion; fallback: vectorized numpy.
                from .. import native

                expanded = native.expand_appends(
                    self.hist, a_users, a_items, a_slot)
                if expanded is not None:
                    blocks.append(PairDeltaBatch(*expanded))
                else:
                    col = _ragged_arange(sizes)
                    row_u = np.repeat(a_users, sizes)
                    partners = self.hist[row_u, col].astype(np.int64)
                    new_rep = np.repeat(a_items, sizes)
                    ones = np.ones(len(partners), dtype=np.int32)
                    # Both directions (reference :180-193).
                    blocks.append(PairDeltaBatch(new_rep, partners, ones))
                    blocks.append(PairDeltaBatch(partners, new_rep, ones))
                self.counters.add(OBSERVED_COOCCURRENCES, 2 * total_partners)

        # ---- Draw path ----
        d_mask = ~is_append
        if np.any(d_mask):
            d_users = s_users[d_mask]
            d_items = s_items[d_mask]
            d_total = s_total[d_mask]
            # Per-user draw indices: draws_before + rank among draw events.
            d_rank = grouped_rank(d_users)
            d_idx = self.draws[d_users] + d_rank
            np.add.at(self.draws, d_users, 1)
            k = reservoir_draw(self.seed, s_rng[d_mask], d_idx, d_total)
            replace = k < self.user_cut
            feedback_items = d_items[~replace]

            # Replacements mutate slots sequentially (same slot can be hit
            # twice in one window). Hot path: native C++ expansion
            # (native/reservoir_expand.cpp); fallback: per-event loop with
            # O(kMax) numpy ops each.
            kc = self.user_cut
            r_users = d_users[replace]
            r_items = d_items[replace]
            r_slots = k[replace]
            if len(r_users) and self.hist.shape[1] == kc:
                from .. import native

                expanded = native.expand_replacements(
                    self.hist, r_users, r_items, r_slots)
                if expanded is not None:
                    src, dst, delta = expanded
                    blocks.append(PairDeltaBatch(src, dst, delta))
                    return PairDeltaBatch.concat(blocks), feedback_items
            for u, item, slot in zip(r_users.tolist(), r_items.tolist(), r_slots.tolist()):
                hist_row = self.hist[u, :kc]
                previous = int(hist_row[slot])
                # kMax-1 partners (skip slot)
                others = np.delete(hist_row, slot).astype(np.int64)
                new_rep = np.full(kc - 1, item, dtype=np.int64)
                prev_rep = np.full(kc - 1, previous, dtype=np.int64)
                plus = np.ones(kc - 1, dtype=np.int32)
                minus = -plus
                # (item -> others, +1), (previous -> others, -1),
                # (others -> item, +1), (others -> previous, -1)
                # (reference :215-243).
                blocks.append(PairDeltaBatch(new_rep, others, plus))
                blocks.append(PairDeltaBatch(prev_rep, others.copy(), minus))
                blocks.append(PairDeltaBatch(others.copy(), new_rep, plus))
                blocks.append(PairDeltaBatch(others.copy(), prev_rep, minus))
                self.hist[u, slot] = item
        else:
            feedback_items = np.zeros(0, dtype=np.int64)

        return PairDeltaBatch.concat(blocks), feedback_items

    # -- checkpoint -------------------------------------------------------

    def clean_hist(self, n_users: int) -> np.ndarray:
        """``hist[:n_users]`` with the unspecified cells beyond each
        row's ``hist_len`` zeroed — the deterministic persistence view.
        Growth allocates with np.empty (see ``_ensure_rows``), so the raw
        array may hold stale heap bytes that must not reach disk: a
        checkpoint has to be byte-reproducible (and compressible)."""
        h = self.hist[:n_users].copy()
        cols = np.arange(h.shape[1], dtype=np.int64)[None, :]
        h[cols >= self.hist_len[:n_users, None]] = 0
        return h

    def checkpoint_state(self, n_users: int) -> dict:
        """Reservoir state for the first ``n_users`` dense users.

        The vocab can be ahead of the sampler (users whose events are
        still buffered in unfired windows, or late-dropped) — size the
        state arrays up before slicing, or the slice comes up short."""
        self._ensure_rows(max(n_users - 1, 0))
        return {
            "hist": self.clean_hist(n_users),
            "hist_len": self.hist_len[:n_users],
            "total": self.total[:n_users],
            "draws": self.draws[:n_users],
        }

    def restore_state(self, st: dict, n_users: int) -> None:
        self._ensure_rows(max(n_users - 1, 0))
        self._ensure_cols(st["hist"].shape[1])
        self.hist[:n_users, : st["hist"].shape[1]] = st["hist"]
        self.hist_len[:n_users] = st["hist_len"]
        self.total[:n_users] = st["total"]
        self.draws[:n_users] = st["draws"]
