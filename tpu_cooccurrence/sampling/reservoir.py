"""Vectorized per-user reservoir sampling with eviction deltas.

Replaces the reference's keyed user-counter operator — the algorithmic core
(``UserInteractionCounterOneInputStreamOperator.java:145-257``) — with a
batch formulation that emits NumPy COO pair-delta blocks per window instead
of record-at-a-time tuples.

Key vectorization facts (proved against the reference semantics; tested
directly in ``tests/test_sampler_equivalence.py`` and end-to-end in
``tests/test_pipeline.py``):

  1. Within a window, a user's reservoir length never decreases, so *all
     appends precede all draws*: the first ``kMax - len_before`` sampled
     interactions append, the rest draw. Append targets are distinct slots,
     so all appends can be written first and each append's pair partners are
     then exactly ``history[:slot]`` of the post-write array.
  2. The reservoir denominator counts *every* interaction (sampled or not):
     ``total_at_event = total_before + rank_within_window + 1``
     (reference :158 increments before the ``sample`` check).
  3. Row-sum deltas are exactly the per-source segment-sum of pair deltas
     (append: ``(item, size)`` + ``(other, +1)`` each, :183-192; replace:
     ``+/-(kMax-1)`` with partner sums cancelling, :218-236), so they are
     not emitted separately — the scorer derives them.
  4. ``observedCooccurrences`` counts only append-path emissions
     (``2 * size``, :195); the replace path does not touch it.

Draws use the order-independent ``(seed, user, draw_index)`` hash RNG
(``sampling/rng.py``); the draw index is a per-user monotone counter.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

from ..metrics import Counters, OBSERVED_COOCCURRENCES
from .item_cut import grouped_rank
from .rng import reservoir_draw


@dataclasses.dataclass
class PairDeltaBatch:
    """COO pair deltas for one window: ``C[src, dst] += delta``."""

    src: np.ndarray  # int64
    dst: np.ndarray  # int64
    delta: np.ndarray  # int32

    @staticmethod
    def concat(batches: List["PairDeltaBatch"]) -> "PairDeltaBatch":
        if not batches:
            z = np.zeros(0, dtype=np.int64)
            return PairDeltaBatch(z, z, np.zeros(0, dtype=np.int32))
        return PairDeltaBatch(
            np.concatenate([b.src for b in batches]),
            np.concatenate([b.dst for b in batches]),
            np.concatenate([b.delta for b in batches]),
        )

    def __len__(self) -> int:
        return len(self.src)


@dataclasses.dataclass
class BasketBatch:
    """One window's pair deltas in un-expanded *star-op* form.

    The fused-window uplink format (``--fused-window``,
    ``ops/device_scorer``): each row is one expansion op — a new/star
    item against a basket of partner items — and the device performs
    the expansion into COO deltas on chip
    (``ops/pallas_score.pallas_expand_baskets``). One append event is
    one op (basket = the user's history prefix, ``skip = -1``); one
    replacement is two ops over the same pre-write reservoir row
    (``(+1, new item)`` and ``(-1, previous item)``, both with
    ``skip = slot``). The logical pair stream is identical to the
    expanded :class:`PairDeltaBatch` — ``len(self)`` counts logical
    pairs, and :meth:`to_pairs` materializes them host-side for
    consumers that need COO (the chained-path fallback, the scorer
    circuit breaker's host-oracle fallback).

    ``baskets`` cells at ``j >= lens[i]`` are UNSPECIFIED (they come
    straight from the reservoir storage, which grows with ``np.empty``)
    and must be masked by every consumer.
    """

    new_items: np.ndarray  # [N] int32 star item per op
    baskets: np.ndarray    # [N, W] int32 partner rows
    lens: np.ndarray       # [N] int32 valid cells per row
    skips: np.ndarray      # [N] int32 excluded column (-1 = none)
    signs: np.ndarray      # [N] int32 delta sign (+1 / -1)

    @property
    def n_ops(self) -> int:
        return len(self.new_items)

    def _valid(self) -> np.ndarray:
        # Cached: len(), the scorer's routing prep, and the host
        # expansion all need the same mask (instances are per-window,
        # built once and consumed once).
        if not hasattr(self, "_valid_mask"):
            w = self.baskets.shape[1] if self.baskets.ndim == 2 else 0
            j = np.arange(w, dtype=np.int64)[None, :]
            self._valid_mask = ((j < self.lens[:, None])
                                & (j != self.skips[:, None]))
        return self._valid_mask

    def pairs_per_op(self) -> np.ndarray:
        """Directed pairs each op emits per direction (= valid cells)."""
        if not hasattr(self, "_per_op"):
            self._per_op = self._valid().sum(axis=1)
        return self._per_op

    def __len__(self) -> int:
        # Logical expanded pair count — identical to the equivalent
        # PairDeltaBatch's len (both directions), so journal/stat
        # fields agree between the fused and chained configurations.
        return int(2 * self.pairs_per_op().sum())

    def to_pairs(self) -> "PairDeltaBatch":
        """Host-side expansion to COO (the chained-path equivalent).

        Cell-for-cell the same multiset of (src, dst, delta) entries
        the sampler's expanded path emits (entry order differs; every
        consumer folds or segment-sums, so order is immaterial).
        """
        valid = self._valid()
        per_op = valid.sum(axis=1)
        partners = self.baskets[valid].astype(np.int64)
        news = np.repeat(self.new_items.astype(np.int64), per_op)
        deltas = np.repeat(self.signs.astype(np.int32), per_op)
        return PairDeltaBatch(
            np.concatenate([news, partners]),
            np.concatenate([partners, news]),
            np.concatenate([deltas, deltas]),
        )

    # Duck-typing for PairDeltaBatch consumers (the breaker's
    # host-oracle fallback reads .src/.dst/.delta directly): expand
    # lazily, once.
    def _expanded(self) -> "PairDeltaBatch":
        if not hasattr(self, "_pairs"):
            self._pairs = self.to_pairs()
        return self._pairs

    @property
    def src(self) -> np.ndarray:
        return self._expanded().src

    @property
    def dst(self) -> np.ndarray:
        return self._expanded().dst

    @property
    def delta(self) -> np.ndarray:
        return self._expanded().delta

    @staticmethod
    def empty() -> "BasketBatch":
        z = np.zeros(0, dtype=np.int32)
        return BasketBatch(z, np.zeros((0, 0), dtype=np.int32), z.copy(),
                           z.copy(), z.copy())


def _ragged_arange(sizes: np.ndarray) -> np.ndarray:
    """``[0..s0), [0..s1), ...`` concatenated."""
    total = int(sizes.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64)
    ends = np.cumsum(sizes)
    starts = ends - sizes
    return np.arange(total, dtype=np.int64) - np.repeat(starts, sizes)


class UserReservoirSampler:
    """Reservoir state over dense user ids, with 2D history storage.

    In sampled mode histories are bounded by ``kMax`` → a flat
    ``[capacity, kMax]`` int64 array. In skip-cuts mode histories are
    unbounded → the column dimension grows by doubling.
    """

    def __init__(self, user_cut: int, seed: int, skip_cuts: bool,
                 capacity: int = 1024, counters: Optional[Counters] = None) -> None:
        self.user_cut = user_cut
        self.seed = seed
        self.skip_cuts = skip_cuts
        self.counters = counters if counters is not None else Counters()
        init_cols = 8 if skip_cuts else user_cut
        # int32 storage: histories hold dense item ids (< 2^31 by the
        # job's vocab mapping); at 100k+ users x kMax columns the growth
        # memcpys and cache footprint are the sampler's dominant cost.
        self.hist = np.zeros((capacity, init_cols), dtype=np.int32)
        self.hist_len = np.zeros(capacity, dtype=np.int64)
        self.total = np.zeros(capacity, dtype=np.int64)
        self.draws = np.zeros(capacity, dtype=np.int64)
        # Fused-window mode (--fused-window, ops/device_scorer): emit
        # un-expanded star ops (BasketBatch) instead of host-expanded
        # COO — the expansion then happens on chip. Set by the job when
        # the scorer resolved the fused path on; every sampling decision
        # (cuts, draws, reservoir writes, feedback) is identical in
        # either mode, only the output encoding differs.
        self.emit_baskets = False

    # -- storage growth --------------------------------------------------

    def _ensure_rows(self, max_user: int) -> None:
        # ``hist`` grows with np.empty, NOT np.zeros: zeroing the grown
        # region is a 100+ MB memset at benchmark user counts (measured
        # 0.19 s of a 0.44 s host window pass — the single biggest host
        # cost), and cells at column >= hist_len[u] are never read (the
        # append path writes slot then reads [0, slot); the draw path
        # reads [0, kMax) of full reservoirs). Contract: hist content
        # beyond each row's hist_len is UNSPECIFIED. The count vectors
        # stay zero-initialized — their zeros are semantic.
        if max_user >= self.hist.shape[0]:
            # Pow-2 target, not max_user+1: with uniform user ids the
            # first window's max lands a hair under the true user count,
            # and an exact-fit growth forces a second full-array copy one
            # window later (measured: 200 MB of memcpy on config 4).
            new_rows = max(2 * self.hist.shape[0],
                           1 << int(max_user + 1).bit_length())
            for name in ("hist_len", "total", "draws"):
                old = getattr(self, name)
                grown = np.zeros(new_rows, dtype=old.dtype)
                grown[: len(old)] = old
                setattr(self, name, grown)
            grown = np.empty((new_rows, self.hist.shape[1]),
                             dtype=self.hist.dtype)
            grown[: self.hist.shape[0]] = self.hist
            self.hist = grown

    def _ensure_cols(self, max_len: int) -> None:
        if max_len > self.hist.shape[1]:
            new_cols = max(2 * self.hist.shape[1], max_len)
            grown = np.empty((self.hist.shape[0], new_cols),
                             dtype=self.hist.dtype)
            grown[:, : self.hist.shape[1]] = self.hist
            self.hist = grown

    # -- the window fire -------------------------------------------------

    def fire(
        self,
        users: np.ndarray,
        items: np.ndarray,
        sampled: np.ndarray,
        rng_users: Optional[np.ndarray] = None,
    ) -> Tuple[PairDeltaBatch, np.ndarray]:
        """Process one window's tagged interactions (arrival order).

        Returns ``(pair_deltas, feedback_items)`` where ``feedback_items``
        are the rejected interactions' items (each implies a ``-1`` item-cut
        decrement, reference :246-248).

        ``rng_users`` (default: ``users``) supplies the ids hashed by the
        draw RNG. The partitioned sampler indexes state by *part-local*
        compact ids but must draw with the *global* dense ids so its
        decisions are bit-identical to the serial sampler's.
        """
        if rng_users is None:
            rng_users = users
        empty = (BasketBatch.empty() if self.emit_baskets
                 else PairDeltaBatch.concat([]))
        if len(users) == 0:
            return empty, np.zeros(0, dtype=np.int64)
        self._ensure_rows(int(users.max()))

        # Reservoir denominators (fact 2): per-event totals.
        rank_all = grouped_rank(users)
        total_at_event = self.total[users] + rank_all + 1
        np.add.at(self.total, users, 1)

        if not np.any(sampled):
            return empty, np.zeros(0, dtype=np.int64)

        s_users = users[sampled]
        s_items = items[sampled]
        s_rng = rng_users[sampled]
        s_total = total_at_event[sampled]
        s_rank = grouped_rank(s_users)  # rank among *sampled* events per user

        len_before = self.hist_len[s_users]
        if self.skip_cuts:
            is_append = np.ones(len(s_users), dtype=bool)
        else:
            is_append = (len_before + s_rank) < self.user_cut

        blocks: List[PairDeltaBatch] = []
        ap_baskets: Optional[np.ndarray] = None

        # ---- Append path (vectorized; fact 1) ----
        a_users = s_users[is_append]
        a_items = s_items[is_append]
        a_slot = (len_before + s_rank)[is_append]  # the slot each append writes
        if len(a_users):
            self._ensure_cols(int(a_slot.max()) + 1)
            # Write all appends first; partners of event e are hist[u, :slot_e],
            # which equals the state at e's processing time (earlier appends of
            # the same user occupy earlier slots; other users don't interfere).
            self.hist[a_users, a_slot] = a_items
            # Unbuffered scatter-add: exact with duplicate users, and
            # ~6x cheaper than the np.unique sort it replaces.
            np.add.at(self.hist_len, a_users, 1)

            sizes = a_slot  # number of partners per append event
            total_partners = int(sizes.sum())
            if self.emit_baskets:
                # Capture the partner prefixes NOW, not at assembly: the
                # draw path below mutates reservoir rows of users that
                # cross the kMax boundary inside this same window.
                # Advanced indexing copies; cells at j >= slot_e are the
                # storage's unspecified tail, masked by every consumer.
                wa = int(a_slot.max()) if len(a_slot) else 0
                ap_baskets = (self.hist[a_users, :wa] if wa else
                              np.zeros((len(a_users), 0), dtype=np.int32))
                if total_partners > 0:
                    self.counters.add(OBSERVED_COOCCURRENCES,
                                      2 * total_partners)
            elif total_partners > 0:
                # Hot path: native C++ expansion; fallback: vectorized numpy.
                from .. import native

                expanded = native.expand_appends(
                    self.hist, a_users, a_items, a_slot)
                if expanded is not None:
                    blocks.append(PairDeltaBatch(*expanded))
                else:
                    col = _ragged_arange(sizes)
                    row_u = np.repeat(a_users, sizes)
                    partners = self.hist[row_u, col].astype(np.int64)
                    new_rep = np.repeat(a_items, sizes)
                    ones = np.ones(len(partners), dtype=np.int32)
                    # Both directions (reference :180-193).
                    blocks.append(PairDeltaBatch(new_rep, partners, ones))
                    blocks.append(PairDeltaBatch(partners, new_rep, ones))
                self.counters.add(OBSERVED_COOCCURRENCES, 2 * total_partners)

        # ---- Draw path ----
        d_mask = ~is_append
        rep_ops = None
        if np.any(d_mask):
            d_users = s_users[d_mask]
            d_items = s_items[d_mask]
            d_total = s_total[d_mask]
            # Per-user draw indices: draws_before + rank among draw events.
            d_rank = grouped_rank(d_users)
            d_idx = self.draws[d_users] + d_rank
            np.add.at(self.draws, d_users, 1)
            k = reservoir_draw(self.seed, s_rng[d_mask], d_idx, d_total)
            replace = k < self.user_cut
            feedback_items = d_items[~replace]

            # Replacements mutate slots sequentially (same slot can be hit
            # twice in one window). Hot path: native C++ expansion
            # (native/reservoir_expand.cpp); fallback: per-event loop with
            # O(kMax) numpy ops each. Basket mode skips expansion
            # entirely: each replacement becomes two star ops over the
            # pre-write row, expanded on chip.
            kc = self.user_cut
            r_users = d_users[replace]
            r_items = d_items[replace]
            r_slots = k[replace]
            if self.emit_baskets:
                rep_ops = self._replacement_ops(r_users, r_items, r_slots,
                                                kc)
            else:
                if len(r_users) and self.hist.shape[1] == kc:
                    from .. import native

                    expanded = native.expand_replacements(
                        self.hist, r_users, r_items, r_slots)
                    if expanded is not None:
                        src, dst, delta = expanded
                        blocks.append(PairDeltaBatch(src, dst, delta))
                        return PairDeltaBatch.concat(blocks), feedback_items
                for u, item, slot in zip(r_users.tolist(), r_items.tolist(),
                                         r_slots.tolist()):
                    hist_row = self.hist[u, :kc]
                    previous = int(hist_row[slot])
                    # kMax-1 partners (skip slot)
                    others = np.delete(hist_row, slot).astype(np.int64)
                    new_rep = np.full(kc - 1, item, dtype=np.int64)
                    prev_rep = np.full(kc - 1, previous, dtype=np.int64)
                    plus = np.ones(kc - 1, dtype=np.int32)
                    minus = -plus
                    # (item -> others, +1), (previous -> others, -1),
                    # (others -> item, +1), (others -> previous, -1)
                    # (reference :215-243).
                    blocks.append(PairDeltaBatch(new_rep, others, plus))
                    blocks.append(PairDeltaBatch(prev_rep, others.copy(),
                                                 minus))
                    blocks.append(PairDeltaBatch(others.copy(), new_rep,
                                                 plus))
                    blocks.append(PairDeltaBatch(others.copy(), prev_rep,
                                                 minus))
                    self.hist[u, slot] = item
        else:
            feedback_items = np.zeros(0, dtype=np.int64)

        if self.emit_baskets:
            return (self._assemble_baskets(a_items, a_slot, ap_baskets,
                                           rep_ops), feedback_items)
        return PairDeltaBatch.concat(blocks), feedback_items

    def _replacement_ops(self, r_users, r_items, r_slots, kc: int):
        """Replacement events as star ops: per event, two ops over the
        PRE-write reservoir row — ``(+1, new item)`` and ``(-1, previous
        occupant)``, both excluding ``slot`` — then the slot write.

        Event semantics are sequential (the same user's row may be hit
        twice in one window and each op must see the row state at its
        own event time), but the overwhelmingly common window has every
        replacement user distinct — no intra-window row interference —
        and takes the fully vectorized path: one advanced-indexing
        gather of the pre-write rows, one scatter of the writes (the
        basket-mode analogue of the native ``expand_replacements`` fast
        path; this loop runs on the producer hot path in fused mode).
        """
        m = len(r_users)
        new = np.empty(2 * m, dtype=np.int32)
        skips = np.empty(2 * m, dtype=np.int32)
        signs = np.empty(2 * m, dtype=np.int32)
        if m:
            skips[0::2] = skips[1::2] = r_slots
        signs[0::2] = 1
        signs[1::2] = -1
        if m and len(np.unique(r_users)) == m:
            rows = self.hist[r_users, :kc]            # copies (advanced)
            baskets = np.repeat(rows, 2, axis=0)
            new[0::2] = r_items
            new[1::2] = self.hist[r_users, r_slots]   # previous occupants
            self.hist[r_users, r_slots] = r_items
            return new, baskets, skips, signs
        baskets = np.empty((2 * m, kc if m else 0), dtype=np.int32)
        for e, (u, item, slot) in enumerate(zip(
                r_users.tolist(), r_items.tolist(), r_slots.tolist())):
            row = self.hist[u, :kc]
            baskets[2 * e] = row
            baskets[2 * e + 1] = row
            new[2 * e] = item
            new[2 * e + 1] = row[slot]  # previous occupant
            self.hist[u, slot] = item
        return new, baskets, skips, signs

    def _assemble_baskets(self, a_items, a_slot, ap_baskets,
                          rep_ops) -> BasketBatch:
        """Stack the window's append and replacement ops into one
        :class:`BasketBatch` (basket width = the window's widest op)."""
        n_app = len(a_items)
        wa = ap_baskets.shape[1] if ap_baskets is not None else 0
        if rep_ops is not None:
            r_new, r_baskets, r_skips, r_signs = rep_ops
        else:
            r_new = np.zeros(0, dtype=np.int32)
            r_baskets = np.zeros((0, 0), dtype=np.int32)
            r_skips = r_signs = np.zeros(0, dtype=np.int32)
        n_rep = len(r_new)
        n = n_app + n_rep
        if n == 0:
            return BasketBatch.empty()
        w = max(wa, r_baskets.shape[1])
        baskets = np.zeros((n, w), dtype=np.int32)
        new_items = np.empty(n, dtype=np.int32)
        lens = np.empty(n, dtype=np.int32)
        skips = np.full(n, -1, dtype=np.int32)
        signs = np.ones(n, dtype=np.int32)
        if n_app:
            baskets[:n_app, :wa] = ap_baskets
            new_items[:n_app] = a_items
            lens[:n_app] = a_slot
        if n_rep:
            baskets[n_app:, :r_baskets.shape[1]] = r_baskets
            new_items[n_app:] = r_new
            lens[n_app:] = r_baskets.shape[1]
            skips[n_app:] = r_skips
            signs[n_app:] = r_signs
        return BasketBatch(new_items, baskets, lens, skips, signs)

    # -- checkpoint -------------------------------------------------------

    def clean_hist(self, n_users: int) -> np.ndarray:
        """``hist[:n_users]`` with the unspecified cells beyond each
        row's ``hist_len`` zeroed — the deterministic persistence view.
        Growth allocates with np.empty (see ``_ensure_rows``), so the raw
        array may hold stale heap bytes that must not reach disk: a
        checkpoint has to be byte-reproducible (and compressible)."""
        h = self.hist[:n_users].copy()
        cols = np.arange(h.shape[1], dtype=np.int64)[None, :]
        h[cols >= self.hist_len[:n_users, None]] = 0
        return h

    def checkpoint_state(self, n_users: int) -> dict:
        """Reservoir state for the first ``n_users`` dense users.

        The vocab can be ahead of the sampler (users whose events are
        still buffered in unfired windows, or late-dropped) — size the
        state arrays up before slicing, or the slice comes up short."""
        self._ensure_rows(max(n_users - 1, 0))
        return {
            "hist": self.clean_hist(n_users),
            "hist_len": self.hist_len[:n_users],
            "total": self.total[:n_users],
            "draws": self.draws[:n_users],
        }

    def restore_state(self, st: dict, n_users: int) -> None:
        self._ensure_rows(max(n_users - 1, 0))
        self._ensure_cols(st["hist"].shape[1])
        self.hist[:n_users, : st["hist"].shape[1]] = st["hist"]
        self.hist_len[:n_users] = st["hist_len"]
        self.total[:n_users] = st["total"]
        self.draws[:n_users] = st["draws"]
