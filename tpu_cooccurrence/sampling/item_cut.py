"""Vectorized per-item interaction cut.

Replaces the reference's keyed item-counter operator
(``ItemInteractionCounterTwoInputStreamOperator.java:119-143``): within a
window fire, an interaction is tagged ``sample=true`` iff the item's
cumulative accepted count is still below ``fMax``; the counter only grows for
sampled interactions, and user-level rejections later decrement it via
feedback (:94-116).

Vectorization: the tag of the r-th in-window occurrence of item ``i`` (by
arrival order) is ``count[i] + r < fMax`` — computed with a stable grouped
rank, no Python loop.
"""

from __future__ import annotations

import numpy as np


def grouped_rank(keys: np.ndarray) -> np.ndarray:
    """Rank (0-based) of each element within its key group, by position.

    ``grouped_rank([5, 3, 5, 5, 3]) == [0, 0, 1, 2, 1]``.

    Hot path: one native O(n) counting pass (keys here are always dense
    non-negative ids — users or items); fallback: stable argsort +
    segment scan.
    """
    n = len(keys)
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    if n > 512:  # native pays off past the ctypes call overhead
        kmin = int(keys.min())
        kmax = int(keys.max())
        # The native pass costs O(n + max_key) (it zeroes a counter per
        # key id): only take it for non-negative keys whose id space is
        # comparable to the batch — a negative key would write out of
        # bounds in C, and a huge sparse key space would allocate its
        # size in scratch while the argsort fallback stays O(n log n).
        if kmin >= 0 and kmax < 32 * n + (1 << 16):
            from .. import native

            ranks = native.grouped_rank_dense(keys, kmax)
            if ranks is not None:
                return ranks
    order = np.argsort(keys, kind="stable")
    sorted_keys = keys[order]
    group_start = np.zeros(n, dtype=np.int64)
    new_group = np.flatnonzero(sorted_keys[1:] != sorted_keys[:-1]) + 1
    group_start[new_group] = new_group
    group_start = np.maximum.accumulate(group_start)
    ranks_sorted = np.arange(n, dtype=np.int64) - group_start
    ranks = np.empty(n, dtype=np.int64)
    ranks[order] = ranks_sorted
    return ranks


class ItemInteractionCut:
    """Cumulative per-item acceptance counter with feedback decrements."""

    def __init__(self, item_cut: int, capacity: int) -> None:
        self.item_cut = item_cut
        # Degradation plane (robustness/degrade.py): the cut actually
        # applied this window. Tighten-only (clamped to the configured
        # fMax), identity while the controller is at NORMAL — shedding
        # can only *remove* interactions a looser cut would have sampled
        # (the monotonicity contract, tests/test_degrade.py).
        self.effective_cut = item_cut
        self.counts = np.zeros(capacity, dtype=np.int32)

    def set_effective_cut(self, cut: int) -> None:
        """Set the cut applied by the next :meth:`fire` (shedding knob)."""
        self.effective_cut = max(1, min(self.item_cut, cut))

    def _ensure(self, max_id: int) -> None:
        if max_id >= len(self.counts):
            new_cap = max(2 * len(self.counts), max_id + 1)
            grown = np.zeros(new_cap, dtype=np.int32)
            grown[: len(self.counts)] = self.counts
            self.counts = grown

    def fire(self, items: np.ndarray) -> np.ndarray:
        """Tag a window's interactions; updates counters. Returns bool mask."""
        if len(items) == 0:
            return np.zeros(0, dtype=bool)
        self._ensure(int(items.max()))
        ranks = grouped_rank(items)
        sampled = (self.counts[items] + ranks) < self.effective_cut
        # Counter evolution stays governed by the configured fMax (the
        # clamp), whatever cut the mask applied: a shed window must not
        # corrupt the cumulative-acceptance state a later NORMAL window
        # resumes from.
        uniq, n_window = np.unique(items, return_counts=True)
        self.counts[uniq] = np.minimum(self.item_cut, self.counts[uniq] + n_window)
        return sampled

    def apply_feedback(self, items: np.ndarray, development_mode: bool = False,
                       counters=None) -> None:
        """Apply ``(item, -1)`` decrements (reference :94-116)."""
        if len(items) == 0:
            return
        if development_mode:
            if counters is not None:
                from ..metrics import ITEM_FEEDBACK_ELEMENTS

                counters.add(ITEM_FEEDBACK_ELEMENTS, len(items))
            if np.any(self.counts[items] == 0):
                bad = items[self.counts[items] == 0][0]
                raise AssertionError(
                    f"Item interactions 0 for item {bad}, but received decrement feedback.")
        np.subtract.at(self.counts, items, 1)
