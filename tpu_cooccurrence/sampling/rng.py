"""Deterministic, parallelism-independent reservoir RNG.

The reference draws reservoir indices from a single ``java.util.Random(seed)``
shared by all keys of an operator subtask
(``UserInteractionCounterOneInputStreamOperator.java:55,82,207``), which makes
results depend on element processing order and parallelism. We instead derive
each draw from ``(seed, user, draw_index)`` with a splitmix64-based stateless
hash: draws are identical regardless of processing order, vectorize over
users in NumPy, and are trivially portable to device code later. This is a
deliberate, documented deviation — the *distribution* (uniform over
``[0, total)``) is what the algorithm requires, not Java's exact stream.
"""

from __future__ import annotations

import numpy as np

_U64 = np.uint64
_MASK = np.uint64(0xFFFFFFFFFFFFFFFF)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer (public-domain constants).

    uint64 wraparound is the point; numpy's overflow warnings are suppressed.
    """
    with np.errstate(over="ignore"):
        x = (x + _U64(0x9E3779B97F4A7C15)) & _MASK
        z = x
        z = ((z ^ (z >> _U64(30))) * _U64(0xBF58476D1CE4E5B9)) & _MASK
        z = ((z ^ (z >> _U64(27))) * _U64(0x94D049BB133111EB)) & _MASK
        return z ^ (z >> _U64(31))


def reservoir_draw(seed: int, users, draw_indices, totals):
    """Uniform draws in ``[0, totals)`` keyed by ``(seed, user, draw_index)``.

    All of ``users``, ``draw_indices``, ``totals`` broadcast; returns int64.
    Mirrors the role of ``random.nextInt(userInteractionsTotal)`` in the
    reference (``UserInteractionCounterOneInputStreamOperator.java:207``).
    """
    users = np.asarray(users, dtype=np.uint64)
    draw_indices = np.asarray(draw_indices, dtype=np.uint64)
    totals = np.asarray(totals, dtype=np.int64)
    s = _U64(seed & 0xFFFFFFFFFFFFFFFF)
    with np.errstate(over="ignore"):
        h = _splitmix64((_splitmix64((s ^ (users * _U64(0x9E3779B97F4A7C15))) & _MASK)
                         ^ draw_indices) & _MASK)
    # 64-bit modulo bias is negligible for any realistic `totals`.
    return (h % totals.astype(np.uint64)).astype(np.int64)


def reservoir_draw_scalar(seed: int, user: int, draw_index: int, total: int) -> int:
    """Scalar convenience wrapper (used by the record-at-a-time oracle)."""
    return int(reservoir_draw(seed, np.uint64(user), np.uint64(draw_index),
                              np.int64(total)))
