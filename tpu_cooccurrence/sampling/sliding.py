"""Sliding-window basket co-occurrence sampler.

The reference only ever wires tumbling windows (``FlinkCooccurrences.java:
139,153``) and its operators reject multi-window assignment
(``UserInteractionCounterOneInputStreamOperator.java:126-128``); sliding
windows are a framework extension (SURVEY §7 "hard parts", benchmark
config 3: "MovieLens-25M sessions, sliding time window + top-k").

Semantics (documented design choice): with a slide, an interaction belongs
to ``size/slide`` overlapping windows and the persistent-history model of
the tumbling path would multiply-count every event. Sliding mode therefore
computes *windowed-basket* co-occurrence: within each window instance, each
user's in-window interactions form a basket, and every ordered pair of
distinct basket positions is emitted once (the ``outer(m) - diag(m)``
within-window AᵀA). The same pair may legitimately appear in several
overlapping windows — that is the sliding-window recency weighting. Cuts
become per-window caps: the first ``fMax`` interactions per item and the
first ``kMax`` per user within the window (no cross-window feedback — it
has no meaning when windows overlap).

Row sums and ``observed`` remain the per-source segment-sum of pair deltas,
so all scoring backends work unchanged.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..metrics import Counters, OBSERVED_COOCCURRENCES
from .item_cut import grouped_rank
from .reservoir import PairDeltaBatch, _ragged_arange


class SlidingBasketSampler:
    """Stateless per-window basket pair expansion with per-window caps."""

    def __init__(self, item_cut: int, user_cut: int, skip_cuts: bool,
                 counters: Optional[Counters] = None) -> None:
        self.item_cut = item_cut
        self.user_cut = user_cut
        # Degradation plane (robustness/degrade.py): the per-window caps
        # actually applied. Tighten-only; identity at NORMAL. The sampler
        # is stateless across windows, so a shed window's tighter caps
        # can only drop pairs — never reorder or add them.
        self.effective_item_cut = item_cut
        self.effective_user_cut = user_cut
        self.skip_cuts = skip_cuts
        self.counters = counters if counters is not None else Counters()
        from ..native import SlidingScratch

        self._scratch = SlidingScratch()

    def set_effective_cuts(self, item_cut: int, user_cut: int) -> None:
        """Set the caps applied by the next :meth:`fire` (shedding knob)."""
        self.effective_item_cut = max(1, min(self.item_cut, item_cut))
        self.effective_user_cut = max(1, min(self.user_cut, user_cut))

    def fire(self, users: np.ndarray, items: np.ndarray) -> PairDeltaBatch:
        if len(users) == 0:
            return PairDeltaBatch.concat([])
        # Native path: cuts + grouping + expansion as O(n) counting passes
        # over the dense ids (the NumPy path below pays three O(n log n)
        # argsorts per window — ~60% of ML-25M-shape host time). Output is
        # byte-identical; tests pin both paths against each other and the
        # sliding oracle.
        from ..native import sliding_expand

        native = sliding_expand(users, items, self.effective_item_cut,
                                self.effective_user_cut,
                                self.skip_cuts, self._scratch)
        if native is not None:
            src, dst = native
            delta = np.ones(len(src), dtype=np.int32)
            self.counters.add(OBSERVED_COOCCURRENCES, len(src))
            return PairDeltaBatch(src, dst, delta)
        return self._fire_numpy(users, items)

    def _fire_numpy(self, users: np.ndarray,
                    items: np.ndarray) -> PairDeltaBatch:
        if not self.skip_cuts:
            keep = ((grouped_rank(items) < self.effective_item_cut)
                    & (grouped_rank(users) < self.effective_user_cut))
            users, items = users[keep], items[keep]
            if len(users) == 0:
                return PairDeltaBatch.concat([])

        # Group by user (stable: preserves in-window arrival order).
        order = np.argsort(users, kind="stable")
        items_s = items[order]
        users_s = users[order]
        boundaries = np.flatnonzero(users_s[1:] != users_s[:-1]) + 1
        group_starts = np.concatenate(([0], boundaries))
        group_sizes = np.diff(np.concatenate((group_starts, [len(users_s)])))

        # All ordered pairs (i, j), i != j by basket position, per user:
        # for each group of size m, emit m*(m-1) pairs. Build flattened
        # (row, col) position indices with vectorized ragged ops.
        m = group_sizes
        pair_counts = m * (m - 1)
        total = int(pair_counts.sum())
        if total == 0:
            return PairDeltaBatch.concat([])
        # Expand per event: each event in a group of size m pairs with the
        # (m-1) other positions of its group.
        sizes_per_event = np.repeat(m, m) - 1
        base = np.repeat(group_starts, m)  # group start per event
        ev_global = np.arange(len(users_s), dtype=np.int64)
        # Partner local indices 0..m-1 skipping the event's own local index.
        part_local = _ragged_arange(sizes_per_event)
        own_local = ev_global - base
        own_rep = np.repeat(own_local, sizes_per_event)
        # Skip self: partners >= own index shift by one.
        part_local = part_local + (part_local >= own_rep)
        src = np.repeat(items_s, sizes_per_event)
        dst = items_s[np.repeat(base, sizes_per_event) + part_local]
        delta = np.ones(len(src), dtype=np.int32)
        self.counters.add(OBSERVED_COOCCURRENCES, len(src))
        return PairDeltaBatch(src.astype(np.int64), dst.astype(np.int64), delta)
