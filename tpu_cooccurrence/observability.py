"""Tracing / profiling / per-window instrumentation.

The reference's observability is wall-clock duration + accumulators
(SURVEY §5: ``FlinkCooccurrences.java:173-181``); Flink's own metrics UI
provides the rest. The TPU build's upgrade: per-window step timing with
stage breakdown (sampling vs scoring), retained as a ring buffer and
summarizable, plus optional XLA profiler traces (``jax.profiler``) for
TensorBoard.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import time
from typing import Deque, Dict, Iterator, Optional


@dataclasses.dataclass
class WindowStats:
    timestamp: int
    events: int
    pairs: int
    rows_scored: int
    sample_seconds: float
    score_seconds: float

    @property
    def seconds(self) -> float:
        return self.sample_seconds + self.score_seconds


class StepTimer:
    """Ring buffer of per-window stats with aggregate summary."""

    def __init__(self, keep: int = 1024) -> None:
        self.windows: Deque[WindowStats] = collections.deque(maxlen=keep)
        self.total_windows = 0
        self.total_events = 0
        self.total_pairs = 0
        self.total_sample_seconds = 0.0
        self.total_score_seconds = 0.0

    def record(self, stats: WindowStats) -> None:
        self.windows.append(stats)
        self.total_windows += 1
        self.total_events += stats.events
        self.total_pairs += stats.pairs
        self.total_sample_seconds += stats.sample_seconds
        self.total_score_seconds += stats.score_seconds

    def summary(self) -> Dict[str, float]:
        total = self.total_sample_seconds + self.total_score_seconds
        return {
            "windows": self.total_windows,
            "events": self.total_events,
            "pairs": self.total_pairs,
            "sample_seconds": round(self.total_sample_seconds, 4),
            "score_seconds": round(self.total_score_seconds, 4),
            "pairs_per_sec": round(self.total_pairs / total, 1) if total else 0.0,
        }

    def slowest(self, n: int = 3) -> list:
        """The n slowest recent windows (ring-buffer scope) — the first place
        to look when a run's step timing regresses."""
        return sorted(self.windows, key=lambda w: -w.seconds)[:n]


@contextlib.contextmanager
def xla_trace(profile_dir: Optional[str]) -> Iterator[None]:
    """Wrap a run in a ``jax.profiler`` trace when a directory is given."""
    if not profile_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(profile_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class clock:  # noqa: N801 - tiny helper
    """``with clock() as c: ...; c.seconds``"""

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.seconds = time.perf_counter() - self._t0
        return False
