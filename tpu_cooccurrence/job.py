"""The production job: wires ingest -> windowing -> sampling -> scoring.

TPU-native equivalent of the reference's topology builder + driver
(``FlinkCooccurrences.java:36-182``): instead of a DataStream graph with
keyed shuffles, the host streams micro-batches through the window engine and
the vectorized cut operators, and each fired window becomes one device step
(scatter-update + LLR + top-K). The feedback edge (reject -> item-counter
decrement, reference's in-JVM ``BlockingQueueBroker`` hack) is a plain
same-host update applied between window fires.

Duration and the accumulator dump mirror the reference's end-of-run logging
(``FlinkCooccurrences.java:173-181``).
"""

from __future__ import annotations

import json
import logging
import time
from typing import Dict, Iterable, Optional

import numpy as np

from .config import Backend, Config
from .metrics import (
    Counters,
    FEEDBACK_QUEUES,
    ITEM_LATE_ELEMENTS,
    USER_LATE_ELEMENTS,
    USER_RECEIVED_ELEMENTS,
)
from .io.parse import InteractionBatch
from .sampling.item_cut import ItemInteractionCut
from .sampling.reservoir import UserReservoirSampler
from .sampling.sliding import SlidingBasketSampler
from .observability import LEDGER, StepTimer, WindowStats, clock
from .observability.registry import BYTES_BUCKETS, REGISTRY
from .robustness import faults
from .state.rescorer import HostRescorer, WindowTopK
from .state.results import LatestResults, TopKBatch
from .state.vocab import IdMap
from .windowing.engine import WindowEngine

LOG = logging.getLogger("tpu_cooccurrence")


class CooccurrenceJob:
    """Streaming co-occurrence job over a pluggable scoring backend."""

    def __init__(self, config: Config, scorer=None) -> None:
        if config.window_millis <= 0:
            raise ValueError("window size must be positive")
        self.config = config
        self.counters = Counters()
        # Graceful-degradation plane (--degrade, robustness/degrade.py):
        # installed process-globally so the source's admission gate can
        # reach it without plumbing; identity at NORMAL (parity-tested),
        # uninstalled in finish().
        self.degrade = None
        if config.degrade:
            from .robustness import degrade as degrade_mod

            self.degrade = degrade_mod.install(
                degrade_mod.DegradationController(
                    window_wall_s=config.degrade_window_wall_s,
                    trip_windows=config.degrade_trip_windows,
                    clear_windows=config.degrade_clear_windows,
                    shed_factor=config.degrade_shed_factor,
                    pause_ms=config.degrade_pause_ms,
                    stale_after_s=config.degrade_stale_after_s))
        # Sliding mode (framework extension; the reference is tumbling-only,
        # FlinkCooccurrences.java:139,153) switches the sampler to stateless
        # windowed-basket co-occurrence — see sampling/sliding.py for the
        # documented semantics.
        self.sliding = config.window_slide is not None
        self.engine = WindowEngine(config.window_millis, config.slide_millis)
        self.item_vocab = IdMap()
        self.user_vocab = IdMap()
        self.item_cut = ItemInteractionCut(config.item_cut, capacity=1024)
        if self.sliding:
            if config.partition_sampling:
                from .parallel.distributed import init_multihost
                from .sampling.multihost import (
                    ProcessPartitionedSlidingSampler)

                init_multihost(config.coordinator, config.num_processes,
                               config.process_id)
                self.sampler = ProcessPartitionedSlidingSampler(
                    config.item_cut, config.user_cut, config.skip_cuts,
                    counters=self.counters)
            else:
                self.sampler = SlidingBasketSampler(
                    config.item_cut, config.user_cut, config.skip_cuts,
                    counters=self.counters)
        elif config.partition_sampling:
            # Needs the multi-controller runtime up before process_index()
            # is meaningful; idempotent with the scorer's own init.
            from .parallel.distributed import init_multihost
            from .sampling.multihost import ProcessPartitionedSampler

            init_multihost(config.coordinator, config.num_processes,
                           config.process_id)
            self.sampler = ProcessPartitionedSampler(
                config.user_cut, config.seed, config.skip_cuts,
                counters=self.counters)
        else:
            self.sampler = UserReservoirSampler(
                config.user_cut, config.seed, config.skip_cuts,
                counters=self.counters)
        self.scorer = scorer if scorer is not None else self._make_scorer()
        # Incremental-checkpoint job-side dirty tracker (state/delta.py):
        # users touched per fired window + vocab-length cursors. None =
        # incremental off (zero hot-path cost).
        self._ckpt_dirty = None
        if config.checkpoint_incremental:
            # Incremental checkpoints (state/delta.py): arm the store's
            # dirty-row log — the scorer feeds it the same per-window
            # touched-rows set the tiered store's recency clock stamps,
            # and checkpoint.save drains it per generation. Config
            # validation restricted the flag to sparse-family backends,
            # all of which expose a StateStore.
            store = getattr(self.scorer, "store", None)
            if store is None:
                raise ValueError(
                    "--checkpoint-incremental needs a StateStore-backed "
                    "scorer (sparse backends)")
            store.enable_ckpt_dirty()
            from .state.delta import JobDirtyTracker

            self._ckpt_dirty = JobDirtyTracker()
        if self.degrade is not None and config.coordinator is not None:
            # Multi-host degradation (robustness/gang.py plane): every
            # observed window exchanges each host's worst signal
            # (gang-wide max over the overloaded bit, one tiny guarded
            # allgather per window) so all hosts step the ladder
            # identically and sampling stays in lockstep. Wired after
            # scorer construction — its init joined the
            # multi-controller runtime the exchange rides on.
            from .parallel.distributed import allgather_max

            self.degrade.exchange = allgather_max
        # Load-driven autoscaling (--autoscale on, robustness/
        # autoscale.py): the tap votes one packed idle/drain int per
        # window, writes the gang-dir pressure beacon the supervisor's
        # scale policy reads, and flips the drain flag once the whole
        # gang has seen a RESCALE request. Armed only inside a gang
        # worker (gang dir env + multi-controller identity).
        self.autoscale = None
        if config.autoscale == "on" and config.coordinator is not None:
            from . import tuning
            from .robustness.autoscale import AutoscaleTap
            from .robustness.gang import GANG_DIR_ENV

            gang_dir = tuning.env_read(GANG_DIR_ENV)
            if gang_dir:
                self.autoscale = AutoscaleTap(
                    gang_dir, config.process_id, config.num_processes,
                    idle_wall_s=config.degrade_window_wall_s / 4.0)
                if (self.degrade is not None
                        and config.num_processes
                        < config.autoscale_max_workers):
                    # Scale-before-shed precedence: with capacity
                    # headroom the ladder may not leave NORMAL —
                    # sustained pressure is a rescale trigger first.
                    # At max capacity the flag stays False and the
                    # ladder sheds exactly as before. Static per
                    # attempt and identical on every host, so the
                    # multi-host transition lockstep is preserved.
                    # Guarded by the tap arming: a worker launched
                    # outside gang supervision (no gang dir) has no
                    # autoscaler to relieve the pressure, so holding
                    # its ladder would strip ALL shed protection.
                    self.degrade.hold_escalation = True
        if (getattr(self.scorer, "wants_baskets", False)
                and isinstance(self.sampler, UserReservoirSampler)):
            # Fused-window uplink (--fused-window, ops/device_scorer):
            # the sampler hands the scorer un-expanded baskets — host
            # expansion and the 3x-wider COO uplink disappear for
            # fused-routable windows; non-routable ones expand host-side
            # inside the scorer (bit-identical either way). Gated on the
            # tumbling reservoir sampler: sliding/partitioned samplers
            # stay on the expanded-COO contract. Dense backend only
            # (wants_baskets): the sparse fused path keeps the host
            # fold — slot allocation needs the aggregated cells anyway.
            self.sampler.emit_baskets = True
        if config.partition_sampling and not self.sliding:
            # Sliding mode is exempt: its partitioned sampler is stateless
            # (nothing partition-distinct ever reaches a checkpoint).
            import jax

            if (jax.process_count() > 1
                    and not getattr(self.scorer, "process_suffix", "")):
                # Partitioned reservoir snapshots are per-process-distinct;
                # a backend without per-process checkpoint files would have
                # every process clobber the same state.npz (last writer
                # wins, other partitions' reservoirs unrecoverable).
                raise ValueError(
                    "--partition-sampling needs a backend with per-process "
                    "checkpoints: --backend sharded, or sparse with "
                    "--num-shards > 1")
        # results: external item id -> [(external other, score) desc];
        # array-backed, lazily materialized (state/results.py)
        self.latest = LatestResults(self.item_vocab)
        # Online serving plane (--serve-port, serving/): double-buffered
        # zero-lock top-K snapshots swapped at window boundaries plus the
        # per-user history blend behind /recommend. Pure observer of the
        # ingest path: it reads mapped ids and emitted rows, never
        # touches sampling/scorer state — serving on vs off is
        # bit-identical on ingest output (parity-tested at depths 0, 2).
        self.serving = None
        if config.serve_port is not None:
            from .serving import ServingPlane

            self.serving = ServingPlane(
                self.item_vocab, self.user_vocab,
                history_len=config.serve_history,
                query_slo_s=config.serve_query_slo_s)
        # Optional streaming-result hook: called with every materialized
        # window output (dense-id rows, post-absorption) — the consumable
        # form of the reference's continuous emission into its sink
        # (FlinkCooccurrences.java:169-171). None = final-state-only.
        self.on_update = None
        self.emissions = 0
        self.windows_fired = 0
        self.step_timer = StepTimer()
        # Tracing plane (observability/journal.py): fleet correlation
        # identity, stamped on every journal record this job writes. A
        # supervising parent mints run_id once and threads it (plus the
        # restart-attempt ordinal) through the env; an unsupervised run
        # mints its own. --run-id overrides for deliberate joins.
        from .observability.journal import run_context
        env_run_id, self.attempt = run_context()
        self.run_id = config.run_id or env_run_id
        self.process_id = int(config.process_id or 0)
        # Boundary-stage seconds (snapshot-publish measured in _absorb,
        # checkpoint-commit in checkpoint()) land AFTER the window's
        # record flushed — they ride the NEXT record as trailing spans
        # (see journal.SPAN_STAGES) and stay out of the core-span
        # wall-seconds reconciliation.
        self._pending_publish_s = 0.0
        self._pending_ckpt_s = 0.0
        # /healthz last_window block: reassigned atomically per window
        # (readers on the HTTP thread only ever see a whole dict).
        self.last_window_health: Optional[dict] = None
        # Flight recorder (observability/journal.py): one flushed JSONL
        # record per fired window. Per-window counter / wire deltas diff
        # against these snapshots; both are read only by whichever thread
        # records windows (the caller thread serially, the scorer worker
        # pipelined), so no extra locking beyond the registries' own.
        self.journal = None
        if config.journal:
            from .observability.journal import RunJournal

            self.journal = RunJournal(config.journal)
            if self.degrade is not None:
                # Durable sink for admission-side (stale-ingest)
                # transitions: they must reach the journal even when no
                # window ever completes again (the stalled-scorer
                # scenario the escalation exists for). RunJournal.record
                # is locked, so the ingest thread may write concurrently
                # with the window-record thread.
                self.degrade.journal_event = self._journal_degrade_event
        self._prev_counters: Dict[str, int] = {}
        self._prev_wire: Dict[str, int] = LEDGER.snapshot()
        # Metrics plane (observability/registry.py): latency/byte
        # distributions behind BENCH tail summaries and /metrics.
        self._hist_sample = REGISTRY.histogram(
            "cooc_window_sample_seconds",
            help="host sampling stage seconds per fired window")
        self._hist_score = REGISTRY.histogram(
            "cooc_window_score_seconds",
            help="scorer stage seconds per fired window")
        # Fused-vs-chained wall-time split (--fused-window): the same
        # stage seconds, bucketed by which dispatch path the window
        # took, so the fused win (or CPU-fallback neutrality) is a
        # first-class distribution in bench JSON and /metrics.
        self._hist_score_fused = REGISTRY.histogram(
            "cooc_window_score_seconds_fused",
            help="scorer stage seconds for windows on the fused "
                 "one-dispatch path")
        self._hist_score_chained = REGISTRY.histogram(
            "cooc_window_score_seconds_chained",
            help="scorer stage seconds for windows on the chained "
                 "scatter+score path")
        self._hist_total = REGISTRY.histogram(
            "cooc_window_total_seconds",
            help="sample+score seconds per fired window")
        self._hist_uplink = REGISTRY.histogram(
            "cooc_window_uplink_bytes", BYTES_BUCKETS,
            help="host->device bytes shipped per fired window")
        self._gauge_windows = REGISTRY.gauge(
            "cooc_windows_fired", help="fired-window ordinal")
        self._gauge_last_window = REGISTRY.gauge(
            "cooc_last_window_unix_seconds",
            help="wall clock of the last fired window "
                 "(healthz staleness input)")
        # Optional file source attached by the CLI so periodic checkpoints
        # snapshot the input offset too (crash recovery resumes mid-stream).
        self.source = None
        # Per-window ingest snapshots (partitioned source only): captured
        # on the sampling thread at window fire — the only thread driving
        # the line generator — then read by _record_window on whichever
        # thread scores that seq (distinct keys; no lock needed).
        self._ingest_by_seq: Dict[int, dict] = {}
        # One in-process feedback channel (the reference counts one queue
        # handshake per subtask open,
        # UserInteractionCounterOneInputStreamOperator.java:109). Sliding
        # mode has no feedback edge (per-window caps, no rejections).
        if not config.skip_cuts and not self.sliding:
            self.counters.add(FEEDBACK_QUEUES, 1)
        # Pipelined execution (--pipeline-depth > 0): the caller thread
        # keeps sampling window N+1 while a worker thread runs the scorer
        # for window N (pipeline.py — the Flink-operator-overlap
        # analogue). Depth 0 is the serial path, bit-identical by the
        # parity tests. The feedback edge stays on the sampling thread,
        # so its between-fires ordering is untouched.
        self.pipeline = None
        if config.pipeline_depth > 0:
            from .pipeline import PipelineDriver

            self.pipeline = PipelineDriver(self, config.pipeline_depth)

    def _maybe_breaker(self, scorer):
        """Wrap a single-process device scorer in the circuit breaker
        (--scorer-breaker-threshold > 0): consecutive dispatch failures
        fail over to the exact host-oracle scorer instead of killing
        the run (config validation restricts the flag to the backends
        where a host fallback is sound)."""
        if self.config.scorer_breaker_threshold <= 0:
            return scorer
        from .robustness.degrade import ScorerCircuitBreaker

        return ScorerCircuitBreaker(
            scorer, self.config.top_k, self.counters,
            threshold=self.config.scorer_breaker_threshold,
            probe_after_windows=self.config.scorer_breaker_probe_windows)

    def _parse_fixed_score(self):
        fixed = {"auto": None, "on": True,
                 "off": False}.get(self.config.fixed_score, KeyError)
        if fixed is KeyError:
            raise ValueError(
                f"fixed_score must be auto|on|off, got "
                f"{self.config.fixed_score!r}")
        return fixed

    def _make_scorer(self):
        backend = self.config.backend
        if backend == Backend.HYBRID:
            # Retired round 3: on its flagship config (1M-item Zipfian) the
            # sparse backend measured 2.2x the hybrid's on-chip throughput
            # (TPU_ROUND2.jsonl 2026-07-30: 71.9k vs 32.1k pairs/s) and
            # covers the same beyond-dense-ceiling vocabularies. The flag
            # stays accepted: checkpoints were interchangeable by design
            # (state/sparse_scorer.py snapshot docstring), so a hybrid
            # checkpoint restores under sparse unchanged. Aliased before
            # any validation so every sparse flag (e.g. --fixed-score)
            # works identically under the alias.
            LOG.warning("--backend hybrid is retired; running the sparse "
                        "backend (checkpoints are interchangeable)")
            backend = Backend.SPARSE
        if backend != Backend.SPARSE and self._parse_fixed_score() is not None:
            # An explicit setting the backend cannot honor must not be
            # silently ignored (same rule as the sparse branch's
            # emit-updates conflict).
            raise ValueError(
                f"--fixed-score {self.config.fixed_score} only applies to "
                f"--backend sparse (got {backend.value})")
        if backend == Backend.ORACLE:
            return HostRescorer(self.config.top_k, self.counters,
                                self.config.development_mode)
        if backend == Backend.DEVICE:
            from .ops.device_scorer import DeviceScorer

            # num_items == 0 derives the vocab from the data (the scorer
            # doubles its dense C on growth); an explicit value is a hard
            # capacity check, enforced in add_batch.
            num_items = self.config.num_items
            # defer_results: see the sparse branch below.
            return self._maybe_breaker(DeviceScorer(
                num_items, self.config.top_k, self.counters,
                max_pairs_per_step=self.config.max_pairs_per_step,
                use_pallas=self.config.pallas,
                count_dtype=self.config.count_dtype,
                defer_results=not self.config.emit_updates,
                fused_window=self.config.fused_window))
        if backend == Backend.SPARSE:
            fixed = self._parse_fixed_score()
            if self.config.num_shards > 1:
                from .parallel.distributed import maybe_multihost_mesh

                # Join the multi-controller runtime BEFORE importing the
                # scorer module: its jits probe the backend at import
                # (ops/donation.py), and jax.distributed.initialize must
                # precede any backend initialization.
                mesh = maybe_multihost_mesh(self.config)
                from .parallel.sharded_sparse import ShardedSparseScorer
                from .state.wire import (resolve_cell_dtype,
                                         resolve_wire_format)

                return ShardedSparseScorer(
                    self.config.top_k, num_shards=self.config.num_shards,
                    counters=self.counters,
                    mesh=mesh,
                    development_mode=self.config.development_mode,
                    score_ladder=self.config.score_ladder,
                    defer_results=not self.config.emit_updates,
                    fixed_shapes=fixed,
                    use_pallas=self.config.pallas,
                    cell_dtype=resolve_cell_dtype(
                        self.config.cell_dtype, sparse_single_device=False),
                    wire_format=resolve_wire_format(
                        self.config.wire_format,
                        sparse_single_device=False),
                    fused_window=self.config.fused_window)
            if self.config.coordinator is not None:
                # A coordinator with the default single shard would run one
                # full independent job per process (and clobber a shared
                # checkpoint dir) — misconfiguration, not a mode.
                raise ValueError(
                    "--coordinator with --backend sparse needs "
                    "--num-shards > 1 (the sharded-sparse mesh)")
            from .state.sparse_scorer import SparseDeviceScorer
            from .state.wire import resolve_cell_dtype, resolve_wire_format

            # Final-state consumption (no --emit-updates): keep results in
            # a device-resident table and fetch once at flush — per-window
            # result transfer drops to zero (the dominant wall cost of
            # large windows on a high-latency link). Streaming consumers
            # keep the per-window pipeline.
            return self._maybe_breaker(SparseDeviceScorer(
                self.config.top_k, self.counters,
                self.config.development_mode,
                score_ladder=self.config.score_ladder,
                defer_results=not self.config.emit_updates,
                fixed_shapes=fixed,
                use_pallas=self.config.pallas,
                cell_dtype=resolve_cell_dtype(
                    self.config.cell_dtype, sparse_single_device=True),
                wire_format=resolve_wire_format(
                    self.config.wire_format, sparse_single_device=True),
                spill_threshold_windows=self.config.spill_threshold_windows,
                spill_target_hbm_frac=self.config.spill_target_hbm_frac,
                fused_window=self.config.fused_window))
        if backend == Backend.SHARDED:
            from .parallel.distributed import maybe_multihost_mesh

            # Multi-controller init before the scorer import — see the
            # sharded-sparse branch above.
            mesh = maybe_multihost_mesh(self.config)
            from .parallel.sharded import ShardedScorer

            num_items = self.config.num_items
            # num_items == 0 derives the vocab from the data: the scorer
            # starts small and doubles (resharding) on growth, like the
            # dense backend. Multi-host still needs an explicit capacity
            # (ShardedScorer raises: capacity must agree across processes).
            return ShardedScorer(num_items, self.config.top_k,
                                 num_shards=self.config.num_shards,
                                 counters=self.counters,
                                 mesh=mesh,
                                 count_dtype=self.config.count_dtype,
                                 use_pallas=self.config.pallas)
        raise ValueError(f"unknown backend {backend}")

    # ------------------------------------------------------------------

    def add_batch(self, users: np.ndarray, items: np.ndarray, ts: np.ndarray) -> None:
        """Ingest one parsed interaction batch (stream order)."""
        dense_items = self.item_vocab.map_batch(items)
        if self.config.num_items and len(self.item_vocab) > self.config.num_items:
            raise ValueError(
                f"item vocabulary exceeded --num-items capacity "
                f"({len(self.item_vocab)} > {self.config.num_items})")
        dense_users = self.user_vocab.map_batch(users)
        if self.serving is not None:
            # Feed the per-user history rings on the ingest thread (the
            # blend's "recent history" side; bounded memory per user).
            self.serving.feed(dense_users, dense_items)
        n_late = self.engine.add_batch(dense_users, dense_items, ts)
        if n_late:
            # The reference counts late drops at both cut operators
            # (ItemInteractionCounter...:75-77, UserInteractionCounter...:121-123).
            self.counters.add(ITEM_LATE_ELEMENTS, n_late)
            self.counters.add(USER_LATE_ELEMENTS, n_late)
        if self.config.development_mode:
            self.counters.add(USER_RECEIVED_ELEMENTS, len(users) - n_late)
        self._drain(final=False)

    def finish(self) -> None:
        """End of stream — Watermark(MAX_VALUE) fires everything."""
        try:
            self._finish()
        finally:
            if self.degrade is not None:
                # Drop the process-global controller whatever happened —
                # a failed job must not keep gating a successor's source
                # (instance-checked, so it never evicts a newer job's).
                from .robustness import degrade as degrade_mod

                degrade_mod.uninstall(self.degrade)

    def abort(self) -> None:
        """Best-effort teardown after an externally-raised abort mid-run
        (e.g. the quarantine rate breaker firing inside the ingest
        generator, before ``finish`` was ever reachable): join the
        scorer worker so no daemon thread keeps dispatching, close the
        journal so its tail is durable, and drop the process-global
        degradation controller. Idempotent; never raises over the
        original failure."""
        try:
            if self.pipeline is not None:
                self.pipeline._shutdown_worker()
        finally:
            if self.journal is not None:
                self.journal.close()
            if self.degrade is not None:
                from .robustness import degrade as degrade_mod

                degrade_mod.uninstall(self.degrade)

    def _finish(self) -> None:
        try:
            self._drain(final=True)
        except BaseException:
            if self.pipeline is not None:
                # Join the worker so no daemon thread outlives the job,
                # but keep the in-flight exception as THE failure — a
                # close() here could replace it with the worker's own
                # latched error and point the operator at the wrong one.
                self.pipeline._shutdown_worker()
            raise
        if self.pipeline is not None:
            # Ordered shutdown: the final drain already barriered, so the
            # close is immediate; it also surfaces any latched worker
            # error before the balance check below can mask it.
            self.pipeline.close()
        if (self.config.development_mode
                and not getattr(self.scorer, "process_suffix", "")
                and not getattr(self.scorer, "defer_results", False)
                and not getattr(self.scorer, "trips", 0)):
            # A tripped scorer breaker is exempt too: rows the primary
            # dispatched (and counted) before failing may have been
            # re-scored by the fallback and filtered from the final
            # flush — the imbalance is the documented fidelity trade,
            # not a lost window.
            # Pipeline-drain invariant (the moral equivalent of the
            # reference's buffered-element balance counters,
            # UserInteractionCounterOneInputStreamOperator.java:134-137):
            # every row dispatched into a scorer's result pipeline must be
            # materialized exactly once — a flush that drops or double-
            # emits an in-flight window shows up as a mismatch here.
            # Multi-host processes are exempt: each materializes only the
            # rows its chips own while the dispatch counter sees all rows.
            # Deferred-results backends are exempt too: the scatter into
            # the device table rides the same dispatch as the scoring (no
            # separate pipeline to lose), and a row rescored in N windows
            # materializes once from the table, not N times.
            from .metrics import RESCORED_ITEMS

            rescored = self.counters.get(RESCORED_ITEMS)
            if self.emissions != rescored:
                raise AssertionError(
                    f"result pipeline out of balance: {rescored} rows "
                    f"dispatched but {self.emissions} materialized")
        if self.journal is not None:
            # Every window is recorded by now (the final drain barriered);
            # close so the last line is durably on disk at process exit.
            self.journal.close()

    def run(self, batches: Iterable[InteractionBatch]) -> "LatestResults":
        start = time.monotonic_ns()
        for users, items, ts in batches:
            self.add_batch(users, items, ts)
        self.finish()
        duration_ms = (time.monotonic_ns() - start) // 1_000_000
        # Reference end-of-run logging shape (FlinkCooccurrences.java:179-181).
        LOG.info("Duration\t%d", duration_ms)
        LOG.info("Accumulator results: %s", self.counters)
        LOG.info("Step timing: %s", self.step_timer.summary())
        # Per-stage busy fractions over the wall clock: a serial run sums
        # to <= ~100%, an overlapped pipelined run exceeds it — the
        # one-line visibility of the pipeline win (ROADMAP: host bubble).
        LOG.info("Stage occupancy: %s",
                 self.step_timer.occupancy(duration_ms / 1000.0))
        # Tail visibility in the summary itself (not just dev-mode lines):
        # the slowest windows, JSON-shaped so log scrapers can parse them.
        LOG.info("Slowest windows: %s",
                 json.dumps(self.step_timer.slowest_as_dicts()))
        self.duration_ms = duration_ms
        return self.latest

    # ------------------------------------------------------------------

    def _drain(self, final: bool) -> None:
        for ts, users, items in self.engine.fire_ready(final=final):
            self.windows_fired += 1
            if self.source is not None:
                # Wire position at the fire boundary (sampling thread —
                # the generator is suspended, so the snapshot is exact):
                # the journal's per-window ingest fields, matched by the
                # checkpoint this same boundary commits.
                health = self.source.ingest_health()
                if health is not None:
                    self._ingest_by_seq[self.windows_fired] = health
            if self._ckpt_dirty is not None:
                # Incremental-checkpoint user feed: the reservoir only
                # mutates for this window's users, so they are exactly
                # the sampler-state dirty set (state/delta.py).
                self._ckpt_dirty.users.note(np.unique(users))
            if self.degrade is not None:
                # Apply the level in force to this window's cuts BEFORE
                # sampling (sampling-thread-only writes; identity at
                # NORMAL). Tumbling mode sheds via the item cut only —
                # the user reservoir's kMax is structural state whose
                # mid-run shrink would corrupt eviction deltas.
                if self.sliding:
                    self.sampler.set_effective_cuts(
                        self.degrade.effective_item_cut(self.config.item_cut),
                        self.degrade.effective_user_cut(self.config.user_cut))
                elif not self.config.skip_cuts:
                    self.item_cut.set_effective_cut(
                        self.degrade.effective_item_cut(self.config.item_cut))
                if self.pipeline is None:
                    # Host backends can shed at the heap itself (fewer
                    # offers kept per row). Serial mode only: in
                    # pipelined mode the scorer worker owns the heap and
                    # a producer-side swap would race it — the _absorb
                    # truncation below sheds for that mode instead.
                    setk = getattr(self.scorer, "set_effective_top_k", None)
                    if setk is not None:
                        setk(self.degrade.effective_top_k(
                            self.config.top_k))
            with clock() as sample_clock:
                # Inside the sample clock on purpose: a delay_ms
                # injected here bills the window's wall time, so chaos
                # tests can manufacture exactly the overloaded windows
                # the degradation/autoscale planes key on. (Crash kinds
                # are indifferent to the clock.)
                if faults.PLAN is not None:
                    faults.PLAN.fire("window_fire", seq=self.windows_fired)
                admit_seconds = 0.0
                if self.sliding:
                    # The sliding sampler folds admission into its own
                    # fire; no separate admission cut to time.
                    pairs = self.sampler.fire(users, items)
                else:
                    # Item cut (or pass-through when --skip-cuts). Timed
                    # separately: the journal's ingest-admission span is
                    # the admission-cut share of sample_seconds.
                    with clock() as admit_clock:
                        if self.config.skip_cuts:
                            sampled = np.ones(len(items), dtype=bool)
                        else:
                            sampled = self.item_cut.fire(items)
                    admit_seconds = admit_clock.seconds
                    # User reservoir.
                    pairs, feedback_items = self.sampler.fire(users, items, sampled)
                    # Feedback decrements before the next window fire
                    # (ItemInteractionCounterTwoInputStreamOperator.java:94-116).
                    if not self.config.skip_cuts and len(feedback_items):
                        self.item_cut.apply_feedback(
                            feedback_items, self.config.development_mode, self.counters)
                if self.pipeline is not None:
                    # Pre-fold on the sampling thread for backends that
                    # accept aggregated deltas — the scorer worker's turn
                    # then starts at slot allocation / COO packing.
                    payload, slot, stall = self._stage(pairs)
            if self.pipeline is not None:
                from .pipeline import StagedWindow

                self.pipeline.submit(StagedWindow(
                    ts=ts, payload=payload, events=len(items),
                    raw_pairs=len(pairs),
                    sample_seconds=sample_clock.seconds, slot=slot,
                    seq=self.windows_fired, stall_seconds=stall,
                    admit_seconds=admit_seconds))
            else:
                # Score on the backend.
                if faults.PLAN is not None:
                    faults.PLAN.fire("scorer_dispatch",
                                     seq=self.windows_fired)
                with clock() as score_clock:
                    window_out: WindowTopK = self.scorer.process_window(ts, pairs)
                # Pipelined backends return the previous window's results;
                # they expose the count actually dispatched for this window.
                self._record_window(WindowStats(
                    timestamp=ts, events=len(items), pairs=len(pairs),
                    rows_scored=getattr(self.scorer, "last_dispatched_rows",
                                        len(window_out)),
                    sample_seconds=sample_clock.seconds,
                    score_seconds=score_clock.seconds),
                    seq=self.windows_fired,
                    admit_seconds=admit_seconds)
                self._absorb(window_out)
            checkpointed = (
                self.config.checkpoint_dir
                and self.config.checkpoint_every_windows > 0
                and self.windows_fired
                % self.config.checkpoint_every_windows == 0)
            if checkpointed:
                # checkpoint() barriers the pipeline first, so the
                # snapshot point is identical to the serial path's.
                self.checkpoint(source=self.source)
            if self.autoscale is not None and self.autoscale.drain:
                # Rescale drain boundary (gang-voted this window, so
                # every worker drains HERE): commit a checkpoint under
                # the epoch protocol — unless the periodic save above
                # already committed this exact boundary — journal the
                # AUTOSCALE record, and take the voluntary exit. The
                # rescale_drain site sits between commit and exit: a
                # crash there dies inside the seam, after the state is
                # durable and before the supervisor relaunches.
                from .robustness.autoscale import RescaleDrain

                if not checkpointed:
                    self.checkpoint(source=self.source)
                req = self.autoscale.drain
                self._journal_autoscale(req, self.windows_fired)
                if faults.PLAN is not None:
                    faults.PLAN.fire("rescale_drain",
                                     seq=self.windows_fired)
                raise RescaleDrain(req, self.windows_fired)
        if final:
            if self.pipeline is not None:
                self.pipeline.barrier()
            # Backends with a result pipeline (device) hold the last window's
            # top-K in flight; drain it.
            self._absorb(self._flush_scorer())

    def _stage(self, pairs):
        """Producer-side staging: fold into a ring slot when the backend
        accepts pre-aggregated deltas; raw pass-through otherwise.
        Returns ``(payload, slot, stall_seconds)`` — the stall is the
        producer's wait for a free ring slot (memory-bound backpressure),
        surfaced per window in the journal."""
        if len(pairs) and getattr(self.scorer, "accepts_aggregated", False):
            payload, slot = self.pipeline.ring.stage(pairs)
            return payload, slot, self.pipeline.ring.last_stall_seconds
        return pairs, None, 0.0

    def _build_spans(self, stats: WindowStats,
                     admit_seconds: float) -> list:
        """Carve one window's wall time into ordered journal span tuples
        ``[stage, start_offset_s, seconds]`` (journal.SPAN_STAGES).

        The five core stages partition ``sample_seconds +
        score_seconds`` exactly by construction: admission is the timed
        cut share of sampling (clamped), uplink-encode / rescore come
        from the scorer's StageClock (clamped into score_seconds), and
        dispatch is the residual. Boundary stages stashed by the
        PREVIOUS window's post-record work (_absorb publish, checkpoint
        commit) ride this record as trailing spans.
        """
        admit = max(0.0, min(admit_seconds, stats.sample_seconds))
        sc = getattr(self.scorer, "stage_clock", None)
        stage_s = sc.seconds if sc is not None else {}
        enc = max(0.0, min(stage_s.get("uplink-encode", 0.0),
                           stats.score_seconds))
        resc = max(0.0, min(stage_s.get("rescore", 0.0),
                            stats.score_seconds - enc))
        disp = max(0.0, stats.score_seconds - enc - resc)
        off = 0.0
        spans = []
        for name, secs in (("ingest-admission", admit),
                           ("sample", stats.sample_seconds - admit),
                           ("uplink-encode", enc),
                           ("dispatch", disp),
                           ("rescore", resc)):
            spans.append([name, round(off, 9), round(secs, 9)])
            off += secs
        pub, self._pending_publish_s = self._pending_publish_s, 0.0
        ck, self._pending_ckpt_s = self._pending_ckpt_s, 0.0
        if pub > 0.0:
            spans.append(["snapshot-publish", round(off, 9),
                          round(pub, 9)])
            off += pub
        if ck > 0.0:
            spans.append(["checkpoint-commit", round(off, 9),
                          round(ck, 9)])
        return spans

    def _stamp(self, rec: dict) -> dict:
        """Stamp the uniform correlation trio (run_id / process_id /
        attempt) every record type carries — cooc-trace's join keys."""
        rec["run_id"] = self.run_id
        rec["process_id"] = self.process_id
        rec["attempt"] = self.attempt
        return rec

    def _record_window(self, stats: WindowStats, seq: int,
                       ring_depth: int = 0,
                       stall_seconds: float = 0.0,
                       admit_seconds: float = 0.0) -> None:
        """One fired window's observability fan-out: step timer ring,
        latency/byte histograms, liveness gauges, and (when attached)
        one flushed journal record.

        Runs on whichever thread scores windows — the caller thread
        serially, the scorer worker pipelined — so the delta snapshots it
        keeps are single-threaded per mode. Checkpoint uplinks happen on
        the sampling thread between fires; their bytes attribute to the
        next window's wire delta (totals stay exact).
        """
        self.step_timer.record(stats)
        wire = LEDGER.snapshot()
        wire_delta = {k: wire[k] - self._prev_wire.get(k, 0) for k in wire}
        self._prev_wire = wire
        self._prev_counters, counter_delta = self.counters.snapshot_and_diff(
            self._prev_counters)
        self._hist_sample.observe(stats.sample_seconds)
        self._hist_score.observe(stats.score_seconds)
        self._hist_total.observe(stats.seconds)
        self._hist_uplink.observe(wire_delta["h2d_bytes"])
        # Dispatch-path split: only backends that expose the flag
        # (DeviceScorer, incl. behind the breaker wrapper) participate.
        fused = getattr(self.scorer, "last_dispatch_fused", None)
        if fused is not None:
            (self._hist_score_fused if fused
             else self._hist_score_chained).observe(stats.score_seconds)
        self._gauge_windows.set(seq)
        self._gauge_last_window.set(time.time())
        level = degrade_events = None
        if self.degrade is not None:
            # Feed the controller this window's health signals; any
            # transition it applies is journaled on this very record.
            level, degrade_events = self.degrade.observe_window(
                wall_seconds=stats.seconds, ring_depth=ring_depth,
                ring_capacity=(self.pipeline.depth
                               if self.pipeline is not None else 0),
                stall_seconds=stall_seconds)
        if self.autoscale is not None:
            # Autoscale vote + pressure beacon (one guarded allgather;
            # every process, every window, in the same order — right
            # after the controller's own vote). The pressure input is
            # the controller's post-exchange gang-max bit.
            self.autoscale.observe(
                seq, stats.seconds,
                self.degrade.overloaded_bit()
                if self.degrade is not None else False)
        spans = self._build_spans(stats, admit_seconds)
        # Ingest plane (partitioned source only): the wire position the
        # sampling thread snapshotted when this seq fired — per-partition
        # offsets + lag into the journal, the worst lag onto the gauge.
        ingest = self._ingest_by_seq.pop(seq, None)
        if ingest is not None:
            REGISTRY.gauge(
                "cooc_ingest_partition_lag",
                help="worst per-partition unread bytes on disk at the "
                     "last fired window").set(max(
                         (p["lag"] for p in ingest["partitions"].values()),
                         default=0))
        # /healthz last_window block (observability/http.py): the same
        # stage carve, visible without pulling the journal. One dict
        # reassignment — HTTP-thread readers see whole snapshots only.
        self.last_window_health = {
            "window_seq": seq,
            "seconds": round(stats.seconds, 6),
            "fused": bool(fused) if fused is not None else None,
            "stages": {name: round(secs, 6)
                       for name, _off, secs in spans},
        }
        if self.journal is not None:
            from .observability.journal import VERSION

            rec = {
                "v": VERSION, "seq": seq, "ts": stats.timestamp,
                "events": stats.events, "pairs": stats.pairs,
                "rows_scored": stats.rows_scored,
                "sample_seconds": round(stats.sample_seconds, 6),
                "score_seconds": round(stats.score_seconds, 6),
                "ring_depth": ring_depth,
                "stall_seconds": round(stall_seconds, 6),
                "wall_unix": round(time.time(), 3),
                "counters": counter_delta,
                "wire": wire_delta,
            }
            self._stamp(rec)
            rec["spans"] = spans
            if ingest is not None:
                # The exactly-once ledger: the restored checkpoint's
                # ingest_offsets section must match the last committed
                # window's fields here (the chaos capstone asserts it).
                rec["ingest_offsets"] = {
                    name: {"byte_offset": p["byte_offset"],
                           "records": p["records"]}
                    for name, p in sorted(ingest["partitions"].items())}
                rec["ingest_lag"] = {
                    name: p["lag"]
                    for name, p in sorted(ingest["partitions"].items())}
            if level is not None:
                rec["degradation_level"] = level
                if degrade_events:
                    rec["degrade_events"] = degrade_events
            if fused is not None:
                rec["fused"] = int(fused)
                reason = getattr(self.scorer, "last_fallback_reason",
                                 None)
                if not fused and reason:
                    rec["fallback_reason"] = reason
            fc = getattr(self.scorer, "fused_compilations", None)
            if fc is not None:
                # Cumulative distinct fused-program shapes: a seam or a
                # fresh bucket shows up as a step in this series.
                rec["fused_compiles"] = int(fc)
            if self.serving is not None:
                # Swap bookkeeping: the snapshot generation and row count
                # in force when this record was written (this window's
                # own swap lands just after, in _absorb — the fields
                # therefore read "serving state the queries saw while
                # this window computed", identically at every pipeline
                # depth).
                rec["snapshot_generation"] = self.serving.generation
                rec["snapshot_rows"] = self.serving.rows
            breaker_state = getattr(self.scorer, "breaker_state", None)
            if breaker_state is not None:
                rec["breaker_state"] = breaker_state
            if self.config.coordinator is not None:
                # Gang forensics: the newest epoch this process has
                # committed when the record was written — a restart's
                # journal shows exactly which epoch the gang resumed
                # from.
                from .state.checkpoint import EPOCH_GAUGE

                rec["epoch"] = int(REGISTRY.gauge(EPOCH_GAUGE).get())
            self.journal.record(rec)

    def _journal_degrade_event(self, event: str) -> None:
        """Append one out-of-band degradation event record (the
        admission-side transition path — see journal.EVENT_SCHEMA)."""
        from .observability.journal import VERSION

        self.journal.record(self._stamp(
            {"v": VERSION, "event": event,
             "wall_unix": round(time.time(), 3),
             "window_seq": self.windows_fired}))

    def _journal_ingest_event(self, event: str) -> None:
        """Append one out-of-band ingest event record (a rewritten
        in-flight file dead-lettered, a partition quarantined, a
        partition reassignment on the rescale seam — journal
        EVENT_SCHEMA; cooc-trace annotates the reassign seams)."""
        if self.journal is None:
            return
        from .observability.journal import VERSION

        self.journal.record(self._stamp(
            {"v": VERSION, "event": event,
             "wall_unix": round(time.time(), 3),
             "window_seq": self.windows_fired}))

    def _journal_autoscale(self, request: dict, window: int) -> None:
        """Append the AUTOSCALE drain record (journal.AUTOSCALE_SCHEMA)
        before the voluntary rescale exit: decision, from/to workers,
        trigger signal and the policy cooldown armed by the decision —
        the flight-recorder proof of every scale-before-shed event."""
        if self.journal is None:
            return
        from .observability.journal import VERSION

        self.journal.record(self._stamp({
            "v": VERSION,
            "autoscale": str(request.get("decision", "grow")),
            "from": int(request.get("from", 0)),
            "to": int(request.get("to", 0)),
            "trigger": str(request.get("trigger", "pressure")),
            "window": int(window),
            "cooldown": int(request.get("cooldown", 0)),
            "wall_unix": round(time.time(), 3),
        }))

    def _flush_scorer(self) -> WindowTopK:
        flush = getattr(self.scorer, "flush", None)
        return flush() if flush is not None else []

    def _absorb(self, window_out: WindowTopK) -> None:
        if self.degrade is not None and len(window_out):
            # Result-side shedding (level SHED_K): narrow the emitted
            # top-K at absorption — a host-side slice, so device
            # backends keep their compiled K and nothing recompiles.
            # Row count is untouched (the emissions balance holds).
            k = self.degrade.effective_top_k(self.config.top_k)
            if k < self.config.top_k:
                if isinstance(window_out, TopKBatch):
                    window_out = window_out.truncated(k)
                else:
                    window_out = [(item, top[:k])
                                  for item, top in window_out]
        if isinstance(window_out, TopKBatch):
            self.latest.absorb_batch(window_out)
            self.emissions += len(window_out)
        else:
            for dense_item, top in window_out:
                self.latest.set_row(dense_item, top)
                self.emissions += 1
        if self.serving is not None:
            # Window boundary: fold this window's rows into the build
            # buffer and swap the next read-optimized snapshot in (one
            # atomic reference assignment — readers never lock, never
            # tear). Runs on the absorbing thread (caller serially, the
            # scorer worker pipelined), same single-writer contract as
            # `latest` absorption.
            with clock() as publish_clock:
                if len(window_out):
                    self.serving.absorb(window_out)
                self.serving.publish()
            # Rides the NEXT window record as a trailing
            # snapshot-publish span (journal.SPAN_STAGES): this swap
            # lands after the current record already flushed.
            self._pending_publish_s += publish_clock.seconds
        if self.on_update is not None and len(window_out):
            self.on_update(window_out)

    def checkpoint(self, source=None) -> None:
        from .state import checkpoint as ckpt

        if self.pipeline is not None:
            # Feedback-edge/result ordering forces a sync here: every
            # submitted window must be scored and absorbed before the
            # snapshot, or the scorer state would lag the sampler's.
            self.pipeline.barrier()
        # Results still in the scorer's fetch pipeline belong to already-
        # processed windows; land them in `latest` before snapshotting.
        self._absorb(self._flush_scorer())
        ckpt.save(self, self.config.checkpoint_dir, source=source)
        if self.journal is not None and ckpt.LAST_COMMIT is not None:
            # One out-of-band checkpoint record per commit (journal
            # CKPT_SCHEMA): the commit-cost trajectory — bytes, wall
            # seconds, full-vs-delta and the chain depth — is flight-
            # recorder data, not just a gauge snapshot.
            from .observability.journal import VERSION

            c = ckpt.LAST_COMMIT
            self.journal.record(self._stamp({
                "v": VERSION, "checkpoint": c["gen"], "kind": c["kind"],
                "bytes": int(c["bytes"]),
                "seconds": round(c["seconds"], 6),
                "chain_len": int(c["chain_len"]),
                "wall_unix": round(time.time(), 3),
                # cooc-trace's window -> generation join for freshness:
                # the fired-window ordinal this commit snapshotted, and
                # the uniform generation alias replica records share.
                "window_seq": self.windows_fired,
                "generation": int(c["gen"]),
            }))
            # The commit's wall seconds ride the next window record as
            # a trailing checkpoint-commit span (journal.SPAN_STAGES).
            self._pending_ckpt_s += float(c["seconds"])

    def restore_rescaled(self, gen: int, writers: int,
                         source=None) -> None:
        """Cross-topology gang restore (the autoscale rescale seam):
        land the generation the topology-aware restore vote agreed on,
        written by a ``writers``-process gang, in THIS differently-
        sized gang (state/checkpoint.restore_rescaled merges the old
        per-process blobs and re-buckets onto this run's shards)."""
        from .state import checkpoint as ckpt

        ckpt.restore_rescaled(self, self.config.checkpoint_dir, gen,
                              writers, source=source)
        # Same post-restore bookkeeping as restore() below.
        if self.serving is not None:
            self.serving.seed(self.latest.snapshot())
        self._prev_counters = self.counters.as_dict()
        self._prev_wire = LEDGER.snapshot()

    def restore(self, source=None) -> None:
        from .state import checkpoint as ckpt

        ckpt.restore(self, self.config.checkpoint_dir, source=source)
        if self.serving is not None:
            # Serve the checkpointed rows immediately: a resumed job must
            # not answer /recommend from an empty table until its first
            # post-restore window fires.
            self.serving.seed(self.latest.snapshot())
        # Re-baseline the journal's deltas: the restored counter totals
        # predate this attempt, and the restore itself ships state up
        # (e.g. the sparse slab's restore upload) — neither may be
        # reported as the first post-restore window's own delta.
        self._prev_counters = self.counters.as_dict()
        self._prev_wire = LEDGER.snapshot()
