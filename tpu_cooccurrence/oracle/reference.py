"""Record-at-a-time float64 oracle for the full co-occurrence pipeline.

This module is the correctness anchor: a deliberately simple, dict-based,
single-threaded implementation of exactly what the reference job computes —
event-time tumbling windows with late-drop, the item interaction cut with
rejection feedback, the per-user reservoir with eviction deltas, windowed
row/row-sum aggregation, watermark-ordered global row-sum application, LLR
rescoring, and per-item top-K. Every production backend (vectorized host
sampler + JAX device scoring, sharded or not) is tested against it.

Semantics are mirrored operator by operator:
  * item cut           — ItemInteractionCounterTwoInputStreamOperator.java:119-143
  * feedback decrement — :94-116 (applied here deterministically between
                         window fires; the reference's in-JVM queue makes the
                         exact arrival interleaving racy by design,
                         FeedbackSource.java:38)
  * user reservoir     — UserInteractionCounterOneInputStreamOperator.java:145-257
  * non-sampled mode   — NonSampledUserInteractionCounterOneInputStreamOperator.java:113-165
  * row aggregation    — ItemRowAggregator.java:15-57
  * row-sum aggregation (zero-suppressed) — RowSumAggregator.java:53-71
  * rescoring          — ItemRowRescorerTwoInputStreamOperator.java:116-241

Known, documented deviations from the reference:
  1. RNG: per-(user, draw) counter-based hash instead of one shared
     java.util.Random (see ``sampling/rng.py``) — order/parallelism
     independent.
  2. Row deltas whose window emitted *no* row-sum update are still scored;
     the reference would leave them buffered forever and fail at close
     (``ItemRowRescorerTwoInputStreamOperator.java:116-139`` only drains
     timestamps present in the row-sum buffer).
  3. Counts are Python ints / int64 (the reference accumulates Java shorts
     and simply ignores overflow).
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict
from typing import Dict, Iterable, List, Optional, Tuple

from ..config import Config
from ..metrics import (
    Counters,
    FEEDBACK_QUEUES,
    ITEM_LATE_ELEMENTS,
    OBSERVED_COOCCURRENCES,
    RESCORED_ITEMS,
    ROW_SUM_PROCESS_WINDOW,
    USER_LATE_ELEMENTS,
)
from ..sampling.rng import reservoir_draw_scalar
from .heap import TopKHeap


@dataclasses.dataclass
class TopKResult:
    """One rescoring emission: ``(timestamp, item, [(other, score) desc])``."""

    timestamp: int
    item: int
    top_k: List[Tuple[int, float]]


def window_start(ts: int, size_ms: int) -> int:
    """Tumbling window start for an event timestamp (Flink semantics,
    offset 0): ``ts - (ts mod size)``."""
    return ts - (ts % size_ms)


class OracleJob:
    """The full pipeline, record-at-a-time.

    Drive it with :meth:`process` / :meth:`finish`, or one-shot with
    :meth:`run`. Emissions are appended to :attr:`results`; the latest
    top-K per item is in :attr:`latest`.
    """

    def __init__(self, config: Config) -> None:
        self.config = config
        self.counters = Counters()
        if not config.skip_cuts:
            # One feedback channel per (single) subtask (reference :109).
            self.counters.add(FEEDBACK_QUEUES, 1)
        self.window_ms = config.window_millis

        # --- watermarking (AscendingTimestampExtractor: wm = max_ts - 1) ---
        self.max_ts_seen: Optional[int] = None

        # --- window buffers: window_start -> list[(user, item, ts)] ---
        self.window_buffers: Dict[int, List[Tuple[int, int, int]]] = defaultdict(list)

        # --- item-cut state (ItemInteractionCounter...) ---
        self.item_interactions: Dict[int, int] = defaultdict(int)

        # --- user state (UserInteractionCounter...) ---
        self.user_history: Dict[int, List[int]] = defaultdict(list)
        self.user_interactions: Dict[int, int] = defaultdict(int)  # accepted (<= kMax)
        self.user_total: Dict[int, int] = defaultdict(int)  # all seen (reservoir denom)
        self.user_draws: Dict[int, int] = defaultdict(int)  # RNG draw counter

        # --- rescorer state (plain maps, like the reference :33-37) ---
        self.item_rows: Dict[int, Dict[int, int]] = defaultdict(dict)
        self.global_row_sums: Dict[int, int] = defaultdict(int)
        self.observed_cooccurrences = 0

        self.results: List[TopKResult] = []
        self.latest: Dict[int, List[Tuple[int, float]]] = {}
        self._heap = TopKHeap(config.top_k)

    # ------------------------------------------------------------------
    # Ingest
    # ------------------------------------------------------------------

    def process(self, user: int, item: int, ts: int) -> None:
        """Feed one interaction in stream order."""
        wm = self.current_watermark()
        if wm is not None and ts <= wm:
            # Late-drop at both cut operators in the reference; one shared
            # buffer here, so count it at both counters for parity.
            self.counters.add(ITEM_LATE_ELEMENTS, 1)
            self.counters.add(USER_LATE_ELEMENTS, 1)
            return

        if self.max_ts_seen is None or ts > self.max_ts_seen:
            old_wm = self.current_watermark()
            self.max_ts_seen = ts
            new_wm = self.current_watermark()
            self.window_buffers[window_start(ts, self.window_ms)].append((user, item, ts))
            if new_wm is not None and new_wm != old_wm:
                self._advance_watermark(new_wm)
        else:
            self.window_buffers[window_start(ts, self.window_ms)].append((user, item, ts))

    def finish(self) -> None:
        """End of stream: Watermark(MAX) fires all remaining windows
        (reference shutdown path, SURVEY §3.5)."""
        self._advance_watermark(float("inf"))

    def run(self, interactions: Iterable[Tuple[int, int, int]]) -> List[TopKResult]:
        for user, item, ts in interactions:
            self.process(user, item, ts)
        self.finish()
        return self.results

    def current_watermark(self) -> Optional[int]:
        if self.max_ts_seen is None:
            return None
        return self.max_ts_seen - 1

    # ------------------------------------------------------------------
    # Window firing
    # ------------------------------------------------------------------

    def _advance_watermark(self, watermark) -> None:
        """Fire all complete windows (max_ts <= watermark) in timestamp order."""
        ready = sorted(
            start for start in self.window_buffers
            if start + self.window_ms - 1 <= watermark
        )
        for start in ready:
            interactions = self.window_buffers.pop(start)
            self._fire_window(start + self.window_ms - 1, interactions)

    def _fire_window(self, ts: int, interactions: List[Tuple[int, int, int]]) -> None:
        # 1. Item cut (or pass-through in skip-cuts mode).
        if self.config.skip_cuts:
            tagged = [(u, i, True) for (u, i, _t) in interactions]
        else:
            tagged = self._item_cut_fire(interactions)

        # 2. User reservoir -> pair deltas + row-sum deltas (+ feedback).
        pair_deltas, row_sum_deltas, feedback = self._user_fire(tagged)

        # 3. Feedback decrements the item counters before the next window
        #    (reference: ItemInteractionCounterTwoInputStreamOperator.java:94-116).
        for item, inc in feedback:
            if self.config.development_mode:
                if self.item_interactions[item] == 0:
                    raise AssertionError(
                        f"Item interactions 0 for item {item}, but received decrement feedback.")
                if inc != -1:
                    raise AssertionError(f"Received unexpected feedback {inc}")
            self.item_interactions[item] += inc

        # 4. Windowed aggregation (ItemRowAggregator / RowSumAggregator).
        row_delta_maps: Dict[int, Dict[int, int]] = defaultdict(dict)
        for (i, j, inc) in pair_deltas:
            row = row_delta_maps[i]
            row[j] = row.get(j, 0) + inc
        row_sum_updates: Dict[int, int] = defaultdict(int)
        for (i, inc) in row_sum_deltas:
            row_sum_updates[i] += inc
        # Zero suppression (RowSumAggregator.java:66-70).
        row_sum_updates = {i: s for i, s in row_sum_updates.items() if s != 0}
        for s in row_sum_updates.values():
            self.counters.add(ROW_SUM_PROCESS_WINDOW, s)

        # 5. Rescoring: row sums applied before scoring this window's rows
        #    (ItemRowRescorerTwoInputStreamOperator.java:116-142).
        for i, s in row_sum_updates.items():
            self.global_row_sums[i] += s
            self.observed_cooccurrences += s
        if row_delta_maps:
            self._score_rows(ts, row_delta_maps)

    # ------------------------------------------------------------------
    # Operators
    # ------------------------------------------------------------------

    def _item_cut_fire(self, interactions) -> List[Tuple[int, int, bool]]:
        """First fMax interactions per item are tagged sample=true
        (ItemInteractionCounterTwoInputStreamOperator.java:129-139)."""
        tagged = []
        f_max = self.config.item_cut
        for (user, item, _ts) in interactions:
            if self.item_interactions[item] < f_max:
                self.item_interactions[item] += 1
                tagged.append((user, item, True))
            else:
                tagged.append((user, item, False))
        return tagged

    def _user_fire(self, tagged):
        """Reservoir sampling with eviction deltas
        (UserInteractionCounterOneInputStreamOperator.java:145-257).

        Returns (pair_deltas [(i, j, +-1)...], row_sum_deltas [(i, d)...],
        feedback [(item, -1)...]). Interactions are processed per user in
        arrival order; RNG draws are keyed (seed, user, draw_index) so the
        grouping order is irrelevant.
        """
        pair_deltas: List[Tuple[int, int, int]] = []
        row_sum_deltas: List[Tuple[int, int]] = []
        feedback: List[Tuple[int, int]] = []
        k_max = self.config.user_cut
        skip_cuts = self.config.skip_cuts

        for (user, item, sample) in tagged:
            self.user_total[user] += 1
            if not sample:
                continue
            history = self.user_history[user]
            if skip_cuts or self.user_interactions[user] < k_max:
                # Append path (:167-205; non-sampled variant :113-165).
                if not skip_cuts:
                    self.user_interactions[user] += 1
                size = len(history)
                if size > 0:
                    row_sum_deltas.append((item, size))
                    for other in history:
                        pair_deltas.append((item, other, 1))
                        pair_deltas.append((other, item, 1))
                        row_sum_deltas.append((other, 1))
                    self.counters.add(OBSERVED_COOCCURRENCES, 2 * size)
                history.append(item)
            else:
                draw = self.user_draws[user]
                self.user_draws[user] += 1
                k = reservoir_draw_scalar(
                    self.config.seed, user, draw, self.user_total[user])
                if k < k_max:
                    # Replace path (:206-245): pair with all slots except k
                    # (so never with the evicted item or itself-at-k).
                    previous = history[k]
                    row_sum_deltas.append((item, k_max - 1))
                    row_sum_deltas.append((previous, -(k_max - 1)))
                    for idx, other in enumerate(history):
                        if idx == k:
                            continue
                        pair_deltas.append((item, other, 1))
                        pair_deltas.append((previous, other, -1))
                        # Partner row sums cancel: +1 + -1 = 0 (:236).
                        pair_deltas.append((other, item, 1))
                        pair_deltas.append((other, previous, -1))
                    history[k] = item
                else:
                    # Reject path (:246-248): decrement feedback to item cut.
                    feedback.append((item, -1))
        return pair_deltas, row_sum_deltas, feedback

    def _score_rows(self, ts: int, row_delta_maps: Dict[int, Dict[int, int]]) -> None:
        """Merge deltas and LLR-score each updated row
        (ItemRowRescorerTwoInputStreamOperator.java:158-228)."""
        import math

        for item in sorted(row_delta_maps):
            delta = row_delta_maps[item]
            self.counters.add(RESCORED_ITEMS, 1)
            row = self.item_rows[item]
            for j, inc in delta.items():
                # addTo semantics: a zero-delta key still materializes an
                # entry (see module docstring, deviation 2 nuance: we keep
                # the entry but score only count != 0 below).
                row[j] = row.get(j, 0) + inc

            row_sum = self.global_row_sums.get(item, 0)

            if self.config.development_mode:
                actual = sum(row.values())
                if actual != row_sum:
                    raise AssertionError(
                        f"Item row {row_sum} does not match actual row sum {actual}")

            self._heap.reset()
            # Sorted column order: deterministic lowest-index tie-breaking
            # (see state/rescorer.py _score_row).
            for other in sorted(row):
                count = row[other]
                if count == 0:
                    continue
                other_sum = self.global_row_sums.get(other, 0)
                k11 = count
                k12 = row_sum - k11
                k21 = other_sum - k11
                k22 = self.observed_cooccurrences + k11 - k12 - k21
                score = _llr_scalar(k11, k12, k21, k22)
                if self.config.development_mode and math.isnan(score):
                    raise AssertionError(
                        f"Score is NaN (item: {item}, otherItem: {other}, "
                        f"cooccurrenceCount: {count}, itemRowSum: {row_sum}, "
                        f"otherItemRowSum: {other_sum}, "
                        f"observedCooccurrences: {self.observed_cooccurrences})")
                self._heap.offer(other, score)

            top = self._heap.sorted_desc()
            self.results.append(TopKResult(ts, item, top))
            self.latest[item] = top


def _xlogx(x: float) -> float:
    import math

    return 0.0 if x == 0 else x * math.log(x)


def _llr_scalar(k11: int, k12: int, k21: int, k22: int) -> float:
    """Float64 scalar LLR, the reference's 9-log entropy form
    (LogLikelihood.java:41-57) including the round-off clamp."""
    row1 = k11 + k12
    row2 = k21 + k22
    all_ = _xlogx(row1 + row2)
    row = all_ - _xlogx(row1) - _xlogx(row2)
    col = all_ - _xlogx(k11 + k21) - _xlogx(k12 + k22)
    matrix = all_ - _xlogx(k11) - _xlogx(k12) - _xlogx(k21) - _xlogx(k22)
    if row + col < matrix:
        return 0.0
    return 2.0 * (row + col - matrix)
