"""Top-K min-heap with the reference's exact selection semantics.

Replicates the observable behavior of the reference's Lucene-style primitive
heap (``IntDoublePriorityQueue.java:48-150``): bounded size K, O(1) access to
the least score, ``add`` while below capacity, ``update`` (replace-min) only
when the caller observed a strictly greater score — the strictness lives in
the caller (``ItemRowRescorerTwoInputStreamOperator.java:218-226``), which we
mirror in :meth:`offer`. Ties therefore keep the earlier-inserted element,
exactly like the reference.

This is *oracle* code (correctness anchor); the device path uses
``jax.lax.top_k`` (see ``ops/device_scorer.py`` / ``parallel/sharded.py``)
whose tie-breaking (lowest index among equals) can differ — tests compare
score multisets.
"""

from __future__ import annotations

import heapq
from typing import Iterator, List, Tuple


class TopKHeap:
    """Bounded min-heap of ``(score, value)`` keeping the K largest scores."""

    def __init__(self, max_size: int) -> None:
        if max_size <= 0:
            raise ValueError(f"{max_size} is <= 0")
        self.max_size = max_size
        # Entries are (score, seq, value); seq makes comparison total and
        # implements "ties keep the earlier insert" when popping the min.
        self._heap: List[Tuple[float, int, int]] = []
        self._seq = 0

    @property
    def size(self) -> int:
        return self._heap.__len__()

    def least_score(self) -> float:
        return self._heap[0][0]

    def least_value(self) -> int:
        return self._heap[0][2]

    def reset(self) -> None:
        """Cheap reuse between rows (reference: ``IntDoublePriorityQueue.java:120-122``)."""
        self._heap.clear()
        self._seq = 0

    def offer(self, value: int, score: float) -> None:
        """Insert following the rescorer's protocol (:218-226): fill to K,
        then replace the min only on strictly greater score."""
        if len(self._heap) < self.max_size:
            self.add(value, score)
        elif score > self.least_score():
            self.update(value, score)

    def add(self, value: int, score: float) -> None:
        heapq.heappush(self._heap, (score, self._next_seq(), value))

    def update(self, value: int, score: float) -> None:
        """Replace the least element (reference: ``IntDoublePriorityQueue.java:146-150``)."""
        heapq.heapreplace(self._heap, (score, self._next_seq(), value))

    def _next_seq(self) -> int:
        # The replace-min path never sees score ties (offer requires strictly
        # greater), so any total order works; insertion order keeps pops
        # deterministic.
        self._seq += 1
        return self._seq

    def __iter__(self) -> Iterator[Tuple[int, float]]:
        """Min-first, remainder unordered (reference iterator contract,
        ``IntDoublePriorityQueue.java:216-242``)."""
        for score, _, value in self._heap:
            yield value, score

    def sorted_desc(self) -> List[Tuple[int, float]]:
        """Descending by score for display (reference:
        ``IntDoublePriorityQueue.java:244-257`` ``sortBySoreDescending``)."""
        return [(v, s) for s, _, v in sorted(self._heap, key=lambda e: (-e[0], e[1]))]
