"""Record-at-a-time oracle for sliding-window (windowed-basket) mode.

Sliding windows are a framework extension (the reference is tumbling-only,
``FlinkCooccurrences.java:139,153``; its operators reject multi-window
assignment, ``UserInteractionCounterOneInputStreamOperator.java:126-128``),
so this oracle pins the *documented* semantics of ``sampling/sliding.py``
end to end, the way :class:`~tpu_cooccurrence.oracle.reference.OracleJob`
pins the reference's tumbling semantics:

  * every event belongs to ``size/slide`` overlapping windows;
  * within each fired window, the caps are per-window: the first ``fMax``
    in-window interactions per item and first ``kMax`` per user survive
    (arrival order; no cross-window feedback);
  * each user's surviving in-window interactions form a basket, and every
    ordered pair of distinct basket positions contributes ``+1``;
  * pair deltas accumulate into the persistent matrix / row sums /
    ``observed``, and every updated row is LLR-rescored with top-K — the
    same downstream semantics as tumbling mode
    (``ItemRowRescorerTwoInputStreamOperator.java:158-241``).

Everything here is scalar, dict-based float64 Python — deliberately naive
and independent of the vectorized window engine, cap ranking, ragged
basket expansion, and device scorers it validates.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Tuple

from ..config import Config
from ..metrics import (
    Counters,
    ITEM_LATE_ELEMENTS,
    OBSERVED_COOCCURRENCES,
    RESCORED_ITEMS,
    ROW_SUM_PROCESS_WINDOW,
    USER_LATE_ELEMENTS,
)
from .heap import TopKHeap
from .reference import _llr_scalar


class SlidingOracleJob:
    """Naive record-at-a-time sliding-mode pipeline (the test oracle)."""

    def __init__(self, config: Config) -> None:
        assert config.slide_millis is not None, "sliding mode only"
        self.config = config
        self.size = config.window_millis
        self.slide = config.slide_millis
        if self.size % self.slide != 0:
            raise ValueError("window size must be a multiple of slide")
        self.counters = Counters()
        self.max_ts_seen: int | None = None
        # window start -> [(user, item)] in arrival order
        self._buffers: Dict[int, List[Tuple[int, int]]] = defaultdict(list)
        # Persistent scoring state (same roles as OracleJob's).
        self.item_rows: Dict[int, Dict[int, int]] = {}
        self.global_row_sums: Dict[int, int] = defaultdict(int)
        self.observed = 0
        self.latest: Dict[int, List[Tuple[int, float]]] = {}
        self._heap = TopKHeap(config.top_k)

    # -- ingest -----------------------------------------------------------

    def process(self, user: int, item: int, ts: int) -> None:
        if self.max_ts_seen is not None and ts < self.max_ts_seen:
            self.counters.add(ITEM_LATE_ELEMENTS, 1)
            self.counters.add(USER_LATE_ELEMENTS, 1)
            return
        self.max_ts_seen = max(ts, self.max_ts_seen or ts)
        # Every window [start, start+size) containing ts, ascending start.
        last_start = (ts // self.slide) * self.slide
        start = last_start - self.size + self.slide
        while start <= last_start:
            if start <= ts < start + self.size:
                self._buffers[start].append((user, item))
            start += self.slide
        self._fire_ready(self.max_ts_seen - 1)

    def finish(self) -> None:
        self._fire_ready(None)

    # -- window fire ------------------------------------------------------

    def _fire_ready(self, watermark: int | None) -> None:
        ready = sorted(
            s for s in self._buffers
            if watermark is None or s + self.size - 1 <= watermark)
        for start in ready:
            self._fire(self._buffers.pop(start))

    def _fire(self, events: List[Tuple[int, int]]) -> None:
        # Per-window caps, record at a time, in arrival order.
        item_seen: Dict[int, int] = defaultdict(int)
        user_seen: Dict[int, int] = defaultdict(int)
        baskets: Dict[int, List[int]] = defaultdict(list)
        for user, item in events:
            keep = True
            if not self.config.skip_cuts:
                keep = (item_seen[item] < self.config.item_cut
                        and user_seen[user] < self.config.user_cut)
                item_seen[item] += 1
                user_seen[user] += 1
            if keep:
                baskets[user].append(item)
        # Basket expansion: every ordered pair of distinct positions.
        window_delta: Dict[int, Dict[int, int]] = defaultdict(
            lambda: defaultdict(int))
        for basket in baskets.values():
            for a, src in enumerate(basket):
                for b, dst in enumerate(basket):
                    if a != b:
                        window_delta[src][dst] += 1
                        self.counters.add(OBSERVED_COOCCURRENCES, 1)
        if not window_delta:
            return
        # Row sums before scoring (watermark ordering), zero-suppressed.
        for src, row_delta in window_delta.items():
            s = sum(row_delta.values())
            if s != 0:
                self.counters.add(ROW_SUM_PROCESS_WINDOW, s)
                self.global_row_sums[src] += s
                self.observed += s
        # Merge + rescore every updated row.
        for src in sorted(window_delta):
            row = self.item_rows.setdefault(src, {})
            for dst, d in window_delta[src].items():
                row[dst] = row.get(dst, 0) + d
            self._score_row(src, row)

    def _score_row(self, item: int, row: Dict[int, int]) -> None:
        self.counters.add(RESCORED_ITEMS, 1)
        row_sum = self.global_row_sums[item]
        self._heap.reset()
        for other in sorted(j for j, c in row.items() if c != 0):
            k11 = row[other]
            k12 = row_sum - k11
            k21 = self.global_row_sums[other] - k11
            k22 = self.observed + k11 - k12 - k21
            self._heap.offer(other, _llr_scalar(k11, k12, k21, k22))
        self.latest[item] = self._heap.sorted_desc()
