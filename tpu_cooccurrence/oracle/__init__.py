"""Correctness-anchor oracle (pure Python/NumPy float64)."""
from .heap import TopKHeap  # noqa: F401
from .reference import OracleJob, TopKResult, window_start  # noqa: F401
