"""The typed TuningParameter registry: every performance knob, declared.

ROADMAP #5's observation: the system has ~a dozen hand-set performance
parameters (pipeline depth, cell/wire dtypes, spill thresholds, compact
ratio, autoscale hysteresis, the ``TPU_COOC_*`` env knobs) and they
lived as scattered literals — an argparse default here, an
``os.environ.get`` fallback there, a pow2 pad floor hardcoded in a
kernel helper. A future autotune plane cannot steer knobs it cannot
enumerate, and cooclint cannot flag an unregistered knob without a
registry to check against. This module is that registry, in the same
shape as ``metrics.CANONICAL_METRICS`` and ``faults.SITES``: a typed
table the owning modules import, and that the analyzer imports as a
truth table (``analysis/rules_tuning.py``).

Contracts enforced by cooclint's ``tuning-registry`` rule:

* every ``TPU_COOC_*`` env var the package reads or mentions must be a
  registered parameter's ``env`` binding;
* package code outside this module never calls
  ``os.environ.get("TPU_COOC_...")`` directly — reads go through
  :func:`env_read` (same semantics as ``os.environ.get``, plus the
  registration check), so the registry always knows the live read
  sites;
* registered flag bindings must exist in ``config.py`` (and dead
  registry rows are flagged from the other side);
* hot-path modules comparing against an integer literal that equals a
  distinctive registered default get flagged — an inlined copy of a
  knob is how a knob stops being tunable.

``config.py`` reads defaults and bounds from here (:func:`default`,
:func:`bounds`), and the README "Tuning parameters" table is generated
by :func:`markdown_table` (pinned by a test, like the CLI-flag table).

Stdlib only — the analyzer imports this under ``JAX_PLATFORMS=cpu``
with no device.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class TuningParameter:
    """One declared knob.

    ``kind`` separates *performance* parameters (bounded, unit-carrying,
    the autotune plane's search space) from *infra* plumbing
    (correlation ids, directories the supervisor wires through the
    environment) — both resolve through the registry, only the former
    belong in a tuning sweep.
    """

    name: str                 # canonical snake_case registry key
    type: str                 # "int" | "float" | "str" | "choice"
    default: object           # effective default (post env/auto logic)
    doc: str
    bounds: Optional[Tuple[Optional[float], Optional[float]]] = None
    choices: Optional[Tuple[str, ...]] = None
    unit: str = ""
    flag: Optional[str] = None   # the config.py CLI binding
    env: Optional[str] = None    # the TPU_COOC_* env binding
    kind: str = "perf"           # "perf" | "infra"

    def parse(self, raw: str) -> object:
        """Typed parse of a flag/env string (used by tooling; the
        owning call sites keep their own nuanced parsing)."""
        if self.type == "int":
            return int(raw)
        if self.type == "float":
            return float(raw)
        return raw

    def validate(self, value: object) -> None:
        """Bounds/choices check; raises ``ValueError`` with the knob's
        name so autotune rejections are self-describing."""
        if self.type == "choice" and self.choices is not None:
            if value not in self.choices:
                raise ValueError(
                    f"{self.name}: {value!r} not in {self.choices}")
            return
        if self.bounds is not None and isinstance(value, (int, float)):
            lo, hi = self.bounds
            if lo is not None and value < lo:
                raise ValueError(
                    f"{self.name}: {value} below minimum {lo}")
            if hi is not None and value > hi:
                raise ValueError(
                    f"{self.name}: {value} above maximum {hi}")


#: name -> parameter. Declaration order is the README table order.
REGISTRY: Dict[str, TuningParameter] = {}


def _register(p: TuningParameter) -> TuningParameter:
    if p.name in REGISTRY:
        raise ValueError(f"duplicate tuning parameter {p.name!r}")
    REGISTRY[p.name] = p
    return p


def get(name: str) -> TuningParameter:
    return REGISTRY[name]


def default(name: str):
    """The registered effective default — ``config.py`` field defaults
    and helper fallbacks read through here."""
    return REGISTRY[name].default


def bounds(name: str) -> Tuple[Optional[float], Optional[float]]:
    b = REGISTRY[name].bounds
    return b if b is not None else (None, None)


def by_env() -> Dict[str, TuningParameter]:
    return {p.env: p for p in REGISTRY.values() if p.env}


def by_flag() -> Dict[str, TuningParameter]:
    return {p.flag: p for p in REGISTRY.values() if p.flag}


def env_read(env_name: str, fallback: Optional[str] = None,
             environ=None) -> Optional[str]:
    """The sanctioned ``TPU_COOC_*`` read: exactly
    ``os.environ.get(env_name, fallback)``, but the variable must be a
    registered binding — an unregistered knob fails here at runtime and
    in cooclint at commit time."""
    if env_name not in by_env():
        raise KeyError(
            f"{env_name} is not a registered TuningParameter env "
            f"binding (declare it in tpu_cooccurrence/tuning.py)")
    return (environ if environ is not None else os.environ).get(
        env_name, fallback)


# -- the declared knobs -------------------------------------------------
# Performance parameters (the autotune search space).

_register(TuningParameter(
    name="pipeline_depth", type="int", default=0, bounds=(0, 2),
    unit="windows", flag="--pipeline-depth",
    doc="Sampled-but-unscored windows in flight: 0 = serial, 1 "
        "overlaps host sampling with device scoring, 2 double-buffers "
        "against per-window jitter. Bit-identical at every depth."))
_register(TuningParameter(
    name="checkpoint_compact_ratio", type="float", default=0.5,
    bounds=(0.0, None), unit="fraction",
    flag="--checkpoint-compact-ratio",
    doc="Delta-chain bytes over base bytes that trigger rewriting a "
        "fresh full base (bounds restore replay length)."))
_register(TuningParameter(
    name="spill_threshold_windows", type="int", default=0,
    bounds=(0, None), unit="windows", flag="--spill-threshold-windows",
    doc="Windows a slab row must sit cold before it may spill to the "
        "host tier; 0 disables tiered state."))
_register(TuningParameter(
    name="spill_target_hbm_frac", type="float", default=0.5,
    bounds=(0.0, 1.0), unit="fraction", flag="--spill-target-hbm-frac",
    doc="Device-slab occupancy the spiller drives toward; spilling "
        "engages only above it."))
_register(TuningParameter(
    name="wire_format", type="choice", default="auto",
    choices=("auto", "raw", "packed"), flag="--wire-format",
    doc="Sparse per-window uplink encoding: packed bit-packs the COO "
        "stream (fewer uplink bytes, decode in the program prologue), "
        "raw ships int32/int64 columns; auto picks by backend."))
_register(TuningParameter(
    name="cell_dtype", type="choice", default="auto",
    choices=("auto", "int32", "int16", "int8"), flag="--cell-dtype",
    doc="Sparse slab count-cell dtype; narrow cells stay exact via "
        "overflow promotion and halve/quarter slab HBM."))
_register(TuningParameter(
    name="count_dtype", type="choice", default="int32",
    choices=("int32", "int16"), flag="--count-dtype",
    doc="Dense C cell dtype; int16 halves HBM and doubles the "
        "dense/sharded vocab ceiling (reference-style wraparound)."))
_register(TuningParameter(
    name="score_ladder", type="int", default=4, bounds=(2, None),
    unit="x per bucket", flag="--score-ladder",
    env="TPU_COOC_SCORE_LADDER",
    doc="Sparse score-bucket ladder base (power of two >= 2): coarser "
        "ladders mean fewer compiled rectangle shapes but more "
        "padding per dispatch."))
_register(TuningParameter(
    name="fixed_score", type="choice", default="auto",
    choices=("auto", "on", "off"), flag="--fixed-score",
    env="TPU_COOC_FIXED_SCORE",
    doc="Sparse fixed-shape scoring (constant per-bucket rectangles); "
        "auto = on for real TPUs when results are deferred."))
_register(TuningParameter(
    name="upload_chunks", type="int", default=1, bounds=(1, None),
    unit="chunks", env="TPU_COOC_UPLOAD_CHUNKS",
    doc="Fixed K-way split of per-window device uploads (tunnel-cliff "
        "lever); 1 = monolithic until the on-chip A/B proves the "
        "split."))
_register(TuningParameter(
    name="upload_chunk_kb", type="float", default=0.0, bounds=(0.0, None),
    unit="KiB", env="TPU_COOC_UPLOAD_CHUNK_KB",
    doc="Adaptive upload chunking: smallest pow2 K bringing each piece "
        "under this size; 0 = off. A set upload_chunks pins K first."))
_register(TuningParameter(
    name="row_index", type="choice", default="bitmap",
    choices=("bitmap", "dense"), env="TPU_COOC_ROW_INDEX",
    doc="Sparse row-registry layout: bitmap+rank directory "
        "(production) or dense reference arrays (A/B baseline)."))
_register(TuningParameter(
    name="donate", type="choice", default="auto",
    choices=("auto", "on", "off"), env="TPU_COOC_DONATE",
    doc="Donate state buffers to the jitted window dispatch (halves "
        "peak HBM); auto = on for non-CPU backends (TFRT CPU "
        "use-after-donate gating)."))
_register(TuningParameter(
    name="pow2_pad_min", type="int", default=256, bounds=(1, None),
    unit="rows",
    doc="Floor of the pow2 pad ladder for dispatch-shape planning "
        "(ops.device_scorer.pad_pow2): the bucket-plan high-water "
        "minimum under which every shape rounds up."))
_register(TuningParameter(
    name="rect_min_rows", type="int", default=256, bounds=(128, None),
    unit="rows",
    doc="Narrowest score-bucket rectangle routed to the fused Pallas "
        "kernel; narrower buckets stay on the XLA path (they don't "
        "tile the 128-lane VPU cleanly and are cheap for XLA anyway)."))
_register(TuningParameter(
    name="autoscale_trip_windows", type="int", default=3,
    bounds=(1, None), unit="windows", flag="--autoscale-trip-windows",
    doc="Consecutive gang-overloaded windows before ScalePolicy may "
        "scale out (hysteresis: trip)."))
_register(TuningParameter(
    name="autoscale_clear_windows", type="int", default=8,
    bounds=(1, None), unit="windows", flag="--autoscale-clear-windows",
    doc="Consecutive gang-idle windows before ScalePolicy may scale "
        "in (hysteresis: clear)."))
_register(TuningParameter(
    name="autoscale_cooldown_windows", type="int", default=8,
    bounds=(0, None), unit="windows",
    flag="--autoscale-cooldown-windows",
    doc="Observed windows ignored after a rescale while the new gang "
        "warms (hysteresis: cooldown)."))
_register(TuningParameter(
    name="collective_timeout_s", type="float", default=0.0,
    bounds=(0.0, None), unit="seconds", flag="--collective-timeout-s",
    env="TPU_COOC_COLLECTIVE_TIMEOUT_S",
    doc="Collective-entry watchdog: a guarded collective blocked this "
        "long exits 75 instead of hanging the gang; 0 = off."))

# Infra plumbing: resolves through the registry (closed TPU_COOC_*
# surface) but is not a tuning dimension.

_register(TuningParameter(
    name="run_id", type="str", default=None, kind="infra",
    flag="--run-id", env="TPU_COOC_RUN_ID",
    doc="Correlation id stamped on journal/trace records; inherited "
        "from a supervising parent, else minted fresh."))
_register(TuningParameter(
    name="attempt", type="int", default=0, kind="infra",
    env="TPU_COOC_ATTEMPT",
    doc="Supervisor restart ordinal stamped on journal records."))
_register(TuningParameter(
    name="gang_dir", type="str", default=None, kind="infra",
    env="TPU_COOC_GANG_DIR",
    doc="Gang heartbeat directory the supervisor shares with its "
        "workers."))
_register(TuningParameter(
    name="supervisor_state", type="str", default=None, kind="infra",
    env="TPU_COOC_SUPERVISOR_STATE",
    doc="Path of the supervisor's crash-loop state file (restart "
        "budget accounting across respawns)."))
_register(TuningParameter(
    name="compile_cache", type="str", default=None, kind="infra",
    env="TPU_COOC_COMPILE_CACHE",
    doc="Persistent XLA compilation-cache directory; empty string "
        "disables."))
_register(TuningParameter(
    name="smoke_events", type="int", default=None, kind="infra",
    env="TPU_COOC_SMOKE_EVENTS",
    doc="CPU-only bench shrink: cap measured events for smoke runs "
        "(ignored with a warning on accelerator backends)."))


def markdown_table(kind: str = "perf") -> str:
    """The README "Tuning parameters" table, generated — the docs
    cannot drift from the registry because a test diffs them."""
    rows = [p for p in REGISTRY.values() if p.kind == kind]
    out = ["| parameter | flag / env | type | default | bounds | unit "
           "| what it tunes |",
           "|---|---|---|---|---|---|---|"]
    for p in rows:
        binding = " / ".join(x for x in (
            f"`{p.flag}`" if p.flag else "",
            f"`{p.env}`" if p.env else "") if x) or "—"
        if p.choices:
            bound = "{" + ", ".join(p.choices) + "}"
        elif p.bounds:
            lo, hi = p.bounds
            bound = f"[{lo if lo is not None else '-inf'}, " \
                    f"{hi if hi is not None else 'inf'}]"
        else:
            bound = "—"
        out.append(
            f"| `{p.name}` | {binding} | {p.type} | "
            f"{p.default if p.default is not None else '—'} | {bound} "
            f"| {p.unit or '—'} | {p.doc} |")
    return "\n".join(out)
