"""tpu-cooccurrence: a TPU-native streaming item-item co-occurrence framework.

A ground-up JAX/XLA rebuild of the capabilities of the reference Flink job
(`uce/flink-cooccurrence`): event-time windowed ingestion of
``(user, item, timestamp)`` streams, per-item/per-user interaction cuts with
reservoir sampling and eviction deltas, an incrementally maintained item x item
co-occurrence matrix with global row sums, log-likelihood-ratio rescoring, and
per-item top-K output — architected TPU-first: windows are micro-batches,
pair-count aggregation is a sharded scatter/segment-sum on device, LLR and
top-K are vectorized XLA kernels, and multi-chip scale-out uses
``shard_map``/``psum`` over an item-sharded mesh instead of a keyed shuffle.

See ``SURVEY.md`` for the structural analysis of the reference this was built
to, with file:line parity citations throughout the code.
"""

__version__ = "0.1.0"

from .config import Backend, Config, WindowUnit  # noqa: F401
from .metrics import Counters  # noqa: F401

__all__ = [
    "Backend",
    "Config",
    "Counters",
    "WindowUnit",
    "__version__",
]
