"""Batch-oriented event-time windowing engine.

Replaces the reference's per-record operator buffering + internal timer
service (``UserInteractionCounterOneInputStreamOperator.java:116-142``,
``ItemInteractionCounterTwoInputStreamOperator.java:70-91``) with a
vectorized micro-batcher:

  * ascending watermarks: ``wm = max_ts_seen - 1`` (Flink
    ``AscendingTimestampExtractor`` semantics,
    ``FlinkCooccurrences.java:221-229``),
  * vectorized late-drop: an event is late iff ``ts <= wm`` at arrival
    (reference :121-123), which for the ascending extractor reduces to
    ``ts < running_max`` — computed with a prefix max, no Python loop,
  * window buffers keyed by window start, fired in timestamp order once the
    watermark passes ``max_timestamp`` (equivalent to the reference's
    event-time timers: a window fires exactly when a later event, or end of
    stream, advances the watermark past its end).

Equivalence argument (why one shared buffer is enough): in the reference the
tagged output of the item-cut fire for window W carries ``W.maxTimestamp`` and
is re-buffered by the user operator into the *same* window W, whose timer
fires on the very watermark that fired the item operator (watermarks traverse
operators in order). So firing item-cut then user-cut per window in timestamp
order is exactly the reference's schedule.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from .assigners import SlidingWindows, TumblingWindows


class WindowEngine:
    """Accumulates interaction batches, drops late events, fires windows.

    With ``slide_ms`` set, windows overlap and each event is buffered into
    every window containing it (``size/slide`` copies — the framework's
    sliding extension; the reference is tumbling-only)."""

    def __init__(self, size_ms: int, slide_ms: Optional[int] = None) -> None:
        if slide_ms is None:
            self.assigner = TumblingWindows(size_ms)
        else:
            self.assigner = SlidingWindows(size_ms, slide_ms)
        self.size_ms = size_ms
        self.slide_ms = slide_ms
        self.max_ts_seen: Optional[int] = None
        # window start -> list of (users, items, ts) array chunks
        self._buffers: Dict[int, List[Tuple[np.ndarray, np.ndarray, np.ndarray]]] = {}

    @property
    def watermark(self) -> Optional[int]:
        return None if self.max_ts_seen is None else self.max_ts_seen - 1

    def add_batch(self, users: np.ndarray, items: np.ndarray, ts: np.ndarray) -> int:
        """Buffer a batch; returns the number of late-dropped events."""
        if len(ts) == 0:
            return 0
        carry = self.max_ts_seen if self.max_ts_seen is not None else np.iinfo(np.int64).min
        running = np.maximum.accumulate(np.concatenate(([carry], ts)))
        prev_max = running[:-1]
        late = ts < prev_max
        n_late = int(late.sum())
        if n_late:
            keep = ~late
            users, items, ts = users[keep], items[keep], ts[keep]
        self.max_ts_seen = int(running[-1])
        if len(ts):
            starts = self.assigner.assign(ts)
            # Post-drop ``ts`` is non-decreasing (every kept event meets the
            # running max), and both assigners are monotone in ts — so each
            # starts column is already sorted: group with a boundary scan,
            # no argsort and no per-window-copy repeat (the former sliding
            # path materialized size/slide copies and stable-sorted them).
            cols = starts.T if starts.ndim == 2 else starts[None, :]
            # Sliding column j of window W covers ts in
            # [W + j*slide, W + (j+1)*slide) (assigners.SlidingWindows:
            # start = last - j*slide), so natural column order appends each
            # window's chunks in arrival order — which the cut operators'
            # per-window ranks depend on.
            for col in cols:
                bounds = np.flatnonzero(col[1:] != col[:-1]) + 1
                lo = 0
                for hi in (*bounds.tolist(), len(col)):
                    self._buffers.setdefault(int(col[lo]), []).append(
                        (users[lo:hi], items[lo:hi], ts[lo:hi]))
                    lo = hi
        return n_late

    def fire_ready(self, final: bool = False) -> Iterator[Tuple[int, np.ndarray, np.ndarray]]:
        """Yield ``(window_max_ts, users, items)`` for every complete window,
        in timestamp order. ``final=True`` == Watermark(MAX_VALUE): fire all
        (reference shutdown, SURVEY §3.5)."""
        wm = np.iinfo(np.int64).max if final else self.watermark
        if wm is None:
            return
        ready = sorted(s for s in self._buffers
                       if self.assigner.max_timestamp(s) <= wm)
        for start in ready:
            chunks = self._buffers.pop(start)
            users = np.concatenate([c[0] for c in chunks])
            items = np.concatenate([c[1] for c in chunks])
            yield self.assigner.max_timestamp(start), users, items
