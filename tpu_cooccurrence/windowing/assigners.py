"""Event-time window assigners.

Flink-subset replacement (SURVEY.md §1): tumbling windows are what the
reference wires everywhere (``FlinkCooccurrences.java:139,153``; operators
reject multi-window assignment, e.g.
``UserInteractionCounterOneInputStreamOperator.java:126-128``). Sliding
windows are a framework extension needed by benchmark config 3.

A window is identified by its start; it covers ``[start, start + size)`` and
its ``max_timestamp`` is ``start + size - 1`` (Flink ``TimeWindow`` semantics).
"""

from __future__ import annotations

import dataclasses
from typing import List

import numpy as np


@dataclasses.dataclass(frozen=True)
class TumblingWindows:
    size_ms: int

    def assign(self, ts: np.ndarray) -> np.ndarray:
        """Vectorized window-start assignment (one window per event)."""
        ts = np.asarray(ts, dtype=np.int64)
        return (ts // self.size_ms) * self.size_ms

    def assign_scalar(self, ts: int) -> List[int]:
        return [int((ts // self.size_ms) * self.size_ms)]

    def max_timestamp(self, start: int) -> int:
        return start + self.size_ms - 1


@dataclasses.dataclass(frozen=True)
class SlidingWindows:
    size_ms: int
    slide_ms: int

    def __post_init__(self):
        if self.size_ms % self.slide_ms != 0:
            raise ValueError(
                f"window size {self.size_ms} must be a multiple of slide {self.slide_ms}")

    @property
    def windows_per_event(self) -> int:
        return self.size_ms // self.slide_ms

    def assign_scalar(self, ts: int) -> List[int]:
        """All window starts containing ts, ascending."""
        last_start = (ts // self.slide_ms) * self.slide_ms
        starts = []
        start = last_start - self.size_ms + self.slide_ms
        while start <= last_start:
            if start + self.size_ms > ts >= start:
                starts.append(int(start))
            start += self.slide_ms
        return starts

    def assign(self, ts: np.ndarray) -> np.ndarray:
        """Vectorized: returns [n_events, windows_per_event] window starts."""
        ts = np.asarray(ts, dtype=np.int64)
        last = (ts // self.slide_ms) * self.slide_ms
        offsets = (np.arange(self.windows_per_event, dtype=np.int64)
                   * self.slide_ms)
        return last[:, None] - offsets[None, :]

    def max_timestamp(self, start: int) -> int:
        return start + self.size_ms - 1
