"""cooclint runner: ``python -m tpu_cooccurrence.analysis``.

Exit codes: 0 = clean (baseline-covered findings allowed), 1 = new
findings, 2 = usage error. The run summary always records the
analyzer's own runtime — the tier-1 lane budget is <10 s and a slow
rule should fail loudly in review, not quietly tax every commit.

``--changed`` is the pre-commit path: per-file rules run only over
files that differ from ``git merge-base HEAD main`` (plus untracked
files), and the whole-program pass-1 index is restored from a
sha256-keyed cache (``.cooclint-cache.json``, git-ignored) so the
cross-module rules still see the full project without re-walking every
unchanged AST. Findings are reported only in the changed files — the
"what did MY edit break" contract.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
from typing import Dict, Optional, Sequence, Set

from . import Analyzer, load_baseline
from .core import default_baseline_path, save_baseline

_CACHE_NAME = ".cooclint-cache.json"
_CACHE_SCHEMA = "cooclint-pass1/1"


def _git(root: str, *args: str) -> Optional[str]:
    try:
        out = subprocess.run(
            ["git", "-C", root, *args], capture_output=True, text=True,
            timeout=10)
    except (OSError, subprocess.TimeoutExpired):
        return None
    return out.stdout if out.returncode == 0 else None


def _changed_files(root: str) -> Optional[Set[str]]:
    """Repo-relative paths differing from ``merge-base HEAD main``
    (committed + staged + worktree) plus untracked files, or None when
    git/merge-base is unavailable (caller falls back to a full run)."""
    base = None
    for ref in ("main", "origin/main"):
        out = _git(root, "merge-base", "HEAD", ref)
        if out:
            base = out.strip()
            break
    if base is None:
        return None
    diff = _git(root, "diff", "--name-only", base)
    untracked = _git(root, "ls-files", "--others", "--exclude-standard")
    if diff is None or untracked is None:
        return None
    return {p.strip() for p in (diff + untracked).splitlines()
            if p.strip()}


def _sha256(text: str) -> str:
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


def _load_pass1_cache(root: str) -> Dict[str, dict]:
    """path -> module index, for files whose content sha still matches
    (the stale majority of a pre-commit run)."""
    try:
        with open(os.path.join(root, _CACHE_NAME),
                  encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    if not isinstance(data, dict) or data.get("schema") != _CACHE_SCHEMA:
        return {}
    cache: Dict[str, dict] = {}
    for path, rec in data.get("modules", {}).items():
        full = os.path.join(root, path)
        try:
            with open(full, encoding="utf-8", errors="replace") as f:
                if _sha256(f.read()) == rec.get("sha256"):
                    cache[path] = rec["index"]
        except OSError:
            continue
    if isinstance(data.get("test_refs"), dict):
        # Joint-sha-validated inside RepoContext.test_referenced_names.
        cache["__test_refs__"] = data["test_refs"]
    return cache


def _save_pass1_cache(root: str, analyzer: Analyzer) -> None:
    repo = getattr(analyzer, "last_repo", None)
    if repo is None or repo._graph is None:
        return
    source_by_path = {c.path: c.source for c in repo.files}
    modules = {}
    for idx in repo.graph.modules.values():
        src = source_by_path.get(idx["path"])
        if src is not None:
            modules[idx["path"]] = {"sha256": _sha256(src),
                                    "index": idx}
    data = {"schema": _CACHE_SCHEMA, "modules": modules}
    if repo._test_refs is not None:
        data["test_refs"] = {"sha256": repo.test_refs_sha,
                             "refs": sorted(repo._test_refs),
                             "strings": sorted(repo._test_strings or ())}
    try:
        with open(os.path.join(root, _CACHE_NAME), "w",
                  encoding="utf-8") as f:
            json.dump(data, f)
            f.write("\n")
    except OSError:
        pass  # a read-only checkout just loses the speedup


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpu_cooccurrence.analysis",
        description=("cooclint: whole-program AST invariant checker "
                     "(thread ownership, transitive jit purity, tuning "
                     "registry, lock discipline, registry drift)"))
    p.add_argument("--root", default=None,
                   help="repo root to scan (default: the checkout "
                        "containing this package)")
    p.add_argument("--format", choices=["text", "json"], default="text",
                   dest="fmt", help="finding output format")
    p.add_argument("--baseline", default=None,
                   help="baseline JSON path (default: the checked-in "
                        "analysis/baseline.json)")
    p.add_argument("--prune-baseline", action="store_true",
                   dest="prune_baseline",
                   help="rewrite the baseline: drop stale entries and "
                        "upgrade matched legacy line-keyed entries to "
                        "the stable rule+symbol fingerprint form")
    p.add_argument("--changed", action="store_true",
                   help="check only files changed vs git merge-base "
                        "with main (pass-1 index restored from the "
                        "sha-keyed cache); falls back to a full run "
                        "outside a git checkout")
    args = p.parse_args(argv)

    root = args.root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    baseline_path = args.baseline or default_baseline_path()
    if args.baseline is not None and not os.path.isfile(baseline_path):
        # A missing DEFAULT baseline means "empty" (the common clean
        # repo); an explicitly named one that does not exist is a typo
        # the operator must hear about, not a silent full re-report.
        print(f"error: --baseline {baseline_path!r} does not exist",
              file=sys.stderr)
        return 2
    try:
        baseline = load_baseline(baseline_path)
    except ValueError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2

    changed_only = pass1_cache = None
    if args.changed:
        changed_only = _changed_files(root)
        if changed_only is not None:
            pass1_cache = _load_pass1_cache(root)

    analyzer = Analyzer(root, baseline=baseline,
                        changed_only=changed_only,
                        pass1_cache=pass1_cache)
    result = analyzer.run()
    if args.changed:
        _save_pass1_cache(root, analyzer)

    if args.prune_baseline and baseline:
        # Upgrade-in-place: a legacy {rule, file, line} entry a current
        # finding matched becomes {rule, file, symbol} (line drift can
        # no longer orphan it); stale entries are dropped.
        by_line_key = {("line", f.rule, f.file, f.line): f
                       for f in result.baselined}
        stale_keys = set()
        for e in result.stale_baseline:
            if e.get("symbol"):
                stale_keys.add(("symbol", e["rule"], e["file"],
                                e["symbol"]))
            else:
                stale_keys.add(("line", e["rule"], e["file"],
                                int(e["line"])))
        kept = []
        for e in baseline:
            if e.get("symbol"):
                key = ("symbol", e["rule"], e["file"], e["symbol"])
            else:
                key = ("line", e["rule"], e["file"], int(e["line"]))
            if key in stale_keys:
                continue
            match = by_line_key.get(key)
            if match is not None and match.symbol:
                e = {k: v for k, v in e.items() if k != "line"}
                e["symbol"] = match.symbol
            kept.append(e)
        save_baseline(kept, baseline_path)

    if args.fmt == "json":
        print(json.dumps(result.to_dict(), indent=2, sort_keys=True))
    else:
        for f in result.findings:
            print(str(f))
        for e in result.stale_baseline:
            tag = ("pruned" if args.prune_baseline
                   else "stale baseline entry (--prune-baseline "
                        "candidate)")
            print(f"{e['file']}:{e.get('line', e.get('symbol'))}: "
                  f"{e['rule']}: {tag}")
        scope = (f" ({len(changed_only)} changed)"
                 if changed_only is not None else "")
        print(f"cooclint: {len(result.findings)} new finding(s), "
              f"{len(result.baselined)} baselined, "
              f"{len(result.stale_baseline)} stale baseline entr(y/ies) "
              f"across {result.files_scanned} files{scope} in "
              f"{result.elapsed_seconds:.2f}s")
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())
