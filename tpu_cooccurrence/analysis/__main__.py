"""cooclint runner: ``python -m tpu_cooccurrence.analysis``.

Exit codes: 0 = clean (baseline-covered findings allowed), 1 = new
findings, 2 = usage error. The run summary always records the
analyzer's own runtime — the tier-1 lane budget is <10 s and a slow
rule should fail loudly in review, not quietly tax every commit.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

from . import Analyzer, load_baseline
from .core import default_baseline_path, save_baseline


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpu_cooccurrence.analysis",
        description=("cooclint: AST-based invariant checker (lock "
                     "discipline, jit purity, registry drift, native "
                     "dtype boundaries)"))
    p.add_argument("--root", default=None,
                   help="repo root to scan (default: the checkout "
                        "containing this package)")
    p.add_argument("--format", choices=["text", "json"], default="text",
                   dest="fmt", help="finding output format")
    p.add_argument("--baseline", default=None,
                   help="baseline JSON path (default: the checked-in "
                        "analysis/baseline.json)")
    p.add_argument("--prune-baseline", action="store_true",
                   dest="prune_baseline",
                   help="rewrite the baseline dropping entries no "
                        "current finding matches (stale entries)")
    args = p.parse_args(argv)

    root = args.root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    baseline_path = args.baseline or default_baseline_path()
    if args.baseline is not None and not os.path.isfile(baseline_path):
        # A missing DEFAULT baseline means "empty" (the common clean
        # repo); an explicitly named one that does not exist is a typo
        # the operator must hear about, not a silent full re-report.
        print(f"error: --baseline {baseline_path!r} does not exist",
              file=sys.stderr)
        return 2
    try:
        baseline = load_baseline(baseline_path)
    except ValueError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    result = Analyzer(root, baseline=baseline).run()

    if args.prune_baseline and result.stale_baseline:
        stale_keys = {(e["rule"], e["file"], int(e["line"]))
                      for e in result.stale_baseline}
        kept = [e for e in baseline
                if (e["rule"], e["file"], int(e["line"]))
                not in stale_keys]
        save_baseline(kept, baseline_path)

    if args.fmt == "json":
        print(json.dumps(result.to_dict(), indent=2, sort_keys=True))
    else:
        for f in result.findings:
            print(str(f))
        for e in result.stale_baseline:
            tag = ("pruned" if args.prune_baseline
                   else "stale baseline entry (--prune-baseline "
                        "candidate)")
            print(f"{e['file']}:{e['line']}: {e['rule']}: {tag}")
        print(f"cooclint: {len(result.findings)} new finding(s), "
              f"{len(result.baselined)} baselined, "
              f"{len(result.stale_baseline)} stale baseline entr(y/ies) "
              f"across {result.files_scanned} files in "
              f"{result.elapsed_seconds:.2f}s")
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())
