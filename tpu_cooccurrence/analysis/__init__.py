"""cooclint: repo-native static analysis for conventions nothing else enforces.

PRs 1-3 grew the codebase around invariants that exist only as prose and
one-off tests: locked shared state (``Counters`` / ``TransferLedger`` /
``LatestResults``) must be touched through its own methods or under its
``_lock`` across the pipeline's two threads; jit-compiled hot paths must
stay free of host syncs; donated device buffers must not be read after
the dispatch that consumed them; and the string registries (metric
names, fault sites, CLI flags vs ``config.py`` fields vs docs) must stay
in sync. Each of these already caused a real bug (the PR-2
``TransferLedger``/``Counters.merge`` races) or is pinned by a single
brittle test. This package makes them fail in tier-1 at commit time,
not on a TPU mid-soak.

Since PR 19 the analyzer is a two-pass whole-program engine: pass 1
(:mod:`.graph`) builds a project-wide symbol table, call graph and
thread-root table once (cached per file by content sha256 in
``.cooclint-cache.json``, which is what makes ``--changed`` runs
sub-second); pass 2 is the rules, which query those cross-module facts
instead of re-deriving them per file. Findings carry a stable
fingerprint (rule + qualified enclosing symbol) so baseline entries
survive line drift.

Layout:

* :mod:`.core` — the ``ast``-based framework: file walker, rule
  registry, :class:`~.core.Finding`, per-line
  ``# cooclint: disable=<rule>`` suppressions and the checked-in
  ``baseline.json`` for grandfathered findings;
* :mod:`.graph` — pass 1: the project symbol table, call graph
  (attribute calls resolved by receiver class, denylisted duck edges),
  thread-root labelling (``threading.Thread`` spawn sites, HTTP
  ``do_*`` self-concurrent handlers, ``main``), and per-class
  attribute-write-site extraction;
* :mod:`.rules_threads` — graph-backed thread-ownership analysis (an
  attribute written from two mutually exclusive thread roots with no
  lock and no ``# thread-owner:`` annotation is a race; rediscovers
  both PR-2 races from the pre-fix code);
* :mod:`.rules_tuning` — the typed ``TuningParameter`` registry
  (``tpu_cooccurrence/tuning.py``) enforcement: every ``TPU_COOC_*``
  env read goes through ``tuning.env_read``, unregistered knobs and
  dead registry rows are findings, and distinctive registered defaults
  re-inlined as literals in hot-path modules are warnings;
* :mod:`.rules_lock` — lock discipline on the shared-state classes and
  annotation requirements for new locks in worker code paths;
* :mod:`.rules_jit` — jit/device hygiene (host syncs inside jitted
  functions, donated-buffer reuse);
* :mod:`.rules_journal` — journal schema-registry drift (every key a
  ``journal.record(...)`` writer emits must be in the journal schema
  tables, documented in the ARCHITECTURE journal table and referenced
  under ``tests/`` — cooc-trace and validate_record only see
  registered fields);
* :mod:`.rules_registry` — registry drift (metric names, fault sites,
  CLI flags vs config fields vs docs);
* :mod:`.rules_native` — dtype discipline at the native (ctypes) and
  fold boundaries;
* :mod:`.rules_degrade` — degradation-level registry drift (every
  ``DegradationLevel`` member documented, journaled, and in the
  ARCHITECTURE level table);
* :mod:`.rules_wire` — wire/checkpoint codec round-trip evidence and
  narrow-dtype cast guards (every encoder needs its decoder + a test
  referencing both; every int16/int8 cast needs a visible overflow
  guard);
* :mod:`.rules_gang` — gang-robustness invariants (host-level
  collectives must ride the watchdog wrappers in
  ``parallel/distributed.py``; the gang chaos sites must stay
  registered and fired);
* :mod:`.rules_fused` — Pallas kernel registry drift (every
  ``pallas_call`` entry point under ``tpu_cooccurrence/`` parity-tested
  from ``tests/`` and listed in the ARCHITECTURE kernel table) plus the
  fused fallback-reason registry (every
  ``_fallback_chained("<reason>")`` literal quoted in the ARCHITECTURE
  fused fallback table and asserted by a test);
* :mod:`.rules_serving` — HTTP route registry drift (every route in
  ``observability/http.py``'s ``ROUTE_METRICS`` needs a
  CANONICAL_METRICS latency metric, a README mention and a tests/
  reference; unregistered route literals are flagged);
* :mod:`.rules_state` — state-store registry drift (every
  ``StateStore`` implementation in ``state/store.py`` needs a
  checkpoint round-trip test reference under ``tests/`` and a row in
  the ARCHITECTURE state-store table);
* :mod:`.rules_ckpt` — checkpoint-format drift (every field written
  into generation meta or delta headers needs a restore-side reader in
  its module and a ``tests/`` round-trip reference — the two ends of
  the incremental-checkpoint format cannot drift silently);
* :mod:`.rules_ingest` — ingest offset-codec drift (every field
  written into a source's offset section — the files in-flight guard,
  the partitioned per-partition cursors — needs a restore-side reader
  in its module and a ``tests/`` round-trip reference: a writer-only
  offset field silently turns exactly-once resume into replay);
* :mod:`.rules_autoscale` — scale-policy registry drift (every
  ``ScalePolicy`` implementation in ``robustness/autoscale.py`` needs
  a ``tests/`` reference and a row in the ARCHITECTURE scale-policy
  table — a rescale trigger nobody exercises tears down live gangs on
  untested hysteresis);
* ``__main__`` — the runner: ``python -m tpu_cooccurrence.analysis``
  exits 1 on non-baseline findings (``--format json|text``).

The analyzer imports only stdlib plus the repo's own stdlib-only
registry modules (``metrics``, ``robustness.faults``,
``observability.registry``) — it runs under ``JAX_PLATFORMS=cpu`` with
no device and never imports jax.
"""

from __future__ import annotations

from .core import (  # noqa: F401
    Analyzer,
    AnalysisResult,
    Finding,
    RULES,
    analyze_source,
    load_baseline,
)

# Importing the rule modules registers their rules in RULES.
from . import rules_autoscale  # noqa: F401,E402
from . import rules_ckpt  # noqa: F401,E402
from . import rules_degrade  # noqa: F401,E402
from . import rules_fused  # noqa: F401,E402
from . import rules_gang  # noqa: F401,E402
from . import rules_ingest  # noqa: F401,E402
from . import rules_jit  # noqa: F401,E402
from . import rules_journal  # noqa: F401,E402
from . import rules_lock  # noqa: F401,E402
from . import rules_native  # noqa: F401,E402
from . import rules_registry  # noqa: F401,E402
from . import rules_serving  # noqa: F401,E402
from . import rules_state  # noqa: F401,E402
from . import rules_threads  # noqa: F401,E402
from . import rules_tuning  # noqa: F401,E402
from . import rules_wire  # noqa: F401,E402

__all__ = [
    "Analyzer",
    "AnalysisResult",
    "Finding",
    "RULES",
    "analyze_source",
    "load_baseline",
]
