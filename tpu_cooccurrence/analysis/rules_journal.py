"""Journal schema-registry guard (baseline-free).

``journal-schema-registry`` — the journal is the fleet's flight
recorder and, since the tracing plane landed, also cooc-trace's input
format: three consumers (``validate_record``, the offline analyzer, the
operators reading ``docs/ARCHITECTURE.md``) all believe the schema
tables in ``observability/journal.py`` are the whole truth. Nothing
structural stops a writer from emitting a key the tables never heard
of: with validation off the record flushes fine, cooc-trace silently
ignores the field, and the ARCHITECTURE table quietly lies.

The rule walks every ``*.journal.record(...)`` call site in the package
(dict-literal args, args wrapped in a stamping helper such as
``self._stamp({...})``, and ``record(rec)`` where ``rec`` is built up
by dict-literal assignment plus constant subscript stores) and requires
every emitted string key to

* appear in one of the journal schema tables (``SCHEMA`` /
  ``EVENT_SCHEMA`` / ``CKPT_SCHEMA`` / ``AUTOSCALE_SCHEMA`` /
  ``REPLICA_SCHEMA`` — imported directly, so the registry can never
  drift from what the analyzer enforces),
* be documented in the ARCHITECTURE journal table (backtick-quoted in
  ``docs/ARCHITECTURE.md``), and
* appear as a string constant somewhere under ``tests/`` — the fixture
  reference that pins the field's semantics
  (``tests/test_trace.py`` keeps the canonical registry list).

Baseline-free: a new journal field lands in the same PR as its schema
entry, its docs row and its test, or tier-1 fails. The docs and tests
legs are scope-guarded on those trees being present in the scan (pure
fixture snippets exercise the schema-membership leg only).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Set, Tuple

from .core import (FileContext, Finding, RepoContext, Rule, dotted_name,
                   register)
from ..observability.journal import (AUTOSCALE_SCHEMA, CKPT_SCHEMA,
                                     EVENT_SCHEMA, REPLICA_SCHEMA, SCHEMA)

#: Union of every schema table's keys — the registry this rule enforces.
_SCHEMA_KEYS: Set[str] = (set(SCHEMA) | set(EVENT_SCHEMA)
                          | set(CKPT_SCHEMA) | set(AUTOSCALE_SCHEMA)
                          | set(REPLICA_SCHEMA))

#: Where the operator-facing journal table lives.
_DOCS_PATH = "docs/ARCHITECTURE.md"


def _dict_keys(node: ast.Dict) -> "Iterable[Tuple[str, int]]":
    for k in node.keys:
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            yield k.value, k.lineno


def _name_keys(ctx: FileContext, var: str) -> "Iterable[Tuple[str, int]]":
    """Keys flowing into a ``record(rec)``-style Name argument: dict
    literals assigned to ``var`` plus constant subscript stores on it,
    module-wide (this also catches stamping helpers whose parameter
    shares the name — ``def _stamp(self, rec): rec["run_id"] = ...``)."""
    for node in ctx.nodes(ast.Assign):
        for tgt in node.targets:
            if (isinstance(tgt, ast.Name) and tgt.id == var
                    and isinstance(node.value, ast.Dict)):
                yield from _dict_keys(node.value)
            if (isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == var
                    and isinstance(tgt.slice, ast.Constant)
                    and isinstance(tgt.slice.value, str)):
                yield tgt.slice.value, tgt.lineno


def _emitted_keys(ctx: FileContext) -> Dict[str, int]:
    """``{key: first emission line}`` for every ``*.journal.record(...)``
    call site in one module."""
    out: Dict[str, int] = {}
    # Cheap substring gate: every matched call site's dotted name ends
    # with "journal.record", so the source text must contain it.
    if "journal.record" not in ctx.source or ctx.tree is None:
        return out
    for node in ctx.nodes(ast.Call):
        if not node.args:
            continue
        name = dotted_name(node.func)
        if name is None or not name.endswith("journal.record"):
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Call):
            # Stamping wrapper: journal.record(self._stamp({...})) /
            # journal.record(self._stamp(rec)).
            arg = arg.args[0] if arg.args else arg
        if isinstance(arg, ast.Dict):
            for key, line in _dict_keys(arg):
                out.setdefault(key, line)
        elif isinstance(arg, ast.Name):
            for key, line in _name_keys(ctx, arg.id):
                out.setdefault(key, line)
    return out


def _tests_constants(repo: RepoContext) -> Set[str]:
    return repo.test_string_constants()


@register
class JournalSchemaRegistryRule(Rule):
    name = "journal-schema-registry"
    description = ("every key a journal writer emits must be in the "
                   "journal schema tables, documented in the "
                   "ARCHITECTURE journal table, and referenced under "
                   "tests/")

    def finalize(self, repo: RepoContext) -> Iterable[Finding]:
        emitters = [(ctx, _emitted_keys(ctx))
                    for ctx in repo.package_files()]
        emitters = [(ctx, keys) for ctx, keys in emitters if keys]
        # Scope guard: a scan root with no journal writer at all (other
        # rules' fixture repos, partial trees) is silent.
        if not emitters:
            return
        docs = next((c for c in repo.files if c.path == _DOCS_PATH), None)
        has_tests = any(c.path.startswith("tests/")
                        for c in repo.python_files())
        tests = _tests_constants(repo) if has_tests else None
        for ctx, keys in emitters:
            for key, line in sorted(keys.items()):
                if key not in _SCHEMA_KEYS:
                    yield Finding(
                        rule=self.name, file=ctx.path, line=line,
                        message=(f"journal writer emits key {key!r} "
                                 f"that no journal schema table "
                                 f"declares — add it to the matching "
                                 f"*_SCHEMA in observability/journal.py "
                                 f"(validate_record and cooc-trace "
                                 f"only see registered fields)"))
                if docs is not None and f"`{key}`" not in docs.source:
                    yield Finding(
                        rule=self.name, file=ctx.path, line=line,
                        message=(f"journal key {key!r} is emitted but "
                                 f"undocumented — add a `{key}` row to "
                                 f"the journal table in {_DOCS_PATH}"))
                if tests is not None and key not in tests:
                    yield Finding(
                        rule=self.name, file=ctx.path, line=line,
                        message=(f"journal key {key!r} has no tests/ "
                                 f"reference — pin it in "
                                 f"tests/test_trace.py's "
                                 f"JOURNAL_SCHEMA_KEYS registry"))
