"""Pallas-kernel registry drift.

Every Pallas program entry point in ``ops/pallas_score.py`` — a
module-level function whose body issues a ``pl.pallas_call`` — is a
compiled device artifact whose correctness rests entirely on a parity
test (kernel output vs the XLA/oracle formulation; TPU behavior cannot
be unit-tested any other way on this CPU-only CI) and whose existence
is operator-facing contract: the ARCHITECTURE "Pallas kernel table"
names each one with its role and routing rule. A kernel added without
both is exactly how the fused-window plane would rot — a Mosaic
miscompile class (see the float32-id workaround in
``_score_topk_kernel``) that nothing ever compares against a reference
implementation, documented nowhere an operator looks.

Coverage is one call hop wide: a private kernel core (e.g.
``_pallas_topk_gathered``) counts as parity-tested when a module-level
wrapper that calls it is referenced from ``tests/`` — the wrappers are
the public surface the tests drive. AST-checked (nothing imported) and
baseline-free by construction, mirroring the ``degrade-registry`` rule.

Scope (extended for the fused-sparse plane): EVERY module under
``tpu_cooccurrence/`` is scanned for ``pallas_call`` entry points, not
just ``ops/pallas_score.py`` — a fused-sparse program that grew its own
kernel in ``state/`` must register a parity surface and an ARCHITECTURE
kernel-table row exactly like the ops-layer kernels (wrapper coverage
stays one hop wide *within the defining module*). The sharded scorer in
``parallel/sharded_sparse.py`` is covered by the same sweep — its fused
program bodies call the shared kernels through module-level wrappers.

A second registry rides the same module (``fused-fallback-registry``):
every *chained-fallback reason* the sharded fused window can take — the
string literal at a ``_fallback_chained("<reason>")`` call site — is an
operator-facing contract twice over: the ARCHITECTURE fallback table
names it (an operator reading ``last_fallback_reason`` in the journal
must find it documented), and a test exercises it (a fallback branch
nothing ever drives is exactly the untested-escape-hatch class the
fused plane's bit-identity claim cannot survive). Baseline-free,
AST-only, fixture-tested in ``tests/test_cooclint.py``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from .core import (
    FileContext,
    Finding,
    RepoContext,
    Rule,
    register,
)

_PALLAS_PATH = "tpu_cooccurrence/ops/pallas_score.py"
_PKG_PREFIX = "tpu_cooccurrence/"
_ARCH_PATH = "docs/ARCHITECTURE.md"


def _module_functions(tree: ast.Module) -> Dict[str, ast.FunctionDef]:
    return {node.name: node for node in tree.body
            if isinstance(node, ast.FunctionDef)}


def _called_names(fn: ast.FunctionDef) -> Set[str]:
    """Last segments of every callee in ``fn``'s body (``pl.pallas_call``
    -> ``pallas_call``; ``foo(...)`` -> ``foo``)."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute):
            out.add(f.attr)
        elif isinstance(f, ast.Name):
            out.add(f.id)
    return out


def _kernel_entry_points(tree: ast.Module) -> Dict[str, ast.FunctionDef]:
    """Module-level functions that issue a ``pallas_call`` directly."""
    return {name: fn for name, fn in _module_functions(tree).items()
            if "pallas_call" in _called_names(fn)}


def _test_referenced_names(repo: RepoContext) -> Set[str]:
    """Every identifier the test suite mentions (names, attributes,
    imported aliases) — the "registered parity test" evidence."""
    return repo.test_referenced_names()


@register
class FusedKernelRegistryRule(Rule):
    name = "pallas-kernel-registry"
    description = ("every Pallas kernel entry point under "
                   "tpu_cooccurrence/ needs a registered parity test "
                   "(referenced from tests/, directly or via a calling "
                   "wrapper in the same module) and a row in the "
                   "ARCHITECTURE Pallas kernel table")

    def finalize(self, repo: RepoContext) -> Iterable[Finding]:
        # No anchor-file gate: a vanished/unparseable ops/pallas_score.py
        # must not silently waive the rule for kernels elsewhere in the
        # package (the state-store-registry rule's vanished-ARCHITECTURE
        # precedent) — the package-wide scan below is the whole gate.
        sources = [c for c in repo.python_files()
                   if c.path.startswith(_PKG_PREFIX)
                   and "pallas_call" in c.source  # cheap pre-filter
                   and c.tree is not None]
        per_file = [(ctx, _kernel_entry_points(ctx.tree))
                    for ctx in sources]
        if not any(kernels for _ctx, kernels in per_file):
            # The registry-gone finding is anchored on the kernel home
            # module existing at all — fixture repos for OTHER rules
            # carry no ops/pallas_score.py and are not kernel registries.
            if any(c.path == _PALLAS_PATH for c in repo.files):
                yield Finding(
                    rule=self.name, file=_PALLAS_PATH, line=1,
                    message="no pallas_call entry points found (the "
                            "kernel registry this rule guards is gone)")
            return
        refs = _test_referenced_names(repo)
        arch = next((c for c in repo.files if c.path == _ARCH_PATH), None)
        for ctx, kernels in per_file:
            if not kernels:
                continue
            functions = _module_functions(ctx.tree)
            # Wrappers: module-level functions that call a kernel entry
            # point (one hop within the defining module — the public
            # surface parity tests drive).
            callers: Dict[str, Set[str]] = {k: set() for k in kernels}
            for name, fn in functions.items():
                for callee in _called_names(fn) & set(kernels):
                    if name != callee:
                        callers[callee].add(name)
            for kernel, fn in sorted(kernels.items()):
                covered = kernel in refs or bool(callers[kernel] & refs)
                if not covered:
                    yield Finding(
                        rule=self.name, file=ctx.path, line=fn.lineno,
                        message=(f"Pallas kernel entry point {kernel!r} "
                                 f"has no registered parity test: nothing "
                                 f"under tests/ references it (or a "
                                 f"wrapper that calls it) — a kernel "
                                 f"nothing compares against a reference "
                                 f"is a silent-miscompile risk"))
                if arch is not None and kernel not in arch.source:
                    yield Finding(
                        rule=self.name, file=ctx.path, line=fn.lineno,
                        message=(f"Pallas kernel entry point {kernel!r} "
                                 f"is not in {_ARCH_PATH} — add it to "
                                 f"the Pallas kernel table"))


_SHARDED_PATH = "tpu_cooccurrence/parallel/sharded_sparse.py"


def _fallback_sites(
        tree: ast.Module) -> Tuple[List[Tuple[int, str]], List[int]]:
    """``_fallback_chained("<reason>")`` call sites: (line, reason) for
    literal reasons, plus lines whose reason is NOT a string literal
    (those defeat static registry checking and are findings
    themselves)."""
    literal: List[Tuple[int, str]] = []
    dynamic: List[int] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "_fallback_chained"):
            continue
        if (node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            literal.append((node.lineno, node.args[0].value))
        else:
            dynamic.append(node.lineno)
    return literal, dynamic


@register
class FusedFallbackRegistryRule(Rule):
    name = "fused-fallback-registry"
    description = ("every chained-fallback reason literal at a "
                   "_fallback_chained(...) call site must be quoted in "
                   "the ARCHITECTURE fallback table and asserted by a "
                   "test under tests/")

    def finalize(self, repo: RepoContext) -> Iterable[Finding]:
        sites: List[Tuple[FileContext, int, str]] = []
        any_call_sites = False
        for ctx in repo.package_files():
            if "_fallback_chained" not in ctx.source or ctx.tree is None:
                continue
            literal, dynamic = _fallback_sites(ctx.tree)
            any_call_sites = any_call_sites or bool(literal or dynamic)
            for lineno in dynamic:
                yield Finding(
                    rule=self.name, file=ctx.path, line=lineno,
                    message=("_fallback_chained reason is not a string "
                             "literal — the fallback-reason registry is "
                             "only checkable when every call site names "
                             "its reason inline"))
            for lineno, reason in literal:
                sites.append((ctx, lineno, reason))
        if not any_call_sites:
            # Anchor: the sharded scorer defining _fallback_chained with
            # zero call sites means the fallback taxonomy this rule
            # guards is gone (every fused gate must route through it).
            src = next((c for c in repo.files
                        if c.path == _SHARDED_PATH), None)
            if (src is not None and src.tree is not None
                    and "_fallback_chained" in src.source):
                yield Finding(
                    rule=self.name, file=_SHARDED_PATH, line=1,
                    message=("_fallback_chained is defined but never "
                             "called with a reason literal (the "
                             "fallback-reason registry this rule guards "
                             "is gone)"))
            return
        if not sites:
            return
        arch = next((c for c in repo.files if c.path == _ARCH_PATH), None)
        if arch is None:
            yield Finding(
                rule=self.name, file=sites[0][0].path, line=1,
                message=(f"{_ARCH_PATH} not found — the fused fallback "
                         f"table this rule checks reasons against is "
                         f"gone"))
        test_literals: Set[str] = repo.test_string_constants()
        seen: Set[str] = set()
        for ctx, lineno, reason in sites:
            if reason in seen:
                continue
            seen.add(reason)
            # The table quotes reasons backticked — plain prose mention
            # of a generic word like "promotion" is not registry
            # evidence.
            if arch is not None and f"`{reason}`" not in arch.source:
                yield Finding(
                    rule=self.name, file=ctx.path, line=lineno,
                    message=(f"fallback reason {reason!r} is not in the "
                             f"{_ARCH_PATH} fused fallback table — an "
                             f"operator reading last_fallback_reason "
                             f"must find it documented"))
            if reason not in test_literals:
                yield Finding(
                    rule=self.name, file=ctx.path, line=lineno,
                    message=(f"fallback reason {reason!r} is never "
                             f"asserted under tests/ — a fallback "
                             f"branch nothing drives is an untested "
                             f"escape hatch in the fused plane's "
                             f"bit-identity contract"))
