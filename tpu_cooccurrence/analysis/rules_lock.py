"""Lock discipline on the pipeline's shared state.

The pipelined execution mode (``pipeline.py``) runs two threads — the
sampling/caller thread and the scorer worker — against three shared
registries: ``metrics.Counters``, ``observability.TransferLedger`` and
``state.results.LatestResults``. Each guards its mutable state with a
``_lock``; the PR-2 races happened exactly where code outside those
classes touched the raw attributes (an unlocked ``+=`` on the ledger's
byte totals, ``Counters.merge`` folding a mid-add snapshot). These rules
make that shape un-committable:

* ``lock-discipline`` — any attribute read/write of a protected class's
  internal state outside the owning class body and outside a
  ``with <obj>._lock:`` block is a finding. Attribute *names* identify
  the state (``_counters``, ``h2d_bytes``, ``_ptr_batch``, ...): the
  names are distinctive enough that a non-owner touching one is either
  the bug we hunt or close enough to deserve a justification comment.
* ``lock-annotation`` — a new ``threading.Lock()``/``RLock()`` acquired
  in the worker code paths (``pipeline.py`` / ``job.py``) must carry a
  ``lock-ordering:`` annotation (same or preceding line) stating its
  acquisition order relative to the registries' locks or its timeout
  strategy — the two-thread deadlock the PR-1/PR-2 design avoided by
  never holding two locks at once.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from .core import FileContext, Finding, Rule, dotted_name, register

#: Owning class -> the internal-state attribute names only it (or a
#: ``with x._lock`` block) may touch. Names are chosen to be distinctive
#: (``events`` is deliberately absent: too generic to key on).
PROTECTED_STATE = {
    "Counters": {"_counters"},
    "TransferLedger": {"h2d_bytes", "d2h_bytes", "h2d_calls", "d2h_calls",
                       "uplink_raw_bytes", "uplink_enc_bytes",
                       "basket_h2d_bytes", "basket_h2d_calls"},
    "LatestResults": {"_batches", "_ptr_batch", "_ptr_row", "_total_rows"},
}

_ALL_PROTECTED: Set[str] = set().union(*PROTECTED_STATE.values())

#: Files whose module-level worker threads make a bare new lock a
#: deadlock hazard (the ``lock-annotation`` rule's scope).
_WORKER_FILES = ("tpu_cooccurrence/pipeline.py", "tpu_cooccurrence/job.py")

_ANNOTATION_TOKEN = "lock-ordering:"


def _with_lock_spans(tree: ast.Module) -> List[tuple]:
    """``(start, end, lock_base)`` line spans of ``with <expr>._lock``
    (or ``.acquire()``-style context) bodies. ``lock_base`` is the
    dotted name of the object whose lock is held (``self``, ``ledger``,
    ...) — the exemption is object-sensitive: holding ``a._lock`` says
    nothing about ``b``'s state (the PR-2 ``Counters.merge`` race was
    exactly self's lock over *other*'s dict)."""
    spans = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        for item in node.items:
            expr = item.context_expr
            # unwrap `with obj._lock:` and `with obj._lock.acquire_timeout(...)`
            target = expr.func if isinstance(expr, ast.Call) else expr
            name = dotted_name(target) or ""
            if name.endswith("._lock") or "._lock." in name:
                base = name.split("._lock")[0]
                spans.append((node.lineno,
                              max(getattr(n, 'lineno', node.lineno)
                                  for n in ast.walk(node)),
                              base))
                break
    return spans


@register
class LockDisciplineRule(Rule):
    name = "lock-discipline"
    description = ("internal state of Counters/TransferLedger/"
                   "LatestResults touched outside the owning class and "
                   "outside a `with obj._lock:` block")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.path.startswith("tpu_cooccurrence/"):
            return ()
        tree = ctx.tree
        if tree is None:
            return ()
        # Line spans of owning-class bodies in this file.
        owner_spans = []
        for node in ast.walk(tree):
            if (isinstance(node, ast.ClassDef)
                    and node.name in PROTECTED_STATE):
                owner_spans.append(
                    (node.name, node.lineno,
                     max(getattr(n, "lineno", node.lineno)
                         for n in ast.walk(node))))
        lock_spans = _with_lock_spans(tree)
        out = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Attribute):
                continue
            if node.attr not in _ALL_PROTECTED:
                continue
            base = dotted_name(node.value)
            # `self._counters` inside class Counters et al. is the
            # owner's own (locked-method) access — but ONLY on `self`:
            # inside `Counters.merge`, `other._counters` is a foreign
            # instance and holding self's lock does not cover it (the
            # PR-2 merge race, object-sensitively).
            owner = next((name for name, lo, hi in owner_spans
                          if lo <= node.lineno <= hi
                          and node.attr in PROTECTED_STATE[name]), None)
            if owner is not None and base == "self":
                continue
            # A surrounding `with <base>._lock:` covers accesses on
            # that same object only; an unresolvable lock base (a
            # complex expression) is trusted, an identified-but-
            # different one is not.
            if any(lo <= node.lineno <= hi
                   and (lock_base == "" or base is None
                        or base == lock_base)
                   for lo, hi, lock_base in lock_spans):
                continue
            out.append(Finding(
                rule=self.name, file=ctx.path, line=node.lineno,
                message=(f"access to protected attribute "
                         f"{node.attr!r} on {base or 'an expression'} "
                         f"outside its owning class's self-methods and "
                         f"outside a `with {base or 'obj'}._lock:` "
                         f"block (two-thread pipeline state; use "
                         f"snapshot()/locked methods)")))
        return out


@register
class LockAnnotationRule(Rule):
    name = "lock-annotation"
    description = ("new threading.Lock/RLock in pipeline.py/job.py "
                   "worker paths without a `lock-ordering:` annotation")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.path not in _WORKER_FILES:
            return ()
        tree = ctx.tree
        if tree is None:
            return ()
        out = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            if name not in ("threading.Lock", "threading.RLock",
                            "Lock", "RLock"):
                continue
            if not name.startswith("threading.") and not any(
                    "import threading" in ln or "from threading" in ln
                    for ln in ctx.lines):
                continue  # a local Lock() that isn't threading's
            nearby = ctx.lines[max(0, node.lineno - 2):node.lineno]
            if any(_ANNOTATION_TOKEN in ln for ln in nearby):
                continue
            out.append(Finding(
                rule=self.name, file=ctx.path, line=node.lineno,
                message=(f"{name}() acquired in a two-thread worker "
                         f"module without a `{_ANNOTATION_TOKEN}` "
                         f"annotation (state its order relative to the "
                         f"registry locks, or its timeout strategy, on "
                         f"the same or preceding line)")))
        return out
