"""Lock discipline on the pipeline's shared state.

The pipelined execution mode (``pipeline.py``) runs two threads — the
sampling/caller thread and the scorer worker — against shared
registries (``metrics.Counters``, ``observability.TransferLedger``,
``state.results.LatestResults``, ...). Each guards its mutable state
with a ``_lock``; the PR-2 races happened exactly where code outside
those classes touched the raw attributes (an unlocked ``+=`` on the
ledger's byte totals, ``Counters.merge`` folding a mid-add snapshot).
These rules make that shape un-committable:

* ``lock-discipline`` — any attribute read/write of a protected class's
  internal state outside the owning class body and outside a
  ``with <obj>._lock:`` block is a finding. The protected map is
  **derived from the package source itself**, not hardcoded: a class
  that creates ``self._lock`` owns every attribute it writes under
  ``with self._lock:`` — declaring the lock *is* declaring the
  discipline, so a new registry class is covered the moment it is
  written, and the map can never rot the way the old three-class list
  would have. Detection keys on attribute *names* (so a single-file
  fixture with ``ledger.h2d_bytes += n`` is judged without seeing the
  owning class), which is why only distinctive names participate:
  an attr claimed by two owners, or a bare dictionary word
  (``count``, ``max``, ``events``), is dropped as too generic to key
  on.
* ``lock-annotation`` — a new ``threading.Lock()``/``RLock()`` acquired
  in the worker code paths (``pipeline.py`` / ``job.py``) must carry a
  ``lock-ordering:`` annotation (same or preceding line) stating its
  acquisition order relative to the registries' locks or its timeout
  strategy — the two-thread deadlock the PR-1/PR-2 design avoided by
  never holding two locks at once.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Set

from .core import FileContext, Finding, Rule, dotted_name, register

#: The package whose source the protected-state map is derived from —
#: always the real installed tpu_cooccurrence, even when the analyzer
#: runs over a fixture repo (fixtures exercise the *rule*, and the rule
#: keys on the production registries' attribute names).
_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_LOCK_CTORS = ("threading.Lock", "threading.RLock", "Lock", "RLock")


def _creates_lock(cls: ast.ClassDef) -> bool:
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call) and \
                (dotted_name(node.value.func) or "") in _LOCK_CTORS:
            if any(isinstance(t, ast.Attribute) and t.attr == "_lock"
                   for t in node.targets):
                return True
    return False


def _locked_self_writes(cls: ast.ClassDef) -> Set[str]:
    """Attribute names the class writes on ``self`` inside its own
    ``with self._lock:`` spans — the state the lock exists for."""
    attrs: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        if not any((dotted_name(
                i.context_expr.func if isinstance(i.context_expr,
                                                  ast.Call)
                else i.context_expr) or "").startswith("self._lock")
                for i in node.items):
            continue
        for sub in ast.walk(node):
            tgt = None
            if isinstance(sub, ast.Attribute) and isinstance(
                    sub.ctx, ast.Store):
                tgt = sub
            elif isinstance(sub, ast.Subscript) and isinstance(
                    sub.ctx, ast.Store) and isinstance(
                    sub.value, ast.Attribute):
                tgt = sub.value  # self._counters[k] = v
            if tgt is not None and isinstance(
                    tgt.value, ast.Name) and tgt.value.id == "self":
                attrs.add(tgt.attr)
    return attrs


_DERIVED: Optional[Dict[str, Set[str]]] = None


def protected_state() -> Dict[str, Set[str]]:
    """Owning class -> internal-state attribute names only it (or a
    ``with x._lock`` block) may touch. Derived once per process by
    parsing the installed package source; two distinctiveness gates
    keep name-keyed detection sound: an attr written under lock by two
    different owners is ambiguous, and a name without an underscore
    (``count``, ``sum``, ``events``) is a dictionary word that
    legitimately appears on unrelated objects everywhere."""
    global _DERIVED
    if _DERIVED is not None:
        return _DERIVED
    owners: Dict[str, Set[str]] = {}
    for dirpath, dirnames, files in os.walk(_PKG_ROOT):
        dirnames[:] = [d for d in dirnames
                       if d not in ("analysis", "__pycache__")]
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            try:
                with open(os.path.join(dirpath, fname),
                          encoding="utf-8") as fh:
                    src = fh.read()
                if "_lock" not in src:
                    continue  # cheap pre-filter: nothing to derive
                tree = ast.parse(src)
            except (OSError, SyntaxError):
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.ClassDef) and _creates_lock(node):
                    attrs = _locked_self_writes(node)
                    if attrs:
                        owners.setdefault(node.name, set()).update(attrs)
    claims: Dict[str, int] = {}
    for attrs in owners.values():
        for a in attrs:
            claims[a] = claims.get(a, 0) + 1
    derived = {}
    for cls, attrs in owners.items():
        keep = {a for a in attrs if claims[a] == 1 and "_" in a}
        if keep:
            derived[cls] = keep
    _DERIVED = derived
    return derived


_ALL: Optional[Set[str]] = None


def _all_protected() -> Set[str]:
    global _ALL
    if _ALL is None:
        state = protected_state()
        _ALL = set().union(*state.values()) if state else set()
    return _ALL

#: Files whose module-level worker threads make a bare new lock a
#: deadlock hazard (the ``lock-annotation`` rule's scope).
_WORKER_FILES = ("tpu_cooccurrence/pipeline.py", "tpu_cooccurrence/job.py")

_ANNOTATION_TOKEN = "lock-ordering:"


def _with_lock_spans(ctx: FileContext) -> List[tuple]:
    """``(start, end, lock_base)`` line spans of ``with <expr>._lock``
    (or ``.acquire()``-style context) bodies. ``lock_base`` is the
    dotted name of the object whose lock is held (``self``, ``ledger``,
    ...) — the exemption is object-sensitive: holding ``a._lock`` says
    nothing about ``b``'s state (the PR-2 ``Counters.merge`` race was
    exactly self's lock over *other*'s dict)."""
    spans = []
    for node in ctx.nodes(ast.With, ast.AsyncWith):
        for item in node.items:
            expr = item.context_expr
            # unwrap `with obj._lock:` and `with obj._lock.acquire_timeout(...)`
            target = expr.func if isinstance(expr, ast.Call) else expr
            name = dotted_name(target) or ""
            if name.endswith("._lock") or "._lock." in name:
                base = name.split("._lock")[0]
                spans.append((node.lineno,
                              node.end_lineno or node.lineno,
                              base))
                break
    return spans


@register
class LockDisciplineRule(Rule):
    name = "lock-discipline"
    description = ("internal state of a lock-owning registry class "
                   "(derived from the package source: writes under "
                   "`with self._lock`) touched outside the owning "
                   "class and outside a `with obj._lock:` block")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.path.startswith("tpu_cooccurrence/"):
            return ()
        tree = ctx.tree
        if tree is None:
            return ()
        PROTECTED_STATE = protected_state()
        all_protected = _all_protected()
        # Line spans of owning-class bodies in this file.
        owner_spans = [
            (node.name, node.lineno, node.end_lineno or node.lineno)
            for node in ctx.nodes(ast.ClassDef)
            if node.name in PROTECTED_STATE]
        lock_spans = _with_lock_spans(ctx)
        out = []
        for node in ctx.nodes(ast.Attribute):
            if node.attr not in all_protected:
                continue
            base = dotted_name(node.value)
            # `self._counters` inside class Counters et al. is the
            # owner's own (locked-method) access — but ONLY on `self`:
            # inside `Counters.merge`, `other._counters` is a foreign
            # instance and holding self's lock does not cover it (the
            # PR-2 merge race, object-sensitively).
            owner = next((name for name, lo, hi in owner_spans
                          if lo <= node.lineno <= hi
                          and node.attr in PROTECTED_STATE[name]), None)
            if owner is not None and base == "self":
                continue
            # A surrounding `with <base>._lock:` covers accesses on
            # that same object only; an unresolvable lock base (a
            # complex expression) is trusted, an identified-but-
            # different one is not.
            if any(lo <= node.lineno <= hi
                   and (lock_base == "" or base is None
                        or base == lock_base)
                   for lo, hi, lock_base in lock_spans):
                continue
            out.append(Finding(
                rule=self.name, file=ctx.path, line=node.lineno,
                message=(f"access to protected attribute "
                         f"{node.attr!r} on {base or 'an expression'} "
                         f"outside its owning class's self-methods and "
                         f"outside a `with {base or 'obj'}._lock:` "
                         f"block (two-thread pipeline state; use "
                         f"snapshot()/locked methods)")))
        return out


@register
class LockAnnotationRule(Rule):
    name = "lock-annotation"
    description = ("new threading.Lock/RLock in pipeline.py/job.py "
                   "worker paths without a `lock-ordering:` annotation")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.path not in _WORKER_FILES:
            return ()
        tree = ctx.tree
        if tree is None:
            return ()
        out = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            if name not in ("threading.Lock", "threading.RLock",
                            "Lock", "RLock"):
                continue
            if not name.startswith("threading.") and not any(
                    "import threading" in ln or "from threading" in ln
                    for ln in ctx.lines):
                continue  # a local Lock() that isn't threading's
            nearby = ctx.lines[max(0, node.lineno - 2):node.lineno]
            if any(_ANNOTATION_TOKEN in ln for ln in nearby):
                continue
            out.append(Finding(
                rule=self.name, file=ctx.path, line=node.lineno,
                message=(f"{name}() acquired in a two-thread worker "
                         f"module without a `{_ANNOTATION_TOKEN}` "
                         f"annotation (state its order relative to the "
                         f"registry locks, or its timeout strategy, on "
                         f"the same or preceding line)")))
        return out
