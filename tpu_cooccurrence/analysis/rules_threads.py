"""thread-ownership: shared mutable state must have one writing thread.

The generalization that subsumes the old hardcoded lock-discipline
class list: instead of enumerating which classes hold races (the PR-2
postmortem list — ``TransferLedger``, ``Counters``), derive the race
condition itself from the whole-program graph. Pass 1 records every
attribute/global write site with its exemption flags; pass 2 asks, for
each piece of state, *which thread roots can be executing each write*.

A finding requires two write sites with **mutually exclusive** root
sets — each reachable from a thread the other is not. That is the shape
of both historical races (a spawned worker writing ledger fields the
main thread also writes) and deliberately does *not* fire on
mode-dependent sharing: ``job.py`` is reachable from ``main`` (serial
mode) *and* the pipeline worker (pipelined mode), but every write site
there has the same ``{main, worker}`` root set — the modes are
exclusive at runtime, and no single run has two threads in those
writes. Requiring set-difference in both directions encodes exactly
"two different threads, same state, same run".

A site is exempt when the write is inside a ``with *._lock`` span
(``L``), carries / sits under a ``# thread-owner:`` annotation (``A``),
or happens in ``__init__`` (``I`` — construction precedes publication).
A single unlocked site reachable from a *self-concurrent* root (HTTP
handlers: one thread per request) is also flagged — that root races
with itself.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from .core import Finding, RepoContext, Rule, register
from .graph import OWNER_TOKEN  # noqa: F401  (re-export for tests)


def _fmt_site(path: str, caller: str, line: int) -> str:
    return f"{path}:{line} in `{caller}`"


@register
class ThreadOwnershipRule(Rule):
    name = "thread-ownership"
    description = (
        "mutable module/instance state written from two mutually "
        "exclusive thread roots (or one self-concurrent root) without "
        "`with *._lock` or a `# thread-owner:` annotation")

    def finalize(self, repo: RepoContext):
        graph = repo.graph
        path_of = {m: idx["path"] for m, idx in graph.modules.items()}
        findings: List[Finding] = []
        for (cls, attr), sites in sorted(graph.attr_write_sites().items()):
            findings.extend(self._judge(
                graph, path_of, f"{cls}.{attr}", sites))
        for (mod, name), sites in sorted(graph.global_write_sites().items()):
            findings.extend(self._judge(
                graph, path_of, f"{mod}:{name}",
                [(mod, caller, line, flags)
                 for caller, line, flags in sites]))
        return findings

    def _judge(self, graph, path_of: Dict[str, str], state: str,
               sites: List[Tuple[str, str, int, str]]) -> List[Finding]:
        live = []
        for mod, caller, line, flags in sites:
            if "L" in flags or "A" in flags or "I" in flags:
                continue
            roots = graph.roots_of(f"{mod}:{caller}")
            if roots:
                live.append((mod, caller, line, roots))
        for i in range(len(live)):
            for j in range(i + 1, len(live)):
                mi, ci, li, ri = live[i]
                mj, cj, lj, rj = live[j]
                only_i, only_j = ri - rj, rj - ri
                if only_i and only_j:
                    # anchor on the non-main side when there is one —
                    # the spawned writer is the actionable site
                    if graph.MAIN in only_i:
                        (mi, ci, li, ri, only_i,
                         mj, cj, lj, rj, only_j) = (
                            mj, cj, lj, rj, only_j,
                            mi, ci, li, ri, only_i)
                    return [Finding(
                        rule=self.name, file=path_of.get(mi, mi),
                        line=li,
                        message=(
                            f"`{state}` is written from thread root(s) "
                            f"{sorted(only_i)} here and from "
                            f"{sorted(only_j)} at "
                            f"{_fmt_site(path_of.get(mj, mj), cj, lj)} "
                            f"— hold the owner's lock or annotate the "
                            f"single writer with `# thread-owner: "
                            f"<why>`"))]
        # a single site needs strong-edge evidence: a duck edge is a
        # guess, and a guess may widen a real two-site conflict but
        # must not manufacture a one-site finding on its own
        for mod, caller, line, roots in live:
            conc = sorted(r for r in graph.strong_roots_of(
                f"{mod}:{caller}") if graph.is_concurrent_root(r))
            if conc:
                return [Finding(
                    rule=self.name, file=path_of.get(mod, mod),
                    line=line,
                    message=(
                        f"`{state}` is written under self-concurrent "
                        f"root(s) {conc} (one thread per request) "
                        f"without a lock — two requests race on it"))]
        return []
