"""Registry drift: string registries that must stay in sync.

Three registries hold names that appear as plain strings across the
repo, each previously guarded by at most one brittle test:

* **metric names** — every ``cooc_*`` gauge/histogram name emitted by a
  ``REGISTRY.gauge(...)``/``REGISTRY.histogram(...)`` call (or quoted in
  docs) must be in
  :data:`~tpu_cooccurrence.observability.registry.CANONICAL_METRICS`;
  counter names passed to ``counters.add/get`` must be constants of
  ``metrics.py``. A misspelled name creates a parallel series the
  dashboards never see.
* **fault sites** — every ``fire("<site>")`` call, spec string, or
  ``--inject-fault`` doc example must name a key of
  :data:`~tpu_cooccurrence.robustness.faults.SITES`, and every
  registered site must actually be fired somewhere in the package
  (no dead entries). Generalizes (and is wrapped by) the PR-3 static
  consistency test.
* **CLI flags** — every ``--flag`` registered by ``add_argument`` in
  ``config.py`` must map to a ``Config`` dataclass field and be
  mentioned in README.md or docs/, so a new flag cannot land
  undocumented or orphaned from config state.

The truth tables are imported from the modules that own them (all
stdlib-only), so the analyzer can never enforce a stale copy.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional, Set

from .. import metrics as _metrics_mod
from ..observability.registry import CANONICAL_METRICS
from ..robustness.faults import KINDS, SITES
from .core import (
    FileContext,
    Finding,
    RepoContext,
    Rule,
    dotted_name,
    register,
    string_constants,
)

#: Every string-valued module constant of ``metrics.py`` — the full
#: legal counter-name set (CANONICAL_COUNTERS plus dev-mode names).
KNOWN_COUNTER_NAMES: Set[str] = {
    v for k, v in vars(_metrics_mod).items()
    if k.isupper() and isinstance(v, str)}

#: A complete metric name: ``cooc_`` then word chars, not ending in
#: ``_`` and not followed by more name chars or a glob ``*`` — so doc
#: prose like ``cooc_window_*`` (a family glob) is not a name.
_METRIC_NAME_RE = re.compile(r"cooc_[a-z0-9_]*[a-z0-9](?![a-z0-9_*])")

_SPEC_RE = re.compile(rf"^([a-z_]+)(?::\d+)?:(?:{'|'.join(KINDS)})")
#: Quoted spec embedded anywhere in raw text ("pass \"x:3:crash\" to
#: ..."), the shape docstrings and docs use — the AST constant check
#: above it only sees specs that ARE the whole literal.
_TEXT_SPEC_RE = re.compile(
    rf'"([a-z_]+)(?::\d+)?:(?:{"|".join(KINDS)})')
#: Doc/CLI examples: ``--inject-fault <site>[:...]`` — the captured name
#: must be followed by ``:`` (spec tail) or ``"`` (bare site in an argv
#: list) so prose like "--inject-fault spec fires once" doesn't match.
_MD_INJECT_RE = re.compile(r'--inject-fault[="\s,]+([a-z_]+)[:"]')
_MD_FIRE_RE = re.compile(r'\bfire\(\s*"([a-z_]+)"')


def _is_fire_call(node: ast.Call) -> bool:
    """``plan.fire(...)`` or a bare imported ``fire(...)``."""
    return ((isinstance(node.func, ast.Attribute)
             and node.func.attr == "fire")
            or (isinstance(node.func, ast.Name)
                and node.func.id == "fire"))


@register
class MetricNameRule(Rule):
    name = "metric-name"
    description = ("cooc_* metric names and counter-name literals must "
                   "be registered in CANONICAL_METRICS / metrics.py")

    def _check_py(self, ctx: FileContext) -> Iterable[Finding]:
        tree = ctx.tree
        if tree is None:
            return
        # cooc_* literals anywhere in package source (registration call
        # sites, constants, docstrings — a doc name that drifts is the
        # same operator-facing lie as a misregistered gauge).
        for lineno, value in ctx.strings():
            for m in _METRIC_NAME_RE.finditer(value):
                if m.group(0) not in CANONICAL_METRICS:
                    yield Finding(
                        rule=self.name, file=ctx.path, line=lineno,
                        message=(f"metric name {m.group(0)!r} is not in "
                                 f"observability.registry."
                                 f"CANONICAL_METRICS — register it or "
                                 f"fix the spelling"))
        # Counter-name literals at counters.add/get call sites.
        for node in ctx.nodes(ast.Call):
            if not (isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("add", "get")):
                continue
            recv = dotted_name(node.func.value) or ""
            if not recv.endswith("counters"):
                continue
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                name = node.args[0].value
                if name not in KNOWN_COUNTER_NAMES:
                    yield Finding(
                        rule=self.name, file=ctx.path, line=node.lineno,
                        message=(f"counter name {name!r} is not a "
                                 f"metrics.py constant — add it there "
                                 f"(and to CANONICAL_COUNTERS if it "
                                 f"must appear on /metrics at zero)"))

    def _check_md(self, ctx: FileContext) -> Iterable[Finding]:
        for i, line in enumerate(ctx.lines, start=1):
            for m in _METRIC_NAME_RE.finditer(line):
                if m.group(0) not in CANONICAL_METRICS:
                    yield Finding(
                        rule=self.name, file=ctx.path, line=i,
                        message=(f"doc quotes metric name "
                                 f"{m.group(0)!r} which is not in "
                                 f"CANONICAL_METRICS (stale doc or "
                                 f"unregistered metric)"))

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.path.startswith("tpu_cooccurrence/") and ctx.is_python:
            return self._check_py(ctx)
        if ctx.path.endswith(".md"):
            return self._check_md(ctx)
        return ()

    def finalize(self, repo: RepoContext) -> Iterable[Finding]:
        # Reverse direction (mirrors the fault-site dead-entry check):
        # every CANONICAL_METRICS name must appear as a literal
        # somewhere in package source — a registration call site or a
        # named constant. A name in the table that nothing emits is a
        # dead registry row blessing stale docs.
        anchor = "tpu_cooccurrence/observability/registry.py"
        if not any(c.path == anchor for c in repo.files):
            return
        emitted: Set[str] = set()
        for ctx in repo.package_files():
            tree = ctx.tree
            if tree is None:
                continue
            # The CANONICAL_METRICS definition itself must not count as
            # an emission, or the reverse check is vacuous (every entry
            # trivially "appears" at its own definition). Skip literals
            # inside that assignment's span in the anchor file.
            skip_spans = []
            if ctx.path == anchor:
                for node in ctx.nodes(ast.Assign):
                    if any(isinstance(t, ast.Name)
                           and t.id == "CANONICAL_METRICS"
                           for t in node.targets):
                        skip_spans.append(
                            (node.lineno,
                             node.end_lineno or node.lineno))
            for lineno, value in ctx.strings():
                if any(lo <= lineno <= hi for lo, hi in skip_spans):
                    continue
                emitted.update(m.group(0)
                               for m in _METRIC_NAME_RE.finditer(value))
        for name in sorted(CANONICAL_METRICS - emitted):
            yield Finding(
                rule=self.name, file=anchor, line=1,
                message=(f"CANONICAL_METRICS entry {name!r} is never "
                         f"emitted anywhere in the package (dead "
                         f"registry entry — remove it, and fix any "
                         f"docs still quoting it)"))


@register
class FaultSiteRule(Rule):
    name = "fault-site"
    description = ("fault-site strings must be keys of faults.SITES; "
                   "every registered site must be fired in the package")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.is_python:
            tree = ctx.tree
            if tree is None:
                return
            flagged_lines = set()
            for node in ctx.nodes(ast.Call):
                # fire("<site>", ...) call sites (package and tests) —
                # both plan.fire(...) and a bare imported fire(...).
                if (_is_fire_call(node)
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    site = node.args[0].value
                    if site not in SITES:
                        flagged_lines.add(node.lineno)
                        yield Finding(
                            rule=self.name, file=ctx.path,
                            line=node.lineno,
                            message=(f"fire({site!r}) names an "
                                     f"unregistered fault site "
                                     f"(register it in faults.SITES)"))
            # Spec strings ("site[:seq]:kind") in any literal.
            for lineno, value in ctx.strings():
                m = _SPEC_RE.match(value)
                if m and m.group(1) not in SITES:
                    flagged_lines.add(lineno)
                    yield Finding(
                        rule=self.name, file=ctx.path, line=lineno,
                        message=(f"fault spec {value!r} names an "
                                 f"unregistered site {m.group(1)!r}"))
            # Raw-text scans (the deleted PR-3 test's coverage): argv
            # pairs whose spec omits the kind, and quoted specs
            # embedded mid-string (docstring examples) that the
            # whole-literal check above cannot see. Lines the AST scans
            # already flagged are skipped — one defect, one finding.
            for i, line in enumerate(ctx.lines, start=1):
                if i in flagged_lines:
                    continue
                for pat in (_MD_INJECT_RE, _TEXT_SPEC_RE, _MD_FIRE_RE):
                    for m in pat.finditer(line):
                        if m.group(1) not in SITES:
                            yield Finding(
                                rule=self.name, file=ctx.path, line=i,
                                message=(f"text references "
                                         f"unregistered fault site "
                                         f"{m.group(1)!r}"))
        elif ctx.path.endswith(".md"):
            for i, line in enumerate(ctx.lines, start=1):
                for pat in (_MD_INJECT_RE, _TEXT_SPEC_RE, _MD_FIRE_RE):
                    for m in pat.finditer(line):
                        if m.group(1) not in SITES:
                            yield Finding(
                                rule=self.name, file=ctx.path, line=i,
                                message=(f"doc references unregistered "
                                         f"fault site {m.group(1)!r}"))

    def finalize(self, repo: RepoContext) -> Iterable[Finding]:
        # Reverse direction: a SITES entry nothing in the package fires
        # is a dead registry row (the old test's second assertion).
        # Only meaningful on a full-repo pass — a single-fixture run
        # (analyze_source) has no business declaring sites dead.
        if not any(c.path == "tpu_cooccurrence/robustness/faults.py"
                   for c in repo.files):
            return
        fired: Set[str] = set()
        for ctx in repo.package_files():
            tree = ctx.tree
            if tree is None:
                continue
            for node in ctx.nodes(ast.Call):
                if (_is_fire_call(node)
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    fired.add(node.args[0].value)
        for site in sorted(set(SITES) - fired):
            yield Finding(
                rule=self.name,
                file="tpu_cooccurrence/robustness/faults.py", line=1,
                message=(f"registered fault site {site!r} is never "
                         f"fired anywhere in the package (dead "
                         f"registry entry)"))


def _config_fields(tree: ast.Module) -> Set[str]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            return {stmt.target.id for stmt in node.body
                    if isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)}
    return set()


@register
class CliFlagRule(Rule):
    name = "cli-flag"
    description = ("every --flag in config.py must map to a Config "
                   "field and be documented in README.md or docs/")

    def finalize(self, repo: RepoContext) -> Iterable[Finding]:
        cfg: Optional[FileContext] = next(
            (c for c in repo.files
             if c.path == "tpu_cooccurrence/config.py"), None)
        if cfg is None or cfg.tree is None:
            return
        fields = _config_fields(cfg.tree)
        docs_text = "\n".join(
            c.source for c in repo.files
            if c.path == "README.md" or c.path.startswith("docs/"))
        for node in ast.walk(cfg.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "add_argument"):
                continue
            long_flag = next(
                (a.value for a in node.args
                 if isinstance(a, ast.Constant)
                 and isinstance(a.value, str)
                 and a.value.startswith("--")), None)
            if long_flag is None:
                continue
            dest = next(
                (kw.value.value for kw in node.keywords
                 if kw.arg == "dest"
                 and isinstance(kw.value, ast.Constant)),
                long_flag[2:].replace("-", "_"))
            if dest not in fields:
                yield Finding(
                    rule=self.name, file=cfg.path, line=node.lineno,
                    message=(f"{long_flag} parses into dest "
                             f"{dest!r} which is not a Config "
                             f"dataclass field"))
            if docs_text and long_flag not in docs_text:
                yield Finding(
                    rule=self.name, file=cfg.path, line=node.lineno,
                    message=(f"{long_flag} is not mentioned in "
                             f"README.md or docs/ — document it "
                             f"(even one line in the Flags section)"))
