"""Degradation-level registry drift.

The degradation plane (``robustness/degrade.py``) is a state machine
whose levels are operator-facing contract: every level must have a
documented transition rule (``TRANSITION_RULES``), a journal event
token (``LEVEL_EVENTS`` — what the window record's ``degrade_events``
carries when the level is entered), and a row in the ARCHITECTURE
"Backpressure & degradation" level table. A level added to the enum
without all three is a silent operational lie — the journal would show
a numeric level nothing documents.

AST-checked (the enum members and both dict literals are read from the
source, not imported) and baseline-free by construction: the rule ships
with a clean repo and there is nothing to grandfather.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from .core import FileContext, Finding, RepoContext, Rule, register

_DEGRADE_PATH = "tpu_cooccurrence/robustness/degrade.py"
_ARCH_PATH = "docs/ARCHITECTURE.md"


def _enum_members(tree: ast.Module, class_name: str) -> Dict[str, int]:
    """``{member: lineno}`` of a module-level enum class's assignments."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            return {t.id: stmt.lineno
                    for stmt in node.body if isinstance(stmt, ast.Assign)
                    for t in stmt.targets if isinstance(t, ast.Name)}
    return {}


def _dict_literal_keys(tree: ast.Module, name: str) -> Optional[Set[str]]:
    """String keys of a module-level ``NAME = {...}`` dict literal, or
    ``None`` when no such literal assignment exists."""
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == name
                        for t in node.targets)
                and isinstance(node.value, ast.Dict)):
            return {k.value for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
    return None


@register
class DegradeRegistryRule(Rule):
    name = "degrade-registry"
    description = ("every DegradationLevel member needs a TRANSITION_RULES "
                   "entry, a LEVEL_EVENTS journal token, and an "
                   "ARCHITECTURE level-table mention")

    def finalize(self, repo: RepoContext) -> Iterable[Finding]:
        src: Optional[FileContext] = next(
            (c for c in repo.files if c.path == _DEGRADE_PATH), None)
        if src is None or src.tree is None:
            return
        members = _enum_members(src.tree, "DegradationLevel")
        if not members:
            yield Finding(
                rule=self.name, file=_DEGRADE_PATH, line=1,
                message="DegradationLevel enum not found (the degrade "
                        "plane's level registry is gone)")
            return
        for table in ("TRANSITION_RULES", "LEVEL_EVENTS"):
            keys = _dict_literal_keys(src.tree, table)
            if keys is None:
                kind = ("transition-rule" if table == "TRANSITION_RULES"
                        else "journal-event")
                yield Finding(
                    rule=self.name, file=_DEGRADE_PATH, line=1,
                    message=(f"{table} dict literal not found in "
                             f"degrade.py (the per-level {kind} "
                             f"registry is gone)"))
                continue
            for member, lineno in sorted(members.items()):
                if member not in keys:
                    yield Finding(
                        rule=self.name, file=_DEGRADE_PATH, line=lineno,
                        message=(f"DegradationLevel.{member} has no "
                                 f"{table} entry — every level needs a "
                                 f"documented transition rule and a "
                                 f"journal event token"))
            for key in sorted(keys - set(members)):
                yield Finding(
                    rule=self.name, file=_DEGRADE_PATH, line=1,
                    message=(f"{table} entry {key!r} names no "
                             f"DegradationLevel member (dead registry "
                             f"row)"))
        arch = next((c for c in repo.files if c.path == _ARCH_PATH), None)
        if arch is not None:
            for member, lineno in sorted(members.items()):
                if member not in arch.source:
                    yield Finding(
                        rule=self.name, file=_DEGRADE_PATH, line=lineno,
                        message=(f"DegradationLevel.{member} is not "
                                 f"mentioned in {_ARCH_PATH} — add it to "
                                 f"the Backpressure & degradation level "
                                 f"table"))
