"""cooclint framework: findings, rules, the walker, suppressions, baseline.

Design constraints (the reasons this is repo-native instead of a generic
linter plugin):

* rules need the repo's own truth tables (``metrics.py`` constants,
  ``faults.SITES``, ``CANONICAL_METRICS``) — imported directly, so the
  tables can never drift from what the analyzer enforces;
* findings must be *suppressable at the line* with a justification
  visible in the diff (``# cooclint: disable=<rule>``) and
  *grandfatherable* in a checked-in ``baseline.json`` so the analyzer
  can land strict and the repo can be paid down incrementally;
* it must run in tier-1: stdlib only, no jax import, whole-repo pass in
  single-digit seconds.

A rule is a subclass of :class:`Rule` registered with :func:`register`.
File-scoped checks implement :meth:`Rule.check`; repo-scoped invariants
(e.g. "every registered fault site is fired somewhere") implement
:meth:`Rule.finalize`, called once after every file was visited.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
import time
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

#: Suppression comment grammar: ``# cooclint: disable`` silences every
#: rule on that line; ``# cooclint: disable=rule-a,rule-b`` silences the
#: named rules only. The comment must sit on the exact line the finding
#: anchors to (findings carry one line; block pragmas invite rot).
#: ``# cooclint: disable-file=rule-a`` (anywhere in the file, named
#: rules only — no blanket form) opts a whole file out of a rule: the
#: escape hatch for fixture-holding test files whose *text* quotes the
#: exact bad patterns the text-scanning rules hunt.
_SUPPRESS_RE = re.compile(
    r"#\s*cooclint:\s*disable(?!-file)(?:=([a-z0-9_,-]+))?")
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*cooclint:\s*disable-file=([a-z0-9_,-]+)")

#: Directories never walked (caches, VCS, the analyzer's own package —
#: its rule definitions quote the very patterns they hunt for).
_SKIP_DIRS = {".git", "__pycache__", ".pytest_cache", "node_modules"}
_SKIP_SUFFIXES = ("/tpu_cooccurrence/analysis",)


@dataclasses.dataclass
class Finding:
    """One rule violation, anchored to ``file:line``.

    ``symbol`` is the qualified symbol path of the enclosing def
    (``PipelineDriver._run``, ``<module>`` for top-level code, ``""``
    for non-Python files) — the stable half of the fingerprint:
    baseline entries match on ``(rule, file, symbol)`` so unrelated
    line drift above a grandfathered finding does not resurrect it.
    ``severity`` / ``rule_doc`` ride into ``--format json`` for
    downstream tooling; neither participates in identity.
    """

    rule: str
    file: str  # repo-relative, forward slashes
    line: int
    message: str
    symbol: str = ""
    severity: str = "error"
    rule_doc: str = ""

    def key(self) -> Tuple[str, str, int]:
        """Exact identity for dedup/suppression matching."""
        return (self.rule, self.file, self.line)

    def fingerprint(self) -> Tuple[str, str, str]:
        """Line-drift-stable identity for baseline matching."""
        return (self.rule, self.file, self.symbol)

    def to_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "file": self.file, "line": self.line,
                "symbol": self.symbol, "severity": self.severity,
                "rule_doc": self.rule_doc, "message": self.message}

    @classmethod
    def from_dict(cls, d: Dict[str, object]) -> "Finding":
        return cls(rule=str(d["rule"]), file=str(d["file"]),
                   line=int(d["line"]), message=str(d.get("message", "")),
                   symbol=str(d.get("symbol", "")),
                   severity=str(d.get("severity", "error")),
                   rule_doc=str(d.get("rule_doc", "")))

    def __str__(self) -> str:
        return f"{self.file}:{self.line}: {self.rule}: {self.message}"


class FileContext:
    """One scanned file: source, (lazy) AST, suppression map.

    ``path`` is repo-relative with forward slashes — rules filter on it
    (``ctx.path.endswith("pipeline.py")``). Markdown files have
    ``tree=None``; rules that read docs use ``ctx.source`` directly.
    """

    def __init__(self, path: str, source: str) -> None:
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self._tree: Optional[ast.Module] = None
        self._parse_error: Optional[SyntaxError] = None
        self._suppress: Optional[Dict[int, Optional[set]]] = None
        self._file_suppress: Optional[set] = None
        self._node_index: Optional[Dict[type, list]] = None
        self._symbol_spans: Optional[list] = None

    @property
    def is_python(self) -> bool:
        return self.path.endswith(".py")

    @property
    def tree(self) -> Optional[ast.Module]:
        if not self.is_python:
            return None
        if self._tree is None and self._parse_error is None:
            try:
                self._tree = ast.parse(self.source)
            except SyntaxError as exc:
                self._parse_error = exc
        return self._tree

    def nodes(self, *types: type) -> list:
        """Every AST node of the given types, in one shared walk.

        Twenty-three rules each re-walking every file's full AST was
        the analyzer's whole runtime; the tree is walked once per file
        and bucketed by node type, and rules query the buckets.
        """
        if self._node_index is None:
            self._node_index = {}
            tree = self.tree
            if tree is not None:
                for node in ast.walk(tree):
                    self._node_index.setdefault(type(node), []).append(
                        node)
        out: list = []
        for t in types:
            out.extend(self._node_index.get(t, ()))
        return out

    def strings(self) -> list:
        """``(line, value)`` for every string literal, off the shared
        node index (use instead of ``string_constants(tree)`` whenever
        a FileContext is in hand)."""
        return [(n.lineno, n.value) for n in self.nodes(ast.Constant)
                if isinstance(n.value, str)]

    def symbol_at(self, line: int) -> str:
        """Qualified symbol path of the innermost def containing
        ``line`` (``Cls.method`` / ``fn`` / ``<module>``) — the stable
        fingerprint component for findings in this file."""
        if not self.is_python or self.tree is None:
            return ""
        if self._symbol_spans is None:
            spans = []

            def walk(node, prefix):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.ClassDef)):
                        name = (f"{prefix}.{child.name}" if prefix
                                else child.name)
                        spans.append((child.lineno,
                                      child.end_lineno or child.lineno,
                                      name))
                        walk(child, name)
                    else:
                        walk(child, prefix)

            walk(self.tree, "")
            self._symbol_spans = spans
        best = None
        for lo, hi, name in self._symbol_spans:
            if lo <= line <= hi and (
                    best is None or hi - lo < best[0]):
                best = (hi - lo, name)
        return best[1] if best else "<module>"

    def suppressions(self) -> Dict[int, Optional[set]]:
        """``{lineno: None (all rules) | {rule names}}`` for this file."""
        if self._suppress is None:
            self._suppress = {}
            for i, line in enumerate(self.lines, start=1):
                m = _SUPPRESS_RE.search(line)
                if not m:
                    continue
                names = m.group(1)
                self._suppress[i] = (None if names is None
                                     else set(names.split(",")))
        return self._suppress

    def file_suppressions(self) -> set:
        """Rule names disabled for this whole file."""
        if self._file_suppress is None:
            self._file_suppress = set()
            for line in self.lines:
                m = _SUPPRESS_FILE_RE.search(line)
                if m:
                    self._file_suppress.update(m.group(1).split(","))
        return self._file_suppress

    def is_suppressed(self, finding: Finding) -> bool:
        if finding.rule in self.file_suppressions():
            return True
        rules = self.suppressions().get(finding.line, False)
        if rules is False:
            return False
        return rules is None or finding.rule in rules


class RepoContext:
    """Every scanned file, for repo-scoped ``finalize`` checks."""

    def __init__(self, root: str, files: List[FileContext],
                 pass1_cache: Optional[Dict[str, dict]] = None) -> None:
        self.root = root
        self.files = files
        self._graph = None
        self._pass1_cache = pass1_cache
        self._test_refs: Optional[set] = None
        self._test_strings: Optional[set] = None

    def python_files(self) -> Iterator[FileContext]:
        return (f for f in self.files if f.is_python)

    def package_files(self) -> Iterator[FileContext]:
        """Package source only (``tpu_cooccurrence/``) — the scope for
        rules about what production code *does* (tests deliberately poke
        internals and seed bad patterns as fixtures)."""
        return (f for f in self.python_files()
                if f.path.startswith("tpu_cooccurrence/"))

    @property
    def graph(self):
        """The pass-1 :class:`~.graph.ProjectGraph` over the package
        files, built lazily (and from the sha-keyed cache under
        ``--changed``) — the cross-module facts pass-2 rules query."""
        if self._graph is None:
            from .graph import build_graph
            self._graph = build_graph(self.package_files(),
                                      cached=self._pass1_cache)
        return self._graph

    def _test_evidence(self) -> None:
        """Compute (or restore from the pass-1 cache) the two test-
        evidence sets several registry rules share: every identifier
        tests/ mentions, and every string constant tests/ contains.
        One pass over the tests/ trees; under ``--changed`` both are
        restored when the tests/ tree is byte-identical (parsing ~100
        test files costs more than the changed files themselves)."""
        tests = [c for c in self.python_files()
                 if c.path.startswith("tests/")]
        joint = hashlib.sha256("".join(
            c.path + "\0" + c.source for c in tests).encode(
            "utf-8", "replace")).hexdigest()
        rec = (self._pass1_cache or {}).get("__test_refs__")
        if (isinstance(rec, dict) and rec.get("sha256") == joint
                and "strings" in rec):
            self._test_refs = set(rec.get("refs", ()))
            self._test_strings = set(rec.get("strings", ()))
            self.test_refs_sha = joint
            return
        refs: set = set()
        strings: set = set()
        for ctx in tests:
            if ctx.tree is None:
                continue
            for node in ctx.nodes(ast.Name):
                refs.add(node.id)
            for node in ctx.nodes(ast.Attribute):
                refs.add(node.attr)
            for node in ctx.nodes(ast.Import, ast.ImportFrom):
                for alias in node.names:
                    refs.add(alias.name.rsplit(".", 1)[-1])
            for _line, value in ctx.strings():
                strings.add(value)
        self._test_refs = refs
        self._test_strings = strings
        self.test_refs_sha = joint

    def test_referenced_names(self) -> set:
        """Every identifier tests/ mentions (names, attributes,
        imported aliases) — the "registered test" evidence."""
        if self._test_refs is None:
            self._test_evidence()
        return self._test_refs

    def test_string_constants(self) -> set:
        """Every string constant under tests/ — the "asserted by a
        test" evidence (journal keys, fallback reasons, ckpt keys)."""
        if self._test_strings is None:
            self._test_evidence()
        return self._test_strings


class Rule:
    """Base rule. Subclasses set ``name`` (kebab-case, the suppression /
    baseline key) and implement ``check`` and/or ``finalize``.
    ``severity`` ("error" | "warning") is metadata carried into the
    JSON output; both severities gate commits."""

    name = ""
    description = ""
    severity = "error"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def finalize(self, repo: RepoContext) -> Iterable[Finding]:
        return ()


#: Registered rules by name (import of the rules_* modules populates it).
RULES: Dict[str, Rule] = {}


def register(rule_cls):
    """Class decorator: instantiate and register a rule."""
    rule = rule_cls()
    if not rule.name:
        raise ValueError(f"rule {rule_cls.__name__} has no name")
    if rule.name in RULES:
        raise ValueError(f"duplicate rule name {rule.name!r}")
    RULES[rule.name] = rule
    return rule_cls


@dataclasses.dataclass
class AnalysisResult:
    """One analyzer pass: surviving findings + bookkeeping."""

    findings: List[Finding]            # new (non-baseline, non-suppressed)
    baselined: List[Finding]           # matched a baseline entry
    stale_baseline: List[dict]         # baseline entries nothing matched
    files_scanned: int
    elapsed_seconds: float

    #: ``--format json`` envelope version. 2 added the schema field
    #: itself plus per-finding ``symbol`` / ``severity`` / ``rule_doc``
    #: — downstream tooling (cooc-trace-style consumers) should reject
    #: majors it does not know.
    SCHEMA = "cooclint-findings/2"

    def to_dict(self) -> Dict[str, object]:
        """The ``--format json`` schema (round-trips through
        ``Finding.from_dict`` for the findings list)."""
        return {
            "schema": self.SCHEMA,
            "findings": [f.to_dict() for f in self.findings],
            "baselined": len(self.baselined),
            "stale_baseline": self.stale_baseline,
            "files_scanned": self.files_scanned,
            "elapsed_seconds": round(self.elapsed_seconds, 3),
            "exit_code": 1 if self.findings else 0,
        }


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "baseline.json")


def load_baseline(path: Optional[str] = None) -> List[dict]:
    """Baseline entries. Missing file = empty baseline.

    Two entry formats coexist: the fingerprint form
    ``{rule, file, symbol, justification}`` (stable across line drift)
    and the legacy ``{rule, file, line, ...}`` form, which
    ``--prune-baseline`` rewrites in place once a current finding
    matches it.
    """
    path = path or default_baseline_path()
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except OSError:
        return []
    entries = data.get("findings", []) if isinstance(data, dict) else data
    for e in entries:
        if not isinstance(e, dict) or "rule" not in e or "file" not in e \
                or ("line" not in e and "symbol" not in e):
            raise ValueError(
                f"malformed baseline entry (need rule/file and "
                f"symbol or line): {e!r}")
    return entries


def save_baseline(entries: List[dict], path: Optional[str] = None) -> None:
    path = path or default_baseline_path()
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"findings": entries}, f, indent=2, sort_keys=True)
        f.write("\n")


def _walk_files(root: str) -> Iterator[str]:
    for dirpath, dirs, files in os.walk(root):
        rel_dir = os.path.relpath(dirpath, root).replace(os.sep, "/")
        dirs[:] = sorted(
            d for d in dirs
            if d not in _SKIP_DIRS
            and not ("/" + rel_dir + "/" + d).endswith(_SKIP_SUFFIXES))
        for name in sorted(files):
            if name.endswith((".py", ".md")):
                yield os.path.join(dirpath, name)


def annotate_finding(f: Finding, ctx: Optional[FileContext]) -> Finding:
    """Fill the derived fields rules do not set themselves: the
    enclosing-symbol fingerprint component and the owning rule's
    severity/doc."""
    if not f.symbol and ctx is not None:
        f.symbol = ctx.symbol_at(f.line)
    rule = RULES.get(f.rule)
    if rule is not None:
        if f.severity == "error":
            f.severity = rule.severity
        if not f.rule_doc:
            f.rule_doc = rule.description
    return f


def _baseline_entry_key(e: dict):
    """A baseline entry's match key: fingerprint form if it carries a
    symbol, legacy exact-line form otherwise."""
    if e.get("symbol"):
        return ("symbol", e["rule"], e["file"], e["symbol"])
    return ("line", e["rule"], e["file"], int(e["line"]))


class Analyzer:
    """Walk ``root``, run every registered rule, fold in suppressions
    and the baseline.

    ``changed_only`` (a set of repo-relative paths) scopes pass 2's
    per-file ``check`` to those files — the ``--changed`` pre-commit
    path. Repo-scoped ``finalize`` rules still see the whole repo (the
    pass-1 index is what the sha-keyed cache accelerates); findings
    they raise in unchanged files are filtered out, matching the
    "what did MY edit break" contract of an incremental run.
    """

    def __init__(self, root: str,
                 rules: Optional[Iterable[Rule]] = None,
                 baseline: Optional[List[dict]] = None,
                 changed_only: Optional[set] = None,
                 pass1_cache: Optional[Dict[str, dict]] = None) -> None:
        self.root = os.path.abspath(root)
        self.rules = list(rules) if rules is not None else list(
            RULES.values())
        self.baseline = baseline if baseline is not None else []
        self.changed_only = changed_only
        self.pass1_cache = pass1_cache

    def _contexts(self) -> List[FileContext]:
        out = []
        for path in _walk_files(self.root):
            rel = os.path.relpath(path, self.root).replace(os.sep, "/")
            try:
                with open(path, encoding="utf-8", errors="replace") as f:
                    out.append(FileContext(rel, f.read()))
            except OSError:
                continue
        return out

    def run(self) -> AnalysisResult:
        t0 = time.perf_counter()
        contexts = self._contexts()
        repo = RepoContext(self.root, contexts,
                           pass1_cache=self.pass1_cache)
        # Exposed for the runner: ``--changed`` persists the pass-1
        # module indexes (sha-keyed) out of the repo it just analyzed.
        self.last_repo = repo
        raw: List[Finding] = []
        by_path = {c.path: c for c in contexts}
        check_ctxs = contexts if self.changed_only is None else [
            c for c in contexts if c.path in self.changed_only]
        for rule in self.rules:
            for ctx in check_ctxs:
                raw.extend(rule.check(ctx))
            raw.extend(rule.finalize(repo))
        # Dedup (two scan shapes can anchor to the same line), then
        # per-line suppressions, then the baseline.
        seen = set()
        kept: List[Finding] = []
        for f in raw:
            ident = (*f.key(), f.message)
            if ident in seen:
                continue
            seen.add(ident)
            if self.changed_only is not None and \
                    f.file not in self.changed_only:
                continue
            ctx = by_path.get(f.file)
            if ctx is not None and ctx.is_suppressed(f):
                continue
            kept.append(annotate_finding(f, ctx))
        baseline_keys = {_baseline_entry_key(e) for e in self.baseline}
        matched_keys = set()
        new: List[Finding] = []
        baselined: List[Finding] = []
        for f in kept:
            fp = ("symbol", *f.fingerprint())
            exact = ("line", *f.key())
            hit = next((k for k in (fp, exact) if k in baseline_keys),
                       None)
            if hit is not None:
                matched_keys.add(hit)
                baselined.append(f)
            else:
                new.append(f)
        stale = [e for e in self.baseline
                 if _baseline_entry_key(e) not in matched_keys
                 and (self.changed_only is None
                      or e["file"] in self.changed_only)]
        new.sort(key=lambda f: (f.file, f.line, f.rule))
        return AnalysisResult(
            findings=new, baselined=baselined, stale_baseline=stale,
            files_scanned=len(contexts),
            elapsed_seconds=time.perf_counter() - t0)


def analyze_source(source: str, path: str = "tpu_cooccurrence/_fixture.py",
                   rules: Optional[Iterable[str]] = None) -> List[Finding]:
    """Run rules over one in-memory snippet (the fixture-test entry
    point). ``path`` is the pretended repo-relative path — rules filter
    on it, so fixtures choose which file they impersonate. Suppressions
    apply; the baseline does not."""
    ctx = FileContext(path, source)
    repo = RepoContext("<memory>", [ctx])
    selected = ([RULES[name] for name in rules] if rules is not None
                else list(RULES.values()))
    out: List[Finding] = []
    seen = set()
    for rule in selected:
        for f in list(rule.check(ctx)) + list(rule.finalize(repo)):
            ident = (*f.key(), f.message)
            if ident not in seen:
                seen.add(ident)
                out.append(annotate_finding(
                    f, ctx if f.file == ctx.path else None))
    return [f for f in out if not ctx.is_suppressed(f)]


# -- shared AST helpers (used by the rule packs) ------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def string_constants(tree: ast.Module) -> Iterator[Tuple[int, str]]:
    """Every string literal in a module with its line."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node.lineno, node.value
