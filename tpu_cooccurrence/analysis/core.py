"""cooclint framework: findings, rules, the walker, suppressions, baseline.

Design constraints (the reasons this is repo-native instead of a generic
linter plugin):

* rules need the repo's own truth tables (``metrics.py`` constants,
  ``faults.SITES``, ``CANONICAL_METRICS``) — imported directly, so the
  tables can never drift from what the analyzer enforces;
* findings must be *suppressable at the line* with a justification
  visible in the diff (``# cooclint: disable=<rule>``) and
  *grandfatherable* in a checked-in ``baseline.json`` so the analyzer
  can land strict and the repo can be paid down incrementally;
* it must run in tier-1: stdlib only, no jax import, whole-repo pass in
  single-digit seconds.

A rule is a subclass of :class:`Rule` registered with :func:`register`.
File-scoped checks implement :meth:`Rule.check`; repo-scoped invariants
(e.g. "every registered fault site is fired somewhere") implement
:meth:`Rule.finalize`, called once after every file was visited.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import time
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

#: Suppression comment grammar: ``# cooclint: disable`` silences every
#: rule on that line; ``# cooclint: disable=rule-a,rule-b`` silences the
#: named rules only. The comment must sit on the exact line the finding
#: anchors to (findings carry one line; block pragmas invite rot).
#: ``# cooclint: disable-file=rule-a`` (anywhere in the file, named
#: rules only — no blanket form) opts a whole file out of a rule: the
#: escape hatch for fixture-holding test files whose *text* quotes the
#: exact bad patterns the text-scanning rules hunt.
_SUPPRESS_RE = re.compile(
    r"#\s*cooclint:\s*disable(?!-file)(?:=([a-z0-9_,-]+))?")
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*cooclint:\s*disable-file=([a-z0-9_,-]+)")

#: Directories never walked (caches, VCS, the analyzer's own package —
#: its rule definitions quote the very patterns they hunt for).
_SKIP_DIRS = {".git", "__pycache__", ".pytest_cache", "node_modules"}
_SKIP_SUFFIXES = ("/tpu_cooccurrence/analysis",)


@dataclasses.dataclass
class Finding:
    """One rule violation, anchored to ``file:line``."""

    rule: str
    file: str  # repo-relative, forward slashes
    line: int
    message: str

    def key(self) -> Tuple[str, str, int]:
        """Identity for baseline/suppression matching."""
        return (self.rule, self.file, self.line)

    def to_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "file": self.file, "line": self.line,
                "message": self.message}

    @classmethod
    def from_dict(cls, d: Dict[str, object]) -> "Finding":
        return cls(rule=str(d["rule"]), file=str(d["file"]),
                   line=int(d["line"]), message=str(d.get("message", "")))

    def __str__(self) -> str:
        return f"{self.file}:{self.line}: {self.rule}: {self.message}"


class FileContext:
    """One scanned file: source, (lazy) AST, suppression map.

    ``path`` is repo-relative with forward slashes — rules filter on it
    (``ctx.path.endswith("pipeline.py")``). Markdown files have
    ``tree=None``; rules that read docs use ``ctx.source`` directly.
    """

    def __init__(self, path: str, source: str) -> None:
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self._tree: Optional[ast.Module] = None
        self._parse_error: Optional[SyntaxError] = None
        self._suppress: Optional[Dict[int, Optional[set]]] = None
        self._file_suppress: Optional[set] = None

    @property
    def is_python(self) -> bool:
        return self.path.endswith(".py")

    @property
    def tree(self) -> Optional[ast.Module]:
        if not self.is_python:
            return None
        if self._tree is None and self._parse_error is None:
            try:
                self._tree = ast.parse(self.source)
            except SyntaxError as exc:
                self._parse_error = exc
        return self._tree

    def suppressions(self) -> Dict[int, Optional[set]]:
        """``{lineno: None (all rules) | {rule names}}`` for this file."""
        if self._suppress is None:
            self._suppress = {}
            for i, line in enumerate(self.lines, start=1):
                m = _SUPPRESS_RE.search(line)
                if not m:
                    continue
                names = m.group(1)
                self._suppress[i] = (None if names is None
                                     else set(names.split(",")))
        return self._suppress

    def file_suppressions(self) -> set:
        """Rule names disabled for this whole file."""
        if self._file_suppress is None:
            self._file_suppress = set()
            for line in self.lines:
                m = _SUPPRESS_FILE_RE.search(line)
                if m:
                    self._file_suppress.update(m.group(1).split(","))
        return self._file_suppress

    def is_suppressed(self, finding: Finding) -> bool:
        if finding.rule in self.file_suppressions():
            return True
        rules = self.suppressions().get(finding.line, False)
        if rules is False:
            return False
        return rules is None or finding.rule in rules


class RepoContext:
    """Every scanned file, for repo-scoped ``finalize`` checks."""

    def __init__(self, root: str, files: List[FileContext]) -> None:
        self.root = root
        self.files = files

    def python_files(self) -> Iterator[FileContext]:
        return (f for f in self.files if f.is_python)

    def package_files(self) -> Iterator[FileContext]:
        """Package source only (``tpu_cooccurrence/``) — the scope for
        rules about what production code *does* (tests deliberately poke
        internals and seed bad patterns as fixtures)."""
        return (f for f in self.python_files()
                if f.path.startswith("tpu_cooccurrence/"))


class Rule:
    """Base rule. Subclasses set ``name`` (kebab-case, the suppression /
    baseline key) and implement ``check`` and/or ``finalize``."""

    name = ""
    description = ""

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def finalize(self, repo: RepoContext) -> Iterable[Finding]:
        return ()


#: Registered rules by name (import of the rules_* modules populates it).
RULES: Dict[str, Rule] = {}


def register(rule_cls):
    """Class decorator: instantiate and register a rule."""
    rule = rule_cls()
    if not rule.name:
        raise ValueError(f"rule {rule_cls.__name__} has no name")
    if rule.name in RULES:
        raise ValueError(f"duplicate rule name {rule.name!r}")
    RULES[rule.name] = rule
    return rule_cls


@dataclasses.dataclass
class AnalysisResult:
    """One analyzer pass: surviving findings + bookkeeping."""

    findings: List[Finding]            # new (non-baseline, non-suppressed)
    baselined: List[Finding]           # matched a baseline entry
    stale_baseline: List[dict]         # baseline entries nothing matched
    files_scanned: int
    elapsed_seconds: float

    def to_dict(self) -> Dict[str, object]:
        """The ``--format json`` schema (round-trips through
        ``Finding.from_dict`` for the findings list)."""
        return {
            "findings": [f.to_dict() for f in self.findings],
            "baselined": len(self.baselined),
            "stale_baseline": self.stale_baseline,
            "files_scanned": self.files_scanned,
            "elapsed_seconds": round(self.elapsed_seconds, 3),
            "exit_code": 1 if self.findings else 0,
        }


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "baseline.json")


def load_baseline(path: Optional[str] = None) -> List[dict]:
    """Baseline entries (``[{rule, file, line, justification}]``).
    Missing file = empty baseline."""
    path = path or default_baseline_path()
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except OSError:
        return []
    entries = data.get("findings", []) if isinstance(data, dict) else data
    for e in entries:
        if not isinstance(e, dict) or not {"rule", "file", "line"} <= set(e):
            raise ValueError(
                f"malformed baseline entry (need rule/file/line): {e!r}")
    return entries


def save_baseline(entries: List[dict], path: Optional[str] = None) -> None:
    path = path or default_baseline_path()
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"findings": entries}, f, indent=2, sort_keys=True)
        f.write("\n")


def _walk_files(root: str) -> Iterator[str]:
    for dirpath, dirs, files in os.walk(root):
        rel_dir = os.path.relpath(dirpath, root).replace(os.sep, "/")
        dirs[:] = sorted(
            d for d in dirs
            if d not in _SKIP_DIRS
            and not ("/" + rel_dir + "/" + d).endswith(_SKIP_SUFFIXES))
        for name in sorted(files):
            if name.endswith((".py", ".md")):
                yield os.path.join(dirpath, name)


class Analyzer:
    """Walk ``root``, run every registered rule, fold in suppressions
    and the baseline."""

    def __init__(self, root: str,
                 rules: Optional[Iterable[Rule]] = None,
                 baseline: Optional[List[dict]] = None) -> None:
        self.root = os.path.abspath(root)
        self.rules = list(rules) if rules is not None else list(
            RULES.values())
        self.baseline = baseline if baseline is not None else []

    def _contexts(self) -> List[FileContext]:
        out = []
        for path in _walk_files(self.root):
            rel = os.path.relpath(path, self.root).replace(os.sep, "/")
            try:
                with open(path, encoding="utf-8", errors="replace") as f:
                    out.append(FileContext(rel, f.read()))
            except OSError:
                continue
        return out

    def run(self) -> AnalysisResult:
        t0 = time.perf_counter()
        contexts = self._contexts()
        repo = RepoContext(self.root, contexts)
        raw: List[Finding] = []
        by_path = {c.path: c for c in contexts}
        for rule in self.rules:
            for ctx in contexts:
                raw.extend(rule.check(ctx))
            raw.extend(rule.finalize(repo))
        # Dedup (two scan shapes can anchor to the same line), then
        # per-line suppressions, then the baseline.
        seen = set()
        kept: List[Finding] = []
        for f in raw:
            ident = (*f.key(), f.message)
            if ident in seen:
                continue
            seen.add(ident)
            ctx = by_path.get(f.file)
            if ctx is not None and ctx.is_suppressed(f):
                continue
            kept.append(f)
        baseline_keys = {(e["rule"], e["file"], int(e["line"]))
                         for e in self.baseline}
        matched_keys = set()
        new: List[Finding] = []
        baselined: List[Finding] = []
        for f in kept:
            if f.key() in baseline_keys:
                matched_keys.add(f.key())
                baselined.append(f)
            else:
                new.append(f)
        stale = [e for e in self.baseline
                 if (e["rule"], e["file"], int(e["line"]))
                 not in matched_keys]
        new.sort(key=lambda f: (f.file, f.line, f.rule))
        return AnalysisResult(
            findings=new, baselined=baselined, stale_baseline=stale,
            files_scanned=len(contexts),
            elapsed_seconds=time.perf_counter() - t0)


def analyze_source(source: str, path: str = "tpu_cooccurrence/_fixture.py",
                   rules: Optional[Iterable[str]] = None) -> List[Finding]:
    """Run rules over one in-memory snippet (the fixture-test entry
    point). ``path`` is the pretended repo-relative path — rules filter
    on it, so fixtures choose which file they impersonate. Suppressions
    apply; the baseline does not."""
    ctx = FileContext(path, source)
    repo = RepoContext("<memory>", [ctx])
    selected = ([RULES[name] for name in rules] if rules is not None
                else list(RULES.values()))
    out: List[Finding] = []
    seen = set()
    for rule in selected:
        for f in list(rule.check(ctx)) + list(rule.finalize(repo)):
            ident = (*f.key(), f.message)
            if ident not in seen:
                seen.add(ident)
                out.append(f)
    return [f for f in out if not ctx.is_suppressed(f)]


# -- shared AST helpers (used by the rule packs) ------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def string_constants(tree: ast.Module) -> Iterator[Tuple[int, str]]:
    """Every string literal in a module with its line."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node.lineno, node.value
