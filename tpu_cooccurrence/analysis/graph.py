"""Pass 1 of the two-pass engine: project symbol table + call graph.

cooclint grew up as a per-file AST pattern matcher; the rules that need
to know *who calls whom across modules* (transitive jit purity,
thread-ownership of shared state, tuning-knob dataflow) are structurally
impossible in that shape — a helper two hops below a ``jit`` doing host
I/O looks identical to any other function when its file is scanned
alone. This module is the whole-program half: one cheap extraction walk
per file (:func:`extract_module`, JSON-serializable so the ``--changed``
pre-commit path can cache it keyed on the file's sha256), then a link
step (:class:`ProjectGraph`) that resolves names into edges:

* **symbol table** — every module / class / function def and every
  assignment to a module-level name, under qualified names of the form
  ``tpu_cooccurrence.pipeline:PipelineDriver._run`` (module-level code
  is the pseudo-function ``<module>``);
* **call graph** — intra-project call edges. ``self.m()`` resolves
  through the enclosing class and its bases; bare names resolve through
  module scope then imports (``from .x import f``); ``alias.f()``
  resolves through module imports. Attribute calls on unresolvable
  receivers (``job.scorer.process_window()``) become *duck edges* to
  every project method of that (sufficiently distinctive) name — used
  for thread reachability, where missing an edge hides a race, and
  excluded from jit tracing, where inventing one invents a bug;
* **thread roots** — entry points that run on a thread of their own:
  ``threading.Thread(target=...)`` / ``threading.Timer`` spawn sites
  (the pipeline scorer worker, the gang monitor, the metrics server
  loop), ``do_*`` methods of ``BaseHTTPRequestHandler`` subclasses
  (ThreadingHTTPServer runs each request on a fresh thread, so these
  are additionally *self-concurrent*), and ``main`` — the union of
  functions no thread entry reaches first (zero strong in-edges).
  :meth:`ProjectGraph.roots_of` answers "which threads can be executing
  this function", the fact the thread-ownership pack queries.

The extraction also records attribute/global *write sites* (receiver,
attr, enclosing function, and whether the write sits inside a
``with *._lock:`` span, inside ``__init__``, or under a
``# thread-owner:`` annotation) so pass-2 rules never re-walk ASTs.

Stdlib only, no jax — same constraints as the rest of the analyzer.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import FileContext, dotted_name

#: Method names too generic to duck-type on: an edge to every class
#: defining ``get`` would connect the whole program to itself and
#: flatten the thread-root partition the ownership rule depends on.
_DUCK_DENYLIST = {
    "get", "put", "set", "add", "pop", "close", "join", "start", "run",
    "read", "write", "append", "extend", "update", "clear", "items",
    "keys", "values", "copy", "flush", "send", "recv", "next", "result",
    "observe", "inc",
}

#: Annotation token: a write site carrying it (same or preceding line,
#: or on its enclosing ``def``) declares single-threaded ownership and
#: is exempt from the thread-ownership rule — the justification lives
#: in the diff, like ``lock-ordering:``.
OWNER_TOKEN = "thread-owner:"

_HANDLER_BASES = {"BaseHTTPRequestHandler",
                  "http.server.BaseHTTPRequestHandler"}


def module_name_for(path: str) -> str:
    """``tpu_cooccurrence/state/results.py`` → dotted module name."""
    mod = path[:-3] if path.endswith(".py") else path
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _lock_spans(tree: ast.AST) -> List[Tuple[int, int]]:
    """Line spans of ``with <expr>._lock`` bodies (object-insensitive —
    the ownership rule only needs "some lock is held here"; the
    object-sensitive form stays in rules_lock)."""
    spans = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        for item in node.items:
            expr = item.context_expr
            target = expr.func if isinstance(expr, ast.Call) else expr
            name = dotted_name(target) or ""
            if name.endswith("._lock") or "._lock." in name:
                spans.append((node.lineno, node.end_lineno or node.lineno))
                break
    return spans


def _has_owner_annotation(lines: List[str], lineno: int,
                          def_line: Optional[int]) -> bool:
    for ln in (lineno, lineno - 1, def_line):
        if ln and 1 <= ln <= len(lines) and OWNER_TOKEN in lines[ln - 1]:
            return True
    return False


def extract_module(ctx: FileContext) -> Optional[dict]:
    """One file → a JSON-serializable symbol/call/write summary."""
    tree = ctx.tree
    if tree is None:
        return None
    mod = module_name_for(ctx.path)
    package = mod.rsplit(".", 1)[0] if "." in mod else ""
    index: dict = {
        "path": ctx.path, "module": mod,
        "functions": {},       # qual -> {line,end,params,cls}
        "classes": {},         # name -> {bases,line,end,methods}
        "imports": {},         # local name -> dotted target
        "module_names": [],    # module-level assigned names
        "calls": {},           # caller qual -> [[callee_str, line], ...]
        "threads": [],         # [target_str, caller, line, label]
        "attr_writes": [],     # [recv, attr, caller, line, flags]
        "global_writes": [],   # [name, caller, line, flags]
        "handlers": [],        # request-handler class names
    }
    locks = _lock_spans(tree)

    def locked(lineno: int) -> bool:
        return any(lo <= lineno <= hi for lo, hi in locks)

    # -- imports ---------------------------------------------------------
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                index["imports"][alias.asname or
                                 alias.name.split(".")[0]] = alias.name
        elif isinstance(node, ast.ImportFrom):
            if node.level:  # relative: anchor at this file's package
                up = package.split(".")
                if node.level > 1:
                    up = up[: -(node.level - 1)] or [""]
                base = ".".join(up)
                base = base + "." + node.module if node.module else base
            else:
                base = node.module or ""
            for alias in node.names:
                if alias.name == "*":
                    continue
                index["imports"][alias.asname or alias.name] = (
                    f"{base}.{alias.name}" if base else alias.name)

    # -- defs, calls, writes: one recursive walk tracking scope ----------
    _in_init = [False]
    _def_line: List[Optional[int]] = [None]

    def _write_flags(lineno: int) -> str:
        flags = ""
        if locked(lineno):
            flags += "L"
        if _has_owner_annotation(ctx.lines, lineno, _def_line[0]):
            flags += "A"
        if _in_init[0]:
            flags += "I"
        return flags

    def qual(stack: List[str]) -> str:
        return stack[-1] if stack else "<module>"

    def visit(node: ast.AST, fn_stack: List[str],
              cls: Optional[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            name = (f"{cls}.{node.name}" if cls else node.name)
            if name not in index["functions"]:
                index["functions"][name] = {
                    "line": node.lineno,
                    "end": node.end_lineno or node.lineno,
                    "params": [a.arg for a in node.args.args],
                    "cls": cls,
                }
            # decorators execute at def time in the *enclosing* scope,
            # not inside the function they wrap
            for dec in node.decorator_list:
                visit(dec, fn_stack, cls)
            prev_init, prev_def = _in_init[0], _def_line[0]
            _in_init[0] = prev_init or node.name in (
                "__init__", "__post_init__", "__new__")
            _def_line[0] = node.lineno
            for child in ast.iter_child_nodes(node):
                if child in node.decorator_list:
                    continue
                visit(child, fn_stack + [name], cls)
            _in_init[0], _def_line[0] = prev_init, prev_def
            return
        if isinstance(node, ast.ClassDef):
            bases = [dotted_name(b) or "" for b in node.bases]
            crec = index["classes"].setdefault(node.name, {
                "bases": bases, "line": node.lineno,
                "end": node.end_lineno or node.lineno, "methods": []})
            if any(b in _HANDLER_BASES or b.endswith("RequestHandler")
                   for b in bases):
                index["handlers"].append(node.name)
            for child in ast.iter_child_nodes(node):
                visit(child, fn_stack, node.name)
            crec["methods"] = [
                f.split(".", 1)[1]
                for f in index["functions"]
                if f.startswith(node.name + ".") and "." not in
                f.split(".", 1)[1]]
            return

        caller = qual(fn_stack)
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func)
            if callee:
                index["calls"].setdefault(caller, []).append(
                    [callee, node.lineno])
                if callee in ("threading.Thread", "Thread",
                              "threading.Timer", "Timer"):
                    target = label = None
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target = dotted_name(kw.value)
                        elif kw.arg == "name" and isinstance(
                                kw.value, ast.Constant):
                            label = str(kw.value.value)
                    if target is None and callee.endswith("Timer") and \
                            len(node.args) >= 2:
                        target = dotted_name(node.args[1])
                    if target:
                        index["threads"].append(
                            [target, caller, node.lineno, label])
        elif isinstance(node, ast.Attribute) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            recv = dotted_name(node.value)
            if recv:
                index["attr_writes"].append(
                    [recv, node.attr, caller, node.lineno,
                     _write_flags(node.lineno)])
        elif isinstance(node, ast.Subscript) and isinstance(
                node.ctx, (ast.Store, ast.Del)) and isinstance(
                node.value, ast.Attribute):
            # ``self._counters[k] += v`` mutates the container held in
            # the attribute — a write for ownership purposes.
            recv = dotted_name(node.value.value)
            if recv:
                index["attr_writes"].append(
                    [recv, node.value.attr, caller, node.lineno,
                     _write_flags(node.lineno)])
        elif isinstance(node, ast.Global) and fn_stack:
            for name in node.names:
                index["global_writes"].append(
                    [name, caller, node.lineno,
                     _write_flags(node.lineno)])
        elif isinstance(node, ast.Assign) and not fn_stack and \
                cls is None:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    index["module_names"].append(tgt.id)
        elif isinstance(node, ast.AnnAssign) and not fn_stack and \
                cls is None and isinstance(node.target, ast.Name):
            index["module_names"].append(node.target.id)

        for child in ast.iter_child_nodes(node):
            visit(child, fn_stack, cls)

    for top in tree.body:
        visit(top, [], None)
    return index


class ProjectGraph:
    """The linked whole-program view pass-2 rules query."""

    #: Root label for code only the process's original thread runs.
    MAIN = "main"

    def __init__(self, indexes: Iterable[dict]) -> None:
        self.modules: Dict[str, dict] = {}
        for idx in indexes:
            if idx is not None:
                self.modules[idx["module"]] = idx
        # symbol table: qualified function name -> record
        self.functions: Dict[str, dict] = {}
        # class name -> [(module, record)] (bare names: cross-module
        # base resolution works on how code spells the base)
        self.classes: Dict[str, List[Tuple[str, dict]]] = {}
        # method name -> {qualnames} for duck edges
        self._methods: Dict[str, Set[str]] = {}
        for mod, idx in self.modules.items():
            for fname, rec in idx["functions"].items():
                q = f"{mod}:{fname}"
                self.functions[q] = {**rec, "module": mod, "name": fname}
                if rec["cls"]:
                    self._methods.setdefault(
                        fname.split(".")[-1], set()).add(q)
            for cname, crec in idx["classes"].items():
                self.classes.setdefault(cname, []).append((mod, crec))
        self._edges: Dict[str, Set[str]] = {}       # strong call edges
        self._duck_edges: Dict[str, Set[str]] = {}
        self._link()
        self._roots: Optional[Dict[str, Set[str]]] = None
        self._strong_roots: Dict[str, Set[str]] = {}
        self._root_meta: Dict[str, dict] = {}

    # -- linking ---------------------------------------------------------

    def _class_methods(self, cls: str, seen: Optional[Set[str]] = None
                       ) -> Dict[str, str]:
        """method name -> qualname for ``cls`` including its bases."""
        seen = seen or set()
        if cls in seen:
            return {}
        seen.add(cls)
        out: Dict[str, str] = {}
        for mod, crec in self.classes.get(cls, ()):  # later defs lose
            for base in crec["bases"]:
                base = base.split(".")[-1]
                for name, q in self._class_methods(base, seen).items():
                    out.setdefault(name, q)
            for m in crec["methods"]:
                out[m] = f"{mod}:{cls}.{m}"
        return out

    def resolve(self, callee: str, module: str,
                cls: Optional[str]) -> Tuple[Optional[str], bool]:
        """``(qualname, is_strong)`` for a callee string, or (None, _).

        Strong resolutions: self-methods (through bases), module-local
        names, imported names, ``alias.f`` through module imports, and
        class constructors (edge to ``__init__``). Everything else
        falls back to a duck edge handled by the caller.
        """
        idx = self.modules.get(module)
        if idx is None:
            return None, False
        parts = callee.split(".")
        if parts[0] in ("self", "cls") and cls and len(parts) == 2:
            q = self._class_methods(cls).get(parts[1])
            if q:
                return q, True
            return None, False
        if len(parts) == 1:
            name = parts[0]
            if name in idx["functions"]:
                return f"{module}:{name}", True
            if name in idx["classes"]:
                ctor = self._class_methods(name).get("__init__")
                return ctor, True
            target = idx["imports"].get(name)
            if target:
                tmod, _, tname = target.rpartition(".")
                if tmod in self.modules:
                    if tname in self.modules[tmod]["functions"]:
                        return f"{tmod}:{tname}", True
                    if tname in self.modules[tmod]["classes"]:
                        ctor = self._class_methods(tname).get("__init__")
                        return ctor, True
            return None, False
        head, rest = parts[0], parts[1:]
        target = idx["imports"].get(head)
        if target and len(rest) == 1:
            # ``alias.f()`` — alias imported as a module
            for cand in (target, ):
                if cand in self.modules:
                    sub = self.modules[cand]
                    if rest[0] in sub["functions"]:
                        return f"{cand}:{rest[0]}", True
                    if rest[0] in sub["classes"]:
                        ctor = self._class_methods(rest[0]).get("__init__")
                        return ctor, True
        if target and len(rest) == 2 and f"{target}.{rest[0]}" \
                in self.modules:
            sub = self.modules[f"{target}.{rest[0]}"]
            if rest[1] in sub["functions"]:
                return f"{target}.{rest[0]}:{rest[1]}", True
        if head in idx["classes"] and len(rest) == 1:
            q = self._class_methods(head).get(rest[0])
            if q:
                return q, True
        return None, False

    def _link(self) -> None:
        for mod, idx in self.modules.items():
            for caller, calls in idx["calls"].items():
                cq = f"{mod}:{caller}"
                cls = caller.split(".")[0] if "." in caller else (
                    idx["functions"].get(caller, {}).get("cls"))
                if caller in idx["functions"]:
                    cls = idx["functions"][caller]["cls"]
                for callee, _line in calls:
                    q, strong = self.resolve(callee, mod, cls)
                    if q:
                        self._edges.setdefault(cq, set()).add(q)
                        continue
                    # duck edge: unresolvable receiver, distinctive
                    # method name defined by few project classes
                    mname = callee.split(".")[-1]
                    if mname in _DUCK_DENYLIST or \
                            mname.startswith("__"):
                        continue
                    cands = self._methods.get(mname, ())
                    if 0 < len(cands) <= 4:
                        self._duck_edges.setdefault(
                            cq, set()).update(cands)

    # -- queries ---------------------------------------------------------

    def reachable(self, starts: Iterable[str], duck: bool = False
                  ) -> Dict[str, Optional[str]]:
        """BFS over call edges: ``{qualname: parent}`` for every
        function reachable from ``starts`` (parents give rules a
        printable trace path)."""
        parents: Dict[str, Optional[str]] = {}
        frontier = []
        for s in starts:
            if s not in parents:
                parents[s] = None
                frontier.append(s)
        while frontier:
            nxt = []
            for q in frontier:
                outs = set(self._edges.get(q, ()))
                if duck:
                    outs |= self._duck_edges.get(q, set())
                for o in outs:
                    if o not in parents:
                        parents[o] = q
                        nxt.append(o)
            frontier = nxt
        return parents

    def trace(self, parents: Dict[str, Optional[str]], q: str
              ) -> List[str]:
        path = [q]
        while parents.get(q):
            q = parents[q]
            path.append(q)
        return list(reversed(path))

    def thread_roots(self) -> Dict[str, dict]:
        """root label -> {"entries": [qualnames], "concurrent": bool}.

        ``concurrent`` marks roots that can run several instances at
        once (one thread per HTTP request).
        """
        self._compute_roots()
        return self._root_meta

    def _thread_entry_quals(self) -> Dict[str, Tuple[str, bool]]:
        """thread-entry qualname -> (root label, self-concurrent)."""
        entries: Dict[str, Tuple[str, bool]] = {}
        for mod, idx in self.modules.items():
            for target, caller, _line, label in idx["threads"]:
                cls = None
                if caller in idx["functions"]:
                    cls = idx["functions"][caller]["cls"]
                q, _ = self.resolve(target, mod, cls)
                if q is None and "." not in target:
                    # closure target: nested ``def worker()`` inside a
                    # method is recorded as ``Cls.worker``
                    for fname in idx["functions"]:
                        if fname == target or \
                                fname.endswith("." + target):
                            q = f"{mod}:{fname}"
                            break
                if q:
                    entries[q] = (label or f"thread:{q}", False)
            for hname in idx["handlers"]:
                crec = idx["classes"].get(hname)
                if crec:
                    for m in crec["methods"]:
                        if m.startswith("do_"):
                            entries[f"{mod}:{hname}.{m}"] = (
                                "http-handler", True)
        return entries

    def _compute_roots(self) -> None:
        if self._roots is not None:
            return
        entries = self._thread_entry_quals()
        in_deg: Set[str] = set()
        for q, outs in self._edges.items():
            in_deg.update(outs)
        for q, outs in self._duck_edges.items():
            in_deg.update(outs)
        roots: Dict[str, Set[str]] = {}
        strong: Dict[str, Set[str]] = {}
        self._root_meta = {}
        for q, (label, concurrent) in entries.items():
            meta = self._root_meta.setdefault(
                label, {"entries": [], "concurrent": concurrent})
            meta["entries"].append(q)
            for reached in self.reachable([q], duck=True):
                roots.setdefault(reached, set()).add(label)
            for reached in self.reachable([q], duck=False):
                strong.setdefault(reached, set()).add(label)
        main_entries = [
            q for q in self.functions
            if q not in entries and (
                q not in in_deg
                or self.functions[q]["name"] == "main")]
        # module-level code is always a main entry
        for mod, idx in self.modules.items():
            if "<module>" in idx["calls"]:
                main_entries.append(f"{mod}:<module>")
        self._root_meta[self.MAIN] = {
            "entries": sorted(main_entries), "concurrent": False}
        for reached in self.reachable(main_entries, duck=True):
            roots.setdefault(reached, set()).add(self.MAIN)
        for reached in self.reachable(main_entries, duck=False):
            strong.setdefault(reached, set()).add(self.MAIN)
        self._roots = roots
        self._strong_roots = strong

    def roots_of(self, qualname: str) -> Set[str]:
        """Which thread roots can be executing this function."""
        self._compute_roots()
        return self._roots.get(qualname, set())

    def strong_roots_of(self, qualname: str) -> Set[str]:
        """Roots via strong (resolved) call edges only — the evidence
        bar for indicting a *single* write site, where a speculative
        duck edge would manufacture the whole finding rather than
        merely widen one."""
        self._compute_roots()
        return self._strong_roots.get(qualname, set())

    def is_concurrent_root(self, label: str) -> bool:
        self._compute_roots()
        return bool(self._root_meta.get(label, {}).get("concurrent"))

    # -- write-site queries (thread-ownership, lock derivation) ----------

    def _thread_local(self, cls: str) -> bool:
        """Classes subclassing ``threading.local`` hold per-thread
        state by construction — their instance writes never race."""
        for _mod, crec in self.classes.get(cls, ()):
            for base in crec["bases"]:
                if base in ("threading.local", "local"):
                    return True
        return False

    def attr_write_sites(self) -> Dict[Tuple[str, str],
                                       List[Tuple[str, str, int, str]]]:
        """(owner class, attr) -> [(module, caller qual, line, flags)].

        ``self.x`` binds to the enclosing class. A write through any
        other receiver (``ledger.h2d_bytes += n``) binds by attribute
        name when exactly one project class self-writes that attribute
        — distinctive names identify the state, ambiguous ones are
        skipped rather than guessed.
        """
        self_owner: Dict[str, Set[str]] = {}  # attr -> classes
        for mod, idx in self.modules.items():
            for recv, attr, caller, _line, _flags in idx["attr_writes"]:
                if recv == "self":
                    cls = None
                    if caller in idx["functions"]:
                        cls = idx["functions"][caller]["cls"]
                    if cls and not self._thread_local(cls):
                        self_owner.setdefault(attr, set()).add(cls)
        sites: Dict[Tuple[str, str], List[Tuple[str, str, int, str]]] = {}
        for mod, idx in self.modules.items():
            for recv, attr, caller, line, flags in idx["attr_writes"]:
                if recv == "self":
                    cls = None
                    if caller in idx["functions"]:
                        cls = idx["functions"][caller]["cls"]
                    if cls and not self._thread_local(cls):
                        sites.setdefault((cls, attr), []).append(
                            (mod, caller, line, flags))
                else:
                    owners = self_owner.get(attr, set())
                    if len(owners) == 1:
                        sites.setdefault(
                            (next(iter(owners)), attr), []).append(
                            (mod, caller, line, flags))
        return sites

    def global_write_sites(self) -> Dict[Tuple[str, str],
                                         List[Tuple[str, int, str]]]:
        """(module, global name) -> [(caller qual, line, flags)]."""
        sites: Dict[Tuple[str, str], List[Tuple[str, int, str]]] = {}
        for mod, idx in self.modules.items():
            for name, caller, line, flags in idx["global_writes"]:
                sites.setdefault((mod, name), []).append(
                    (caller, line, flags))
        return sites


def build_graph(contexts: Iterable[FileContext],
                cached: Optional[Dict[str, dict]] = None
                ) -> ProjectGraph:
    """Link a graph from file contexts; ``cached`` maps path → a
    previously extracted (sha-validated) module index to skip the AST
    walk for unchanged files."""
    indexes = []
    for ctx in contexts:
        if not ctx.path.startswith("tpu_cooccurrence/") or \
                not ctx.is_python:
            continue
        idx = (cached or {}).get(ctx.path)
        if idx is None:
            idx = extract_module(ctx)
        if idx is not None:
            indexes.append(idx)
    return ProjectGraph(indexes)
