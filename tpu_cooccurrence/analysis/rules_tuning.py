"""tuning-registry: every performance knob resolves through tuning.py.

The enforcement half of the :mod:`tpu_cooccurrence.tuning` registry
(same truth-table import idiom as the metric/fault/flag rules — the
analyzer imports the live registry, so the rule can never drift from
it):

* **unregistered knobs** — any ``TPU_COOC_*`` token in package source
  that is not a registered parameter's ``env`` binding is a knob
  someone added without declaring it (the exact failure mode that
  motivated the registry);
* **direct environ reads** — ``os.environ.get("TPU_COOC_...")`` /
  ``os.getenv`` / ``os.environ[...]`` outside ``tuning.py`` bypass the
  registration check; reads go through :func:`tuning.env_read` (same
  semantics, plus the check) so the registry always knows the live
  read surface;
* **dead rows** — a registered env binding no code mentions, or a
  registered flag ``config.py`` does not define, is a row that rotted
  out of the codebase;
* **magic thresholds** (separate rule, ``tuning-magic-number``) — a
  hot-path comparison against a numeric literal equal to a distinctive
  registered perf default is an inlined copy of a knob: when the knob
  moves, the copy does not. Only distinctive defaults participate
  (ints with ``abs >= 16``, floats outside {0, 0.5, 1}) — flagging
  every ``x > 0`` would be noise, not analysis.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List

from .core import (FileContext, Finding, RepoContext, Rule, dotted_name,
                   register)

from tpu_cooccurrence import tuning as _tuning

_ENV_TOKEN_RE = re.compile(r"TPU_COOC_[A-Z0-9_]+")

#: The one module allowed to touch ``os.environ`` for knobs, and whose
#: registrations are the ground truth the tokens are checked against.
_REGISTRY_PATH = "tpu_cooccurrence/tuning.py"

#: Where a magic copy of a knob default is a perf bug, not style.
_HOT_PATH_PREFIXES = ("tpu_cooccurrence/ops/", "tpu_cooccurrence/state/",
                      "tpu_cooccurrence/parallel/")

_ENV_READ_FUNCS = {"os.environ.get", "os.getenv", "environ.get"}


def _distinctive_defaults():
    """{numeric default: parameter name} for perf knobs whose default
    is unlikely to appear in unrelated code."""
    out = {}
    for p in _tuning.REGISTRY.values():
        if p.kind != "perf":
            continue
        v = p.default
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        if isinstance(v, int) and abs(v) >= 16:
            out[v] = p.name
        elif isinstance(v, float) and v not in (0.0, 0.5, 1.0):
            out[v] = p.name
    return out


@register
class TuningRegistryRule(Rule):
    name = "tuning-registry"
    description = (
        "TPU_COOC_* knobs must be declared in the TuningParameter "
        "registry and read via tuning.env_read; registered bindings "
        "must stay live (env mentioned somewhere, flag in config.py)")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.path.startswith("tpu_cooccurrence/") or \
                not ctx.is_python or ctx.path == _REGISTRY_PATH:
            return
        registered = set(_tuning.by_env())
        seen_lines = set()
        for i, line in enumerate(ctx.lines, start=1):
            for tok in _ENV_TOKEN_RE.findall(line):
                if tok not in registered and (i, tok) not in seen_lines:
                    seen_lines.add((i, tok))
                    yield Finding(
                        rule=self.name, file=ctx.path, line=i,
                        message=(
                            f"`{tok}` is not a registered "
                            f"TuningParameter env binding — declare "
                            f"the knob in tpu_cooccurrence/tuning.py"))
        # module-level string constants, so `os.environ.get(RUN_ID_ENV)`
        # with RUN_ID_ENV = "TPU_COOC_RUN_ID" is caught like a literal
        consts = {}
        for node in ctx.nodes(ast.Assign):
            if isinstance(node.value, ast.Constant) and isinstance(
                    node.value.value, str):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        consts[tgt.id] = node.value.value

        def knob_arg(arg) -> str:
            if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str):
                v = arg.value
            elif isinstance(arg, ast.Name):
                v = consts.get(arg.id, "")
            else:
                return ""
            return v if v.startswith("TPU_COOC_") else ""

        for node in ctx.nodes(ast.Call):
            name = dotted_name(node.func) or ""
            if name in _ENV_READ_FUNCS and node.args:
                knob = knob_arg(node.args[0])
                if knob:
                    yield Finding(
                        rule=self.name, file=ctx.path, line=node.lineno,
                        message=(
                            f"direct `{name}({knob!r})` — knob reads "
                            f"go through tuning.env_read so the "
                            f"registry sees every read site"))
        for node in ctx.nodes(ast.Subscript):
            if isinstance(node.ctx, ast.Load) and \
                    (dotted_name(node.value) or "") in ("os.environ",
                                                        "environ"):
                knob = knob_arg(node.slice)
                if knob:
                    yield Finding(
                        rule=self.name, file=ctx.path, line=node.lineno,
                        message=(
                            f"direct `os.environ[{knob!r}]` — knob "
                            f"reads go through tuning.env_read"))

    def finalize(self, repo: RepoContext) -> Iterable[Finding]:
        reg_ctx = next((c for c in repo.files
                        if c.path == _REGISTRY_PATH), None)
        if reg_ctx is None:
            return

        def reg_line(pname: str) -> int:
            needle = f'name="{pname}"'
            for i, line in enumerate(reg_ctx.lines, start=1):
                if needle in line:
                    return i
            return 1

        sources = [(c.path, c.source) for c in repo.python_files()
                   if c.path != _REGISTRY_PATH]
        config_src = next((s for p, s in sources
                           if p.endswith("/config.py")), "")
        for p in _tuning.REGISTRY.values():
            if p.env and not any(p.env in s for _, s in sources):
                yield Finding(
                    rule=self.name, file=_REGISTRY_PATH,
                    line=reg_line(p.name),
                    message=(
                        f"registered env binding `{p.env}` "
                        f"(`{p.name}`) is read nowhere — dead "
                        f"registry row"))
            if p.flag and f'"{p.flag}"' not in config_src:
                yield Finding(
                    rule=self.name, file=_REGISTRY_PATH,
                    line=reg_line(p.name),
                    message=(
                        f"registered flag binding `{p.flag}` "
                        f"(`{p.name}`) is not defined in config.py — "
                        f"dead registry row"))


@register
class TuningMagicNumberRule(Rule):
    name = "tuning-magic-number"
    severity = "warning"
    description = (
        "hot-path comparison against a literal equal to a registered "
        "perf knob's distinctive default — read the knob from the "
        "tuning registry instead of inlining a copy")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.path.startswith(_HOT_PATH_PREFIXES):
            return ()
        distinctive = _distinctive_defaults()
        if not distinctive:
            return ()
        out: List[Finding] = []
        for node in ctx.nodes(ast.Compare):
            for operand in [node.left, *node.comparators]:
                if isinstance(operand, ast.Constant) and isinstance(
                        operand.value, (int, float)) and not isinstance(
                        operand.value, bool) and \
                        operand.value in distinctive:
                    out.append(Finding(
                        rule=self.name, file=ctx.path,
                        line=node.lineno,
                        message=(
                            f"threshold literal {operand.value} equals "
                            f"registered knob "
                            f"`{distinctive[operand.value]}`'s default "
                            f"— use tuning.default("
                            f"{distinctive[operand.value]!r})")))
        return out
