"""Gang-robustness invariants: watchdog-wrapped collectives + live sites.

Two rules guard the distributed robustness plane (ISSUE 10):

* **collective-watchdog** — a JAX multi-controller collective whose
  peer died does not fail, it *hangs forever*. Every host-level
  collective the framework issues must therefore go through the
  watchdog wrappers in ``parallel/distributed.py``
  (``guarded_allgather`` / ``gang_barrier`` / the ``allgather_min``/
  ``allgather_max`` votes), which convert the silent wedge into a
  supervised exit the gang supervisor can restart. A raw
  ``multihost_utils.process_allgather`` / ``sync_global_devices`` call
  anywhere else in the package is an unguarded hang waiting for its
  first dead peer.

* **gang-fault-sites** — the gang's process-qualified chaos sites
  (``robustness/gang.GANG_SITES``: ``barrier_enter``, ``ckpt_commit``,
  ``peer_heartbeat``) must stay registered in ``faults.SITES`` *and*
  fired by real package code — the whole-gang recovery tests address
  workers by these names, so a renamed or unplugged site silently
  removes chaos coverage while the tests keep passing on stale specs.

Both are AST-checked and baseline-free by construction: the repo ships
clean and there is nothing to grandfather.
"""

from __future__ import annotations

import ast
from typing import Iterable, Set

from ..robustness.faults import SITES
from ..robustness.gang import GANG_SITES
from .core import FileContext, Finding, RepoContext, Rule, register

#: The one module allowed to touch the raw collective entry points —
#: it owns the watchdog that wraps them.
_WRAPPER_PATH = "tpu_cooccurrence/parallel/distributed.py"

#: Raw multi-controller collective entry points that hang (not fail) on
#: peer loss.
_RAW_COLLECTIVES = ("process_allgather", "sync_global_devices")

_FAULTS_PATH = "tpu_cooccurrence/robustness/faults.py"


@register
class CollectiveWatchdogRule(Rule):
    name = "collective-watchdog"
    description = ("host-level collectives must go through the watchdog "
                   "wrappers in parallel/distributed.py (raw "
                   "multihost_utils calls hang forever on peer loss)")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if (not ctx.path.startswith("tpu_cooccurrence/")
                or not ctx.is_python or ctx.path == _WRAPPER_PATH):
            return
        if not any(c in ctx.source for c in _RAW_COLLECTIVES):
            return
        if ctx.tree is None:
            return
        for node in ctx.nodes(ast.Call):
            func = node.func
            callee = None
            if isinstance(func, ast.Attribute):
                callee = func.attr
            elif isinstance(func, ast.Name):
                callee = func.id
            if callee in _RAW_COLLECTIVES:
                yield Finding(
                    rule=self.name, file=ctx.path, line=node.lineno,
                    message=(f"raw collective {callee}() bypasses the "
                             f"collective-entry watchdog — call the "
                             f"wrapper in parallel/distributed.py "
                             f"(guarded_allgather / gang_barrier) so a "
                             f"dead peer becomes a supervised exit, "
                             f"not a silent hang"))


@register
class GangFaultSiteRule(Rule):
    name = "gang-fault-sites"
    description = ("every gang chaos site (gang.GANG_SITES) must be "
                   "registered in faults.SITES and fired by package "
                   "code")

    def finalize(self, repo: RepoContext) -> Iterable[Finding]:
        # Full-repo passes only: a single-fixture run has no business
        # declaring sites unplugged (same scoping as the fault-site
        # rule's reverse check).
        if not any(c.path == _FAULTS_PATH for c in repo.files):
            return
        fired: Set[str] = set()
        for ctx in repo.package_files():
            if "fire(" not in ctx.source or ctx.tree is None:
                continue
            for node in ctx.nodes(ast.Call):
                if (isinstance(node, ast.Call)
                        and ((isinstance(node.func, ast.Attribute)
                              and node.func.attr == "fire")
                             or (isinstance(node.func, ast.Name)
                                 and node.func.id == "fire"))
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    fired.add(node.args[0].value)
        for site in GANG_SITES:
            if site not in SITES:
                yield Finding(
                    rule=self.name, file=_FAULTS_PATH, line=1,
                    message=(f"gang chaos site {site!r} "
                             f"(gang.GANG_SITES) is not registered in "
                             f"faults.SITES — the whole-gang recovery "
                             f"tests address workers by this name"))
            elif site not in fired:
                yield Finding(
                    rule=self.name, file=_FAULTS_PATH, line=1,
                    message=(f"gang chaos site {site!r} is registered "
                             f"but never fired by package code — the "
                             f"chaos specs that target it can no "
                             f"longer trigger"))
