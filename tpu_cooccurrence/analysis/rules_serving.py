"""HTTP route registry drift (serving plane).

Every route the live HTTP plane answers is operator-facing contract
three times over: its request latency must be measurable (a route
without a latency histogram is invisible to the p99 the serving plane
exists to bound), it must be documented where operators look (README),
and it must be exercised from ``tests/`` (an unprobed route is exactly
how ``/recommend`` would rot — the one endpoint nothing scrapes in CI).

``observability/http.py`` therefore keeps a single literal table,
``ROUTE_METRICS`` (route -> latency-metric name), and this rule holds it
to all three obligations plus the reverse direction: a route string
handled in ``do_GET`` (or quoted anywhere in the module) that is not in
the table is a silent, unmeasured endpoint. AST-checked, baseline-free
by construction — mirroring ``rules_fused``.

The serving FLEET (ISSUE 13) adds a second server: the read replica
(``serving/replica.py``). Two more obligations:

* every route-shaped literal the replica module quotes must be in the
  SAME ``ROUTE_METRICS`` table — a replica cannot grow an unmeasured
  endpoint the job's server never had (``ServingRouteRule``, extended);
* replica ``/recommend`` responses must carry the ``generation`` tag —
  the read-your-window token a front tier compares across the fleet.
  The replica serves through a ``MetricsServer`` subclass, so the tag
  obligation lands on whichever ``recommend`` body actually answers:
  the replica's own override when it has one, the inherited
  ``observability/http.py`` body otherwise
  (``ReplicaGenerationTagRule``).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, Optional

from ..observability.registry import CANONICAL_METRICS
from .core import (
    FileContext,
    Finding,
    RepoContext,
    Rule,
    dotted_name,
    register,
)

_HTTP_PATH = "tpu_cooccurrence/observability/http.py"

_REPLICA_PATH = "tpu_cooccurrence/serving/replica.py"

#: A route-shaped string literal: one absolute path segment, lowercase.
#: (Error bodies, content types and log lines never fully match.)
_ROUTE_RE = re.compile(r"^/[a-z][a-z0-9_]*$")


def _route_table(tree: ast.Module) -> "tuple[Optional[Dict[str, str]], int]":
    """The ``ROUTE_METRICS`` literal dict and its line, or (None, 0)."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "ROUTE_METRICS"
                        for t in node.targets)):
            continue
        if not isinstance(node.value, ast.Dict):
            return None, node.lineno
        table: Dict[str, str] = {}
        for k, v in zip(node.value.keys, node.value.values):
            if (isinstance(k, ast.Constant) and isinstance(k.value, str)
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)):
                table[k.value] = v.value
        return table, node.lineno
    return None, 0


@register
class ServingRouteRule(Rule):
    name = "serving-route"
    description = ("every HTTP route in observability/http.py must be in "
                   "ROUTE_METRICS with a CANONICAL_METRICS latency "
                   "metric, a README mention and a tests/ reference")

    def finalize(self, repo: RepoContext) -> Iterable[Finding]:
        src: Optional[FileContext] = next(
            (c for c in repo.files if c.path == _HTTP_PATH), None)
        if src is None or src.tree is None:
            return
        table, lineno = _route_table(src.tree)
        if table is None:
            yield Finding(
                rule=self.name, file=_HTTP_PATH, line=max(lineno, 1),
                message="ROUTE_METRICS literal dict not found (the route "
                        "registry this rule guards is gone or no longer "
                        "a plain literal)")
            return
        readme = next((c for c in repo.files if c.path == "README.md"),
                      None)
        tests_text = "\n".join(c.source for c in repo.files
                               if c.path.startswith("tests/"))
        for route, metric in sorted(table.items()):
            if metric not in CANONICAL_METRICS:
                yield Finding(
                    rule=self.name, file=_HTTP_PATH, line=lineno,
                    message=(f"route {route!r} maps to latency metric "
                             f"{metric!r} which is not in "
                             f"CANONICAL_METRICS — register it (the "
                             f"route's tail latency must be scrapeable)"))
            if readme is not None and route not in readme.source:
                yield Finding(
                    rule=self.name, file=_HTTP_PATH, line=lineno,
                    message=(f"route {route!r} is not mentioned in "
                             f"README.md — document it in the operator "
                             f"guide"))
            if route not in tests_text:
                yield Finding(
                    rule=self.name, file=_HTTP_PATH, line=lineno,
                    message=(f"route {route!r} has no tests/ reference — "
                             f"an unprobed endpoint cannot claim its "
                             f"latency or schema in CI"))
        # Reverse direction: any route-shaped literal in the module that
        # is not registered is an unmeasured endpoint (or a stale doc).
        for ln, value in src.strings():
            if _ROUTE_RE.match(value) and value not in table:
                yield Finding(
                    rule=self.name, file=_HTTP_PATH, line=ln,
                    message=(f"route-shaped literal {value!r} is not in "
                             f"ROUTE_METRICS — register it (with a "
                             f"latency metric) or rename it"))
        # The replica server (serving/replica.py, ISSUE 13) answers
        # through the same table: every route it quotes must be
        # registered there too — a replica cannot grow an unmeasured
        # endpoint the job's server never had.
        rep = next((c for c in repo.files if c.path == _REPLICA_PATH),
                   None)
        if rep is not None and rep.tree is not None:
            for ln, value in rep.strings():
                if _ROUTE_RE.match(value) and value not in table:
                    yield Finding(
                        rule=self.name, file=_REPLICA_PATH, line=ln,
                        message=(f"replica route-shaped literal "
                                 f"{value!r} is not in "
                                 f"observability/http.py ROUTE_METRICS "
                                 f"— the replica serves through the "
                                 f"job's route table; register it "
                                 f"(with a latency metric) or rename "
                                 f"it"))


def _subtree_strings(node: ast.AST) -> "set[str]":
    return {n.value for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)}


def _find_recommend(tree: ast.Module) -> Optional[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "recommend":
            return node
    return None


@register
class ReplicaGenerationTagRule(Rule):
    name = "replica-generation-tag"
    description = ("replica /recommend responses must carry the "
                   "generation tag (read-your-window token), served "
                   "through a MetricsServer subclass")

    def finalize(self, repo: RepoContext) -> Iterable[Finding]:
        rep: Optional[FileContext] = next(
            (c for c in repo.files if c.path == _REPLICA_PATH), None)
        if rep is None or rep.tree is None:
            return  # no replica module in this repo: nothing to pin
        server_cls: Optional[ast.ClassDef] = None
        for node in ast.walk(rep.tree):
            if isinstance(node, ast.ClassDef) and any(
                    (dotted_name(b) or "").endswith("MetricsServer")
                    for b in node.bases):
                server_cls = node
                break
        if server_cls is None:
            yield Finding(
                rule=self.name, file=_REPLICA_PATH, line=1,
                message="no MetricsServer subclass found — the replica "
                        "must serve through the shared HTTP plane (one "
                        "ROUTE_METRICS table, one latency histogram "
                        "per route), not a parallel server")
            return
        own = next((n for n in server_cls.body
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))
                    and n.name == "recommend"), None)
        if own is not None:
            if "generation" not in _subtree_strings(own):
                yield Finding(
                    rule=self.name, file=_REPLICA_PATH, line=own.lineno,
                    message=(f"{server_cls.name}.recommend overrides "
                             f"the route body without a 'generation' "
                             f"response key — replica responses must "
                             f"carry the generation tag (the "
                             f"read-your-window token)"))
            return
        # No override: the inherited observability/http.py body answers
        # — the tag obligation lands there.
        src = next((c for c in repo.files if c.path == _HTTP_PATH), None)
        if src is None or src.tree is None:
            return  # fixture repos without http.py cannot be judged
        fn = _find_recommend(src.tree)
        if fn is None or "generation" not in _subtree_strings(fn):
            yield Finding(
                rule=self.name, file=_HTTP_PATH,
                line=fn.lineno if fn is not None else 1,
                message="the inherited MetricsServer.recommend body "
                        "serves the replica's /recommend but carries "
                        "no 'generation' response key — replica "
                        "responses must be generation-tagged")
