"""HTTP route registry drift (serving plane).

Every route the live HTTP plane answers is operator-facing contract
three times over: its request latency must be measurable (a route
without a latency histogram is invisible to the p99 the serving plane
exists to bound), it must be documented where operators look (README),
and it must be exercised from ``tests/`` (an unprobed route is exactly
how ``/recommend`` would rot — the one endpoint nothing scrapes in CI).

``observability/http.py`` therefore keeps a single literal table,
``ROUTE_METRICS`` (route -> latency-metric name), and this rule holds it
to all three obligations plus the reverse direction: a route string
handled in ``do_GET`` (or quoted anywhere in the module) that is not in
the table is a silent, unmeasured endpoint. AST-checked, baseline-free
by construction — mirroring ``rules_fused``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, Optional

from ..observability.registry import CANONICAL_METRICS
from .core import (
    FileContext,
    Finding,
    RepoContext,
    Rule,
    register,
    string_constants,
)

_HTTP_PATH = "tpu_cooccurrence/observability/http.py"

#: A route-shaped string literal: one absolute path segment, lowercase.
#: (Error bodies, content types and log lines never fully match.)
_ROUTE_RE = re.compile(r"^/[a-z][a-z0-9_]*$")


def _route_table(tree: ast.Module) -> "tuple[Optional[Dict[str, str]], int]":
    """The ``ROUTE_METRICS`` literal dict and its line, or (None, 0)."""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "ROUTE_METRICS"
                        for t in node.targets)):
            continue
        if not isinstance(node.value, ast.Dict):
            return None, node.lineno
        table: Dict[str, str] = {}
        for k, v in zip(node.value.keys, node.value.values):
            if (isinstance(k, ast.Constant) and isinstance(k.value, str)
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)):
                table[k.value] = v.value
        return table, node.lineno
    return None, 0


@register
class ServingRouteRule(Rule):
    name = "serving-route"
    description = ("every HTTP route in observability/http.py must be in "
                   "ROUTE_METRICS with a CANONICAL_METRICS latency "
                   "metric, a README mention and a tests/ reference")

    def finalize(self, repo: RepoContext) -> Iterable[Finding]:
        src: Optional[FileContext] = next(
            (c for c in repo.files if c.path == _HTTP_PATH), None)
        if src is None or src.tree is None:
            return
        table, lineno = _route_table(src.tree)
        if table is None:
            yield Finding(
                rule=self.name, file=_HTTP_PATH, line=max(lineno, 1),
                message="ROUTE_METRICS literal dict not found (the route "
                        "registry this rule guards is gone or no longer "
                        "a plain literal)")
            return
        readme = next((c for c in repo.files if c.path == "README.md"),
                      None)
        tests_text = "\n".join(c.source for c in repo.files
                               if c.path.startswith("tests/"))
        for route, metric in sorted(table.items()):
            if metric not in CANONICAL_METRICS:
                yield Finding(
                    rule=self.name, file=_HTTP_PATH, line=lineno,
                    message=(f"route {route!r} maps to latency metric "
                             f"{metric!r} which is not in "
                             f"CANONICAL_METRICS — register it (the "
                             f"route's tail latency must be scrapeable)"))
            if readme is not None and route not in readme.source:
                yield Finding(
                    rule=self.name, file=_HTTP_PATH, line=lineno,
                    message=(f"route {route!r} is not mentioned in "
                             f"README.md — document it in the operator "
                             f"guide"))
            if route not in tests_text:
                yield Finding(
                    rule=self.name, file=_HTTP_PATH, line=lineno,
                    message=(f"route {route!r} has no tests/ reference — "
                             f"an unprobed endpoint cannot claim its "
                             f"latency or schema in CI"))
        # Reverse direction: any route-shaped literal in the module that
        # is not registered is an unmeasured endpoint (or a stale doc).
        for ln, value in string_constants(src.tree):
            if _ROUTE_RE.match(value) and value not in table:
                yield Finding(
                    rule=self.name, file=_HTTP_PATH, line=ln,
                    message=(f"route-shaped literal {value!r} is not in "
                             f"ROUTE_METRICS — register it (with a "
                             f"latency metric) or rename it"))
