"""Ingest offset-codec drift guard (baseline-free).

``ingest-offset-registry`` — the ingest offset section rides the
checkpoint meta (``meta["ingest_offsets"]``) and the delta header, but
its INTERNAL keys are produced and consumed inside the sources
themselves (``io/source.py`` ``offsets_state``/``restore_offsets`` for
the files-format in-flight guard; ``io/partitioned.py`` for the
per-partition byte/record cursors). Nothing structural stops a
writer-side offset field from landing with no restore-side reader: the
checkpoint still commits, the digest still verifies, and the field
silently never influences where the wire resumes — exactly-once becomes
at-least-once one rescale later.

The rule makes the offset codec explicit: every string key written into
the section dicts (the dict literals / subscript stores on ``offsets``
and ``in_flight`` in ``io/source.py``; ``offsets`` and ``partitions``
in ``io/partitioned.py``) must

* have a matching restore-side READ of the same key string somewhere in
  its module (a read-position constant — subscript load, ``.get``,
  membership test), and
* appear as a string constant somewhere under ``tests/`` — the
  round-trip fixture reference that pins the field's semantics
  (``tests/test_ingest_offsets.py`` keeps the canonical list).

Baseline-free: a new offset field lands in the same PR as its reader
and its test, or tier-1 fails.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Set, Tuple

from .core import FileContext, Finding, RepoContext, Rule, register

#: Module -> dict-variable names whose string keys form the offset codec.
_FORMAT_FILES = {
    "tpu_cooccurrence/io/source.py": ("offsets", "in_flight"),
    "tpu_cooccurrence/io/partitioned.py": ("offsets", "partitions"),
}


def _written_keys(ctx: FileContext,
                  names) -> "Tuple[Dict[str, int], Set[int]]":
    """``{key: first write line}`` plus the AST node ids of the write-
    position key constants (so the read scan can exclude them)."""
    written: Dict[str, int] = {}
    write_nodes: Set[int] = set()
    for node in ctx.nodes(ast.Assign):
        for tgt in node.targets:
            # offsets = {"k": ...} / in_flight = {"k": ...}
            if (isinstance(tgt, ast.Name) and tgt.id in names
                    and isinstance(node.value, ast.Dict)):
                for k in node.value.keys:
                    if (isinstance(k, ast.Constant)
                            and isinstance(k.value, str)):
                        written.setdefault(k.value, k.lineno)
                        write_nodes.add(id(k))
            # offsets["k"] = ... / partitions[name] = {"k": ...}
            if (isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id in names):
                if (isinstance(tgt.slice, ast.Constant)
                        and isinstance(tgt.slice.value, str)):
                    written.setdefault(tgt.slice.value, tgt.lineno)
                    write_nodes.add(id(tgt.slice))
                # The partitioned source stores one dict PER
                # partition name (a variable subscript): its value
                # literal's keys are format keys too.
                if isinstance(node.value, ast.Dict):
                    for k in node.value.keys:
                        if (isinstance(k, ast.Constant)
                                and isinstance(k.value, str)):
                            written.setdefault(k.value, k.lineno)
                            write_nodes.add(id(k))
    return written, write_nodes


def _read_constants(ctx: FileContext, write_nodes: Set[int]) -> Set[str]:
    """Every string constant in the module that is NOT one of the
    write-position keys — the reader-evidence pool (subscript loads,
    ``.get`` arguments, membership tests all surface here)."""
    out: Set[str] = set()
    for node in ctx.nodes(ast.Constant):
        if isinstance(node.value, str) and id(node) not in write_nodes:
            out.add(node.value)
    return out


def _tests_constants(repo: RepoContext) -> Set[str]:
    return repo.test_string_constants()


@register
class IngestOffsetRegistryRule(Rule):
    name = "ingest-offset-registry"
    description = ("every field written into an ingest offset section "
                   "needs a restore-side reader in its module and a "
                   "tests/ round-trip reference")

    def finalize(self, repo: RepoContext) -> Iterable[Finding]:
        # Scope guard (the rules_ckpt posture): silent when neither
        # source module is present (fixture repos, partial trees); a
        # repo where one end of the codec vanished is flagged.
        present = {path: next((c for c in repo.files if c.path == path),
                              None)
                   for path in _FORMAT_FILES}
        if not any(c is not None for c in present.values()):
            return
        tests = None
        for path, names in sorted(_FORMAT_FILES.items()):
            src = present[path]
            if src is None or src.tree is None:
                yield Finding(
                    rule=self.name, file=path, line=1,
                    message=(f"ingest module {path} is missing or "
                             f"unparseable — the offset-codec registry "
                             f"this rule guards is gone"))
                continue
            written, write_nodes = _written_keys(src, names)
            if not written:
                yield Finding(
                    rule=self.name, file=path, line=1,
                    message=(f"no offset-section keys found on {names} "
                             f"in {path} (writer moved? update "
                             f"rules_ingest._FORMAT_FILES)"))
                continue
            reads = _read_constants(src, write_nodes)
            if tests is None:
                tests = _tests_constants(repo)
            for key, line in sorted(written.items()):
                if key not in reads:
                    yield Finding(
                        rule=self.name, file=path, line=line,
                        message=(f"offset key {key!r} is written but "
                                 f"never read back in {path} — a "
                                 f"writer-only field silently stops "
                                 f"steering where the wire resumes; add "
                                 f"the restore-side reader (or drop the "
                                 f"field)"))
                if key not in tests:
                    yield Finding(
                        rule=self.name, file=path, line=line,
                        message=(f"offset key {key!r} has no tests/ "
                                 f"round-trip reference — pin it in "
                                 f"tests/test_ingest_offsets.py's "
                                 f"offset-key registry"))
