"""jit / device hygiene — transitive over the whole-program call graph.

The scorers' hot paths are jit-compiled (``@jax.jit`` /
``functools.partial(jax.jit, ...)`` / ``jax.jit(shard_map(...))``) and
stay fast only while they remain *pure device programs*: a stray
``np.asarray``/``float()`` on a traced value forces a host sync per
window, a ``print`` retraces, host RNG silently freezes into the traced
constant, and an ``os.environ`` read bakes the launch-time value into
the compiled program. Separately, the state-carrying jits donate their
input buffers (``ops/donation.py``); a donated array is dead the moment
the dispatch is enqueued, and reading it afterwards is exactly the TFRT
use-after-donate crash class the CPU backend gating exists for.

* ``jit-purity`` — two passes. Per file: the body of every jitted
  function (decorated, or wrapped at module level). Whole-program:
  every function *reachable from a jit entry over strong call edges*
  (:mod:`.graph`) — everything called while tracing runs under the
  trace, so a host sync two modules below the entry point is the same
  bug as one in its body. This replaced the old "one intra-module hop,
  ops/ only" special case, which provably missed a host-RNG call two
  hops down. Duck edges are excluded: a speculative edge would invent
  a purity bug on code that never traces.
* ``donation-reuse`` — after a call to a donating jit (its
  ``donate_argnums`` positions read straight from the AST), any read of
  the same argument expression before it is reassigned is a finding.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from .core import FileContext, Finding, RepoContext, Rule, dotted_name, \
    register
from .graph import module_name_for

_NUMPY_SYNC = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_RNG_PREFIXES = ("np.random.", "numpy.random.", "random.")
_ENV_READS = {"os.environ.get", "os.getenv", "environ.get",
              "tuning.env_read", "env_read"}


def _is_jit_ref(node: ast.AST) -> bool:
    """Does this expression reference jax.jit / pjit?"""
    name = dotted_name(node) or ""
    return name in ("jax.jit", "jit", "pjit", "jax.pjit") or \
        name.endswith(".pjit")


def _partial_of_jit(call: ast.Call) -> bool:
    """``functools.partial(jax.jit, ...)``"""
    fname = dotted_name(call.func) or ""
    return (fname in ("functools.partial", "partial") and call.args
            and _is_jit_ref(call.args[0]))


def _static_argnames(call: ast.Call) -> Set[str]:
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            if isinstance(kw.value, ast.Constant) and isinstance(
                    kw.value.value, str):
                return {kw.value.value}
            if isinstance(kw.value, (ast.Tuple, ast.List)):
                return {e.value for e in kw.value.elts
                        if isinstance(e, ast.Constant)}
    return set()


def _donated_positions(call: ast.Call) -> Tuple[int, ...]:
    """Literal argnums out of ``donate_argnums=donate_argnums(0, 1)`` /
    ``donate_argnums=(0, 1)`` / ``donate_argnums=0``."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Call):  # the ops.donation.donate_argnums gate
            return tuple(a.value for a in v.args
                         if isinstance(a, ast.Constant))
        if isinstance(v, (ast.Tuple, ast.List)):
            return tuple(e.value for e in v.elts
                         if isinstance(e, ast.Constant))
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
    return ()


class _JitInfo:
    def __init__(self, fn: ast.FunctionDef, static: Set[str]) -> None:
        self.fn = fn
        self.static = static


def _collect_jitted(tree: ast.Module
                    ) -> Tuple[List[_JitInfo], Dict[str, Tuple[int, ...]]]:
    """(jit *entry points* in this file, donating-callable name ->
    donated argnums).

    Entry points only — transitive closure over callees lives in the
    whole-program pass. Donating callables are keyed by how call sites
    spell them: a bare name (module-level def / assignment) or
    ``self.<attr>``. Memoized on the tree: both rules and the
    whole-program pass ask for the same file's entries.
    """
    cached = getattr(tree, "_cooclint_jitted", None)
    if cached is not None:
        return cached
    fns_by_name = {n.name: n for n in ast.walk(tree)
                   if isinstance(n, ast.FunctionDef)}
    jitted: Dict[str, _JitInfo] = {}
    donating: Dict[str, Tuple[int, ...]] = {}

    def mark(fn: Optional[ast.FunctionDef], static: Set[str]) -> None:
        if fn is not None and fn.name not in jitted:
            jitted[fn.name] = _JitInfo(fn, static)

    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                if _is_jit_ref(dec):
                    mark(node, set())
                elif isinstance(dec, ast.Call) and (
                        _is_jit_ref(dec.func) or _partial_of_jit(dec)):
                    mark(node, _static_argnames(dec))
                    pos = _donated_positions(dec)
                    if pos:
                        donating[node.name] = pos
        elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call):
            call = node.value
            jit_call = None
            if _is_jit_ref(call.func):  # name = jax.jit(fn, ...)
                jit_call = call
                inner = call.args[0] if call.args else None
            elif isinstance(call.func, ast.Call) and _partial_of_jit(
                    call.func):  # name = partial(jax.jit, ...)(fn)
                jit_call = call.func
                inner = call.args[0] if call.args else None
            else:
                continue
            if isinstance(inner, ast.Name):
                mark(fns_by_name.get(inner.id), _static_argnames(jit_call))
            elif isinstance(inner, ast.Lambda):
                pass  # lambda bodies are single exprs; purity scan below
            pos = _donated_positions(jit_call)
            if pos:
                for tgt in node.targets:
                    key = dotted_name(tgt)
                    if key:
                        donating[key] = pos
    result = (list(jitted.values()), donating)
    tree._cooclint_jitted = result
    return result


def _purity_scan(calls: Iterable[ast.Call],
                 env_subscripts: Iterable[ast.Subscript],
                 traced: Set[str], label: str, path: str,
                 suffix: str = "") -> Iterator[Finding]:
    """Host-sync findings for traced code. ``calls``/``env_subscripts``
    are the nodes inside the traced span; ``label`` names the jitted
    function for the message; ``suffix`` carries the call-graph trace
    for transitively reached code."""
    for node in calls:
        name = dotted_name(node.func) or ""
        bad = None
        if name in _NUMPY_SYNC:
            bad = f"{name}() materializes the traced value on host"
        elif name == "print":
            bad = "print() inside a traced function (retraces)"
        elif name.startswith(_RNG_PREFIXES):
            bad = (f"host RNG {name}() freezes into the trace; "
                   f"use jax.random with a threaded key")
        elif name in _ENV_READS:
            bad = (f"{name}() in traced code bakes the launch-time "
                   f"environment into the compiled program")
        elif name in ("float", "int") and len(node.args) == 1:
            arg = node.args[0]
            if isinstance(arg, ast.Name) and arg.id in traced:
                bad = (f"{name}({arg.id}) forces a host sync on "
                       f"a traced parameter")
        elif isinstance(node.func, ast.Attribute) and \
                node.func.attr == "block_until_ready":
            bad = ("block_until_ready() inside a jitted "
                   "function defeats async dispatch")
        if bad is not None:
            yield Finding(
                rule="jit-purity", file=path, line=node.lineno,
                message=f"in jitted `{label}`: {bad}{suffix}")
    for node in env_subscripts:
        if isinstance(node.ctx, ast.Load) and \
                (dotted_name(node.value) or "") in ("os.environ",
                                                    "environ"):
            yield Finding(
                rule="jit-purity", file=path, line=node.lineno,
                message=(f"in jitted `{label}`: os.environ[...] in "
                         f"traced code bakes the launch-time "
                         f"environment into the compiled "
                         f"program{suffix}"))


@register
class JitPurityRule(Rule):
    name = "jit-purity"
    description = ("host syncs (np.asarray, float()/int() on traced "
                   "params, block_until_ready, print, host RNG, "
                   "environ reads) inside jit entry points or any "
                   "function they reach on the call graph")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if "jit" not in ctx.source:
            return ()
        tree = ctx.tree
        if tree is None:
            return ()
        jitted, _ = _collect_jitted(tree)
        out: List[Finding] = []
        for info in jitted:
            params = {a.arg for a in info.fn.args.args}
            nodes = list(ast.walk(info.fn))
            out.extend(_purity_scan(
                (n for n in nodes if isinstance(n, ast.Call)),
                (n for n in nodes if isinstance(n, ast.Subscript)),
                params - info.static, info.fn.name, ctx.path))
        return out

    def finalize(self, repo: RepoContext) -> Iterable[Finding]:
        graph = repo.graph
        by_path = {c.path: c for c in repo.package_files()}
        # jit entry defs -> their graph qualnames (matched on def line)
        entries: Dict[str, str] = {}
        for ctx in by_path.values():
            if "jit" not in ctx.source or ctx.tree is None:
                continue
            jitted, _ = _collect_jitted(ctx.tree)
            if not jitted:
                continue
            idx = graph.modules.get(module_name_for(ctx.path))
            if idx is None:
                continue
            lines = {info.fn.lineno for info in jitted}
            for fname, rec in idx["functions"].items():
                if rec["line"] in lines:
                    entries[f"{idx['module']}:{fname}"] = fname
        if not entries:
            return ()
        parents = graph.reachable(entries, duck=False)
        out: List[Finding] = []
        for q in sorted(parents):
            if q in entries:
                continue  # entry bodies are covered by check()
            mod, _, fname = q.partition(":")
            idx = graph.modules.get(mod)
            rec = (idx or {}).get("functions", {}).get(fname)
            ctx = by_path.get((idx or {}).get("path", ""))
            if rec is None or ctx is None:
                continue
            lo, hi = rec["line"], rec["end"]
            trace = graph.trace(parents, q)
            suffix = (" (traced from `"
                      f"{entries[trace[0]]}`: "
                      + " -> ".join(t.partition(':')[2] for t in trace)
                      + ")")
            out.extend(_purity_scan(
                (n for n in ctx.nodes(ast.Call)
                 if lo <= n.lineno <= hi),
                (n for n in ctx.nodes(ast.Subscript)
                 if lo <= n.lineno <= hi),
                set(rec["params"]),
                fname.split(".")[-1], ctx.path, suffix))
        return out


@register
class DonationReuseRule(Rule):
    name = "donation-reuse"
    description = ("a buffer passed at a donate_argnums position is "
                   "read again before reassignment")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if "donate_argnums" not in ctx.source:
            return ()
        tree = ctx.tree
        if tree is None:
            return ()
        _, donating = _collect_jitted(tree)
        if not donating:
            return ()
        out: List[Finding] = []
        for fn in ast.walk(tree):
            if not isinstance(fn, ast.FunctionDef):
                continue
            stmts = [n for n in ast.walk(fn) if isinstance(n, ast.stmt)]

            def innermost_stmt(node: ast.AST) -> ast.stmt:
                """Smallest statement span containing ``node`` — the
                dispatch-and-rebind unit treated as atomic."""
                containing = [s for s in stmts
                              if s.lineno <= node.lineno
                              <= (s.end_lineno or s.lineno)]
                return min(containing,
                           key=lambda s: (s.end_lineno or s.lineno)
                           - s.lineno)

            # (donated key, end line of the donating statement).
            donated: List[Tuple[str, int]] = []
            loads: List[Tuple[str, int]] = []
            stores: List[Tuple[str, int]] = []
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    callee = dotted_name(node.func)
                    pos = donating.get(callee or "")
                    if not pos:
                        continue
                    stmt = innermost_stmt(node)
                    stmt_end = stmt.end_lineno or stmt.lineno
                    stmt_keys = {
                        dotted_name(s)
                        for s in ast.walk(stmt)
                        if isinstance(s, (ast.Name, ast.Attribute))
                        and isinstance(getattr(s, "ctx", None), ast.Store)}
                    for i in pos:
                        if i < len(node.args):
                            key = dotted_name(node.args[i])
                            # A rebind inside the same statement
                            # (`x, y = f(x, y)`) revives the buffer.
                            if key and key not in stmt_keys:
                                donated.append((key, stmt_end))
                elif isinstance(node, (ast.Name, ast.Attribute)):
                    key = dotted_name(node)
                    if key is None:
                        continue
                    if isinstance(getattr(node, "ctx", None), ast.Store):
                        stores.append((key, node.lineno))
                    elif isinstance(getattr(node, "ctx", None), ast.Load):
                        loads.append((key, node.lineno))
            for key, stmt_end in donated:
                rebind = min((ln for k, ln in stores
                              if k == key and ln > stmt_end),
                             default=None)
                for k, ln in loads:
                    if k != key or ln <= stmt_end:
                        continue
                    if rebind is not None and ln >= rebind:
                        continue
                    out.append(Finding(
                        rule=self.name, file=ctx.path, line=ln,
                        message=(f"`{key}` was donated to a "
                                 f"donate_argnums call ending on line "
                                 f"{stmt_end} and is read again before "
                                 f"reassignment (use-after-donate)")))
        return out
