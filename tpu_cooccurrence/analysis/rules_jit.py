"""jit / device hygiene.

The scorers' hot paths are jit-compiled (``@jax.jit`` /
``functools.partial(jax.jit, ...)`` / ``jax.jit(shard_map(...))``) and
stay fast only while they remain *pure device programs*: a stray
``np.asarray``/``float()`` on a traced value forces a host sync per
window, a ``print`` retraces, host RNG silently freezes into the traced
constant. Separately, the state-carrying jits donate their input
buffers (``ops/donation.py``); a donated array is dead the moment the
dispatch is enqueued, and reading it afterwards is exactly the TFRT
use-after-donate crash class the CPU backend gating exists for.

* ``jit-purity`` — inside a jitted function (decorated, wrapped at
  module level, or reachable by one intra-module call hop from one),
  flag host syncs: ``np.asarray``/``np.array``, ``float()``/``int()``
  on non-static traced parameters, ``.block_until_ready()``, ``print``,
  and host RNG (``np.random.*`` / ``random.*``).
* ``donation-reuse`` — after a call to a donating jit (its
  ``donate_argnums`` positions read straight from the AST), any read of
  the same argument expression before it is reassigned is a finding.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import FileContext, Finding, Rule, dotted_name, register

_NUMPY_SYNC = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_RNG_PREFIXES = ("np.random.", "numpy.random.", "random.")


def _is_jit_ref(node: ast.AST) -> bool:
    """Does this expression reference jax.jit / pjit?"""
    name = dotted_name(node) or ""
    return name in ("jax.jit", "jit", "pjit", "jax.pjit") or \
        name.endswith(".pjit")


def _partial_of_jit(call: ast.Call) -> bool:
    """``functools.partial(jax.jit, ...)``"""
    fname = dotted_name(call.func) or ""
    return (fname in ("functools.partial", "partial") and call.args
            and _is_jit_ref(call.args[0]))


def _static_argnames(call: ast.Call) -> Set[str]:
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            if isinstance(kw.value, ast.Constant) and isinstance(
                    kw.value.value, str):
                return {kw.value.value}
            if isinstance(kw.value, (ast.Tuple, ast.List)):
                return {e.value for e in kw.value.elts
                        if isinstance(e, ast.Constant)}
    return set()


def _donated_positions(call: ast.Call) -> Tuple[int, ...]:
    """Literal argnums out of ``donate_argnums=donate_argnums(0, 1)`` /
    ``donate_argnums=(0, 1)`` / ``donate_argnums=0``."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Call):  # the ops.donation.donate_argnums gate
            return tuple(a.value for a in v.args
                         if isinstance(a, ast.Constant))
        if isinstance(v, (ast.Tuple, ast.List)):
            return tuple(e.value for e in v.elts
                         if isinstance(e, ast.Constant))
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
    return ()


class _JitInfo:
    def __init__(self, fn: ast.FunctionDef, static: Set[str]) -> None:
        self.fn = fn
        self.static = static


def _collect_jitted(tree: ast.Module, in_ops: bool
                    ) -> Tuple[List[_JitInfo], Dict[str, Tuple[int, ...]]]:
    """(jitted function defs, donating-callable name -> donated argnums).

    Donating callables are keyed by how call sites spell them:
    a bare name (module-level def / assignment) or ``self.<attr>``.
    """
    fns_by_name = {n.name: n for n in ast.walk(tree)
                   if isinstance(n, ast.FunctionDef)}
    jitted: Dict[str, _JitInfo] = {}
    donating: Dict[str, Tuple[int, ...]] = {}

    def mark(fn: Optional[ast.FunctionDef], static: Set[str]) -> None:
        if fn is not None and fn.name not in jitted:
            jitted[fn.name] = _JitInfo(fn, static)

    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                if _is_jit_ref(dec):
                    mark(node, set())
                elif isinstance(dec, ast.Call) and (
                        _is_jit_ref(dec.func) or _partial_of_jit(dec)):
                    mark(node, _static_argnames(dec))
                    pos = _donated_positions(dec)
                    if pos:
                        donating[node.name] = pos
        elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call):
            call = node.value
            jit_call = None
            if _is_jit_ref(call.func):  # name = jax.jit(fn, ...)
                jit_call = call
                inner = call.args[0] if call.args else None
            elif isinstance(call.func, ast.Call) and _partial_of_jit(
                    call.func):  # name = partial(jax.jit, ...)(fn)
                jit_call = call.func
                inner = call.args[0] if call.args else None
            else:
                continue
            if isinstance(inner, ast.Name):
                mark(fns_by_name.get(inner.id), _static_argnames(jit_call))
            elif isinstance(inner, ast.Lambda):
                pass  # lambda bodies are single exprs; purity scan below
            pos = _donated_positions(jit_call)
            if pos:
                for tgt in node.targets:
                    key = dotted_name(tgt)
                    if key:
                        donating[key] = pos
    # One intra-module call hop: ops/ scorers factor their jitted bodies
    # into helpers; a host sync inside the helper is the same bug.
    if in_ops:
        changed = True
        while changed:
            changed = False
            for info in list(jitted.values()):
                for node in ast.walk(info.fn):
                    if isinstance(node, ast.Call) and isinstance(
                            node.func, ast.Name):
                        callee = fns_by_name.get(node.func.id)
                        if callee is not None and callee.name not in jitted:
                            jitted[callee.name] = _JitInfo(callee, set())
                            changed = True
    return list(jitted.values()), donating


@register
class JitPurityRule(Rule):
    name = "jit-purity"
    description = ("host syncs (np.asarray, float()/int() on traced "
                   "params, block_until_ready, print, host RNG) inside "
                   "jit-compiled functions")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.path.startswith("tpu_cooccurrence/"):
            return ()
        tree = ctx.tree
        if tree is None:
            return ()
        in_ops = "/ops/" in ("/" + ctx.path)
        jitted, _ = _collect_jitted(tree, in_ops)
        out: List[Finding] = []
        for info in jitted:
            params = {a.arg for a in info.fn.args.args}
            traced = params - info.static
            for node in ast.walk(info.fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func) or ""
                bad = None
                if name in _NUMPY_SYNC:
                    bad = f"{name}() materializes the traced value on host"
                elif name == "print":
                    bad = "print() inside a traced function (retraces)"
                elif name.startswith(_RNG_PREFIXES):
                    bad = (f"host RNG {name}() freezes into the trace; "
                           f"use jax.random with a threaded key")
                elif name in ("float", "int") and len(node.args) == 1:
                    arg = node.args[0]
                    if isinstance(arg, ast.Name) and arg.id in traced:
                        bad = (f"{name}({arg.id}) forces a host sync on "
                               f"a traced parameter")
                elif isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "block_until_ready":
                    bad = ("block_until_ready() inside a jitted "
                           "function defeats async dispatch")
                if bad is not None:
                    out.append(Finding(
                        rule=self.name, file=ctx.path, line=node.lineno,
                        message=(f"in jitted `{info.fn.name}`: {bad}")))
        return out


@register
class DonationReuseRule(Rule):
    name = "donation-reuse"
    description = ("a buffer passed at a donate_argnums position is "
                   "read again before reassignment")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.path.startswith("tpu_cooccurrence/"):
            return ()
        tree = ctx.tree
        if tree is None:
            return ()
        _, donating = _collect_jitted(tree, "/ops/" in ("/" + ctx.path))
        if not donating:
            return ()
        out: List[Finding] = []
        for fn in ast.walk(tree):
            if not isinstance(fn, ast.FunctionDef):
                continue
            stmts = [n for n in ast.walk(fn) if isinstance(n, ast.stmt)]

            def innermost_stmt(node: ast.AST) -> ast.stmt:
                """Smallest statement span containing ``node`` — the
                dispatch-and-rebind unit treated as atomic."""
                containing = [s for s in stmts
                              if s.lineno <= node.lineno
                              <= (s.end_lineno or s.lineno)]
                return min(containing,
                           key=lambda s: (s.end_lineno or s.lineno)
                           - s.lineno)

            # (donated key, end line of the donating statement).
            donated: List[Tuple[str, int]] = []
            loads: List[Tuple[str, int]] = []
            stores: List[Tuple[str, int]] = []
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    callee = dotted_name(node.func)
                    pos = donating.get(callee or "")
                    if not pos:
                        continue
                    stmt = innermost_stmt(node)
                    stmt_end = stmt.end_lineno or stmt.lineno
                    stmt_keys = {
                        dotted_name(s)
                        for s in ast.walk(stmt)
                        if isinstance(s, (ast.Name, ast.Attribute))
                        and isinstance(getattr(s, "ctx", None), ast.Store)}
                    for i in pos:
                        if i < len(node.args):
                            key = dotted_name(node.args[i])
                            # A rebind inside the same statement
                            # (`x, y = f(x, y)`) revives the buffer.
                            if key and key not in stmt_keys:
                                donated.append((key, stmt_end))
                elif isinstance(node, (ast.Name, ast.Attribute)):
                    key = dotted_name(node)
                    if key is None:
                        continue
                    if isinstance(getattr(node, "ctx", None), ast.Store):
                        stores.append((key, node.lineno))
                    elif isinstance(getattr(node, "ctx", None), ast.Load):
                        loads.append((key, node.lineno))
            for key, stmt_end in donated:
                rebind = min((ln for k, ln in stores
                              if k == key and ln > stmt_end),
                             default=None)
                for k, ln in loads:
                    if k != key or ln <= stmt_end:
                        continue
                    if rebind is not None and ln >= rebind:
                        continue
                    out.append(Finding(
                        rule=self.name, file=ctx.path, line=ln,
                        message=(f"`{key}` was donated to a "
                                 f"donate_argnums call ending on line "
                                 f"{stmt_end} and is read again before "
                                 f"reassignment (use-after-donate)")))
        return out
