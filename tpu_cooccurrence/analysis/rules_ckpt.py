"""Checkpoint-format drift guard (baseline-free).

``ckpt-format-roundtrip`` — the two ends of the checkpoint format live
in different functions (``save`` vs ``restore`` in
``state/checkpoint.py``; ``encode_delta`` vs ``decode_delta`` in
``state/delta.py``) and nothing structural stops a writer-side field
from landing with no reader: the file still round-trips, the digest
still verifies, and the field silently never influences restore — until
a replica or a future restore path needs it and finds garbage semantics.

The rule makes the registry explicit: every string key written into the
generation meta (the dict literal assigned to ``meta`` / subscript
stores on it) or into the delta header (the ``header`` dict in
``state/delta.py``) must

* have a matching restore-side READ of the same key string somewhere in
  its module (a read-position constant — ``meta["k"]`` load,
  ``meta.get("k")``, membership test), and
* appear as a string constant somewhere under ``tests/`` — the
  round-trip fixture reference that pins the field's semantics
  (``tests/test_incremental_checkpoint.py`` keeps the canonical list).

Baseline-free: a new meta/header field lands in the same PR as its
reader and its test, or tier-1 fails.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Set, Tuple

from .core import FileContext, Finding, RepoContext, Rule, register

#: Module -> dict-variable names whose string keys form the format.
_FORMAT_FILES = {
    "tpu_cooccurrence/state/checkpoint.py": ("meta",),
    "tpu_cooccurrence/state/delta.py": ("header",),
}


def _written_keys(ctx: FileContext,
                  names) -> "Tuple[Dict[str, int], Set[int]]":
    """``{key: first write line}`` plus the AST node ids of the write-
    position key constants (so the read scan can exclude them)."""
    written: Dict[str, int] = {}
    write_nodes: Set[int] = set()
    for node in ctx.nodes(ast.Assign):
        for tgt in node.targets:
            # meta = {"k": ...} / header = {"k": ...}
            if (isinstance(tgt, ast.Name) and tgt.id in names
                    and isinstance(node.value, ast.Dict)):
                for k in node.value.keys:
                    if (isinstance(k, ast.Constant)
                            and isinstance(k.value, str)):
                        written.setdefault(k.value, k.lineno)
                        write_nodes.add(id(k))
            # meta["k"] = ...
            if (isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id in names
                    and isinstance(tgt.slice, ast.Constant)
                    and isinstance(tgt.slice.value, str)):
                written.setdefault(tgt.slice.value, tgt.lineno)
                write_nodes.add(id(tgt.slice))
    return written, write_nodes


def _read_constants(ctx: FileContext, write_nodes: Set[int]) -> Set[str]:
    """Every string constant in the module that is NOT one of the
    write-position keys — the reader-evidence pool (subscript loads,
    ``.get`` arguments, membership tests all surface here)."""
    out: Set[str] = set()
    for node in ctx.nodes(ast.Constant):
        if isinstance(node.value, str) and id(node) not in write_nodes:
            out.add(node.value)
    return out


def _tests_constants(repo: RepoContext) -> Set[str]:
    return repo.test_string_constants()


@register
class CkptFormatRoundtripRule(Rule):
    name = "ckpt-format-roundtrip"
    description = ("every field written into checkpoint generation meta "
                   "or delta headers needs a restore-side reader in its "
                   "module and a tests/ round-trip reference")

    def finalize(self, repo: RepoContext) -> Iterable[Finding]:
        # Scope guard (the rules_fused posture): the missing-module
        # finding stays anchored on the format SUBSYSTEM existing — a
        # scan root with neither module (other rules' fixture repos,
        # partial trees) is silent, while a repo where one end of the
        # format vanished out from under the other is flagged.
        present = {path: next((c for c in repo.files if c.path == path),
                              None)
                   for path in _FORMAT_FILES}
        if not any(c is not None for c in present.values()):
            return
        tests = None
        for path, names in sorted(_FORMAT_FILES.items()):
            src = present[path]
            if src is None or src.tree is None:
                yield Finding(
                    rule=self.name, file=path, line=1,
                    message=(f"format module {path} is missing or "
                             f"unparseable — the checkpoint-format "
                             f"registry this rule guards is gone"))
                continue
            written, write_nodes = _written_keys(src, names)
            if not written:
                yield Finding(
                    rule=self.name, file=path, line=1,
                    message=(f"no format keys found on {names} in "
                             f"{path} (writer moved? update "
                             f"rules_ckpt._FORMAT_FILES)"))
                continue
            reads = _read_constants(src, write_nodes)
            if tests is None:
                tests = _tests_constants(repo)
            for key, line in sorted(written.items()):
                if key not in reads:
                    yield Finding(
                        rule=self.name, file=path, line=line,
                        message=(f"format key {key!r} is written but "
                                 f"never read back in {path} — a "
                                 f"writer-only field is silent format "
                                 f"drift; add the restore-side reader "
                                 f"(or drop the field)"))
                if key not in tests:
                    yield Finding(
                        rule=self.name, file=path, line=line,
                        message=(f"format key {key!r} has no tests/ "
                                 f"round-trip reference — pin it in "
                                 f"tests/test_incremental_checkpoint.py"
                                 f"'s format-key registry"))
