"""State-store registry drift (baseline-free).

Every :class:`~tpu_cooccurrence.state.store.StateStore` implementation
in ``state/store.py`` is a placement policy whose correctness claim is
"the canonical checkpoint blob round-trips bit-identically through me"
— a claim only a checkpoint round-trip test can back, and an
operator-facing contract the ARCHITECTURE "State-store table" names
with its placement semantics. A store added without both is exactly how
the elastic-state plane would rot: a policy nothing ever round-trips
against the canonical blob, documented nowhere an operator looks —
the silent-restores-garbage failure class the checkpoint digests exist
to prevent, reintroduced one layer up.

Evidence model mirrors ``pallas-kernel-registry`` / ``wire-codec-
roundtrip``: AST-only (nothing imported), a class counts as covered
when its NAME is referenced anywhere under ``tests/`` and appears in
``docs/ARCHITECTURE.md``. Fixture-tested in ``tests/test_cooclint.py``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Optional, Set

from .core import FileContext, Finding, RepoContext, Rule, register

_STORE_PATH = "tpu_cooccurrence/state/store.py"
_ARCH_PATH = "docs/ARCHITECTURE.md"
_BASE = "StateStore"


def _store_subclasses(tree: ast.Module) -> Dict[str, ast.ClassDef]:
    """Module-level classes deriving (directly or through another class
    in the module) from ``StateStore``."""
    classes = {n.name: n for n in tree.body if isinstance(n, ast.ClassDef)}
    derived: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for name, node in classes.items():
            if name in derived or name == _BASE:
                continue
            for b in node.bases:
                base = (b.id if isinstance(b, ast.Name)
                        else b.attr if isinstance(b, ast.Attribute)
                        else None)
                if base == _BASE or base in derived:
                    derived.add(name)
                    changed = True
    return {name: classes[name] for name in derived}


def _test_referenced_names(repo: RepoContext) -> Set[str]:
    return repo.test_referenced_names()


@register
class StateStoreRegistryRule(Rule):
    name = "state-store-registry"
    description = ("every StateStore implementation in state/store.py "
                   "needs a checkpoint round-trip test reference under "
                   "tests/ and a row in the ARCHITECTURE state-store "
                   "table")

    def finalize(self, repo: RepoContext) -> Iterable[Finding]:
        src: Optional[FileContext] = next(
            (c for c in repo.files if c.path == _STORE_PATH), None)
        if src is None or src.tree is None:
            return
        stores = _store_subclasses(src.tree)
        if not stores:
            yield Finding(
                rule=self.name, file=_STORE_PATH, line=1,
                message="no StateStore implementations found (the "
                        "state-store registry this rule guards is gone)")
            return
        refs = _test_referenced_names(repo)
        arch = next((c for c in repo.files if c.path == _ARCH_PATH), None)
        if arch is None:
            # A vanished anchor doc must be a finding, not a silent
            # waiver of the doc requirement for every store (same
            # posture as the vanished ROUTE_METRICS table in
            # rules_serving).
            yield Finding(
                rule=self.name, file=_STORE_PATH, line=1,
                message=(f"{_ARCH_PATH} not found — the state-store "
                         f"table this rule checks implementations "
                         f"against is gone"))
        for name, node in sorted(stores.items()):
            if name not in refs:
                yield Finding(
                    rule=self.name, file=_STORE_PATH, line=node.lineno,
                    message=(f"StateStore implementation {name!r} has no "
                             f"checkpoint round-trip evidence: nothing "
                             f"under tests/ references it — a placement "
                             f"policy nothing round-trips against the "
                             f"canonical blob is a silent-restore-"
                             f"garbage risk"))
            if arch is not None and name not in arch.source:
                yield Finding(
                    rule=self.name, file=_STORE_PATH, line=node.lineno,
                    message=(f"StateStore implementation {name!r} is not "
                             f"in {_ARCH_PATH} — add it to the "
                             f"state-store table"))
