"""Scale-policy registry drift (baseline-free).

Every :class:`~tpu_cooccurrence.robustness.autoscale.ScalePolicy`
implementation in ``robustness/autoscale.py`` decides when a live gang
is torn down and relaunched at a different size — a policy nothing
exercises is a policy whose hysteresis, bounds and cooldown are
untested folklore, and one the ARCHITECTURE scale-policy table does not
name is a rescale trigger operators cannot reason about when the gang
starts cycling. Same evidence model as ``state-store-registry``:
AST-only (nothing imported), a class counts as covered when its NAME is
referenced anywhere under ``tests/`` and appears in
``docs/ARCHITECTURE.md``. Fixture-tested in ``tests/test_cooclint.py``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Optional, Set

from .core import FileContext, Finding, RepoContext, Rule, register

_POLICY_PATH = "tpu_cooccurrence/robustness/autoscale.py"
_ARCH_PATH = "docs/ARCHITECTURE.md"
_BASE = "ScalePolicy"


def _policy_subclasses(tree: ast.Module) -> Dict[str, ast.ClassDef]:
    """Module-level classes deriving (directly or through another class
    in the module) from ``ScalePolicy``."""
    classes = {n.name: n for n in tree.body if isinstance(n, ast.ClassDef)}
    derived: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for name, node in classes.items():
            if name in derived or name == _BASE:
                continue
            for b in node.bases:
                base = (b.id if isinstance(b, ast.Name)
                        else b.attr if isinstance(b, ast.Attribute)
                        else None)
                if base == _BASE or base in derived:
                    derived.add(name)
                    changed = True
    return {name: classes[name] for name in derived}


def _test_referenced_names(repo: RepoContext) -> Set[str]:
    return repo.test_referenced_names()


@register
class ScalePolicyRegistryRule(Rule):
    name = "scale-policy-registry"
    description = ("every ScalePolicy implementation in "
                   "robustness/autoscale.py needs a tests/ reference "
                   "and a row in the ARCHITECTURE scale-policy table")

    def finalize(self, repo: RepoContext) -> Iterable[Finding]:
        src: Optional[FileContext] = next(
            (c for c in repo.files if c.path == _POLICY_PATH), None)
        if src is None or src.tree is None:
            return
        policies = _policy_subclasses(src.tree)
        if not policies:
            yield Finding(
                rule=self.name, file=_POLICY_PATH, line=1,
                message="no ScalePolicy implementations found (the "
                        "scale-policy registry this rule guards is gone)")
            return
        refs = _test_referenced_names(repo)
        arch = next((c for c in repo.files if c.path == _ARCH_PATH), None)
        if arch is None:
            # A vanished anchor doc must be a finding, not a silent
            # waiver of the doc requirement for every policy (same
            # posture as state-store-registry).
            yield Finding(
                rule=self.name, file=_POLICY_PATH, line=1,
                message=(f"{_ARCH_PATH} not found — the scale-policy "
                         f"table this rule checks implementations "
                         f"against is gone"))
        for name, node in sorted(policies.items()):
            if name not in refs:
                yield Finding(
                    rule=self.name, file=_POLICY_PATH, line=node.lineno,
                    message=(f"ScalePolicy implementation {name!r} has "
                             f"no test evidence: nothing under tests/ "
                             f"references it — a rescale trigger nobody "
                             f"exercises tears down live gangs on "
                             f"untested hysteresis"))
            if arch is not None and name not in arch.source:
                yield Finding(
                    rule=self.name, file=_POLICY_PATH, line=node.lineno,
                    message=(f"ScalePolicy implementation {name!r} is "
                             f"not in {_ARCH_PATH} — add it to the "
                             f"scale-policy table"))
