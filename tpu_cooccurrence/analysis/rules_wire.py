"""Wire/checkpoint codec and narrow-dtype invariants (baseline-free).

Two rules guarding the compression layer (``state/wire.py``):

* ``wire-codec-roundtrip`` — every encoder entry point in the wire
  module (a module-level ``encode_*`` / ``pack_*`` function) must have
  its matching decoder (``decode_*`` / ``unpack_*``, same stem) in the
  module, and BOTH must be referenced from ``tests/`` — the round-trip
  test is the only thing standing between an encoding tweak and a
  checkpoint that silently restores garbage. Mirrors the
  ``pallas-kernel-registry`` rule's evidence model.

* ``narrow-cast-guard`` — every cast to a narrow integer dtype
  (``astype(np.int16 / np.int8 / jnp.int16 / jnp.int8)``, or their
  string forms) anywhere in the package must sit behind a VISIBLE
  saturation/overflow guard: the enclosing function either routes
  through a registered guard helper (``checked_narrow``,
  ``narrow_deltas_int32``), consults dtype bounds (``np.iinfo`` /
  ``cell_promote_threshold``), or compares against an explicit dtype
  limit literal. The immediate sign-extend idiom
  (``.astype(int16).astype(int32)``) is exempt — it never stores a
  narrow value. Everything else is exactly how the reference's silent
  Java-short wraparound class of bug re-enters the codebase.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Optional, Set

from .core import FileContext, Finding, RepoContext, Rule, register

_WIRE_PATH = "tpu_cooccurrence/state/wire.py"

#: Encoder-name prefix -> required decoder prefix.
_CODEC_PAIRS = {"encode_": "decode_", "pack_": "unpack_"}

#: Call names that count as a visible overflow guard in a function.
_GUARD_CALLS = {"checked_narrow", "narrow_deltas_int32", "iinfo",
                "cell_promote_threshold"}

#: Literals that count as an explicit dtype-bound check.
_LIMIT_LITERALS = {127, -128, 255, 32767, -32768, 65535}

_NARROW_NAMES = {"int16", "int8"}
_WIDE_NAMES = {"int32", "int64"}


def _dtype_token(node: ast.AST) -> Optional[str]:
    """``np.int16`` / ``jnp.int8`` / ``"int16"`` -> the dtype name."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _astype_to(node: ast.AST, names: Set[str]) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "astype"
            and len(node.args) == 1
            and _dtype_token(node.args[0]) in names)


def _test_referenced_names(repo: RepoContext) -> Set[str]:
    return repo.test_referenced_names()


@register
class WireCodecRoundtripRule(Rule):
    name = "wire-codec-roundtrip"
    description = ("every encoder in state/wire.py needs its matching "
                   "decoder and a round-trip test referencing both from "
                   "tests/")

    def finalize(self, repo: RepoContext) -> Iterable[Finding]:
        src: Optional[FileContext] = next(
            (c for c in repo.files if c.path == _WIRE_PATH), None)
        if src is None or src.tree is None:
            return
        fns: Dict[str, ast.FunctionDef] = {
            n.name: n for n in src.tree.body
            if isinstance(n, ast.FunctionDef)}
        encoders = {name: fn for name, fn in fns.items()
                    if any(name.startswith(p) for p in _CODEC_PAIRS)}
        if not encoders:
            yield Finding(
                rule=self.name, file=_WIRE_PATH, line=1,
                message="no encoder entry points found (the codec "
                        "registry this rule guards is gone)")
            return
        refs = _test_referenced_names(repo)
        for name, fn in sorted(encoders.items()):
            prefix = next(p for p in _CODEC_PAIRS if name.startswith(p))
            stem = name[len(prefix):]
            decoder = _CODEC_PAIRS[prefix] + stem
            if decoder not in fns:
                yield Finding(
                    rule=self.name, file=_WIRE_PATH, line=fn.lineno,
                    message=(f"encoder {name!r} has no matching decoder "
                             f"{decoder!r} in {_WIRE_PATH} — a one-way "
                             f"wire format is unrecoverable state"))
                continue
            missing = [n for n in (name, decoder) if n not in refs]
            if missing:
                yield Finding(
                    rule=self.name, file=_WIRE_PATH, line=fn.lineno,
                    message=(f"codec pair ({name}, {decoder}) has no "
                             f"round-trip evidence: {missing} never "
                             f"referenced from tests/"))


@register
class NarrowCastGuardRule(Rule):
    name = "narrow-cast-guard"
    description = ("casts to int16/int8 must sit behind a visible "
                   "saturation/overflow guard (checked_narrow, iinfo, "
                   "an explicit bound literal) or be an immediate "
                   "sign-extend")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None or not ctx.path.startswith("tpu_cooccurrence/"):
            return
        calls = ctx.nodes(ast.Call)
        narrow = [n for n in calls if _astype_to(n, _NARROW_NAMES)]
        if not narrow:
            return
        # Narrow casts that are immediately re-widened never store a
        # narrow value: collect the inner nodes of `.astype(narrow)
        # .astype(wide)` chains to exempt them.
        sign_extended = {
            id(n.func.value) for n in calls
            if _astype_to(n, _WIDE_NAMES)
            and _astype_to(n.func.value, _NARROW_NAMES)}
        casts = [n for n in narrow if id(n) not in sign_extended]
        if not casts:
            return
        # Guard evidence is function-scoped: map each cast to its
        # innermost enclosing function, then check that function's body
        # (module-level casts have no enclosing guard scope).
        fns = ctx.nodes(ast.FunctionDef, ast.AsyncFunctionDef)
        guard_cache: dict = {}
        for c in casts:
            containing = [f for f in fns
                          if f.lineno <= c.lineno
                          <= (f.end_lineno or f.lineno)]
            if containing:
                fn = min(containing,
                         key=lambda f: (f.end_lineno or f.lineno)
                         - f.lineno)
                if id(fn) not in guard_cache:
                    guard_cache[id(fn)] = self._has_guard(fn)
                if guard_cache[id(fn)]:
                    continue
            yield Finding(
                rule=self.name, file=ctx.path, line=c.lineno,
                message=("narrow-dtype cast without a visible "
                         "saturation/overflow guard — route through "
                         "state/wire.checked_narrow or add an "
                         "explicit bounds check in this function "
                         "(silent wraparound is the reference's "
                         "Java-short bug class)"))

    @staticmethod
    def _has_guard(fn: ast.AST) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                f = node.func
                callee = (f.attr if isinstance(f, ast.Attribute)
                          else f.id if isinstance(f, ast.Name) else None)
                if callee in _GUARD_CALLS:
                    return True
            elif (isinstance(node, ast.Constant)
                  and isinstance(node.value, int)
                  and not isinstance(node.value, bool)
                  and node.value in _LIMIT_LITERALS):
                return True
            elif (isinstance(node, ast.UnaryOp)
                  and isinstance(node.op, ast.USub)
                  and isinstance(node.operand, ast.Constant)
                  and isinstance(node.operand.value, int)
                  and -node.operand.value in _LIMIT_LITERALS):
                return True
        return False
