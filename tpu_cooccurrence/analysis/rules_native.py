"""Dtype discipline at the native (ctypes) and fold boundaries.

The C kernels in ``native/`` read raw pointers: an array that reaches
``lib.<fn>(...)`` with the wrong dtype or layout is silent memory
corruption, not an exception (the numpy fallbacks raise; the C loop
reads past buffers). And the shared fold (``ops/aggregate.py``) sums
deltas into exact int64 — a float delta sneaking in would truncate
differently on the native path than the float64-bincount fallback.

* ``native-dtype`` — in ``native/__init__.py``, every array handed to a
  ``lib.<fn>(...)`` call through ``_ptr64``/``_ptr32``/``_ptr8`` must
  have a visible dtype guarantee in the enclosing function: an
  ``np.ascontiguousarray(x, dtype=...)`` rebind, an
  ``np.empty/zeros(..., dtype=...)`` allocation, an ``x.astype(...)``
  rebind, or an ``assert`` mentioning ``x.dtype``. Attribute-held
  buffers (scratch arrays) need the assert form — allocation elsewhere
  is invisible at the call site and refactors silently break it.
* ``fold-dtype-guard`` — ``ops/aggregate.py``'s
  ``aggregate_window_coo`` must keep an integer-dtype guard on its
  ``delta`` parameter (an ``np.issubdtype`` check): both fold paths sum
  exactly only for integer deltas, and the guard is the single place
  that keeps a future float-delta caller from diverging by buffer size.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from .core import FileContext, Finding, Rule, dotted_name, register

_PTR_WRAPPERS = {"_ptr64", "_ptr32", "_ptr8"}
_DTYPE_ALLOCATORS = {"np.empty", "np.zeros", "np.ones", "np.full",
                     "numpy.empty", "numpy.zeros", "numpy.ones",
                     "numpy.full"}
_CONTIG = {"np.ascontiguousarray", "numpy.ascontiguousarray"}


def _guarded_names(fn: ast.FunctionDef) -> Set[str]:
    """Dotted names with a visible dtype guarantee inside ``fn``."""
    guarded: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            call = node.value
            if isinstance(call, ast.Call):
                fname = dotted_name(call.func) or ""
                has_dtype = (any(kw.arg == "dtype"
                                 for kw in call.keywords)
                             or len(call.args) >= 2)
                is_astype = (isinstance(call.func, ast.Attribute)
                             and call.func.attr == "astype")
                if is_astype or ((fname in _CONTIG
                                  or fname in _DTYPE_ALLOCATORS)
                                 and has_dtype):
                    for tgt in node.targets:
                        name = dotted_name(tgt)
                        if name:
                            guarded.add(name)
        elif isinstance(node, ast.Assert):
            # Any dotted name whose `.dtype` the assert inspects.
            for sub in ast.walk(node.test):
                if isinstance(sub, ast.Attribute) and sub.attr == "dtype":
                    name = dotted_name(sub.value)
                    if name:
                        guarded.add(name)
    return guarded


@register
class NativeDtypeRule(Rule):
    name = "native-dtype"
    description = ("arrays crossing the ctypes boundary must carry a "
                   "visible dtype guarantee in the calling function")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.path != "tpu_cooccurrence/native/__init__.py":
            return ()
        tree = ctx.tree
        if tree is None:
            return ()
        out: List[Finding] = []
        for fn in ast.walk(tree):
            if not isinstance(fn, ast.FunctionDef):
                continue
            guarded = _guarded_names(fn)
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "lib"):
                    continue
                for arg in node.args:
                    if not (isinstance(arg, ast.Call)
                            and isinstance(arg.func, ast.Name)
                            and arg.func.id in _PTR_WRAPPERS):
                        continue
                    target = dotted_name(arg.args[0]) if arg.args else None
                    if target is None:
                        continue
                    if target in guarded:
                        continue
                    out.append(Finding(
                        rule=self.name, file=ctx.path, line=arg.lineno,
                        message=(f"`{target}` crosses the ctypes "
                                 f"boundary via {arg.func.id} without a "
                                 f"dtype guarantee in "
                                 f"`{fn.name}` (ascontiguousarray/"
                                 f"dtype= allocation/astype rebind, or "
                                 f"an assert on its .dtype)")))
        return out


@register
class FoldDtypeGuardRule(Rule):
    name = "fold-dtype-guard"
    description = ("aggregate_window_coo must keep an integer-dtype "
                   "guard (np.issubdtype) on its delta parameter")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.path != "tpu_cooccurrence/ops/aggregate.py":
            return ()
        tree = ctx.tree
        if tree is None:
            return ()
        fn: Optional[ast.FunctionDef] = next(
            (n for n in ast.walk(tree)
             if isinstance(n, ast.FunctionDef)
             and n.name == "aggregate_window_coo"), None)
        if fn is None:
            return ()  # renamed/removed: the import sites break loudly
        has_guard = any(
            isinstance(n, ast.Call)
            and (dotted_name(n.func) or "").endswith("issubdtype")
            for n in ast.walk(fn))
        if has_guard:
            return ()
        return [Finding(
            rule=self.name, file=ctx.path, line=fn.lineno,
            message=("aggregate_window_coo lost its integer-dtype "
                     "guard on `delta` — a float delta would truncate "
                     "on the native path and sum exactly on the numpy "
                     "path (fold diverges by buffer size)"))]
