"""Command-line entry point.

Mirrors the reference driver (``FlinkCooccurrences.java:36-182``): parse
config, echo it, build and run the job over the file input, then log
duration and the accumulator dump in the reference's format.
"""

from __future__ import annotations

import json
import logging
import os
import sys
from typing import Optional, Sequence

from . import tuning
from .config import Config
from .io.parse import batched_lines
from .io.source import FileMonitorSource
from .job import CooccurrenceJob
from .supervisor import EX_CONFIG, SUPERVISOR_STATE_ENV

LOG = logging.getLogger("tpu_cooccurrence")


def _render_row(item, top) -> str:
    """The output row format (stream and final dump share it)."""
    return f"{item}	" + " ".join(f"{other}:{score:.4f}"
                                  for other, score in top)


def main(argv: Optional[Sequence[str]] = None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        stream=sys.stderr,  # reference logs INFO to stderr (log4j.properties:1-6)
        format="%(asctime)s %(levelname)s %(name)s - %(message)s",
    )
    from .robustness.faults import UnknownFaultSiteError

    try:
        config = Config.from_args(argv)
    except UnknownFaultSiteError as exc:
        # Exit 2 (already in the supervisor's PERMANENT_EXIT_CODES): a
        # typo'd --inject-fault site must kill the run outright, not
        # spend the restart budget on a child that can never arm. The
        # message lists the registered sites (faults.SITES).
        LOG.error("configuration error: %s", exc)
        return 2
    except ValueError as exc:
        # EX_CONFIG (sysexits): a permanent failure the supervisor must
        # not retry — a bad flag does not get better with restarts.
        LOG.error("configuration error: %s", exc)
        return EX_CONFIG

    if config.gang_workers:
        # Gang-supervisor mode (robustness/gang.py — the JobManager
        # analogue): launch/monitor one multi-controller worker per
        # gang slot and gang-restart the WHOLE set from the last
        # committed epoch on any failure. Workers run the job path
        # below with the coordinator flags filled in; their stdouts are
        # spooled and forwarded in process order only on clean exit.
        from .robustness.gang import GangSupervisor

        import tempfile

        raw = list(argv) if argv is not None else sys.argv[1:]
        gang_dir = (os.path.join(config.checkpoint_dir, "gang")
                    if config.checkpoint_dir
                    else tempfile.mkdtemp(prefix="cooc-gang-"))
        scale_policy = None
        if config.autoscale == "on":
            # The supervisor-side half of the autoscaler: the policy
            # reads the workers' pressure beacons from the gang dir and
            # decides target topologies (robustness/autoscale.py).
            from .robustness.autoscale import LadderScalePolicy

            scale_policy = LadderScalePolicy(
                max_workers=config.autoscale_max_workers,
                min_workers=config.autoscale_min_workers,
                trip_windows=config.autoscale_trip_windows,
                clear_windows=config.autoscale_clear_windows,
                cooldown_windows=config.autoscale_cooldown_windows)
            LOG.info("autoscale armed: %d..%d workers, trip=%d "
                     "clear=%d cooldown=%d windows",
                     config.autoscale_min_workers,
                     config.autoscale_max_workers,
                     config.autoscale_trip_windows,
                     config.autoscale_clear_windows,
                     config.autoscale_cooldown_windows)
        if config.inject_fault and any(
                s.startswith("rescale_relaunch")
                for s in config.inject_fault):
            # The rescale_relaunch site fires in THIS (supervisor)
            # process; every other site only ever fires in the job
            # children, which arm their own plans from the pass-through
            # argv — so the supervisor arms only when a spec actually
            # targets its side of the seam. Markers are unqualified
            # (no .p<i>), disjoint from the workers' namespaced ones.
            from .robustness import faults

            faults.arm(config.inject_fault, config.fault_state_dir)
            LOG.warning("fault injection armed in the gang supervisor: "
                        "%s", config.inject_fault)
        LOG.info("gang supervising %d workers (up to %d restart(s); "
                 "heartbeats in %s)", config.gang_workers,
                 config.restart_on_failure, gang_dir)
        return GangSupervisor(
            raw, config.gang_workers,
            attempts=config.restart_on_failure,
            gang_dir=gang_dir,
            stale_after_s=config.gang_stale_after_s,
            delay_s=config.restart_delay_ms / 1000.0,
            backoff_base_s=(config.restart_backoff_base_ms / 1000.0
                            if config.restart_backoff_base_ms > 0
                            else None),
            backoff_max_s=config.restart_backoff_max_ms / 1000.0,
            journal_path=config.journal,
            watchdog_stale_after_s=(config.watchdog_stale_after_s
                                    if config.watchdog_stale_after_s > 0
                                    else None),
            scale_policy=scale_policy).run()

    if config.restart_on_failure > 0:
        # Supervisor mode (Flink restart-strategy analogue, SURVEY §5):
        # respawn the job as a child process on abnormal exit; the child
        # resumes from --checkpoint-dir by itself via the restore path
        # below. The child runs WITHOUT the restart flags.
        from .supervisor import child_argv, supervise

        raw = list(argv) if argv is not None else sys.argv[1:]
        cmd = [sys.executable, "-m", "tpu_cooccurrence.cli"] + child_argv(raw)
        LOG.info("supervising job (up to %d restart(s), delay %d ms)",
                 config.restart_on_failure, config.restart_delay_ms)
        # --journal flows through to the child (it writes the records);
        # the supervisor only reads the tail for crash forensics and the
        # hang watchdog's liveness signal. --inject-fault flows through
        # too: faults fire in the job child, never in the supervisor.
        return supervise(
            cmd, config.restart_on_failure,
            delay_s=config.restart_delay_ms / 1000.0,
            journal_path=config.journal,
            backoff_base_s=(config.restart_backoff_base_ms / 1000.0
                            if config.restart_backoff_base_ms > 0 else None),
            backoff_max_s=config.restart_backoff_max_ms / 1000.0,
            crash_loop_threshold=config.crash_loop_threshold,
            crash_loop_window_s=config.crash_loop_window_s,
            watchdog_stale_after_s=(config.watchdog_stale_after_s
                                    if config.watchdog_stale_after_s > 0
                                    else None),
            checkpoint_dir=config.checkpoint_dir)

    if config.collective_timeout_s > 0:
        # The watchdog reads the env at every collective entry; setting
        # it here (before any backend init) arms the whole process —
        # including collectives issued during scorer construction.
        from .parallel.distributed import COLLECTIVE_TIMEOUT_ENV

        os.environ[COLLECTIVE_TIMEOUT_ENV] = str(
            config.collective_timeout_s)

    if config.inject_fault:
        # Armed only on the job path: a supervising parent passes the
        # specs through to its child instead of firing them itself.
        # process_id resolves site@proc qualifiers (gang chaos: kill
        # exactly worker 1) and namespaces the fired markers so gang
        # workers sharing one --fault-state-dir stay independent.
        from .robustness import faults

        faults.arm(config.inject_fault, config.fault_state_dir,
                   process_id=config.process_id)
        LOG.warning("fault injection armed: %s", config.inject_fault)

    # Gang worker: the supervising parent hands down the gang state dir;
    # start the heartbeat beacon BEFORE job construction so liveness
    # covers jax.distributed startup (a hang there must read as a stale
    # peer, not silence).
    heartbeat = None
    from .robustness.gang import GANG_DIR_ENV, HeartbeatWriter

    gang_dir = tuning.env_read(GANG_DIR_ENV)
    if gang_dir and config.process_id is not None:
        heartbeat = HeartbeatWriter(
            gang_dir, config.process_id,
            interval_s=config.gang_heartbeat_s).start()

    config.log_configuration(LOG)
    if config.degrade:
        LOG.info("graceful degradation armed: wall>%.3fs trips after %d "
                 "windows, clears after %d; shed factor %d; pause %d ms",
                 config.degrade_window_wall_s, config.degrade_trip_windows,
                 config.degrade_clear_windows, config.degrade_shed_factor,
                 config.degrade_pause_ms)
    if config.spill_threshold_windows > 0:
        # Make the tiering unmissable in the run log: cold rows leave
        # HBM, so slab-footprint numbers in the same log read
        # differently from an untiered run (results do not).
        LOG.info("tiered state armed: rows idle for %d windows spill to "
                 "the host arena (target HBM frac %.2f); output stays "
                 "bit-identical to spill-off",
                 config.spill_threshold_windows,
                 config.spill_target_hbm_frac)
    if config.pipeline_depth > 0:
        # Make the execution mode unmissable in the run log: with
        # --emit-updates the result stream is produced by the pipeline's
        # scorer worker (one step behind the device frontier), not the
        # ingest thread — relevant when correlating stdout with stderr
        # timing lines.
        LOG.info("pipelined execution: depth=%d (host sampling overlaps "
                 "device scoring; output is bit-identical to serial)",
                 config.pipeline_depth)

    job = CooccurrenceJob(config)
    # Ingest source selection (--source-format): the file-monitor tail,
    # or the partitioned log whose per-partition offsets commit with the
    # checkpoint (io/partitioned.py). Constructed before the HTTP plane
    # so /healthz can carry the ingest block.
    if config.source_format == "partitioned":
        from .io.partitioned import PartitionedLogSource

        source = PartitionedLogSource(
            config.input, job.counters,
            process_continuously=config.process_continuously,
            expected_partitions=config.ingest_partitions,
            process_id=config.process_id or 0,
            num_processes=config.num_processes or 1)
    else:
        source = FileMonitorSource(
            config.input, job.counters,
            process_continuously=config.process_continuously)
    # The job sees the source unconditionally: checkpoints snapshot its
    # cursor + offsets, and the journal's per-window ingest fields read
    # its health even on checkpoint-less runs.
    job.source = source
    # Supervisor state rides in on an env var (the scrape plane lives in
    # this child process, not the parent): restart/backoff gauges on
    # /metrics, last-restart info on /healthz.
    supervisor_info = None
    raw_state = tuning.env_read(SUPERVISOR_STATE_ENV)
    if raw_state:
        try:
            supervisor_info = json.loads(raw_state)
        except ValueError:
            LOG.warning("unparseable %s=%r; ignoring",
                        SUPERVISOR_STATE_ENV, raw_state)
    metrics_server = None
    serve_server = None
    if config.metrics_port is not None or config.serve_port is not None:
        # Live HTTP plane (observability/http.py): a long-running job is
        # monitorable (--metrics-port) and queryable (--serve-port)
        # without attaching to stdout/stderr. Port 0 binds an ephemeral
        # port; the bound port is in the startup log line.
        from .observability import LEDGER
        from .observability.http import MetricsServer
        from .observability.registry import REGISTRY

        if supervisor_info is not None:
            REGISTRY.gauge(
                "cooc_supervisor_restarts",
                help="restarts the supervising parent has performed "
                     "this run").set(supervisor_info.get("restarts", 0))
            REGISTRY.gauge(
                "cooc_supervisor_backoff_ms",
                help="restart backoff delay the supervisor applied "
                     "before this attempt").set(
                         supervisor_info.get("backoff_ms", 0))
            if "rescales" in supervisor_info:
                # Gang autoscale accounting relayed by the supervisor:
                # voluntary rescales performed so far (the /healthz
                # autoscale block reads this beside the tap's gauges).
                from .robustness.autoscale import RESCALES_GAUGE

                REGISTRY.gauge(
                    RESCALES_GAUGE,
                    help="voluntary gang rescales the supervisor has "
                         "performed this run").set(
                             supervisor_info.get("rescales", 0))
        peers = None
        if gang_dir and config.num_processes:
            # /healthz peers table: heartbeat ages + committed epochs
            # for every gang slot, 503 ("peer_stale") when any peer is
            # stale — the load-balancer drain signal ahead of the gang
            # restart.
            from .robustness.gang import PeerTable

            peers = PeerTable(gang_dir, config.num_processes,
                              stale_after_s=config.gang_stale_after_s,
                              checkpoint_dir=config.checkpoint_dir)
        # /healthz last_window block: the job reassigns the dict whole
        # per window, so the HTTP thread's read is a snapshot.
        last_window = lambda: job.last_window_health  # noqa: E731
        if config.metrics_port is not None:
            metrics_server = MetricsServer(
                REGISTRY, counters=job.counters, ledger=LEDGER,
                port=config.metrics_port,
                stale_after_s=config.healthz_stale_after_s,
                supervisor_info=supervisor_info, peers=peers,
                last_window=last_window,
                ingest=source.ingest_health).start()
        if config.serve_port is not None:
            # The serving endpoint carries the scrape routes too (one
            # port to probe behind a load balancer); --metrics-port may
            # still run its scrape-only twin on a second port.
            serve_server = MetricsServer(
                REGISTRY, counters=job.counters, ledger=LEDGER,
                port=config.serve_port,
                stale_after_s=config.healthz_stale_after_s,
                supervisor_info=supervisor_info,
                serving=job.serving,
                serve_stale_after_s=config.serve_stale_after_s,
                last_window=last_window,
                ingest=source.ingest_health).start()
    # Crash recovery (the reference delegates this to Flink restarts): when
    # a checkpoint exists in --checkpoint-dir, restore it — including the
    # source's exact position, mid-file included — and continue from there.
    # Periodic checkpoints during the run snapshot the source too
    # (job.source).
    if config.checkpoint_dir:
        from .state import checkpoint as ckpt

        if config.coordinator is not None and config.autoscale == "on":
            # Topology-aware restore vote (the autoscale seam): the
            # newest generation may have been committed by a DIFFERENT
            # gang size — agree on the newest generation whose WHOLE
            # writing topology committed, quarantine anything newer
            # across every suffix, then restore either normally (same
            # topology) or through the N->M merge + re-bucket path.
            from .robustness.gang import agree_restore_topology

            try:
                agreed, writers = agree_restore_topology(
                    config.checkpoint_dir, config.process_id)
            except ValueError as exc:
                # Pre-autoscale markers (upgrade hazard): a permanent
                # config-shaped failure — restarting cannot help.
                LOG.error("autoscale restore vote refused: %s", exc)
                return EX_CONFIG
            LOG.info("gang restore vote: committed epoch %d (written "
                     "by %d workers)", agreed, writers)
            if agreed >= 0:
                try:
                    if writers == config.num_processes:
                        job.restore(source=source)
                    else:
                        job.restore_rescaled(agreed, writers,
                                             source=source)
                except ValueError as exc:
                    # A checkpoint the launch flags cannot consume
                    # (e.g. an ingest-offset section written by the
                    # other --source-format) is permanent: restarting
                    # replays the same mismatch.
                    LOG.error("restore refused: %s", exc)
                    return EX_CONFIG
                LOG.info("restored checkpoint from %s "
                         "(windows_fired=%d)", config.checkpoint_dir,
                         job.windows_fired)
        else:
            if config.coordinator is not None:
                # Gang restore vote (robustness/gang.py): agree on the
                # newest generation committed on EVERY host and
                # quarantine anything newer as *.partial — a crash
                # mid-epoch-commit falls back one generation
                # everywhere instead of restoring a torn global state.
                # Runs after job construction (the scorer's init
                # joined the multi-controller runtime the vote's
                # allgather needs).
                from .robustness.gang import agree_restore_generation

                agreed = agree_restore_generation(
                    config.checkpoint_dir,
                    getattr(job.scorer, "process_suffix", ""))
                LOG.info("gang restore vote: committed epoch %d", agreed)
            if ckpt.exists(job, config.checkpoint_dir):
                try:
                    job.restore(source=source)
                except ValueError as exc:
                    LOG.error("restore refused: %s", exc)
                    return EX_CONFIG
                LOG.info("restored checkpoint from %s "
                         "(windows_fired=%d)", config.checkpoint_dir,
                         job.windows_fired)
    if config.emit_updates:
        from .state.results import TopKBatch

        def _stream(window_out) -> None:
            # One line per updated row, as windows materialize — the
            # consumable form of the reference's continuous emission into
            # its sink. on_update fires post-absorption, so job.latest
            # already holds each row in final (external-id, finite-
            # filtered) form — one shared renderer with the final dump.
            if isinstance(window_out, TopKBatch):
                dense_rows = window_out.rows.tolist()
            else:
                dense_rows = [dense for dense, _ in window_out]
            to_ext = job.item_vocab.to_external
            for dense in dense_rows:
                item = to_ext(dense)
                print(_render_row(item, job.latest[item]),
                      flush=config.process_continuously)

        job.on_update = _stream
        if job.windows_fired:
            # Resumed run: replay the restored state so the stream is
            # complete (rows not re-updated after the checkpoint would
            # otherwise never appear). One consistent snapshot — the
            # replay must not interleave with concurrent absorption.
            snap = job.latest.snapshot()
            for item in sorted(snap):
                print(_render_row(item, snap[item]),
                      flush=config.process_continuously)

    # Poison-input quarantine (robustness/quarantine.py): malformed
    # lines divert to the dead-letter file under the rate breaker
    # instead of crashing the job.
    quarantine = None
    if config.quarantine_file:
        from .robustness.quarantine import Quarantine

        quarantine = Quarantine(config.quarantine_file,
                                max_rate=config.max_quarantine_rate,
                                max_bytes=config.max_quarantine_bytes)
        LOG.info("quarantine armed: dead-letter %s, max rate %.2f%%",
                 config.quarantine_file, config.max_quarantine_rate * 100)
    # Arm the source's own dead-letter path (rewritten in-flight files,
    # poisoned partitions) and its journal event hook — after quarantine
    # construction, before the stream starts.
    source.attach(quarantine=quarantine,
                  on_event=job._journal_ingest_event)

    from .observability import xla_trace
    from .robustness.autoscale import RESCALE_EXIT, RescaleDrain
    from .robustness.quarantine import QuarantineRateExceeded
    from .state.sparse_scorer import SlabCapacityError

    try:
        with xla_trace(config.profile_dir):
            # --buffer-timeout bounds how long a parsed line may wait in a
            # partial batch (reference: FlinkCooccurrences.java:46); it only
            # matters when tailing input continuously — process-once runs
            # always flush at end of stream.
            latency = (config.buffer_timeout / 1000.0
                       if config.process_continuously else None)
            job.run(batched_lines(source.lines(), max_latency_s=latency,
                                  origin=source.origin,
                                  quarantine=quarantine))
        if quarantine is not None:
            # End-of-stream verdict (warm-up waived): a short input that
            # was mostly garbage must exit 2, not succeed on its crumbs.
            quarantine.check_final()
    except RescaleDrain as exc:
        # Voluntary rescale exit (robustness/autoscale.py): the drain
        # checkpoint is committed gang-wide and the supervisor is
        # waiting to relaunch this gang at the new size. Tear down
        # cleanly (join workers, seal the journal — the AUTOSCALE
        # record is already on disk) and take the dedicated exit code
        # the supervisor never bills against the restart budget.
        job.abort()
        if heartbeat is not None:
            heartbeat.stop()
        LOG.info("rescale drain complete: %s; exiting %d for the gang "
                 "supervisor to relaunch", exc, RESCALE_EXIT)
        return RESCALE_EXIT
    except QuarantineRateExceeded as exc:
        # Exit 2 (permanent): a systematically malformed input does not
        # get better with supervised restarts — stop the run and point
        # the operator at the dead-letter file. The breaker fires inside
        # the ingest generator, before finish() is reachable: tear the
        # job down explicitly (join the scorer worker, seal the journal,
        # drop the degradation controller).
        job.abort()
        LOG.error("quarantine rate breaker tripped: %s", exc)
        return 2
    except SlabCapacityError as exc:
        # EX_CONFIG (permanent): the stream outgrew the int32 cell-slot
        # space of one slab — a capacity/topology decision (shard it),
        # not a transient failure; restarts would only replay the growth.
        job.abort()
        LOG.error("slab capacity exhausted: %s", exc)
        return EX_CONFIG
    finally:
        if quarantine is not None:
            quarantine.close()

    if config.development_mode:
        for w in job.step_timer.slowest():
            LOG.info("slow window ts=%d events=%d pairs=%d rows=%d "
                     "sample=%.4fs score=%.4fs", w.timestamp, w.events,
                     w.pairs, w.rows_scored, w.sample_seconds, w.score_seconds)

    # Print the latest top-K per item to stdout (the reference's result
    # stream ends in a no-op sink, FlinkCooccurrences.java:169-171; we make
    # the results consumable instead). With --emit-updates the stream
    # already carried every update; skip the duplicate final dump.
    if not config.emit_updates:
        # One consistent point-in-time copy (state/results.snapshot):
        # with --serve-port the query plane may still be reading while
        # this dump runs, and the dump itself must not lock-step every
        # row read against it.
        snap = job.latest.snapshot()
        for item in sorted(snap):
            print(_render_row(item, snap[item]))
    for server in (metrics_server, serve_server):
        if server is not None:
            # A clean shutdown, not a finally: on a crash the daemon
            # thread dies with the process and the supervisor's
            # journal-tail read covers the forensics.
            server.stop()
    if heartbeat is not None:
        # Same rationale: stop only on the clean path — on a crash the
        # daemon beacon dies with the process and the resulting stale
        # heartbeat is exactly the gang supervisor's death signal.
        heartbeat.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
