"""Multi-host (multi-controller) distribution layer.

The reference scales beyond one process with Flink's JobManager/TaskManager
runtime and its Netty shuffle (SURVEY §2.6). The TPU-native equivalent is
JAX's multi-controller runtime: one Python process per host, each driving
its local chips, with collectives riding ICI within a host/pod slice and
DCN across slices. This module owns that boundary:

  * ``init_multihost()`` — wraps ``jax.distributed.initialize`` (no-op when
    single-process; auto-detects coordinator on TPU pods).
  * ``make_multihost_mesh()`` — a 1-D ``items`` mesh over ALL chips of all
    hosts, built DCN-aware (hosts major) so XLA lowers ``psum`` over the
    item axis into a hierarchical ICI-reduce + DCN-exchange instead of a
    flat ring over DCN.
  * ``put_global(arr, mesh, spec)`` — turn a host-replicated NumPy array
    into a global sharded device array. Every process must call it with the
    same values (the framework's ingest is deterministic, so replaying the
    same stream on each host satisfies this — the analogue of the
    reference's deterministic keyed partitioning of one logical stream).

Result extraction stays process-local: each host materializes only the
top-K blocks of rows its chips own (``Array.addressable_shards``), exactly
like a Flink subtask emitting only its key partition.
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .mesh import ITEM_AXIS

LOG = logging.getLogger("tpu_cooccurrence")

_initialized = False


def init_multihost(coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None) -> None:
    """Join the multi-controller runtime (idempotent; no-op standalone).

    On TPU pods all three arguments are auto-detected from the metadata
    server; on other fabrics pass them explicitly (the coordinator is
    process 0 at ``host:port``).
    """
    global _initialized
    if coordinator_address is None and num_processes is None:
        # Standalone run (or TPU-pod autodetection handled by the runtime
        # when env vars are present) — nothing to do. Deliberately does NOT
        # latch ``_initialized``: an argument-free probe must not swallow a
        # later real ``initialize(coordinator, ...)`` call.
        return
    if _initialized:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    _initialized = True
    LOG.info("multihost: process %d/%d, %d local / %d global devices",
             jax.process_index(), jax.process_count(),
             jax.local_device_count(), jax.device_count())


def is_multihost() -> bool:
    return jax.process_count() > 1


def make_multihost_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """1-D ``items`` mesh over all chips of all hosts, DCN-aware.

    Device order is hosts-major (all of host 0's chips, then host 1's, …)
    so that contiguous item-row shards live within a host and the item-axis
    ``psum`` decomposes into intra-host ICI reductions plus one inter-host
    DCN exchange.
    """
    if devices is None:
        devices = jax.devices()
    if jax.process_count() > 1:
        devices = sorted(devices, key=lambda d: (d.process_index, d.id))
    return Mesh(np.asarray(devices), (ITEM_AXIS,))


def put_global(arr: np.ndarray, mesh: Mesh, spec: PartitionSpec):
    """Host-replicated array -> global sharded device array.

    Single-process this is ``device_put``; multi-controller it assembles a
    global ``jax.Array`` where each process supplies only the shards its
    devices own (the callback is invoked per addressable shard).
    """
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_callback(
        np.shape(arr), sharding, lambda idx: np.asarray(arr[idx]))


def maybe_multihost_mesh(config) -> Optional[Mesh]:
    """Join the multi-controller runtime and build the global mesh when the
    config asks for one (``--coordinator``); None for standalone runs."""
    if config.coordinator is None:
        return None
    init_multihost(config.coordinator, config.num_processes,
                   config.process_id)
    return make_multihost_mesh()
