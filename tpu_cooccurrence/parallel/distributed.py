"""Multi-host (multi-controller) distribution layer.

The reference scales beyond one process with Flink's JobManager/TaskManager
runtime and its Netty shuffle (SURVEY §2.6). The TPU-native equivalent is
JAX's multi-controller runtime: one Python process per host, each driving
its local chips, with collectives riding ICI within a host/pod slice and
DCN across slices. This module owns that boundary:

  * ``init_multihost()`` — wraps ``jax.distributed.initialize`` (no-op when
    single-process; auto-detects coordinator on TPU pods).
  * ``make_multihost_mesh()`` — a 1-D ``items`` mesh over ALL chips of all
    hosts, built DCN-aware (hosts major) so XLA lowers ``psum`` over the
    item axis into a hierarchical ICI-reduce + DCN-exchange instead of a
    flat ring over DCN.
  * ``put_global(arr, mesh, spec)`` — turn a host-replicated NumPy array
    into a global sharded device array. Every process must call it with the
    same values (the framework's ingest is deterministic, so replaying the
    same stream on each host satisfies this — the analogue of the
    reference's deterministic keyed partitioning of one logical stream).

Result extraction stays process-local: each host materializes only the
top-K blocks of rows its chips own (``Array.addressable_shards``), exactly
like a Flink subtask emitting only its key partition.

**Collective-entry watchdog** (robustness plane, ISSUE 10): a JAX
multi-controller collective whose peer has died does not fail — it
*hangs*, silently, forever (the runtime cannot distinguish "peer slow"
from "peer gone"). Every host-level collective this framework issues
goes through :func:`guarded_allgather` / :func:`gang_barrier`, which arm
a timer (:func:`collective_watchdog`, ``TPU_COOC_COLLECTIVE_TIMEOUT_S``
env, 0/unset = off) that converts the silent wedge into a supervised
exit with :data:`PEER_LOST_EXIT` — a code the gang supervisor treats as
"restart the whole gang", which is the only recovery JAX's
multi-controller model permits (a lost peer invalidates every surviving
process's collectives). The cooclint ``collective-watchdog`` rule keeps
raw ``multihost_utils`` calls from bypassing the wrappers.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..robustness import faults
from .. import tuning
from .mesh import ITEM_AXIS

LOG = logging.getLogger("tpu_cooccurrence")

#: Exit code for a collective-entry watchdog trip: EX_TEMPFAIL from
#: sysexits(3) — transient by definition (the peer died; a gang restart
#: fixes it), so deliberately NOT in the supervisor's permanent set.
PEER_LOST_EXIT = 75

#: Env var holding the collective-entry timeout in seconds; 0/unset
#: disables the watchdog (single-process runs, or externally-supervised
#: pods that prefer the runtime's own coordinator heartbeats). The gang
#: supervisor sets it for its children.
COLLECTIVE_TIMEOUT_ENV = "TPU_COOC_COLLECTIVE_TIMEOUT_S"

_initialized = False

#: 1-based ordinal of guarded collective entries in this process — the
#: ``barrier_enter`` fault site's seq, so chaos tests can kill a worker
#: at exactly the Nth collective.
_collective_seq = 0
_collective_seq_lock = threading.Lock()


def _peer_lost_exit(label: str, timeout_s: float) -> None:
    """Watchdog expiry: the collective has been blocked past the
    timeout, which in a multi-controller gang means a peer is gone and
    this process can never make progress again. ``os._exit`` (not
    ``sys.exit``): the main thread is wedged inside a C++ collective
    and an exception raised here would never unwind it. A module
    function so tests can monkeypatch the exit away."""
    LOG.error(
        "collective watchdog: %s blocked for more than %.1fs — a gang "
        "peer is unreachable; exiting %d for the gang supervisor to "
        "restart the whole gang", label, timeout_s, PEER_LOST_EXIT)
    os._exit(PEER_LOST_EXIT)


@contextlib.contextmanager
def collective_watchdog(label: str):
    """Arm a peer-loss timer around one collective entry.

    Fires the ``barrier_enter`` fault site (chaos hook), then runs the
    body under a daemon timer that calls :func:`_peer_lost_exit` if the
    collective is still blocked after ``TPU_COOC_COLLECTIVE_TIMEOUT_S``
    seconds. With the env unset the site still fires but no timer is
    armed (zero threads on the hot path).
    """
    global _collective_seq
    with _collective_seq_lock:
        _collective_seq += 1
        seq = _collective_seq
    if faults.PLAN is not None:
        faults.PLAN.fire("barrier_enter", seq=seq)
    timeout_s = float(tuning.env_read(COLLECTIVE_TIMEOUT_ENV, "0") or 0)
    if timeout_s <= 0:
        yield
        return
    timer = threading.Timer(timeout_s, _peer_lost_exit,
                            args=(label, timeout_s))
    timer.daemon = True
    timer.start()
    try:
        yield
    finally:
        timer.cancel()


def guarded_allgather(arr: np.ndarray):
    """``multihost_utils.process_allgather`` behind the collective-entry
    watchdog — the only allgather entry point the framework uses (the
    cooclint ``collective-watchdog`` rule enforces it)."""
    from jax.experimental import multihost_utils

    with collective_watchdog("process_allgather"):
        return multihost_utils.process_allgather(arr)


def allgather_max(value: int) -> int:
    """Worst-signal exchange: every process contributes one int, every
    process receives the gang-wide max. The multi-host degradation
    plane's per-window vote (robustness/degrade.py ``exchange``)."""
    return int(guarded_allgather(
        np.asarray([int(value)], dtype=np.int64)).max())


def allgather_min(value: int) -> int:
    """Gang-wide minimum of one int per process — the checkpoint
    restore vote (robustness/gang.py ``agree_restore_generation``): the
    newest generation committed on EVERY host is the min of the
    per-host newest-committed values."""
    return int(guarded_allgather(
        np.asarray([int(value)], dtype=np.int64)).min())


def gang_barrier(name: str) -> None:
    """All-process rendezvous behind the watchdog (checkpoint epoch
    commits and other whole-gang sync points)."""
    from jax.experimental import multihost_utils

    with collective_watchdog(f"barrier:{name}"):
        multihost_utils.sync_global_devices(name)


def _enable_cpu_collectives() -> None:
    """Select gloo as the CPU backend's cross-process collective fabric.

    Without an implementation selected, every multi-process computation
    on the CPU backend fails with "Multiprocess computations aren't
    implemented on the CPU backend" — which is exactly what a 2-process
    CPU gang (the chaos tests, or a laptop rehearsal of a pod run) hits
    on its first ``psum``. TPU fabrics ignore the setting (collectives
    ride ICI/DCN); older jaxlibs without the option are left alone.
    Must run before the backend client is created, hence its place
    inside :func:`init_multihost` ahead of ``initialize``.
    """
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # option absent: TPU-only jaxlib, nothing to do
        pass


def init_multihost(coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None) -> None:
    """Join the multi-controller runtime (idempotent; no-op standalone).

    On TPU pods all three arguments are auto-detected from the metadata
    server; on other fabrics pass them explicitly (the coordinator is
    process 0 at ``host:port``).
    """
    global _initialized
    if coordinator_address is None and num_processes is None:
        # Standalone run (or TPU-pod autodetection handled by the runtime
        # when env vars are present) — nothing to do. Deliberately does NOT
        # latch ``_initialized``: an argument-free probe must not swallow a
        # later real ``initialize(coordinator, ...)`` call.
        return
    if _initialized:
        return
    _enable_cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    _initialized = True
    LOG.info("multihost: process %d/%d, %d local / %d global devices",
             jax.process_index(), jax.process_count(),
             jax.local_device_count(), jax.device_count())


def is_multihost() -> bool:
    return jax.process_count() > 1


def hosts_major(devices: Sequence) -> "list":
    """Hosts-major device order: all of host 0's chips, then host 1's, …
    (ties broken by device id). The ordering contract behind
    :func:`make_multihost_mesh`, split out so tests can pin it without a
    real multi-process runtime."""
    return sorted(devices, key=lambda d: (d.process_index, d.id))


def make_multihost_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """1-D ``items`` mesh over all chips of all hosts, DCN-aware.

    Device order is hosts-major (all of host 0's chips, then host 1's, …)
    so that contiguous item-row shards live within a host and the item-axis
    ``psum`` decomposes into intra-host ICI reductions plus one inter-host
    DCN exchange.
    """
    if devices is None:
        devices = jax.devices()
    if jax.process_count() > 1:
        devices = hosts_major(devices)
    return Mesh(np.asarray(devices), (ITEM_AXIS,))


def put_global(arr: np.ndarray, mesh: Mesh, spec: PartitionSpec):
    """Host-replicated array -> global sharded device array.

    Single-process this is ``device_put``; multi-controller it assembles a
    global ``jax.Array`` where each process supplies only the shards its
    devices own (the callback is invoked per addressable shard).
    """
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_callback(
        np.shape(arr), sharding, lambda idx: np.asarray(arr[idx]))


def maybe_multihost_mesh(config) -> Optional[Mesh]:
    """Join the multi-controller runtime and build the global mesh when the
    config asks for one (``--coordinator``); None for standalone runs."""
    if config.coordinator is None:
        return None
    init_multihost(config.coordinator, config.num_processes,
                   config.process_id)
    return make_multihost_mesh()
