"""Device mesh construction for the item-sharded co-occurrence state.

The reference scales out by hash-partitioning keyed state over Flink
subtasks and broadcasting row sums (``FlinkCooccurrences.java:89-117,
162-167``). The TPU analogue (SURVEY §2.6): a 1-D ``jax.sharding.Mesh``
over the ``items`` axis; co-occurrence rows are sharded, the row-sum
vector is replicated (the broadcast analogue), and partial row-sum
reductions ride ICI via ``psum`` inside ``shard_map``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

ITEM_AXIS = "items"


def make_mesh(num_shards: Optional[int] = None, devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over ``num_shards`` devices (default: all available)."""
    if devices is None:
        devices = jax.devices()
    if num_shards is None:
        num_shards = len(devices)
    if num_shards > len(devices):
        raise ValueError(
            f"requested {num_shards} shards but only {len(devices)} devices")
    import numpy as np

    return Mesh(np.asarray(devices[:num_shards]), (ITEM_AXIS,))


def pad_to_multiple(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def shard_map_maybe_relaxed(f, mesh, in_specs, out_specs, relaxed: bool):
    """shard_map, with the varying-mesh-axis check disabled when the body
    contains a pallas_call (its ShapeDtypeStruct outputs carry no vma
    annotation, which ``check_vma=True`` — the default — rejects).
    XLA-only programs keep the full check."""
    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map
    if not relaxed:
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs)
    try:
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
    except TypeError:  # pragma: no cover - older jax spelling
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)
