"""Multi-chip sparse backend: row-sharded HBM slabs over an item mesh.

Combines the two scale axes of this framework: the device-resident sparse
slab of ``state/sparse_scorer.py`` (vocabularies beyond any dense ceiling,
minimal host<->device transfer) and the mesh distribution of
``parallel/sharded.py`` (the TPU-native replacement of the reference's
keyed shuffle + broadcast, SURVEY §2.6):

  * Item rows are **modulo-sharded**: shard ``d`` of ``D`` owns every row
    ``r`` with ``r % D == d`` — the ``keyBy(item)`` analogue. Modulo (not
    block) keeps Zipf-head rows spread across chips. Each shard runs its
    own :class:`~tpu_cooccurrence.state.sparse_scorer.SlabIndex` over
    *shard-local* row ids ``r // D`` and a private slab in its HBM.
  * ``row_sums`` is **replicated** (the broadcast analogue,
    ``FlinkCooccurrences.java:163``): each shard scatters its owned rows'
    window deltas into a partial vector and a ``lax.psum`` over ICI
    makes every replica whole — the only cross-chip communication in the
    entire step. Scoring then reads any partner's sum locally.
  * Scoring and top-K stay **shard-local** (each shard owns its rows
    outright), exactly like the dense sharded backend.

One program per step phase (``shard_map`` under ``jit``), fixed shapes
via the same configurable score ladders (default pow-4) as the
single-device sparse backend, host placement decisions per shard. Works identically on a virtual CPU mesh
and real TPU meshes.

Single-process checkpoints use the canonical sparse-matrix format (global
key space), so they are interchangeable with the single-device sparse and
hybrid backends — a 1-chip checkpoint restores onto 8 shards and back.
Multi-host (multi-controller) runs save per process instead
(``process_suffix``, like the dense sharded backend): the host-replicated
index keys go in every file, the slab counts only for the shards the
process's chips own; restore requires the writing run's process layout.

``--fused-window on`` extends the single-device one-dispatch window
(state/sparse_scorer._fused_sparse_body) to this mesh: per-shard
device-resident registry mirrors (``reg_start``/``reg_len`` blocks
indexed by shard-local row id) sync from each shard's
``_RegistryDirtyLog``, the packed-uplink decode prologue runs per shard
on its ownership-partitioned word streams, and the update scatter + psum
+ mirror sync + rescore + results scatter compile into ONE ``shard_map``
program — a steady-state window is exactly one launch per worker.
Relocation / promotion / upload-split windows and the first window after
construction or restore (the rescale seam: every bucket plan is invalid
until rebuilt from post-restore registry state) route down the chained
path per window, bit-identically — the fused body is built from the same
trace bodies (``_apply_cells``, ``_rect_score``) the chained programs
use. See ``_fallback_chained`` for the reason taxonomy (each reason is a
documented contract enforced by the analyzer's fused-fallback-registry
rule).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..metrics import Counters, RESCORED_ITEMS, ROW_SUM_PROCESS_WINDOW
from .. import tuning
from ..observability import LEDGER, StageClock
from ..ops.aggregate import (aggregate_window_coo, distinct_sorted,
                             narrow_deltas_int32)
from ..ops.device_scorer import pad_pow2, pad_pow4
from ..ops.donation import donate_argnums
from ..sampling.reservoir import PairDeltaBatch
from ..state.results import TopKBatch
from ..state.sparse_scorer import (_SENT, SlabIndex, _apply_cells,
                                   _pow2ceil, _score_rect, bucket_r,
                                   fixed_block, ladder_bits,
                                   make_slab_index, resolve_fixed_shapes,
                                   score_buckets)
from .mesh import ITEM_AXIS, make_mesh, shard_map_maybe_relaxed
from .sharded import _record_shard_metrics


class ShardedSparseScorer:
    """Modulo-row-sharded sparse slabs + replicated row sums via psum."""

    SCORE_BUDGET = 1 << 24  # per-shard padded-cell budget per score call
    # Fixed-shape mode budgets (PER SHARD — every shard pads to the same
    # rectangle; see state/sparse_scorer.SparseDeviceScorer).
    FIXED_BUDGET = 1 << 22
    FIXED_ROW_CAP = 1 << 16

    def __init__(self, top_k: int, num_shards: Optional[int] = None,
                 counters: Optional[Counters] = None,
                 mesh: Optional[Mesh] = None,
                 development_mode: bool = False,
                 capacity: int = 1 << 14,
                 items_capacity: int = 1 << 10,
                 compact_min_heap: int = 1 << 16,
                 score_ladder: Optional[int] = None,
                 defer_results: bool = False,
                 fixed_shapes: Optional[bool] = None,
                 use_pallas: str = "auto",
                 cell_dtype: str = "int32",
                 wire_format: str = "raw",
                 fused_window: str = "off") -> None:
        from ..state.wire import CELL_DTYPES, cell_promote_threshold
        from ..xla_cache import enable_compilation_cache

        enable_compilation_cache()
        if cell_dtype not in CELL_DTYPES:
            raise ValueError(
                f"cell_dtype must be one of {sorted(CELL_DTYPES)}, got "
                f"{cell_dtype!r}")
        if wire_format not in ("raw", "packed"):
            raise ValueError(
                f"wire_format must be raw or packed, got {wire_format!r}")
        self.cell_dtype = cell_dtype
        self._cnt_dtype = CELL_DTYPES[cell_dtype]
        self.promote_threshold = cell_promote_threshold(cell_dtype)
        self.wire_format = wire_format
        self.wire_packed = wire_format == "packed"
        self.top_k = top_k
        self.score_ladder = int(score_ladder if score_ladder is not None
                                else tuning.env_read(
                                    "TPU_COOC_SCORE_LADDER", 4))
        ladder_bits(self.score_ladder)  # validate at construction
        self.counters = counters if counters is not None else Counters()
        self.development_mode = development_mode
        self.mesh = mesh if mesh is not None else make_mesh(num_shards)
        self.n_shards = self.mesh.devices.size
        self.indexes = [make_slab_index(rows_capacity=max(
                            items_capacity // self.n_shards, 16))
                        for _ in range(self.n_shards)]
        self.items_cap = int(items_capacity)
        self.row_sums_host = np.zeros(self.items_cap, dtype=np.int64)
        self.compact_min_heap = int(compact_min_heap)
        self.capacity = int(capacity)  # per-shard slab capacity
        self.observed = 0
        self._pending: Optional[List] = None
        self.last_dispatched_rows = 0
        # (R, pallas-routed) -> jitted shard_map fn
        self._score_fns: Dict[tuple, object] = {}
        # Deferred-results mode (same design as the single-device scorers,
        # ops/device_scorer.DeferredResultsTable, here sharded): each
        # shard scatters its rows' packed top-K into a mesh-sharded
        # [D, 2, local_cap, K] table inside the scoring dispatch; flush
        # drains only rows dirty since the last flush, each process
        # fetching its addressable shards. Per-window result downlink
        # drops to zero. The lifecycle (lazy ensure, resize-on-growth,
        # mark/drain-pop, reset-on-restore) deliberately parallels
        # DeferredResultsTable rather than reusing it: the sharded table
        # shape, the shard_map scatter/gather, and the per-process
        # addressable-shard drain replace every method body — keep the
        # two in sync when changing mask semantics (see that class's
        # docstring for the contract).
        self.defer_results = bool(defer_results)
        self._tbl = None          # lazy [D, 2, local_cap, K] device array
        self._tbl_dirty = np.zeros(self.items_cap, dtype=bool)
        self._score_into_fns: Dict[tuple, object] = {}  # (R, pallas-routed)
        self._score_window_fns: Dict[tuple, object] = {}  # (plan, routed)
        self._tbl_gather_fns: Dict[int, object] = {}
        # Fixed-shape scoring (same contract and env override as the
        # single-device sparse scorer — constant per-bucket rectangles,
        # one fused window dispatch over a monotone high-water plan).
        self.fixed_shapes = resolve_fixed_shapes(fixed_shapes,
                                                 self.defer_results)
        self._plan_buckets = {}  # bucket -> high-water chunk count
        # Fused-kernel routing, same contract as the single-device sparse
        # scorer (ops/pallas_score.resolve_sparse_pallas_flag): the
        # Pallas rectangle kernel runs PER SHARD inside the shard_map
        # bodies (pallas_call is an ordinary per-device op there).
        from ..ops.pallas_score import resolve_sparse_pallas_flag

        self.use_pallas = resolve_sparse_pallas_flag(use_pallas)
        self._pallas_interpret = jax.default_backend() != "tpu"

        from .distributed import put_global

        self._put_global = put_global
        self.cnt = put_global(
            np.zeros((self.n_shards, self.capacity), self._cnt_dtype),
            self.mesh, P(ITEM_AXIS, None))
        self.dst = put_global(
            np.zeros((self.n_shards, self.capacity), np.int32),
            self.mesh, P(ITEM_AXIS, None))
        self.row_sums = put_global(
            np.zeros((self.items_cap,), np.int32), self.mesh, P())
        # Narrow cell dtypes: the wide int32 side-table (same design as
        # the single-device scorer — rows whose sum crossed the narrow
        # bound move wholesale), here a second sharded slab pair over
        # per-shard SlabIndexes. ``wide_rows`` is host-replicated like
        # every placement decision.
        if self.promote_threshold is not None:
            self.indexes_w = [make_slab_index(rows_capacity=max(
                                  items_capacity // self.n_shards, 16))
                              for _ in range(self.n_shards)]
            self.wide_rows = np.zeros(self.items_cap, dtype=bool)
            self.capacity_w = 1 << 10
            self.cnt_w = put_global(
                np.zeros((self.n_shards, self.capacity_w), np.int32),
                self.mesh, P(ITEM_AXIS, None))
            self.dst_w = put_global(
                np.zeros((self.n_shards, self.capacity_w), np.int32),
                self.mesh, P(ITEM_AXIS, None))
        else:
            self.indexes_w = None
            self.wide_rows = None
            self.capacity_w = 0
            self.cnt_w = self.dst_w = None
        self._plan_buckets_w = {}  # wide rows' own monotone plan
        # Fused one-dispatch window on the mesh (--fused-window on the
        # sharded sparse backend): deferred results only; promotion /
        # relocation / upload-split windows and the first window after
        # construction or restore route chained per window (see
        # _fallback_chained). Same contract as the single-device scorer.
        from ..observability.registry import REGISTRY
        from ..ops.device_scorer import resolve_fused_flag

        self.use_fused = self.defer_results and resolve_fused_flag(
            fused_window)
        self.last_dispatch_fused = False
        self.last_fallback_reason: Optional[str] = None
        # Tracing plane: per-window stage-seconds (uplink-encode /
        # rescore) the job carves into journal span tuples.
        self.stage_clock = StageClock()
        self._fused_shapes = set()
        # The rescale/restore seam and cold start: bucket plans must
        # rebuild from live registry state before any fused static plan
        # is baked, so the first window dispatches chained.
        self._fused_cold = True
        self._fused_dispatches = REGISTRY.gauge(
            "cooc_fused_dispatches_total",
            help="windows dispatched through the fused one-dispatch "
                 "window program")
        self._chained_dispatches = REGISTRY.gauge(
            "cooc_chained_dispatches_total",
            help="windows dispatched through the chained "
                 "scatter+score path")
        self._bucket_compiles = REGISTRY.gauge(
            "cooc_fused_bucket_compilations_total",
            help="distinct fused-window program shapes dispatched "
                 "(per-bucket shape-specialization compile churn)")
        if self.use_fused:
            # Host side of the per-shard device registry mirrors: every
            # registry mutation logs its local rows; each fused dispatch
            # uplinks the dirty rows' (start, len) as a delta sync.
            for ix in self.indexes:
                ix.rows.enable_dirty_log()
            self.reg_start = put_global(
                np.zeros((self.n_shards, self._local_cap), np.int32),
                self.mesh, P(ITEM_AXIS))
            self.reg_len = put_global(
                np.zeros((self.n_shards, self._local_cap), np.int32),
                self.mesh, P(ITEM_AXIS))
        else:
            self.reg_start = self.reg_len = None
        self._build_update()
        # Elastic-state interface (state/store.py): single-process
        # checkpoints are global-key-space blobs, so restore re-buckets
        # onto THIS run's shard count — a checkpoint taken at
        # --num-shards N restores onto M (Flink savepoint semantics).
        from ..state.store import ShardedRescaleStore

        self.store = ShardedRescaleStore(self)

    # -- mesh kernels -----------------------------------------------------

    def _build_update(self) -> None:
        """(Re)build the update program for the current items_cap."""
        items_cap = self.items_cap

        def _update(cnt_loc, dst_loc, row_sums, upd_loc, bounds_loc,
                    rs_part_loc):
            # Per-shard slices arrive as leading-1 blocks.
            cnt, dst = _apply_cells(cnt_loc[0], dst_loc[0], upd_loc[0],
                                    bounds_loc[0])
            # Owned-row partial sums -> psum makes every replica whole:
            # the step's only collective (ICI), replacing the reference's
            # keyed shuffle + re-broadcast round trip.
            part = jnp.zeros((items_cap,), jnp.int32).at[
                rs_part_loc[0, 0]].add(rs_part_loc[0, 1], mode="drop")
            row_sums = row_sums + jax.lax.psum(part, ITEM_AXIS)
            return cnt[None], dst[None], row_sums

        self._update = jax.jit(shard_map(
            _update, mesh=self.mesh,
            in_specs=(P(ITEM_AXIS, None), P(ITEM_AXIS, None), P(),
                      P(ITEM_AXIS), P(ITEM_AXIS), P(ITEM_AXIS)),
            out_specs=(P(ITEM_AXIS, None), P(ITEM_AXIS, None), P()),
        ), donate_argnums=donate_argnums(0, 1, 2))

        # Move/grow/compaction programs are built per static width on
        # demand and cached — a fresh jit wrapper per call would miss
        # jax's compile cache every time (cache resets on items_cap
        # growth; they just retrace).
        self._move_fns: Dict[int, object] = {}
        self._grow_fns: Dict[int, object] = {}
        self._compact_fns: Dict[int, object] = {}
        self._promote_fns: Dict[int, object] = {}
        # The fused window program bakes items_cap into its psum scatter
        # (like _update above), so growth invalidates the whole cache.
        self._fused_fns: Dict[tuple, object] = {}

    def _moves_fn(self, L: int):
        fn = self._move_fns.get(L)
        if fn is None:
            def _moves(cnt_loc, dst_loc, mv_loc):
                mv = mv_loc[0]
                old_start, new_start, ln = mv[0], mv[1], mv[2]
                col = jnp.arange(L, dtype=jnp.int32)[None, :]
                valid = col < ln[:, None]
                src_idx = jnp.where(valid, old_start[:, None] + col, 0)
                out_idx = jnp.where(valid, new_start[:, None] + col, _SENT)
                cnt = cnt_loc[0].at[out_idx.ravel()].set(
                    cnt_loc[0][src_idx].ravel(), mode="drop")
                dst = dst_loc[0].at[out_idx.ravel()].set(
                    dst_loc[0][src_idx].ravel(), mode="drop")
                return cnt[None], dst[None]

            fn = jax.jit(shard_map(
                _moves, mesh=self.mesh,
                in_specs=(P(ITEM_AXIS, None), P(ITEM_AXIS, None),
                          P(ITEM_AXIS)),
                out_specs=(P(ITEM_AXIS, None), P(ITEM_AXIS, None)),
            ), donate_argnums=donate_argnums(0, 1))
            self._move_fns[L] = fn
        return fn

    def _rect_pallas(self, R: int) -> bool:
        """Whether bucket width ``R`` routes through the fused kernel
        (ops/pallas_score.rect_routed — the shared routing rule)."""
        from ..ops.pallas_score import rect_routed

        return rect_routed(self.use_pallas, R, self.top_k, self.items_cap)

    def _rect_score(self, cnt, dst, row_sums, meta, observed, R: int):
        """One rectangle on one shard: the fused kernel when routed,
        else the XLA body — identical packed output either way."""
        if self._rect_pallas(R):
            from ..ops.pallas_score import pallas_score_rect

            return pallas_score_rect(cnt, dst, row_sums, meta, observed,
                                     top_k=self.top_k, R=R,
                                     interpret=self._pallas_interpret)
        return _score_rect(cnt, dst, row_sums, meta, observed,
                           self.top_k, R)

    def _score_fn(self, R: int):
        key = (R, self._rect_pallas(R))
        fn = self._score_fns.get(key)
        if fn is None:
            def _score(cnt_loc, dst_loc, row_sums, meta_loc, observed):
                out = self._rect_score(cnt_loc[0], dst_loc[0], row_sums,
                                       meta_loc[0], observed, R)
                return out[None]

            fn = jax.jit(shard_map_maybe_relaxed(
                _score, self.mesh,
                (P(ITEM_AXIS, None), P(ITEM_AXIS, None), P(),
                 P(ITEM_AXIS), P()),
                P(ITEM_AXIS), relaxed=key[1]))
            self._score_fns[key] = fn
        return fn

    @property
    def _local_cap(self) -> int:
        """Per-shard row capacity of the deferred-results table."""
        return -(-self.items_cap // self.n_shards)

    def _score_into_fn(self, R: int):
        """Scoring dispatch that scatters straight into the sharded
        deferred-results table (rows are shard-local: global // D)."""
        key = (R, self._rect_pallas(R))
        fn = self._score_into_fns.get(key)
        if fn is None:
            D = self.n_shards

            def _score_into(tbl_loc, cnt_loc, dst_loc, row_sums, meta_loc,
                            observed):
                out = self._rect_score(cnt_loc[0], dst_loc[0], row_sums,
                                       meta_loc[0], observed, R)
                rowids, lens = meta_loc[0][0], meta_loc[0][2]
                local = jnp.where(lens > 0, rowids // D, _SENT)
                return tbl_loc[0].at[:, local].set(out, mode="drop")[None]

            fn = jax.jit(shard_map_maybe_relaxed(
                _score_into, self.mesh,
                (P(ITEM_AXIS), P(ITEM_AXIS, None),
                 P(ITEM_AXIS, None), P(), P(ITEM_AXIS), P()),
                P(ITEM_AXIS), relaxed=key[1]), donate_argnums=donate_argnums(0))
            self._score_into_fns[key] = fn
        return fn

    def _score_window_into_fn(self, plan: tuple):
        """Fused window scoring into the sharded table: one shard_map
        dispatch runs every plan rectangle on each shard (same static
        plan on all shards — the caller pads every shard's meta to the
        common per-bucket cap)."""
        # Routing is a pure function of R except for the vocab bound,
        # which can flip when items_cap grows past 2^24 — key on it.
        key = (plan, self.use_pallas and self.items_cap <= 1 << 24)
        fn = self._score_window_fns.get(key)
        if fn is None:
            D = self.n_shards

            def _f(tbl_loc, cnt_loc, dst_loc, row_sums, meta_loc, observed):
                tbl = tbl_loc[0]
                for R, S, off in plan:
                    meta = jax.lax.slice(meta_loc[0], (0, off), (3, off + S))
                    out = self._rect_score(cnt_loc[0], dst_loc[0], row_sums,
                                           meta, observed, R)
                    local = jnp.where(meta[2] > 0, meta[0] // D, _SENT)
                    tbl = tbl.at[:, local].set(out, mode="drop")
                return tbl[None]

            fn = jax.jit(shard_map_maybe_relaxed(
                _f, self.mesh,
                (P(ITEM_AXIS), P(ITEM_AXIS, None),
                 P(ITEM_AXIS, None), P(), P(ITEM_AXIS), P()),
                P(ITEM_AXIS), relaxed=key[1]), donate_argnums=donate_argnums(0))
            self._score_window_fns[key] = fn
        return fn

    def _tbl_gather_fn(self, rp: int):
        fn = self._tbl_gather_fns.get(rp)
        if fn is None:
            def _g(tbl_loc, rows_loc):
                return tbl_loc[0][:, rows_loc[0]][None]

            fn = jax.jit(shard_map(
                _g, mesh=self.mesh,
                in_specs=(P(ITEM_AXIS), P(ITEM_AXIS)),
                out_specs=P(ITEM_AXIS),
            ))
            self._tbl_gather_fns[rp] = fn
        return fn

    def _ensure_tbl(self) -> None:
        if self._tbl is None:
            self._tbl = self._put_global(
                np.full((self.n_shards, 2, self._local_cap, self.top_k),
                        -np.inf, np.float32),
                self.mesh, P(ITEM_AXIS))

    def _reset_deferred(self) -> None:
        """Restore path: pre-checkpoint rows already live in the job's
        LatestResults (flushed before every save)."""
        self._tbl = None
        self._tbl_dirty = np.zeros(self.items_cap, dtype=bool)
        self._plan_buckets = {}
        self._plan_buckets_w = {}
        # Rescale seam: every bucket plan above was derived from the OLD
        # topology's per-shard row partition, and the registry rebuild
        # marked every row dirty. The next window dispatches chained
        # (rebuilding the plans from post-restore registry state); the
        # one after re-enters fused with a full all-dirty mirror resync.
        self._fused_cold = True
        if self.use_fused:
            self.reg_start = self._put_global(
                np.zeros((self.n_shards, self._local_cap), np.int32),
                self.mesh, P(ITEM_AXIS))
            self.reg_len = self._put_global(
                np.zeros((self.n_shards, self._local_cap), np.int32),
                self.mesh, P(ITEM_AXIS))

    def _grow_fn(self, n: int):
        fn = self._grow_fns.get(n)
        if fn is None:
            def _grow2(cnt_loc, dst_loc):
                # cnt may be a narrow cell dtype; dst is always int32
                # (jit retraces per input dtype — one cache entry serves
                # the narrow and wide slab pairs).
                zc = jnp.zeros((1, n), cnt_loc.dtype)
                zd = jnp.zeros((1, n), dst_loc.dtype)
                return (zc.at[:, : cnt_loc.shape[1]].set(cnt_loc),
                        zd.at[:, : dst_loc.shape[1]].set(dst_loc))

            fn = jax.jit(shard_map(
                _grow2, mesh=self.mesh,
                in_specs=(P(ITEM_AXIS, None), P(ITEM_AXIS, None)),
                out_specs=(P(ITEM_AXIS, None), P(ITEM_AXIS, None)),
            ))
            self._grow_fns[n] = fn
        return fn

    def _compact_gather_fn(self, g_pad: int):
        fn = self._compact_fns.get(g_pad)
        if fn is None:
            def _cg(cnt_loc, dst_loc, gmap_loc):
                gmap = gmap_loc[0]
                cap = cnt_loc.shape[1]
                return (jnp.zeros((cap,), cnt_loc.dtype).at[: g_pad].set(
                            cnt_loc[0][gmap])[None],
                        jnp.zeros((cap,), dst_loc.dtype).at[: g_pad].set(
                            dst_loc[0][gmap])[None])

            fn = jax.jit(shard_map(
                _cg, mesh=self.mesh,
                in_specs=(P(ITEM_AXIS, None), P(ITEM_AXIS, None),
                          P(ITEM_AXIS)),
                out_specs=(P(ITEM_AXIS, None), P(ITEM_AXIS, None)),
            ), donate_argnums=donate_argnums(0, 1))
            self._compact_fns[g_pad] = fn
        return fn

    # -- capacity ---------------------------------------------------------

    def _ensure_items(self, max_id: int) -> None:
        if max_id >= (1 << 31) - 1:
            raise ValueError("sparse backend supports item ids < 2^31 - 1")
        if max_id < self.items_cap:
            return
        new_cap = int(_pow2ceil(np.asarray([max_id + 1]), 1024)[0])
        grown = np.zeros(new_cap, dtype=np.int64)
        grown[: len(self.row_sums_host)] = self.row_sums_host
        self.row_sums_host = grown
        self.items_cap = new_cap
        if self.wide_rows is not None:
            wr = np.zeros(new_cap, dtype=bool)
            wr[: len(self.wide_rows)] = self.wide_rows
            self.wide_rows = wr
        # The replicated row-sum vector is reconstructible from the host
        # mirror — re-upload instead of growing on device.
        self.row_sums = self._put_global(
            self.row_sums_host.astype(np.int32), self.mesh, P())
        self._build_update()  # items_cap is baked into the psum scatter
        if self.use_fused:
            # Registry mirrors zero-extend (shard-local row ids are
            # stable under items_cap growth: r // D never changes).
            lc = self._local_cap

            def _gr(rs_loc, rl_loc):
                zs = jnp.zeros((1, lc), jnp.int32)
                zl = jnp.zeros((1, lc), jnp.int32)
                return (zs.at[:, : rs_loc.shape[1]].set(rs_loc),
                        zl.at[:, : rl_loc.shape[1]].set(rl_loc))

            self.reg_start, self.reg_len = jax.jit(shard_map(
                _gr, mesh=self.mesh,
                in_specs=(P(ITEM_AXIS), P(ITEM_AXIS)),
                out_specs=(P(ITEM_AXIS), P(ITEM_AXIS)),
            ), donate_argnums=donate_argnums(0, 1))(
                self.reg_start, self.reg_len)
        dirty = np.zeros(new_cap, dtype=bool)
        m = min(new_cap, len(self._tbl_dirty))
        dirty[:m] = self._tbl_dirty[:m]
        self._tbl_dirty = dirty
        if self._tbl is not None:
            old = self._tbl
            lc = self._local_cap

            def _gt(tbl_loc):
                z = jnp.full((1, 2, lc, self.top_k), -jnp.inf, jnp.float32)
                return z.at[:, :, : tbl_loc.shape[2]].set(tbl_loc)

            self._tbl = jax.jit(shard_map(
                _gt, mesh=self.mesh, in_specs=P(ITEM_AXIS),
                out_specs=P(ITEM_AXIS)), donate_argnums=donate_argnums(0))(old)

    def _ensure_heap(self, need_end: int) -> None:
        if need_end <= self.capacity:
            return
        new_cap = self.capacity
        while new_cap < need_end:
            new_cap *= 2
        self.cnt, self.dst = self._grow_fn(new_cap)(self.cnt, self.dst)
        self.capacity = new_cap

    def _ensure_heap_w(self, need_end: int) -> None:
        if need_end <= self.capacity_w:
            return
        new_cap = self.capacity_w
        while new_cap < need_end:
            new_cap *= 2
        self.cnt_w, self.dst_w = self._grow_fn(new_cap)(
            self.cnt_w, self.dst_w)
        self.capacity_w = new_cap

    # -- the window step --------------------------------------------------

    def _local_key(self, src: np.ndarray, dst: np.ndarray) -> np.ndarray:
        return ((src // self.n_shards).astype(np.int64) << 32) | dst

    def process_window(self, ts: int, pairs: PairDeltaBatch):
        self.last_dispatched_rows = 0
        self.last_dispatch_fused = False
        self.last_fallback_reason = None
        self.stage_clock.reset()
        D = self.n_shards
        if len(pairs) == 0:
            if self.defer_results:
                # Nothing in flight; results wait for the final flush.
                return TopKBatch.empty(self.top_k)
            return self.flush()
        if any(ix.needs_compaction(self.compact_min_heap)
               for ix in self.indexes):
            self._compact_all()
        if (self.indexes_w is not None
                and any(ix.needs_compaction(self.compact_min_heap)
                        for ix in self.indexes_w)):
            self._compact_all(wide=True)
        delta64 = pairs.delta.astype(np.int64)
        self._ensure_items(int(max(pairs.src.max(), pairs.dst.max())))
        src_d, dst_d, d_val, _ = aggregate_window_coo(
            pairs.src, pairs.dst, delta64, return_key=True)
        d_val32 = narrow_deltas_int32(d_val)

        # Global row sums (watermark ordering first), host-exact.
        rows = distinct_sorted(src_d)
        row_ends = np.searchsorted(src_d, rows, side="right")
        cum = np.concatenate([[0], np.cumsum(d_val)])
        rs_delta = cum[row_ends] - cum[np.searchsorted(src_d, rows)]
        self.row_sums_host[rows] += rs_delta
        if self.row_sums_host[rows].max(initial=0) >= 2**31:
            raise ValueError("row sum exceeds int32 range")
        window_sum = int(delta64.sum())
        self.observed += window_sum
        self.counters.add(ROW_SUM_PROCESS_WINDOW, window_sum)
        # Incremental-checkpoint dirty feed (state/delta.py): global
        # rows touched this window. No-op unless
        # --checkpoint-incremental armed the store's log.
        self.store.note_touched(rows)
        row_owner = (rows % D).astype(np.int64)
        owner_counts = np.bincount(row_owner, minlength=D)

        # Narrow-cell promotion, then the per-slab split: a cell routes
        # by its row's residency, decided BEFORE this window's deltas
        # apply (same ordering as the single-device scorer).
        if self.indexes_w is not None:
            self._promote_rows(rows)
            cell_wide = self.wide_rows[src_d]
        else:
            cell_wide = None

        # Fused routing gate: steady-state all-narrow windows take the
        # one-launch-per-worker program; everything else routes chained
        # per window, bit-identically.
        prealloc = None
        fused_done = False
        if self.use_fused:
            if cell_wide is not None and cell_wide.any():
                self._fallback_chained("promotion")
            elif self._fused_cold:
                self._fallback_chained("plan-rebuild")
            else:
                fused_done, prealloc = self._fused_window(
                    src_d, dst_d, d_val32, rows, rs_delta, row_owner)
        self._fused_cold = False
        if fused_done:
            if self.development_mode:
                self._check_row_sums(rows)
            self.counters.add(RESCORED_ITEMS, len(rows))
            self.last_dispatched_rows = len(rows)
            self.last_dispatch_fused = True
            self._record_dispatch_gauges(fused=True)
            _record_shard_metrics(len(rows), owner_counts)
            self._record_state_gauges()
            # Deferred results only: this window's top-K was scattered
            # into the sharded device table inside the fused program.
            return TopKBatch.empty(self.top_k)

        self._record_dispatch_gauges(fused=False)
        with self.stage_clock.stage("uplink-encode"):
            if cell_wide is not None and cell_wide.any():
                # Wide rows ride the same update program on the wide slab
                # pair; row sums travel once, with the narrow call.
                self._window_update(src_d[~cell_wide], dst_d[~cell_wide],
                                    d_val32[~cell_wide], rows, rs_delta)
                self._window_update(src_d[cell_wide], dst_d[cell_wide],
                                    d_val32[cell_wide], rows[:0],
                                    rs_delta[:0], wide=True)
            else:
                self._window_update(src_d, dst_d, d_val32, rows, rs_delta,
                                    prealloc=prealloc)

        if self.development_mode:
            self._check_row_sums(rows)

        self.counters.add(RESCORED_ITEMS, len(rows))
        self.last_dispatched_rows = len(rows)
        _record_shard_metrics(len(rows), owner_counts)
        with self.stage_clock.stage("rescore"):
            if self.indexes_w is not None and self.wide_rows[rows].any():
                wmask = self.wide_rows[rows]
                chunks = self._dispatch_scoring(rows[~wmask],
                                                row_owner[~wmask])
                chunks += self._dispatch_scoring(rows[wmask],
                                                 row_owner[wmask], wide=True)
            else:
                chunks = self._dispatch_scoring(rows, row_owner)
        self._record_state_gauges()
        prev, self._pending = self._pending, chunks
        return (self._materialize(prev) if prev is not None
                else TopKBatch.empty(self.top_k))

    def _apply_shards(self, src_d: np.ndarray, dst_d: np.ndarray,
                      d_val32: np.ndarray, wide: bool = False):
        """Allocate this window's cells in every shard's index.

        Per-shard placement: cells by owner, local keys stay sorted
        because src // D is monotone within a fixed residue class.
        Side-effecting (slots are allocated) — a window that allocates
        here and then routes chained must hand the result to
        ``_window_update`` via ``prealloc`` instead of re-applying.
        """
        D = self.n_shards
        indexes = self.indexes_w if wide else self.indexes
        owner = (src_d % D).astype(np.int64)
        plans = []
        sec_new: List[Tuple[np.ndarray, np.ndarray]] = []
        sec_delta: List[Tuple[np.ndarray, np.ndarray]] = []
        mv_blocks: List[Tuple[Optional[np.ndarray], int]] = []
        for d in range(D):
            sel = owner == d
            lk = self._local_key(src_d[sel], dst_d[sel])
            plan = indexes[d].apply(lk)
            plans.append(plan)
            sec_new.append((plan.slots[plan.new_sel],
                            (lk[plan.new_sel] & 0xFFFFFFFF).astype(np.int32)))
            sec_delta.append((plan.slots, d_val32[sel]))
            mv_blocks.append((plan.mv, plan.mv_len))
        return plans, sec_new, sec_delta, mv_blocks

    def _window_update(self, src_d: np.ndarray, dst_d: np.ndarray,
                       d_val32: np.ndarray, rows: np.ndarray,
                       rs_delta: np.ndarray, wide: bool = False,
                       prealloc=None) -> None:
        """The chained update step for one slab pair: moves (if any),
        then one [D, 2, N_pad] cell-section upload + owner-partitioned
        row-sum parts (psum'd to every replica)."""
        D = self.n_shards
        indexes = self.indexes_w if wide else self.indexes
        if prealloc is None:
            prealloc = self._apply_shards(src_d, dst_d, d_val32, wide=wide)
        _plans, sec_new, sec_delta, mv_blocks = prealloc
        if wide:
            self._ensure_heap_w(max(ix.heap_end for ix in indexes))
            cnt_ref, dst_ref = self.cnt_w, self.dst_w
        else:
            self._ensure_heap(max(ix.heap_end for ix in indexes))
            cnt_ref, dst_ref = self.cnt, self.dst
        lbl = "-wide" if wide else ""

        # Moves: one [D, 3, Mv_pad] block at the widest shard's rectangle.
        mv_pad = max((mv.shape[1] for mv, _ in mv_blocks if mv is not None),
                     default=0)
        mv_len = max((ml for mv, ml in mv_blocks if mv is not None),
                     default=0)
        if mv_pad:
            mv_all = np.zeros((D, 3, mv_pad), dtype=np.int32)
            for d, (mv, _) in enumerate(mv_blocks):
                if mv is not None:
                    mv_all[d, :, : mv.shape[1]] = mv
            LEDGER.up("update-moves-sharded" + lbl, mv_all)
            cnt_ref, dst_ref = self._moves_fn(mv_len)(
                cnt_ref, dst_ref,
                self._put_global(mv_all, self.mesh, P(ITEM_AXIS)))

        n_per = [len(s[0]) + len(dl[0]) for s, dl in zip(sec_new, sec_delta)]
        n_pad = pad_pow4(max(n_per + [1]), minimum=1 << 10)
        upd = np.full((D, 2, n_pad), _SENT, dtype=np.int32)
        upd[:, 1, :] = 0
        bounds = np.zeros((D, 2), dtype=np.int32)
        for d in range(D):
            (ns, nd), (ds_, dv) = sec_new[d], sec_delta[d]
            b0 = len(ns)
            b1 = b0 + len(ds_)
            upd[d, 0, :b0] = ns
            upd[d, 1, :b0] = nd
            upd[d, 0, b0:b1] = ds_
            upd[d, 1, b0:b1] = dv
            bounds[d] = (b0, b1)
        row_owner = (rows % D).astype(np.int64)
        rp = pad_pow4(int(np.bincount(row_owner, minlength=D).max())
                      if len(rows) else 1, minimum=256)
        rs_part = np.full((D, 2, rp), _SENT, dtype=np.int32)
        rs_part[:, 1, :] = 0
        for d in range(D):
            sel = row_owner == d
            k = int(sel.sum())
            rs_part[d, 0, :k] = rows[sel]
            rs_part[d, 1, :k] = rs_delta[sel].astype(np.int32)
        # Wire accounting (the single-device scorer's discipline): the
        # sharded update step never recorded its uploads, leaving
        # fused-vs-sharded wire comparisons blind on one side.
        LEDGER.up("update-sharded" + lbl, upd, bounds, rs_part)
        out = self._update(
            cnt_ref, dst_ref, self.row_sums,
            self._put_global(upd, self.mesh, P(ITEM_AXIS)),
            self._put_global(bounds, self.mesh, P(ITEM_AXIS)),
            self._put_global(rs_part, self.mesh, P(ITEM_AXIS)))
        if wide:
            self.cnt_w, self.dst_w, self.row_sums = out
        else:
            self.cnt, self.dst, self.row_sums = out

    def _promote_rows(self, rows: np.ndarray) -> None:
        """Promote rows whose (already-updated) sum crossed the narrow
        bound: move their cells to the wide sharded side-table before
        this window's deltas touch them — saturation can never be
        observed. One shard_map program moves every shard's cells."""
        thr = self.promote_threshold
        sel = (self.row_sums_host[rows] >= thr) & ~self.wide_rows[rows]
        if not sel.any():
            return
        newly = rows[sel]
        self.wide_rows[newly] = True
        D = self.n_shards
        per: List[Tuple[np.ndarray, np.ndarray]] = []
        m_max = 0
        for d in range(D):
            loc = (newly[newly % D == d] // D).astype(np.int64)
            if len(loc):
                keys, slots = self.indexes[d].row_cells(loc)
                self.indexes[d].free_rows(loc)
            else:
                keys = np.zeros(0, dtype=np.int64)
                slots = np.zeros(0, dtype=np.int32)
            if len(keys):
                order = np.argsort(keys, kind="stable")
                keys = keys[order]
                slots = slots[order].astype(np.int32)
                plan_w = self.indexes_w[d].apply(keys)
                dslots = plan_w.slots
            else:
                dslots = np.zeros(0, dtype=np.int32)
            per.append((slots, dslots))
            m_max = max(m_max, len(keys))
        if m_max == 0:
            return  # first-ever window already past the bound: no cells
        self._ensure_heap_w(max(ix.heap_end for ix in self.indexes_w))
        m_pad = pad_pow2(m_max, minimum=64)
        src = np.zeros((D, m_pad), dtype=np.int32)
        dsts = np.full((D, m_pad), _SENT, dtype=np.int32)
        for d, (s, t) in enumerate(per):
            src[d, : len(s)] = s
            dsts[d, : len(t)] = t
        LEDGER.up("promote-cells-sharded", src, dsts)
        self.cnt_w, self.dst_w = self._promote_fn(m_pad)(
            self.cnt, self.dst, self.cnt_w, self.dst_w,
            self._put_global(src, self.mesh, P(ITEM_AXIS)),
            self._put_global(dsts, self.mesh, P(ITEM_AXIS)))

    def _promote_fn(self, m_pad: int):
        fn = self._promote_fns.get(m_pad)
        if fn is None:
            def _p(cnt_loc, dst_loc, cw_loc, dw_loc, src_loc, dsts_loc):
                # Padding: src 0 (any valid slot — the gather is safe),
                # dsts _SENT (scatter-dropped); widen on the way over.
                vals = cnt_loc[0][src_loc[0]].astype(jnp.int32)
                cw = cw_loc[0].at[dsts_loc[0]].set(vals, mode="drop")
                dw = dw_loc[0].at[dsts_loc[0]].set(
                    dst_loc[0][src_loc[0]], mode="drop")
                return cw[None], dw[None]

            fn = jax.jit(shard_map(
                _p, mesh=self.mesh,
                in_specs=(P(ITEM_AXIS, None), P(ITEM_AXIS, None),
                          P(ITEM_AXIS, None), P(ITEM_AXIS, None),
                          P(ITEM_AXIS), P(ITEM_AXIS)),
                out_specs=(P(ITEM_AXIS, None), P(ITEM_AXIS, None)),
            ), donate_argnums=donate_argnums(2, 3))
            self._promote_fns[m_pad] = fn
        return fn

    # -- the fused window -------------------------------------------------

    def _fallback_chained(self, reason: str) -> None:
        """Route this window down the chained path, recording why.

        Every reason string used at a call site is a contract: the
        analyzer's fused-fallback-registry rule requires each to appear
        in docs/ARCHITECTURE.md's fallback table and in a tests/
        reference, so no fallback condition can land undocumented or
        untested.
        """
        self.last_fallback_reason = reason

    @property
    def fused_compilations(self) -> int:
        """Distinct fused-program static shapes dispatched so far (=
        XLA compiles of the fused window; the journal's per-window
        ``fused_compiles`` field)."""
        return len(self._fused_shapes)

    def _note_fused_shape(self, key) -> None:
        """Track distinct fused-program static shapes (= XLA compiles):
        the per-bucket shape-specialization churn gauge."""
        if key not in self._fused_shapes:
            self._fused_shapes.add(key)
            self._bucket_compiles.set(len(self._fused_shapes))

    def _record_dispatch_gauges(self, fused: bool) -> None:
        """Process-level fused/chained dispatch pair plus the per-shard
        split (every shard of one worker sees the same launch count by
        SPMD construction; the suffixed series make per-worker dispatch
        accounting greppable next to the per-shard RSS gauges)."""
        from ..observability.registry import REGISTRY

        (self._fused_dispatches if fused
         else self._chained_dispatches).add(1)
        prefix = ("cooc_fused_dispatches_total_shard" if fused
                  else "cooc_chained_dispatches_total_shard")
        hlp = ("fused windows dispatched, as seen by one shard" if fused
               else "chained windows dispatched, as seen by one shard")
        for d in range(self.n_shards):
            REGISTRY.gauge(f"{prefix}{d}", help=hlp).add(1)

    def _bump_plan(self, plan_buckets: dict, bucket: np.ndarray,
                   order: np.ndarray, row_owner: np.ndarray,
                   min_r: int) -> None:
        """Monotone high-water plan bump, shard-uniform: the shard_map
        program is shared, so a bucket's chunk count is driven by the
        fullest shard and every shard pads to it. Shared by the chained
        fixed-mode dispatch and the fused window so plans cannot drift
        when a run alternates between the two paths."""
        D = self.n_shards
        for bb in np.unique(bucket).tolist():
            members = order[bucket[order] == bb]
            R = bucket_r(bb, min_r, self.score_ladder)
            S = fixed_block(R, self.FIXED_BUDGET, self.FIXED_ROW_CAP)
            per_shard_max = int(np.bincount(row_owner[members],
                                            minlength=D).max())
            plan_buckets[bb] = max(plan_buckets.get(bb, 0),
                                   max(1, -(-per_shard_max // S)))

    def _fused_window(self, src_d: np.ndarray, dst_d: np.ndarray,
                      d_val32: np.ndarray, rows: np.ndarray,
                      rs_delta: np.ndarray, row_owner: np.ndarray):
        """Dispatch one steady-state window through the fused one-
        launch-per-worker program. Returns ``(handled, prealloc)``:
        ``(True, None)`` when the window ran fused, ``(False, prealloc)``
        when it must route chained — the allocation already happened, so
        the chained ``_window_update`` receives it instead of
        re-applying (re-applying would double-insert the new cells).

        Not fused-routable (decided here, after allocation): relocation
        windows (``plan.mv`` on any shard — the fused program carries no
        move kernel) and windows under an explicit upload-split request
        (TPU_COOC_UPLOAD_CHUNKS/_CHUNK_KB pins the raw chunked path).
        The caller gates promotion windows and the post-restore plan
        rebuild before allocation.
        """
        from ..ops.device_scorer import split_upload_auto

        D = self.n_shards
        prealloc = self._apply_shards(src_d, dst_d, d_val32)
        _plans, sec_new, sec_delta, mv_blocks = prealloc
        if any(mv is not None for mv, _ in mv_blocks):
            self._fallback_chained("relocation")
            return False, prealloc
        self._ensure_heap(max(ix.heap_end for ix in self.indexes))

        # Per-shard 3-section update: new | delta | owned row sums. The
        # third section replaces the chained path's separate rs_part
        # upload — the fused body scatters it into the psum partial.
        owner_counts = np.bincount(row_owner, minlength=D)
        n_per = [len(sec_new[d][0]) + len(sec_delta[d][0])
                 + int(owner_counts[d]) for d in range(D)]
        n_pad = pad_pow4(max(n_per + [1]), minimum=1 << 12)
        upd = np.full((D, 2, n_pad), _SENT, dtype=np.int32)
        upd[:, 1, :] = 0
        bounds = np.zeros((D, 2), dtype=np.int32)
        for d in range(D):
            (ns, nd), (ds_, dv) = sec_new[d], sec_delta[d]
            b0 = len(ns)
            b1 = b0 + len(ds_)
            upd[d, 0, :b0] = ns
            upd[d, 1, :b0] = nd
            upd[d, 0, b0:b1] = ds_
            upd[d, 1, b0:b1] = dv
            sel = row_owner == d
            k = int(sel.sum())
            upd[d, 0, b1: b1 + k] = rows[sel]
            upd[d, 1, b1: b1 + k] = rs_delta[sel].astype(np.int32)
            bounds[d] = (b0, b1)
        if split_upload_auto(upd[0]) is not None:
            self._fallback_chained("upload-split")
            return False, prealloc

        # Registry mirror delta sync, per shard in LOCAL row ids: rows
        # whose host (start, len) changed since the mirror last synced.
        # A restore/rescale marked everything dirty — resync every
        # occupied row. Sentinel-padded to the widest shard's count.
        dirty_l: List[np.ndarray] = []
        n_reg = 0
        for d in range(D):
            dirty, all_dirty = self.indexes[d].rows.drain_dirty()
            if all_dirty:
                dirty = self.indexes[d].rows.occupied().astype(np.int64)
            dirty_l.append(dirty)
            n_reg = max(n_reg, len(dirty))
        reg_pad = pad_pow2(max(n_reg, 1), minimum=256)
        reg_upd = np.full((D, 3, reg_pad), _SENT, dtype=np.int32)
        for d, dirty in enumerate(dirty_l):
            k = len(dirty)
            if k:
                r_start, r_len, _c = self.indexes[d].rows.get(dirty)
                reg_upd[d, 0, :k] = dirty
                reg_upd[d, 1, :k] = r_start
                reg_upd[d, 2, :k] = r_len

        # Monotone shard-uniform scoring plan (the fixed-shape rule via
        # _bump_plan): every (bucket, chunk-rank) ever occupied on any
        # shard dispatches — absent ones as all-padding rectangles — so
        # the static plan only grows and compile count stays bounded.
        local = (rows // D).astype(np.int64)
        lens = np.empty(len(rows), dtype=np.int32)
        for d in range(D):
            sel = row_owner == d
            _s, lens[sel], _c = self.indexes[d].rows.get(local[sel])
        min_r = max(16, self.top_k)
        bucket, order = score_buckets(lens, min_r, self.score_ladder)
        self._bump_plan(self._plan_buckets, bucket, order, row_owner,
                        min_r)
        b_sorted = bucket[order]
        plan_t = []
        segs: List[np.ndarray] = []
        off = 0
        for bb in sorted(self._plan_buckets):
            R = bucket_r(bb, min_r, self.score_ladder)
            S = fixed_block(R, self.FIXED_BUDGET, self.FIXED_ROW_CAP)
            lo = int(np.searchsorted(b_sorted, bb))
            hi = int(np.searchsorted(b_sorted, bb, side="right"))
            members = order[lo:hi]
            per_shard = [members[row_owner[members] == d]
                         for d in range(D)]
            for c in range(self._plan_buckets[bb]):
                seg = np.full((D, S), _SENT, dtype=np.int32)
                for d in range(D):
                    p = per_shard[d][c * S: (c + 1) * S]
                    seg[d, : len(p)] = rows[p]
                segs.append(seg)
                plan_t.append((R, S, off, self._rect_pallas(R)))
                off += S
        rows_all = np.concatenate(segs, axis=1)
        plan_t = tuple(plan_t)

        self._ensure_tbl()
        observed = np.float32(self.observed)
        pg = self._put_global
        if self.wire_packed:
            from ..state.wire import encode_update

            # Ownership-partitioned packed uplink: each shard's sections
            # encode independently; word streams pad to the widest
            # shard's pow2 bucket (+1 guard word for the decode gather).
            with self.stage_clock.stage("uplink-encode"):
                enc = [encode_update(upd[d], bounds[d], n_per[d])
                       for d in range(D)]
                wi_w = pad_pow2(max(len(e[0]) for e in enc) + 1,
                                minimum=256)
                wv_w = pad_pow2(max(len(e[1]) for e in enc) + 1,
                                minimum=256)
                wi = np.zeros((D, wi_w), dtype=np.uint32)
                wv = np.zeros((D, wv_w), dtype=np.uint32)
                hdr = np.zeros((D, 5), dtype=np.int32)
                for d, (ei, ev, eh) in enumerate(enc):
                    wi[d, : len(ei)] = ei
                    wv[d, : len(ev)] = ev
                    hdr[d] = eh
            LEDGER.up_encoded("fused-window-packed",
                              upd.nbytes + bounds.nbytes, wi, wv, hdr)
            LEDGER.up("fused-window-meta", reg_upd, rows_all)
            key = ("packed", n_pad, wi_w, wv_w, reg_pad, plan_t)
            self._note_fused_shape(key)
            (self.cnt, self.dst, self.row_sums, self._tbl,
             self.reg_start, self.reg_len) = self._fused_fn(key)(
                self.cnt, self.dst, self.row_sums, self._tbl,
                self.reg_start, self.reg_len,
                pg(wi, self.mesh, P(ITEM_AXIS)),
                pg(wv, self.mesh, P(ITEM_AXIS)),
                pg(hdr, self.mesh, P(ITEM_AXIS)),
                pg(reg_upd, self.mesh, P(ITEM_AXIS)),
                pg(rows_all, self.mesh, P(ITEM_AXIS)), observed)
        else:
            LEDGER.up("fused-window", upd, bounds, reg_upd, rows_all)
            key = ("raw", n_pad, reg_pad, plan_t)
            self._note_fused_shape(key)
            (self.cnt, self.dst, self.row_sums, self._tbl,
             self.reg_start, self.reg_len) = self._fused_fn(key)(
                self.cnt, self.dst, self.row_sums, self._tbl,
                self.reg_start, self.reg_len,
                pg(upd, self.mesh, P(ITEM_AXIS)),
                pg(bounds, self.mesh, P(ITEM_AXIS)),
                pg(reg_upd, self.mesh, P(ITEM_AXIS)),
                pg(rows_all, self.mesh, P(ITEM_AXIS)), observed)
        self._tbl_dirty[rows] = True
        return True, None

    def _fused_fn(self, key: tuple):
        """Build (or fetch) the one-launch fused program for one static
        shape key. The body chains the exact trace bodies the chained
        programs use — ``_apply_cells`` + the psum row-sum merge (the
        ``_update`` body), the mirror scatter, and ``_rect_score`` per
        plan rectangle into the deferred table — so fused and chained
        windows are bit-identical by construction."""
        fn = self._fused_fns.get(key)
        if fn is not None:
            return fn
        D = self.n_shards
        items_cap = self.items_cap
        packed = key[0] == "packed"
        if packed:
            _kind, n_pad, _wi_w, _wv_w, _reg_pad, plan = key
        else:
            _kind, n_pad, _reg_pad, plan = key
        relaxed = any(pl for _R, _S, _off, pl in plan)

        def _body(cnt, dst, row_sums, tbl, reg_start, reg_len, upd,
                  bounds, reg_upd, rows_all, observed):
            cnt, dst = _apply_cells(cnt, dst, upd, bounds)
            # Section 3 (pos >= bounds[1]): this shard's owned rows'
            # window deltas -> partial vector -> psum (the chained
            # _update body's collective, fused in).
            pos = jnp.arange(upd.shape[1], dtype=jnp.int32)
            in_rs = pos >= bounds[1]
            part = jnp.zeros((items_cap,), jnp.int32).at[
                jnp.where(in_rs, upd[0], _SENT)].add(
                jnp.where(in_rs, upd[1], 0), mode="drop")
            row_sums = row_sums + jax.lax.psum(part, ITEM_AXIS)
            reg_start = reg_start.at[reg_upd[0]].set(reg_upd[1],
                                                     mode="drop")
            reg_len = reg_len.at[reg_upd[0]].set(reg_upd[2], mode="drop")
            for R, S, off, _pl in plan:
                g_rows = jax.lax.slice(rows_all, (off,), (off + S,))
                live = g_rows != _SENT
                lr = jnp.where(live, g_rows // D, 0)
                meta = jnp.stack([g_rows, reg_start[lr],
                                  jnp.where(live, reg_len[lr], 0)])
                out = self._rect_score(cnt, dst, row_sums, meta,
                                       observed, R)
                loc = jnp.where(meta[2] > 0, lr, _SENT)
                tbl = tbl.at[:, loc].set(out, mode="drop")
            return cnt, dst, row_sums, tbl, reg_start, reg_len

        if packed:
            from ..state.wire import decode_update

            def _f(cnt_loc, dst_loc, row_sums, tbl_loc, rs_loc, rl_loc,
                   wi_loc, wv_loc, hdr_loc, reg_loc, rows_loc, observed):
                upd, bounds = decode_update(wi_loc[0], wv_loc[0],
                                            hdr_loc[0], n_pad)
                cnt, dst, row_sums, tbl, r_s, r_l = _body(
                    cnt_loc[0], dst_loc[0], row_sums, tbl_loc[0],
                    rs_loc[0], rl_loc[0], upd, bounds, reg_loc[0],
                    rows_loc[0], observed)
                return (cnt[None], dst[None], row_sums, tbl[None],
                        r_s[None], r_l[None])

            wire_specs = (P(ITEM_AXIS), P(ITEM_AXIS), P(ITEM_AXIS))
        else:
            def _f(cnt_loc, dst_loc, row_sums, tbl_loc, rs_loc, rl_loc,
                   upd_loc, bounds_loc, reg_loc, rows_loc, observed):
                cnt, dst, row_sums, tbl, r_s, r_l = _body(
                    cnt_loc[0], dst_loc[0], row_sums, tbl_loc[0],
                    rs_loc[0], rl_loc[0], upd_loc[0], bounds_loc[0],
                    reg_loc[0], rows_loc[0], observed)
                return (cnt[None], dst[None], row_sums, tbl[None],
                        r_s[None], r_l[None])

            wire_specs = (P(ITEM_AXIS), P(ITEM_AXIS))
        in_specs = ((P(ITEM_AXIS, None), P(ITEM_AXIS, None), P(),
                     P(ITEM_AXIS), P(ITEM_AXIS), P(ITEM_AXIS))
                    + wire_specs
                    + (P(ITEM_AXIS), P(ITEM_AXIS), P()))
        out_specs = (P(ITEM_AXIS, None), P(ITEM_AXIS, None), P(),
                     P(ITEM_AXIS), P(ITEM_AXIS), P(ITEM_AXIS))
        fn = jax.jit(shard_map_maybe_relaxed(
            _f, self.mesh, in_specs, out_specs, relaxed=relaxed),
            donate_argnums=donate_argnums(0, 1, 2, 3, 4, 5))
        self._fused_fns[key] = fn
        return fn

    def _record_state_gauges(self) -> None:
        """Per-window state-footprint gauges, per shard AND summed.

        The summed series reuse the single-process sparse backend's
        canonical names (``cooc_host_index_rss_bytes`` /
        ``cooc_slab_live_cells`` / ``cooc_slab_device_bytes``) so
        dashboards read one process-level number regardless of backend;
        the per-shard breakdown rides suffixed series
        (``cooc_host_index_rss_bytes_shard*``) for imbalance debugging.
        """
        from ..observability.registry import REGISTRY

        rss_total = 0
        cells_total = 0
        for d, ix in enumerate(self.indexes):
            rss = ix.nbytes
            cells = len(ix)
            REGISTRY.gauge(
                f"cooc_host_index_rss_bytes_shard{d}",
                help="host-side slab index footprint of one shard"
            ).set(rss)
            REGISTRY.gauge(
                f"cooc_slab_live_cells_shard{d}",
                help="live matrix cells of one shard's slab").set(cells)
            rss_total += rss
            cells_total += cells
        REGISTRY.gauge(
            "cooc_host_index_rss_bytes",
            help="host-side slab index footprint (registry + cell "
                 "index), refreshed per window").set(rss_total)
        REGISTRY.gauge(
            "cooc_slab_live_cells",
            help="live matrix cells across narrow and wide slabs"
        ).set(cells_total)
        REGISTRY.gauge(
            "cooc_slab_device_bytes",
            help="device slab allocation (cnt + dst, narrow and wide)"
        ).set(self.cnt.nbytes + self.dst.nbytes)

    def _dispatch_scoring(self, rows: np.ndarray, row_owner: np.ndarray,
                          wide: bool = False) -> List[Tuple]:
        """Global pow-4 length buckets; within a bucket, rows partition by
        owner into one [D, 3, S_pad] meta block per dispatch. ``wide``
        reads the promoted int32 side-table's slab pair and plan (jit
        retraces per slab dtype, so the trace bodies are shared)."""
        D = self.n_shards
        indexes = self.indexes_w if wide else self.indexes
        plan_buckets = self._plan_buckets_w if wide else self._plan_buckets
        cnt_ref, dst_ref = ((self.cnt_w, self.dst_w) if wide
                            else (self.cnt, self.dst))
        if len(rows) == 0 and not plan_buckets:
            return []
        local = (rows // D).astype(np.int64)
        starts = np.empty(len(rows), dtype=np.int32)
        lens = np.empty(len(rows), dtype=np.int32)
        for d in range(D):
            sel = row_owner == d
            # One registry pass per shard (the _RowField views are the
            # compat shim; this is the per-window hot path).
            starts[sel], lens[sel], _ = indexes[d].rows.get(local[sel])
        min_r = max(16, self.top_k)
        bucket, order = score_buckets(lens, min_r, self.score_ladder)
        b_sorted = bucket[order]
        chunks: List[Tuple] = []
        rects: List[Tuple[int, int, List[np.ndarray]]] = []  # (R, S, parts)
        if self.fixed_shapes:
            # Monotone plan over every (bucket, chunk-rank) ever occupied
            # on ANY shard (the shard_map program is shared, so the plan
            # must be shard-uniform); absent ones ride as all-padding.
            # Shared with the fused window so the plans cannot drift.
            self._bump_plan(plan_buckets, bucket, order, row_owner, min_r)
        pos = 0
        while pos < len(order):
            b = int(b_sorted[pos])
            end = int(np.searchsorted(b_sorted, b, side="right"))
            R = bucket_r(b, min_r, self.score_ladder)
            if self.fixed_shapes:
                s_block = fixed_block(R, self.FIXED_BUDGET,
                                      self.FIXED_ROW_CAP)
            else:
                s_block = max(self.SCORE_BUDGET // R, 16)
            members = order[pos:end]
            counts = np.bincount(row_owner[members], minlength=D)
            # Per-shard chunking: split the bucket so no shard exceeds
            # s_block rows per dispatch.
            n_dispatch = max(1, -(-int(counts.max()) // s_block))
            per_shard = [members[row_owner[members] == d] for d in range(D)]
            for i in range(n_dispatch):
                parts = [p[i * s_block: (i + 1) * s_block]
                         for p in per_shard]
                if self.fixed_shapes:
                    rects.append((R, s_block, parts))
                    continue
                s_max = max((len(p) for p in parts), default=0)
                s_pad = min(pad_pow4(max(s_max, 1), minimum=16), s_block)
                meta = np.zeros((D, 3, s_pad), dtype=np.int32)
                for d, p in enumerate(parts):
                    meta[d, 0, : len(p)] = rows[p]
                    meta[d, 1, : len(p)] = starts[p]
                    meta[d, 2, : len(p)] = lens[p]
                meta_g = self._put_global(meta, self.mesh, P(ITEM_AXIS))
                if self.defer_results:
                    self._ensure_tbl()
                    self._tbl = self._score_into_fn(R)(
                        self._tbl, cnt_ref, dst_ref, self.row_sums,
                        meta_g, np.float32(self.observed))
                    continue
                packed = self._score_fn(R)(
                    cnt_ref, dst_ref, self.row_sums, meta_g,
                    np.float32(self.observed))
                if hasattr(packed, "copy_to_host_async"):
                    packed.copy_to_host_async()
                chunks.append(([rows[p] for p in parts], packed))
            pos = end
        if self.fixed_shapes:
            # Top up to the high-water plan (absent (bucket, chunk-rank)
            # entries dispatch as all-padding).
            have = {}
            for R, _S, _p in rects:
                have[R] = have.get(R, 0) + 1
            for bb, n_chunks in plan_buckets.items():
                R = bucket_r(bb, min_r, self.score_ladder)
                S = fixed_block(R, self.FIXED_BUDGET, self.FIXED_ROW_CAP)
                for _ in range(n_chunks - have.get(R, 0)):
                    rects.append((R, S, [order[:0]] * D))
        if rects:
            # One packed [D, 3, sum(S)] upload + ONE fused dispatch for
            # the whole window (fixed mode is defer-only, enforced at
            # construction); canonical R order keeps the plan identical
            # regardless of which buckets were empty this window.
            rects.sort(key=lambda t: t[0])
            total = sum(S for _R, S, _p in rects)
            meta_all = np.zeros((D, 3, total), dtype=np.int32)
            plan = []
            off = 0
            for R, S, parts in rects:
                for d, p in enumerate(parts):
                    n = len(p)
                    meta_all[d, 0, off: off + n] = rows[p]
                    meta_all[d, 1, off: off + n] = starts[p]
                    meta_all[d, 2, off: off + n] = lens[p]
                plan.append((R, S, off))
                off += S
            self._ensure_tbl()
            self._tbl = self._score_window_into_fn(tuple(plan))(
                self._tbl, cnt_ref, dst_ref, self.row_sums,
                self._put_global(meta_all, self.mesh, P(ITEM_AXIS)),
                np.float32(self.observed))
        if self.defer_results:
            self._tbl_dirty[rows] = True
        return chunks

    def _compact_all(self, wide: bool = False) -> None:
        indexes = self.indexes_w if wide else self.indexes
        cap = self.capacity_w if wide else self.capacity
        gmaps = [ix.compact() for ix in indexes]
        g_pad = min(pad_pow2(max(len(g) for g in gmaps), minimum=1 << 10),
                    cap)
        gm = np.zeros((self.n_shards, g_pad), dtype=np.int32)
        for d, g in enumerate(gmaps):
            gm[d, : len(g)] = g
        gm_g = self._put_global(gm, self.mesh, P(ITEM_AXIS))
        if wide:
            self.cnt_w, self.dst_w = self._compact_gather_fn(g_pad)(
                self.cnt_w, self.dst_w, gm_g)
        else:
            self.cnt, self.dst = self._compact_gather_fn(g_pad)(
                self.cnt, self.dst, gm_g)

    def _local_slabs(self, arr=None) -> Dict[int, np.ndarray]:
        """Fetch the count slab of every ADDRESSABLE shard (multi-host: the
        shards this process's chips own) keyed by global shard id."""
        arr = self.cnt if arr is None else arr
        return {int(shard.index[0].start or 0): np.asarray(shard.data)[0]
                for shard in arr.addressable_shards}

    def _check_row_sums(self, rows: np.ndarray) -> None:
        local = self._local_slabs()
        local_w = (self._local_slabs(self.cnt_w)
                   if self.indexes_w is not None else None)
        D = self.n_shards
        for r in rows.tolist():
            d, lr = r % D, r // D
            if d not in local:  # owned by another process's chips
                continue
            if local_w is not None and self.wide_rows[r]:
                ix = self.indexes_w[d]
                slab = local_w[d]
            else:
                ix = self.indexes[d]
                slab = local[d]
            s = int(ix.row_start[lr])
            ln = int(ix.row_len[lr])
            actual = int(slab[s: s + ln].sum())
            if actual != int(self.row_sums_host[r]):
                raise AssertionError(
                    f"Item row {int(self.row_sums_host[r])} does not match "
                    f"actual row sum {actual} (item {r})")

    # -- results ----------------------------------------------------------

    def flush(self) -> TopKBatch:
        if self.defer_results:
            # Incremental drain, one sharded gather: each process fetches
            # its addressable shards' dirty rows (multi-host emission
            # contract unchanged — a process emits the rows its chips
            # own; the dirty mask is host-replicated so every process
            # clears the same rows).
            rows = np.flatnonzero(self._tbl_dirty)
            if self._tbl is None or len(rows) == 0:
                return TopKBatch.empty(self.top_k)
            D = self.n_shards
            owner = (rows % D).astype(np.int64)
            counts = np.bincount(owner, minlength=D)
            rp = pad_pow2(int(counts.max()), minimum=16)
            rows_b = np.zeros((D, rp), dtype=np.int32)
            per_shard: List[np.ndarray] = []
            for d in range(D):
                sel = rows[owner == d]
                rows_b[d, : len(sel)] = (sel // D).astype(np.int32)
                per_shard.append(sel)
            packed = self._tbl_gather_fn(rp)(
                self._tbl,
                self._put_global(rows_b, self.mesh, P(ITEM_AXIS)))
            rows_l, idx_l, vals_l = [], [], []
            for shard in packed.addressable_shards:
                d = shard.index[0].start or 0
                n = len(per_shard[d])
                if not n:
                    continue
                host = np.asarray(shard.data)[0]  # [2, rp, K]
                rows_l.append(per_shard[d].astype(np.int32))
                vals_l.append(host[0, :n])
                idx_l.append(host[1, :n].view(np.int32))
            # Clear marks only after the host copies are in hand (a
            # transient fetch failure must leave the rows drainable).
            self._tbl_dirty[rows] = False
            return TopKBatch.concatenate(rows_l, idx_l, vals_l, self.top_k)
        prev, self._pending = self._pending, None
        return (self._materialize(prev) if prev is not None
                else TopKBatch.empty(self.top_k))

    def _materialize(self, chunks) -> TopKBatch:
        rows_l, idx_l, vals_l = [], [], []
        for per_shard_rows, packed in chunks:
            for shard in packed.addressable_shards:
                d = shard.index[0].start or 0
                rows_d = per_shard_rows[d]
                if not len(rows_d):
                    continue
                host = np.asarray(shard.data)[0]  # [2, S_pad, K]
                rows_l.append(rows_d)
                vals_l.append(host[0, : len(rows_d)])
                idx_l.append(host[1, : len(rows_d)].view(np.int32))
        return TopKBatch.concatenate(rows_l, idx_l, vals_l, self.top_k)

    # -- checkpoint -------------------------------------------------------

    @property
    def process_suffix(self) -> str:
        """Checkpoint filename suffix: multi-host runs save per process."""
        return f".p{jax.process_index()}" if jax.process_count() > 1 else ""

    @property
    def local_shard_ids(self) -> "List[int]":
        """Global shard ids this process's chips own — the multi-host
        emission/ownership contract, derived from the mesh layout alone
        (no device fetch; the cross-topology restore filters the merged
        top-K table through this before any slab exists)."""
        me = jax.process_index()
        return sorted(d for d, dev in enumerate(
            self.mesh.devices.reshape(-1)) if dev.process_index == me)

    def _global_key(self, d: int, local_key: np.ndarray) -> np.ndarray:
        local_rows = (local_key >> 32).astype(np.int64)
        return ((local_rows * self.n_shards + d) << 32) | (
            local_key & 0xFFFFFFFF)

    def checkpoint_state(self) -> dict:
        """Canonical snapshot via the state store (state/store.py) —
        single-process blobs are global-key-space, shard-count-free."""
        return self.store.checkpoint_state()

    def restore_state(self, st: dict) -> None:
        """Restore via the state store: re-buckets a global blob onto
        THIS run's shard count (N->M rescale-on-restore)."""
        self.store.restore_state(st)

    def _device_checkpoint_state(self) -> dict:
        local = self._local_slabs()
        if jax.process_count() > 1:
            # Per-process snapshot. The *index* (cell keys, placement) is
            # host-replicated — every process has all D of them and saves
            # the identical global key union so a restored process can
            # rebuild every shard's SlabIndex from its own file. The slab
            # *counts* live on chips; each process saves only its
            # addressable shards' (ascending shard id, g_key order).
            views = [ix.keys_and_slots() for ix in self.indexes]
            keys_l = [self._global_key(d, k)
                      for d, (k, _s) in enumerate(views) if len(k)]
            keys = (np.sort(np.concatenate(keys_l)) if keys_l
                    else np.zeros(0, dtype=np.int64))
            shard_ids = sorted(local)
            cnt_l = [local[d][views[d][1]] for d in shard_ids]
            return {
                "mh_rows_key": keys,
                "mh_local_shards": np.asarray(shard_ids, dtype=np.int64),
                "mh_local_cnt": (np.concatenate(cnt_l).astype(np.int64)
                                 if cnt_l else np.zeros(0, np.int64)),
                "row_sums": self.row_sums_host.copy(),
                "observed": np.asarray([self.observed], dtype=np.int64),
            }
        D = self.n_shards
        keys_l, vals_l = [], []
        for d, ix in enumerate(self.indexes):
            k, sl = ix.keys_and_slots()
            if not len(k):
                continue
            keys_l.append(self._global_key(d, k))
            vals_l.append(local[d][sl])
        if self.indexes_w is not None:
            # Wide side-table cells merge into the same global-key
            # blob: the snapshot is dtype-free (int64 counts), and the
            # restoring run re-derives residency from its own threshold.
            local_w = self._local_slabs(self.cnt_w)
            for d, ix in enumerate(self.indexes_w):
                k, sl = ix.keys_and_slots()
                if not len(k):
                    continue
                keys_l.append(self._global_key(d, k))
                vals_l.append(local_w[d][sl])
        if keys_l:
            keys = np.concatenate(keys_l)
            vals = np.concatenate(vals_l)
            order = np.argsort(keys, kind="stable")
            keys, vals = keys[order], vals[order]
            nz = vals != 0
            keys, vals = keys[nz], vals[nz]
        else:
            keys = np.zeros(0, dtype=np.int64)
            vals = np.zeros(0, dtype=np.int64)
        return {
            "rows_key": keys,
            "rows_cnt": vals.astype(np.int64),
            "row_sums": self.row_sums_host.copy(),
            "observed": np.asarray([self.observed], dtype=np.int64),
        }

    def _restore_slabs(self, key: np.ndarray, vals: np.ndarray,
                       wide: bool) -> None:
        """Re-bucket one global-key cell blob onto THIS run's shard count
        and rebuild the matching slab pair (narrow or wide side-table).
        The checkpoint's --num-shards does not constrain the restoring
        mesh (state/store.rebucket_cells)."""
        from ..state.store import rebucket_cells

        D = self.n_shards
        indexes = self.indexes_w if wide else self.indexes
        cnt_dtype = np.int32 if wide else self._cnt_dtype
        need = 0
        per_shard = []
        for d, (lk, cv, dv) in enumerate(rebucket_cells(key, vals, D)):
            slots = indexes[d].rebuild_from_keys(lk)
            per_shard.append((slots, cv, dv))
            need = max(need, indexes[d].heap_end)
        cap = self.capacity_w if wide else self.capacity
        while cap < need:
            cap *= 2
        cnt_host = np.zeros((D, cap), dtype=cnt_dtype)
        dst_host = np.zeros((D, cap), dtype=np.int32)
        for d, (slots, cv, dv) in enumerate(per_shard):
            cnt_host[d, slots] = cv
            dst_host[d, slots] = dv.astype(np.int32)
        cnt_g = self._put_global(cnt_host, self.mesh, P(ITEM_AXIS, None))
        dst_g = self._put_global(dst_host, self.mesh, P(ITEM_AXIS, None))
        if wide:
            self.capacity_w = cap
            self.cnt_w, self.dst_w = cnt_g, dst_g
        else:
            self.capacity = cap
            self.cnt, self.dst = cnt_g, dst_g

    def _device_restore_state(self, st: dict) -> None:
        from ..state.wire import checked_narrow

        if "mh_rows_key" in st:
            return self._restore_multihost(st)
        key = st["rows_key"]
        cnt_vals = st["rows_cnt"].astype(np.int64)
        src = (key >> 32).astype(np.int64)
        dst = (key & 0xFFFFFFFF).astype(np.int64)
        max_id = int(max(src.max(initial=0), dst.max(initial=0)))
        if max_id >= self.items_cap:
            new_cap = int(_pow2ceil(np.asarray([max_id + 1]), 1024)[0])
            self.row_sums_host = np.zeros(new_cap, dtype=np.int64)
            self.items_cap = new_cap
            self._build_update()
        # Row sums land BEFORE the cell split: residency (narrow vs wide
        # side-table) is re-derived from this run's own threshold, so a
        # snapshot round-trips across cell dtypes.
        rs = np.asarray(st["row_sums"], dtype=np.int64)
        if len(rs) > self.items_cap and rs[self.items_cap:].any():
            raise ValueError("checkpoint row sums extend past its cells")
        self.row_sums_host[:] = 0
        m = min(len(rs), self.items_cap)
        self.row_sums_host[:m] = rs[:m]
        self.row_sums = self._put_global(
            self.row_sums_host.astype(np.int32), self.mesh, P())
        if self.indexes_w is not None:
            self.wide_rows = np.zeros(self.items_cap, dtype=bool)
            self.wide_rows[self.row_sums_host >= self.promote_threshold] \
                = True
            wmask = self.wide_rows[src]
            self._restore_slabs(
                key[~wmask],
                checked_narrow(cnt_vals[~wmask], self._cnt_dtype),
                wide=False)
            self._restore_slabs(key[wmask],
                                cnt_vals[wmask].astype(np.int32),
                                wide=True)
        else:
            self._restore_slabs(key, cnt_vals.astype(np.int32),
                                wide=False)
        self.observed = int(st["observed"][0])
        self._pending = None
        self._reset_deferred()

    def _restore_multihost(self, st: dict) -> None:
        """Restore a per-process snapshot (same process layout required).

        The file's key union rebuilds every shard's index (identical in all
        processes by construction); only the locally-owned shards' counts
        are in the file, and only they are uploaded — ``put_global``'s
        callback never asks a process for a remote shard's block. ``dst``
        values are derivable from the keys for every shard.
        """
        if jax.process_count() == 1:
            raise ValueError(
                "checkpoint was written by a multi-host sharded-sparse run "
                "(per-process slab blocks); restore it under the same "
                "process layout")
        if self.indexes_w is not None:
            raise ValueError(
                "multi-host sharded-sparse restore supports --cell-dtype "
                "int32 only (per-process snapshots carry no wide "
                "side-table blocks)")
        local_ids = sorted(self._local_slabs())
        saved_ids = st["mh_local_shards"].tolist()
        if saved_ids != local_ids:
            raise ValueError(
                f"checkpoint owns shards {saved_ids} but this process owns "
                f"{local_ids} — restore under the writing run's layout")
        D = self.n_shards
        key = st["mh_rows_key"]
        src = (key >> 32).astype(np.int64)
        dst = (key & 0xFFFFFFFF).astype(np.int64)
        max_id = int(max(src.max(initial=0), dst.max(initial=0)))
        if max_id >= self.items_cap:
            new_cap = int(_pow2ceil(np.asarray([max_id + 1]), 1024)[0])
            self.row_sums_host = np.zeros(new_cap, dtype=np.int64)
            self.items_cap = new_cap
            self._build_update()
        from ..state.store import rebucket_cells

        need = 0
        slots_by_shard = {}
        for d, (lk, _cv, dv) in enumerate(rebucket_cells(key, None, D)):
            slots_by_shard[d] = (self.indexes[d].rebuild_from_keys(lk), dv)
            need = max(need, self.indexes[d].heap_end)
        while self.capacity < need:
            self.capacity *= 2
        cnt_host = np.zeros((D, self.capacity), dtype=np.int32)
        dst_host = np.zeros((D, self.capacity), dtype=np.int32)
        for d, (slots, dv) in slots_by_shard.items():
            dst_host[d, slots] = dv.astype(np.int32)
        lo = 0
        cnt_local = st["mh_local_cnt"].astype(np.int32)
        for d in local_ids:
            slots, _ = slots_by_shard[d]
            cnt_host[d, slots] = cnt_local[lo: lo + len(slots)]
            lo += len(slots)
        self.cnt = self._put_global(cnt_host, self.mesh, P(ITEM_AXIS, None))
        self.dst = self._put_global(dst_host, self.mesh, P(ITEM_AXIS, None))
        rs = np.asarray(st["row_sums"], dtype=np.int64)
        self.row_sums_host[:] = 0
        m = min(len(rs), self.items_cap)
        self.row_sums_host[:m] = rs[:m]
        self.row_sums = self._put_global(
            self.row_sums_host.astype(np.int32), self.mesh, P())
        self.observed = int(st["observed"][0])
        self._pending = None
        self._reset_deferred()
