"""Multi-chip sharded scoring backend (``shard_map`` over an item mesh).

Distribution design (SURVEY §2.6, §7.6 — the TPU-native replacement of the
reference's keyed Netty shuffle + broadcast):

  * ``C`` (item x item counts) is **row-sharded** over the ``items`` mesh
    axis: shard d owns rows ``[d*R, (d+1)*R)`` — the analogue of
    ``keyBy(item)`` partitioned operator state.
  * ``row_sums`` is **replicated** — the analogue of the broadcast row-sum
    stream every rescorer subtask mirrors
    (``ItemRowRescorerTwoInputStreamOperator.java:33``, broadcast at
    ``FlinkCooccurrences.java:163``). Each shard computes a partial row-sum
    delta from its pair slice and the full update is an ``lax.psum`` over
    ICI — replacing the keyed shuffle + re-broadcast round-trip.
  * pair deltas and rows-to-score are **pre-partitioned by owner on host**
    (the hash-shuffle analogue, but a cheap bucketed sort instead of a
    network shuffle), so each chip receives and processes only its slice.
  * top-K is shard-local: each shard owns its rows outright, so no
    cross-chip merge is needed (SURVEY §7 "sharded top-K"); only the
    replicated row sums and the scalar ``observed`` total require
    cross-chip agreement.

Works identically on a virtual CPU mesh
(``--xla_force_host_platform_device_count``) and real TPU meshes.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..metrics import Counters, RESCORED_ITEMS, ROW_SUM_PROCESS_WINDOW
from ..ops.llr import llr_stable
from ..ops.device_scorer import pad_pow2
from ..sampling.reservoir import PairDeltaBatch
from .mesh import ITEM_AXIS, make_mesh, pad_to_multiple


class ShardedScorer:
    """Item-row-sharded dense co-occurrence state over a 1-D device mesh."""

    def __init__(self, num_items: int, top_k: int, num_shards: Optional[int] = None,
                 counters: Optional[Counters] = None,
                 mesh: Optional[Mesh] = None,
                 max_score_rows_per_call: int = 1024) -> None:
        self.mesh = mesh if mesh is not None else make_mesh(num_shards)
        self.n_shards = self.mesh.devices.size
        self.num_items_logical = num_items
        self.num_items = pad_to_multiple(num_items, self.n_shards)
        self.rows_per_shard = self.num_items // self.n_shards
        self.top_k = top_k
        self.counters = counters if counters is not None else Counters()
        self.max_score_rows = max_score_rows_per_call
        self.observed = 0  # exact host-side total

        c_sharding = NamedSharding(self.mesh, P(ITEM_AXIS, None))
        rep = NamedSharding(self.mesh, P())
        self.C = jax.device_put(
            jnp.zeros((self.num_items, self.num_items), dtype=jnp.int32), c_sharding)
        self.row_sums = jax.device_put(
            jnp.zeros((self.num_items,), dtype=jnp.int32), rep)

        num_items_c = self.num_items
        rows_per_shard_c = self.rows_per_shard

        def _update(C_loc, row_sums, src, dst, delta):
            # Per-shard slices arrive already owner-partitioned; localize rows.
            lo = jax.lax.axis_index(ITEM_AXIS) * rows_per_shard_c
            C_loc = C_loc.at[src[0] - lo, dst[0]].add(delta[0])
            rs_part = jnp.zeros((num_items_c,), dtype=jnp.int32).at[src[0]].add(delta[0])
            row_sums = row_sums + jax.lax.psum(rs_part, ITEM_AXIS)
            return C_loc, row_sums

        def _score(C_loc, row_sums, rows, observed):
            lo = jax.lax.axis_index(ITEM_AXIS) * rows_per_shard_c
            counts = C_loc[rows[0] - lo]  # [S, I] int32 (shard-local rows)
            k11 = counts.astype(jnp.float32)
            rs = row_sums.astype(jnp.float32)
            rsi = rs[rows[0]][:, None]
            rsj = rs[None, :]
            k12 = rsi - k11
            k21 = rsj - k11
            k22 = observed + k11 - k12 - k21
            scores = llr_stable(k11, k12, k21, k22)
            scores = jnp.where(counts != 0, scores, -jnp.inf)
            vals, idx = jax.lax.top_k(scores, top_k)
            return vals[None], idx[None]

        self._update = jax.jit(shard_map(
            _update, mesh=self.mesh,
            in_specs=(P(ITEM_AXIS, None), P(), P(ITEM_AXIS), P(ITEM_AXIS), P(ITEM_AXIS)),
            out_specs=(P(ITEM_AXIS, None), P()),
        ), donate_argnums=(0, 1))
        self._score = jax.jit(shard_map(
            _score, mesh=self.mesh,
            in_specs=(P(ITEM_AXIS, None), P(), P(ITEM_AXIS), P()),
            out_specs=(P(ITEM_AXIS), P(ITEM_AXIS)),
        ))

    # ------------------------------------------------------------------

    def _partition_by_owner(self, values: np.ndarray, owners: np.ndarray,
                            pad_min: int, fill: np.ndarray
                            ) -> Tuple[np.ndarray, np.ndarray]:
        """Bucket ``values`` rows into [n_shards, pad] with per-shard counts.

        ``fill`` supplies the padding value per shard (must target a row the
        shard owns, with delta 0 for updates)."""
        counts = np.bincount(owners, minlength=self.n_shards)
        pad = pad_pow2(int(counts.max()) if len(owners) else 0, minimum=pad_min)
        out = np.tile(fill[:, None], (1, pad)).astype(values.dtype)
        order = np.argsort(owners, kind="stable")
        offsets = np.zeros(self.n_shards + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        for d in range(self.n_shards):
            sel = order[offsets[d]:offsets[d + 1]]
            out[d, : len(sel)] = values[sel]
        return out, counts

    def process_window(self, ts: int, pairs: PairDeltaBatch
                       ) -> List[Tuple[int, List[Tuple[int, float]]]]:
        if len(pairs) == 0:
            return []
        src = pairs.src.astype(np.int32)
        dst = pairs.dst.astype(np.int32)
        delta = pairs.delta.astype(np.int32)
        owners = (src // self.rows_per_shard).astype(np.int64)

        # Owner-partitioned [D, P] blocks; padding rows point at each shard's
        # first owned row with delta 0 (scatter no-op).
        shard_first_row = (np.arange(self.n_shards, dtype=np.int32)
                           * self.rows_per_shard)
        src_b, _ = self._partition_by_owner(src, owners, 256, shard_first_row)
        dst_b, _ = self._partition_by_owner(dst, owners, 256,
                                            np.zeros(self.n_shards, np.int32))
        delta_b, _ = self._partition_by_owner(delta, owners, 256,
                                              np.zeros(self.n_shards, np.int32))

        self.C, self.row_sums = self._update(self.C, self.row_sums,
                                             src_b, dst_b, delta_b)

        window_sum = int(pairs.delta.sum())
        self.observed += window_sum
        self.counters.add(ROW_SUM_PROCESS_WINDOW, window_sum)

        rows = np.unique(pairs.src).astype(np.int32)
        self.counters.add(RESCORED_ITEMS, len(rows))
        row_owners = (rows // self.rows_per_shard).astype(np.int64)
        rows_b, row_counts = self._partition_by_owner(
            rows, row_owners, 64, shard_first_row)

        out: List[Tuple[int, List[Tuple[int, float]]]] = []
        # Chunk the padded column dimension if enormous; typical windows fit.
        vals, idx = self._score(self.C, self.row_sums, rows_b,
                                np.float32(self.observed))
        vals = np.asarray(vals)
        idx = np.asarray(idx)
        for d in range(self.n_shards):
            for r in range(int(row_counts[d])):
                keep = np.isfinite(vals[d, r])
                out.append((int(rows_b[d, r]),
                            list(zip(idx[d, r][keep].tolist(),
                                     vals[d, r][keep].tolist()))))
        return out

    # -- checkpoint ------------------------------------------------------

    def checkpoint_state(self) -> dict:
        return {
            "C": np.asarray(self.C),
            "row_sums": np.asarray(self.row_sums),
            "observed": np.asarray([self.observed], dtype=np.int64),
        }

    def restore_state(self, st: dict) -> None:
        c_sharding = NamedSharding(self.mesh, P(ITEM_AXIS, None))
        rep = NamedSharding(self.mesh, P())
        self.C = jax.device_put(jnp.asarray(st["C"], dtype=jnp.int32), c_sharding)
        self.row_sums = jax.device_put(
            jnp.asarray(st["row_sums"], dtype=jnp.int32), rep)
        self.observed = int(st["observed"][0])
