"""Multi-chip sharded scoring backend (``shard_map`` over an item mesh).

Distribution design (SURVEY §2.6, §7.6 — the TPU-native replacement of the
reference's keyed Netty shuffle + broadcast):

  * ``C`` (item x item counts) is **row-sharded** over the ``items`` mesh
    axis: shard d owns rows ``[d*R, (d+1)*R)`` — the analogue of
    ``keyBy(item)`` partitioned operator state.
  * ``row_sums`` is **replicated** — the analogue of the broadcast row-sum
    stream every rescorer subtask mirrors
    (``ItemRowRescorerTwoInputStreamOperator.java:33``, broadcast at
    ``FlinkCooccurrences.java:163``). Each shard computes a partial row-sum
    delta from its pair slice and the full update is an ``lax.psum`` over
    ICI — replacing the keyed shuffle + re-broadcast round-trip.
  * pair deltas and rows-to-score are **pre-partitioned by owner on host**
    (the hash-shuffle analogue, but a cheap bucketed sort instead of a
    network shuffle), so each chip receives and processes only its slice.
  * top-K is shard-local: each shard owns its rows outright, so no
    cross-chip merge is needed (SURVEY §7 "sharded top-K"); only the
    replicated row sums and the scalar ``observed`` total require
    cross-chip agreement.

Works identically on a virtual CPU mesh
(``--xla_force_host_platform_device_count``) and real TPU meshes.
"""

from __future__ import annotations

import math
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..metrics import Counters, RESCORED_ITEMS, ROW_SUM_PROCESS_WINDOW
from ..observability.registry import REGISTRY, log_buckets
from ..state.results import TopKBatch
from ..ops.aggregate import (aggregate_window_coo, distinct_sorted,
                             narrow_deltas_int32)
from ..ops.llr import llr_stable
from ..ops.device_scorer import (pad_pow2, resolve_pallas_flag,
                                 score_row_budget, topk_padded)
from ..ops.donation import donate_argnums
from ..sampling.reservoir import PairDeltaBatch
from .mesh import (ITEM_AXIS, make_mesh, pad_to_multiple,
                   shard_map_maybe_relaxed)


#: Row-count ladder for the dispatch-size histogram: 1 .. 2^24 rows.
ROWS_BUCKETS = log_buckets(1.0, 2.0 ** 24)


def _record_shard_metrics(n_rows: int, per_shard_counts) -> None:
    """Per-dispatch distribution metrics shared by both sharded backends.

    ``cooc_scorer_dispatch_rows`` is the per-window scored-row
    distribution (the padded-rectangle driver); the imbalance gauge is
    max/mean owned rows across shards — 1.0 is a perfectly balanced
    dispatch, and a sustained high value means one chip's rows gate every
    window (the sharded analogue of a straggler subtask).
    """
    REGISTRY.histogram(
        "cooc_scorer_dispatch_rows", ROWS_BUCKETS,
        help="distinct rows dispatched for scoring per window").observe(
            max(n_rows, 1))
    counts = np.asarray(per_shard_counts, dtype=np.float64)
    mean = counts.mean()
    if mean > 0:
        REGISTRY.gauge(
            "cooc_shard_row_imbalance",
            help="max/mean owned scored rows across shards "
                 "(1.0 = balanced)").set(float(counts.max() / mean))


class ShardedScorer:
    """Item-row-sharded dense co-occurrence state over a 1-D device mesh."""

    #: Initial per-shard row capacity in derive-from-data mode
    #: (``num_items == 0``): the vocab grows with the stream like the
    #: dense backend's, doubling on overflow.
    AUTO_INITIAL_ROWS = 64

    #: Column-tile width for the fused kernel (same measured choice as
    #: DeviceScorer.PALLAS_TILE — swept on-chip, TPU_ROUND2.jsonl).
    PALLAS_TILE = 2048

    def __init__(self, num_items: int, top_k: int, num_shards: Optional[int] = None,
                 counters: Optional[Counters] = None,
                 mesh: Optional[Mesh] = None,
                 max_score_rows_per_call: int = 8192,
                 count_dtype: str = "int32",
                 use_pallas: str = "auto") -> None:
        from ..xla_cache import enable_compilation_cache

        enable_compilation_cache()
        if count_dtype not in ("int32", "int16"):
            raise ValueError(f"count_dtype must be int32|int16, got {count_dtype}")
        self.count_dtype = np.dtype(count_dtype)
        self.mesh = mesh if mesh is not None else make_mesh(num_shards)
        self.n_shards = self.mesh.devices.size
        # Fused-kernel routing: same auto rule (and top-k-overflow
        # warning) as the dense single-chip scorer — the kernel exactly
        # when int16 counts meet a real TPU (XLA collapses 247x there,
        # TPU_ROUND2.jsonl pallas-bench), per shard inside the shard_map
        # body. With pallas on, the vocab pads to a tile multiple so the
        # kernel's column grid divides evenly.
        self.use_pallas = resolve_pallas_flag(use_pallas, self.count_dtype,
                                              top_k)
        self._pallas_interpret = jax.default_backend() != "tpu"
        self._pad_unit = (math.lcm(self.n_shards, self.PALLAS_TILE)
                          if self.use_pallas else self.n_shards)
        self.num_items_logical = num_items
        self.auto_grow = num_items <= 0
        if self.auto_grow:
            if jax.process_count() > 1:
                raise ValueError(
                    "multi-host sharded runs need --num-items: the vocab "
                    "capacity must agree across processes before any "
                    "window fires")
            num_items = self.AUTO_INITIAL_ROWS * self.n_shards
        self.top_k = top_k
        self.counters = counters if counters is not None else Counters()
        self._max_score_rows_per_call = max_score_rows_per_call
        self.observed = 0  # exact host-side total
        # One-window-deep result pipeline (see ops/device_scorer.py): the
        # device->host fetch of window N's top-K overlaps window N+1's host
        # sampling and dispatch; ``flush()`` drains the tail.
        self._pending: Optional[List] = None
        self.last_dispatched_rows = 0

        from .distributed import put_global

        self._put_global = put_global
        self._build(num_items)
        self.C = put_global(
            np.zeros((self.num_items, self.num_items), dtype=self.count_dtype),
            self.mesh, P(ITEM_AXIS, None))
        self.row_sums = put_global(
            np.zeros((self.num_items,), dtype=np.int32), self.mesh, P())

    def _build(self, num_items: int) -> None:
        """(Re)build the capacity-dependent pieces: shard geometry and the
        jitted ``shard_map`` programs (their row arithmetic closes over the
        per-shard row count)."""
        self.num_items = pad_to_multiple(num_items, self._pad_unit)
        self.rows_per_shard = self.num_items // self.n_shards
        # Bound each shard's per-call [S, I] score working set.
        self.max_score_rows = score_row_budget(
            self.num_items, self._max_score_rows_per_call)
        top_k = self.top_k

        num_items_c = self.num_items
        rows_per_shard_c = self.rows_per_shard

        def _update(C_loc, row_sums, coo):
            # Per-shard [1, 3, P] slices arrive owner-partitioned (one packed
            # buffer = one host->device transfer); localize rows.
            src, dst, delta = coo[0, 0], coo[0, 1], coo[0, 2]
            lo = jax.lax.axis_index(ITEM_AXIS) * rows_per_shard_c
            # C may be int16 (--count-dtype, reference-style short counts);
            # row sums stay int32 (see ops/device_scorer._apply_coo).
            C_loc = C_loc.at[src - lo, dst].add(delta.astype(C_loc.dtype))
            rs_part = jnp.zeros((num_items_c,), dtype=jnp.int32).at[src].add(delta)
            row_sums = row_sums + jax.lax.psum(rs_part, ITEM_AXIS)
            return C_loc, row_sums

        use_pallas = self.use_pallas
        interpret = self._pallas_interpret
        tile = self.PALLAS_TILE

        def _score(C_loc, row_sums, rows, observed):
            lo = jax.lax.axis_index(ITEM_AXIS) * rows_per_shard_c
            if use_pallas:
                from ..ops.pallas_score import pallas_score_topk_local

                # Fused LLR+top-K per shard; ids ride as float values
                # (decoded with astype in _materialize, like the dense
                # single-chip pallas path).
                packed = pallas_score_topk_local(
                    C_loc, row_sums, rows[0], lo, observed,
                    top_k=top_k, tile=tile, interpret=interpret)
                return packed[None]
            counts = C_loc[rows[0] - lo]  # [S, I] int32 (shard-local rows)
            k11 = counts.astype(jnp.float32)
            rs = row_sums.astype(jnp.float32)
            rsi = rs[rows[0]][:, None]
            rsj = rs[None, :]
            k12 = rsi - k11
            k21 = rsj - k11
            k22 = observed + k11 - k12 - k21
            scores = llr_stable(k11, k12, k21, k22)
            scores = jnp.where(counts != 0, scores, -jnp.inf)
            # topk_padded: a vocab smaller than K pads with -inf/0.
            vals, idx = topk_padded(scores, top_k)
            # Pack per shard into [1, 2, S, K] f32 => one fetchable buffer.
            return jnp.stack(
                [vals, jax.lax.bitcast_convert_type(idx, jnp.float32)])[None]

        self._update = jax.jit(shard_map(
            _update, mesh=self.mesh,
            in_specs=(P(ITEM_AXIS, None), P(), P(ITEM_AXIS)),
            out_specs=(P(ITEM_AXIS, None), P()),
        ), donate_argnums=donate_argnums(0, 1))
        self._score = jax.jit(shard_map_maybe_relaxed(
            _score, self.mesh,
            (P(ITEM_AXIS, None), P(), P(ITEM_AXIS), P()),
            P(ITEM_AXIS), relaxed=use_pallas))

    def _grow(self, need: int) -> None:
        """Double (at least) the vocab capacity and reshard the state.

        Derive-from-data mode only. Growth changes every row's owning
        shard (rows_per_shard changes), so the old state is materialized
        on host, zero-padded, and re-placed under the new geometry — a
        rare event (doubling) whose cost is one full C round-trip,
        exactly like the dense backend's reallocation."""
        old_items = self.num_items
        C_host = np.asarray(self.C)
        rs_host = np.asarray(self.row_sums)
        self._build(max(2 * old_items, int(need)))
        C_new = np.zeros((self.num_items, self.num_items),
                         dtype=self.count_dtype)
        C_new[:old_items, :old_items] = C_host
        rs_new = np.zeros((self.num_items,), dtype=np.int32)
        rs_new[:old_items] = rs_host
        self.C = self._put_global(C_new, self.mesh, P(ITEM_AXIS, None))
        self.row_sums = self._put_global(rs_new, self.mesh, P())

    # ------------------------------------------------------------------

    def _partition_by_owner(self, values: np.ndarray, owners: np.ndarray,
                            pad_min: int, fill: np.ndarray
                            ) -> Tuple[np.ndarray, np.ndarray]:
        """Bucket ``values`` rows into [n_shards, pad] with per-shard counts.

        ``fill`` supplies the padding value per shard (must target a row the
        shard owns, with delta 0 for updates)."""
        counts = np.bincount(owners, minlength=self.n_shards)
        pad = pad_pow2(int(counts.max()) if len(owners) else 0, minimum=pad_min)
        out = np.tile(fill[:, None], (1, pad)).astype(values.dtype)
        order = np.argsort(owners, kind="stable")
        offsets = np.zeros(self.n_shards + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        for d in range(self.n_shards):
            sel = order[offsets[d]:offsets[d + 1]]
            out[d, : len(sel)] = values[sel]
        return out, counts

    def process_window(self, ts: int, pairs: PairDeltaBatch):
        """One sharded update+score step; returns the *previous* window's
        results as a packed ``TopKBatch`` (one-window-deep pipeline)."""
        self.last_dispatched_rows = 0
        if len(pairs) == 0:
            # No new dispatch this window — drain any completed in-flight
            # results now instead of withholding them behind idle windows.
            return self.flush()
        # Shared per-window cell aggregation (see ops/aggregate.py): the
        # hash-shuffle analogue ships each distinct cell once per window and
        # keeps duplicate indices out of the per-shard scatters.
        src, dst, delta64 = aggregate_window_coo(
            pairs.src, pairs.dst, pairs.delta)
        delta = narrow_deltas_int32(delta64)
        if self.auto_grow:
            max_id = int(max(src.max(), dst.max()))
            if max_id >= self.num_items:
                self._grow(max_id + 1)
        owners = (src // self.rows_per_shard).astype(np.int64)

        # Owner-partitioned [D, P] blocks; padding rows point at each shard's
        # first owned row with delta 0 (scatter no-op). The three blocks ship
        # as one packed [D, 3, P] buffer (one transfer).
        shard_first_row = (np.arange(self.n_shards, dtype=np.int32)
                           * self.rows_per_shard)
        src_b, _ = self._partition_by_owner(src, owners, 256, shard_first_row)
        dst_b, _ = self._partition_by_owner(dst, owners, 256,
                                            np.zeros(self.n_shards, np.int32))
        delta_b, _ = self._partition_by_owner(delta, owners, 256,
                                              np.zeros(self.n_shards, np.int32))
        coo_b = self._put_global(np.stack([src_b, dst_b, delta_b], axis=1),
                                 self.mesh, P(ITEM_AXIS))

        self.C, self.row_sums = self._update(self.C, self.row_sums, coo_b)

        window_sum = int(pairs.delta.sum())
        self.observed += window_sum
        self.counters.add(ROW_SUM_PROCESS_WINDOW, window_sum)

        rows = distinct_sorted(src)
        self.counters.add(RESCORED_ITEMS, len(rows))
        self.last_dispatched_rows = len(rows)
        row_owners = (rows // self.rows_per_shard).astype(np.int64)
        rows_b, row_counts = self._partition_by_owner(
            rows, row_owners, 64, shard_first_row)
        _record_shard_metrics(len(rows), row_counts)

        # Chunk the padded per-shard row dimension to the HBM budget (both
        # are powers of two, so every chunk is shape-stable).
        chunks: List[Tuple[int, np.ndarray, object]] = []
        for lo in range(0, rows_b.shape[1], self.max_score_rows):
            rb = np.ascontiguousarray(rows_b[:, lo: lo + self.max_score_rows])
            rb_g = self._put_global(rb, self.mesh, P(ITEM_AXIS))
            packed = self._score(self.C, self.row_sums, rb_g,
                                 np.float32(self.observed))
            if hasattr(packed, "copy_to_host_async"):
                packed.copy_to_host_async()
            chunks.append((lo, rb, packed))
        prev, self._pending = self._pending, (row_counts, chunks)
        return (self._materialize(prev) if prev is not None
                else TopKBatch.empty(self.top_k))

    def flush(self):
        """Emit the final in-flight window's results (end of pipeline)."""
        prev, self._pending = self._pending, None
        return (self._materialize(prev) if prev is not None
                else TopKBatch.empty(self.top_k))

    def _materialize(self, pending):
        """Fetch in-flight [D, 2, S, K] blocks into one packed TopKBatch.

        Iterates *addressable* shards only: single-process that is all of
        them; multi-host each process emits exactly the rows its chips own
        (the analogue of a Flink subtask emitting its key partition).
        """
        row_counts, chunks = pending
        rows_l, idx_l, vals_l = [], [], []
        for lo, rb, packed in chunks:
            for shard in packed.addressable_shards:
                d = shard.index[0].start or 0
                host = np.asarray(shard.data)[0]  # [2, S, K]
                n_valid = min(rb.shape[1], int(row_counts[d]) - lo)
                if n_valid <= 0:
                    continue
                rows_l.append(rb[d, :n_valid])
                vals_l.append(host[0, :n_valid])
                # Pallas packs ids as float values (astype), XLA as an
                # int32 bitcast (view) — see ops/pallas_score.py.
                idx_l.append(host[1, :n_valid].astype(np.int32)
                             if self.use_pallas
                             else host[1, :n_valid].view(np.int32))
        return TopKBatch.concatenate(rows_l, idx_l, vals_l, self.top_k)

    # -- checkpoint ------------------------------------------------------

    @property
    def process_suffix(self) -> str:
        """Checkpoint filename suffix: multi-host runs save per process."""
        return f".p{jax.process_index()}" if jax.process_count() > 1 else ""

    def checkpoint_state(self) -> dict:
        if jax.process_count() > 1:
            # C is sharded across hosts and not fully addressable from any
            # single process; each process snapshots the contiguous row
            # block its chips own (device order is hosts-major, see
            # distributed.make_multihost_mesh). row_sums is replicated.
            shards = sorted(self.C.addressable_shards,
                            key=lambda s: s.index[0].start or 0)
            c_local = np.concatenate([np.asarray(s.data) for s in shards])
            row_lo = shards[0].index[0].start or 0
            return {
                "C_local": c_local,
                "row_lo": np.asarray([row_lo], dtype=np.int64),
                "row_sums": np.asarray(self.row_sums),
                "observed": np.asarray([self.observed], dtype=np.int64),
            }
        return {
            "C": np.asarray(self.C),
            "row_sums": np.asarray(self.row_sums),
            "observed": np.asarray([self.observed], dtype=np.int64),
        }

    def _fit_count_dtype(self, arr) -> np.ndarray:
        from ..ops.device_scorer import fit_count_dtype

        return fit_count_dtype(arr, self.count_dtype)

    def restore_state(self, st: dict) -> None:
        if "C_local" in st:
            if jax.process_count() == 1:
                raise ValueError(
                    "checkpoint was written by a multi-host run (per-process "
                    "row blocks); restore it under the same process layout")
            from jax.sharding import NamedSharding

            c_local = self._fit_count_dtype(st["C_local"])
            row_lo = int(st["row_lo"][0])
            # Validate the snapshot's row block against the rows this
            # process's chips actually own under the current layout — a
            # different process count/placement must fail loudly, not
            # slice garbage.
            spans = [s.index[0] for s in self.C.addressable_shards]
            own_lo = min(sp.start or 0 for sp in spans)
            own_hi = max(sp.stop if sp.stop is not None else self.num_items
                         for sp in spans)
            if row_lo != own_lo or len(c_local) != own_hi - own_lo:
                raise ValueError(
                    f"checkpoint holds rows [{row_lo}, "
                    f"{row_lo + len(c_local)}) but this process owns "
                    f"[{own_lo}, {own_hi}) — restore under the writing "
                    f"run's process layout")

            def _local_block(idx):
                rows = idx[0]
                return c_local[rows.start - row_lo: rows.stop - row_lo,
                               idx[1]]

            self.C = jax.make_array_from_callback(
                (self.num_items, self.num_items),
                NamedSharding(self.mesh, P(ITEM_AXIS, None)), _local_block)
        else:
            C = self._fit_count_dtype(st["C"])
            if C.shape[0] != self.num_items:
                # The writing run's capacity (already padded to ITS shard
                # count) may differ from this scorer's — e.g. a restore
                # into a derive-from-data run, or a different mesh size.
                # Rebuild at the larger of the two (never shrink below the
                # configured --num-items: the vocab bound the operator
                # asked for must survive the restore) and zero-pad.
                cap = pad_to_multiple(max(C.shape[0], self.num_items),
                                      self._pad_unit)
                self._build(cap)
                grown = np.zeros((self.num_items, self.num_items), C.dtype)
                grown[: C.shape[0], : C.shape[1]] = C
                C = grown
            self.C = self._put_global(C, self.mesh, P(ITEM_AXIS, None))
        rs = np.asarray(st["row_sums"], dtype=np.int32)
        if len(rs) != self.num_items:
            grown_rs = np.zeros((self.num_items,), dtype=np.int32)
            grown_rs[: len(rs)] = rs
            rs = grown_rs
        self.row_sums = self._put_global(rs, self.mesh, P())
        self.observed = int(st["observed"][0])
        # In-flight results belong to windows after the checkpoint; a
        # restore that rolls back must not emit them.
        self._pending = None
