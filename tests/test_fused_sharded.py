"""Fused SHARDED window (--fused-window on, --num-shards > 1): parity,
fallback routing, the rescale seam, and the observability split.

The contract under test (ISSUE 16): with the fused path forced on, every
steady-state sharded sparse window runs ownership-partitioned decode +
slab update scatter + row-sum psum + per-shard registry-mirror sync +
rescore + results-table scatter as ONE jit(shard_map) launch per worker,
BIT-identical to the chained sharded path — across shard counts, cell
dtypes, raw and packed wire, checkpoint/restore (all-dirty mirror
resync), and the 2→4 autoscale seam (plans rebuild cold, the first
post-seam window routes chained, the second re-enters fused with one new
bucket compilation). Non-routable windows fall back chained per window
under the reason taxonomy the cooclint ``fused-fallback-registry`` rule
pins: ``plan-rebuild``, ``relocation``, ``upload-split``, ``promotion``.
"""

import json

import jax
import numpy as np
import pytest

from tpu_cooccurrence.config import Backend
from tpu_cooccurrence.observability.registry import REGISTRY
from tpu_cooccurrence.parallel.sharded_sparse import ShardedSparseScorer
from tpu_cooccurrence.sampling.reservoir import PairDeltaBatch

from test_fused_window import _run_job, _table

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4,
    reason="sharded fused tests need >= 4 (virtual) devices")


# -- scorer-level harness -----------------------------------------------


def _steady_windows(seed=0, n_win=8, n_items=40):
    """A fixed pair population, then per-window subsets of it: after the
    first window every cell exists, so no row ever relocates — the
    zero-relocation steady state the fused path requires."""
    rng = np.random.default_rng(seed)
    src0 = rng.integers(0, n_items, 200).astype(np.int64)
    dst0 = rng.integers(0, n_items, 200).astype(np.int64)
    keep = src0 != dst0
    src0, dst0 = src0[keep], dst0[keep]
    out = [(src0, dst0, np.ones(len(src0), np.int64))]
    for _ in range(n_win - 1):
        sel = rng.random(len(src0)) < 0.6
        out.append((src0[sel], dst0[sel],
                    rng.integers(1, 4, int(sel.sum())).astype(np.int64)))
    return out


def _mk(num_shards, fused, **kw):
    return ShardedSparseScorer(
        5, num_shards=num_shards, defer_results=True,
        development_mode=True, fused_window=fused, **kw)


def _drive(scorer, windows, start=0):
    """Process windows, returning the (fused?, fallback-reason) trace."""
    trace = []
    for i, (src, dst, delta) in enumerate(windows, start=start):
        scorer.process_window(
            i, PairDeltaBatch(src=src, dst=dst, delta=delta))
        trace.append((scorer.last_dispatch_fused,
                      scorer.last_fallback_reason))
    return trace


def _assert_batches_equal(a, b, ctx=""):
    assert np.array_equal(a.rows, b.rows), ctx
    assert np.array_equal(a.vals, b.vals), ctx
    assert np.array_equal(a.idx, b.idx), ctx


# -- steady-state parity matrix -----------------------------------------


@pytest.mark.parametrize("cell_dtype", ["int32", "int16"])
@pytest.mark.parametrize("wire_format", ["raw", "packed"])
def test_fused_sharded_steady_state_bit_identical(cell_dtype, wire_format):
    for num_shards in (2, 3):
        wins = _steady_windows()
        kw = dict(cell_dtype=cell_dtype, wire_format=wire_format)
        chained = _mk(num_shards, "off", **kw)
        _drive(chained, wins)
        fused = _mk(num_shards, "on", **kw)
        trace = _drive(fused, wins)
        ctx = f"shards={num_shards} cell={cell_dtype} wire={wire_format}"
        _assert_batches_equal(chained.flush(), fused.flush(), ctx)
        # First non-empty window is the cold plan-rebuild; every later
        # window of the fixed population re-enters the ONE-launch path.
        assert trace[0] == (False, "plan-rebuild"), (ctx, trace)
        assert all(f for f, _ in trace[1:]), (ctx, trace)
        # One pow2 bucket tuple serves the whole steady stream.
        assert fused.fused_compilations == 1, (ctx, trace)


# -- job-level parity: depths 0 and 2 -----------------------------------


def _steady_job_stream(n_win=6):
    """Per-window repeats of the same event set: user histories saturate
    after window 1, so the pair population stabilizes and later windows
    can fuse."""
    users, items, ts = [], [], []
    for w in range(n_win):
        for j in range(60):
            users.append(j % 6)
            items.append((j * 7) % 30)
            ts.append(w * 10 + 5)
    users.append(0)
    items.append(999)
    ts.append(n_win * 10 + 5)
    return (np.asarray(users), np.asarray(items),
            np.asarray(ts, dtype=np.int64))


@pytest.mark.parametrize("depth", [0, 2])
@pytest.mark.parametrize("num_shards", [2, 3])
def test_fused_sharded_job_parity(depth, num_shards):
    users, items, ts = _steady_job_stream()
    kw = dict(backend=Backend.SPARSE, num_shards=num_shards,
              pipeline_depth=depth)
    chained = _run_job(users, items, ts, fused_window="off", **kw)
    fused = _run_job(users, items, ts, fused_window="on", **kw)
    assert _table(chained) == _table(fused)
    assert chained.counters.as_dict() == fused.counters.as_dict()
    assert chained.windows_fired == fused.windows_fired


# -- checkpoint/restore: all-dirty mirror resync ------------------------


@pytest.mark.parametrize("cell_dtype", ["int32", "int16"])
def test_fused_sharded_restore_resyncs_mirrors(cell_dtype):
    """A restore rebuilds the per-shard registries (all-dirty), so the
    first post-restore window must route chained while plans rebuild and
    the device mirrors resync — and the resumed fused run must stay
    bit-identical to a chained resume over the same schedule."""
    wins = _steady_windows()

    def resume(fused):
        s = _mk(2, fused, cell_dtype=cell_dtype)
        _drive(s, wins[:4])
        state = s.checkpoint_state()
        s.flush()
        s2 = _mk(2, fused, cell_dtype=cell_dtype)
        s2.restore_state(state)
        trace = _drive(s2, wins[4:], start=4)
        return s2.flush(), trace

    b_fused, trace = resume("on")
    b_chained, _ = resume("off")
    _assert_batches_equal(b_fused, b_chained, cell_dtype)
    assert trace[0] == (False, "plan-rebuild"), trace
    assert all(f for f, _ in trace[1:]), trace


# -- the autoscale seam: 2 -> 4 rescale ---------------------------------


def test_fused_sharded_rescale_seam_rebuilds_plans():
    """A 2→4 rescale invalidates every shard's bucket plan: plans must
    rebuild from the post-restore registry state, the first post-seam
    window must fall back chained cleanly, the second must re-enter
    fused with exactly one fresh bucket compilation (no stale-plan
    dispatch, no compile storm) — and stdout stays bit-identical to both
    the chained seam run and a fixed-topology fused run."""
    wins = _steady_windows()
    REGISTRY.reset()

    def seam(fused):
        s = _mk(2, fused)
        trace = _drive(s, wins[:4])
        state = s.checkpoint_state()
        s.flush()
        s2 = _mk(4, fused)
        s2.restore_state(state)
        assert s2._plan_buckets == {}, "stale bucket plan across seam"
        trace += _drive(s2, wins[4:], start=4)
        return s2.flush(), trace, s2

    b_fused, trace, s2 = seam("on")
    # Pre-seam: cold window then fused; post-seam: one chained
    # plan-rebuild window, then fused again.
    assert trace[0] == (False, "plan-rebuild"), trace
    assert all(f for f, _ in trace[1:4]), trace
    assert trace[4] == (False, "plan-rebuild"), trace
    assert all(f for f, _ in trace[5:]), trace
    # One compile before the seam, one after — counted on the gauge.
    assert s2.fused_compilations == 1, trace
    assert (REGISTRY.gauge("cooc_fused_bucket_compilations_total").get()
            == 1)

    b_chained, _, _ = seam("off")
    _assert_batches_equal(b_fused, b_chained, "seam fused-vs-chained")

    # Fixed-topology D=4 fused run over the same windows: the post-seam
    # flush only drains rows touched after the seam, so compare those.
    s4 = _mk(4, "on")
    _drive(s4, wins)
    b_fixed = s4.flush()
    sel = np.isin(b_fixed.rows, b_fused.rows)
    assert np.array_equal(b_fixed.rows[sel], b_fused.rows)
    assert np.array_equal(b_fixed.vals[sel], b_fused.vals)
    assert np.array_equal(b_fixed.idx[sel], b_fused.idx)


# -- fallback taxonomy: relocation, promotion, upload-split -------------


def test_fused_sharded_relocation_falls_back_and_recovers():
    n = 40
    w_small = (np.zeros(10, np.int64), np.arange(1, 11, dtype=np.int64),
               np.ones(10, np.int64))
    w_big = (np.zeros(n, np.int64), np.arange(1, n + 1, dtype=np.int64),
             np.ones(n, np.int64))

    def run(fused):
        s = _mk(2, fused)
        trace = _drive(s, [w_small, w_small, w_big, w_big])
        return s.flush(), trace

    b_fused, trace = run("on")
    b_chained, _ = run("off")
    _assert_batches_equal(b_fused, b_chained, "relocation parity")
    assert trace[0] == (False, "plan-rebuild"), trace
    assert trace[1][0] is True, trace
    # Row 0 outgrows its pow2 cap: moves ride the chained update.
    assert trace[2] == (False, "relocation"), trace
    # The repeated population recovers the one-launch path.
    assert trace[3][0] is True, trace


def test_fused_sharded_promotion_falls_back_chained():
    """int8 cells: the hub row crosses the promote threshold (128) and
    moves to the wide side-table — every window touching it must route
    chained (reason ``promotion``), bit-identical to the chained run."""
    w = (np.zeros(20, np.int64), np.arange(1, 21, dtype=np.int64),
         np.full(20, 3, np.int64))

    def run(fused):
        s = _mk(2, fused, cell_dtype="int8")
        trace = _drive(s, [w, w, w, w])
        return s.flush(), trace

    b_fused, trace = run("on")
    b_chained, _ = run("off")
    _assert_batches_equal(b_fused, b_chained, "promotion parity")
    assert trace[0] == (False, "plan-rebuild"), trace
    assert trace[1][0] is True, trace
    reasons = [r for _, r in trace]
    assert "promotion" in reasons, trace
    # Once wide, the hub row keeps the window chained.
    assert trace[3] == (False, "promotion"), trace


def test_fused_sharded_upload_split_pins_chained(monkeypatch):
    """An explicit TPU_COOC_UPLOAD_CHUNKS request is a measurement
    lever: the chunking A/B must not silently measure the fused program,
    so every window routes chained (reason ``upload-split``)."""
    monkeypatch.setenv("TPU_COOC_UPLOAD_CHUNKS", "2")
    wins = _steady_windows(n_win=3)
    s = _mk(2, "on")
    trace = _drive(s, wins)
    assert trace[0] == (False, "plan-rebuild"), trace
    assert all(t == (False, "upload-split") for t in trace[1:]), trace


# -- observability: gauges, journal, packed-uplink ledger ---------------


def test_fused_sharded_gauges_and_journal(tmp_path):
    REGISTRY.reset()
    users, items, ts = _steady_job_stream()
    jpath = tmp_path / "journal.jsonl"
    _run_job(users, items, ts, backend=Backend.SPARSE, num_shards=2,
             fused_window="on", journal=str(jpath))
    fused_total = REGISTRY.gauge("cooc_fused_dispatches_total").get()
    chained_total = REGISTRY.gauge("cooc_chained_dispatches_total").get()
    assert fused_total > 0, "no window ever took the fused sharded path"
    # The per-shard split sits beside the process-level pair: each
    # worker dispatches once per window, so every shard's gauge equals
    # the process total.
    for d in range(2):
        assert (REGISTRY.gauge(
            f"cooc_fused_dispatches_total_shard{d}").get() == fused_total)
        assert (REGISTRY.gauge(
            f"cooc_chained_dispatches_total_shard{d}").get()
            == chained_total)
    from tpu_cooccurrence.observability.journal import (read_records,
                                                        validate_record)
    recs = [r for r in read_records(str(jpath)) if "seq" in r]
    for r in recs:
        validate_record(r)
    flags = [r["fused"] for r in recs]
    assert set(flags) <= {0, 1}
    assert flags.count(1) == fused_total
    # Chained windows name their fallback reason for the operator —
    # the first (cold-plan) window is always a "plan-rebuild".
    assert recs[0]["fused"] == 0
    assert recs[0]["fallback_reason"] == "plan-rebuild"
    assert all("fallback_reason" not in r for r in recs if r["fused"])
    # The bucket-compile counter rides the journal per window.
    compiles = [r["fused_compiles"] for r in recs if "fused_compiles" in r]
    assert compiles and compiles[-1] == REGISTRY.gauge(
        "cooc_fused_bucket_compilations_total").get()
    assert (REGISTRY.histogram("cooc_window_score_seconds_fused").count
            == fused_total)


def test_fused_sharded_packed_uplink_is_ledger_booked(tmp_path):
    """The sharded packed uplink books encoded vs raw bytes exactly as
    the single-process PR-7 path: per fused window the encoded pair is
    accounted and never exceeds the raw equivalent."""
    users, items, ts = _steady_job_stream()
    jpath = tmp_path / "journal.jsonl"
    _run_job(users, items, ts, backend=Backend.SPARSE, num_shards=2,
             fused_window="on", wire_format="packed", journal=str(jpath))
    recs = [json.loads(line) for line in open(jpath)]
    fused_recs = [r for r in recs if r.get("fused") == 1 and r.get("pairs")]
    assert fused_recs, "no fused window with pairs to account"
    for r in fused_recs:
        assert r["wire"]["h2d_bytes"] > 0
        assert r["wire"]["uplink_enc_bytes"] > 0
        assert (r["wire"]["uplink_raw_bytes"]
                >= r["wire"]["uplink_enc_bytes"])
