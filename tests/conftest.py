"""Test harness: force CPU JAX with an 8-device virtual mesh.

Multi-chip sharding (`shard_map`/`psum`) is tested without real TPUs via
``--xla_force_host_platform_device_count`` (SURVEY.md §4).

Note: the surrounding environment may pre-import jax and register an
accelerator plugin via sitecustomize before pytest starts, so setting
``JAX_PLATFORMS`` in ``os.environ`` here is not enough — we also override
the already-imported config. Backend clients are created lazily on first
use, so doing this in conftest (before any test touches jax) is safe.
"""

import os
import tempfile

os.environ["JAX_PLATFORMS"] = "cpu"
# Persistent XLA compilation cache, shared by the pytest process AND
# every subprocess a test spawns (supervisor children, gang workers,
# CLI chaos runs all re-jit the same small programs). Set via env vars
# rather than jax.config.update so children inherit it; setdefault so
# an operator's own cache dir wins. The zero thresholds matter on CPU:
# this suite's programs are tiny and would otherwise all fall under the
# default min-compile-time cutoff.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(tempfile.gettempdir(), "tpu-cooc-xla-cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
# JAX_PLATFORMS=cpu alone is NOT enough to keep jax off the network:
# the sitecustomize-registered accelerator plugin still contacts its
# pool at import, and a half-dead tunnel (TCP accepts, never answers)
# then hangs the interpreter indefinitely — reproduced 2026-07-31,
# where a supervised soak-test child inherited JAX_PLATFORMS=cpu but
# not this guard and hung the whole suite for an hour. Clearing the
# pool address list here makes every test AND every subprocess a test
# spawns (supervisor children, multihost workers, CLI runs) immune to
# tunnel state.
os.environ["PALLAS_AXON_POOL_IPS"] = ""
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    """Fast lane by default (VERDICT r4 Next #8): the soak / sweep /
    multihost / pallas-rect surfaces are minutes each, pushing the
    default suite past CI-feedback territory. They are deselected
    unless the round gate opts back in (``TPU_COOC_FULL_SUITE=1``) or
    the operator's own selection must win: an explicit ``-m``/``-k``
    expression, or a selection consisting ENTIRELY of slow tests
    (``pytest tests/test_multihost.py`` means run exactly those — while
    the driver's ``pytest tests/`` still gets the fast lane because the
    collection is mixed)."""
    if os.environ.get("TPU_COOC_FULL_SUITE", "").lower() in (
            "1", "true", "yes"):
        return
    if config.getoption("-m") or config.getoption("-k"):
        return
    kept = [i for i in items if "slow" not in i.keywords]
    if not kept:
        return  # everything named is slow: the operator asked for it
    deselected = [i for i in items if "slow" in i.keywords]
    if deselected:
        config.hook.pytest_deselected(items=deselected)
        items[:] = kept
