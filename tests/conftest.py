"""Test harness: force CPU JAX with an 8-device virtual mesh.

Multi-chip sharding (`shard_map`/`psum`) is tested without real TPUs via
``--xla_force_host_platform_device_count`` (SURVEY.md §4).

Note: the surrounding environment may pre-import jax and register an
accelerator plugin via sitecustomize before pytest starts, so setting
``JAX_PLATFORMS`` in ``os.environ`` here is not enough — we also override
the already-imported config. Backend clients are created lazily on first
use, so doing this in conftest (before any test touches jax) is safe.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# JAX_PLATFORMS=cpu alone is NOT enough to keep jax off the network:
# the sitecustomize-registered accelerator plugin still contacts its
# pool at import, and a half-dead tunnel (TCP accepts, never answers)
# then hangs the interpreter indefinitely — reproduced 2026-07-31,
# where a supervised soak-test child inherited JAX_PLATFORMS=cpu but
# not this guard and hung the whole suite for an hour. Clearing the
# pool address list here makes every test AND every subprocess a test
# spawns (supervisor children, multihost workers, CLI runs) immune to
# tunnel state.
os.environ["PALLAS_AXON_POOL_IPS"] = ""
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
