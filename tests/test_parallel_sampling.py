"""The retired ``--sample-workers`` flag: accepted, ignored, serial.

The thread-partitioned sampler was removed in round 3 (VERDICT r2, Weak
#6): it measured ~0.9x serial on this image — per-window work is
dominated by small GIL-holding NumPy kernels, and the native serial
kernels (``native/``) had already taken the host-side wins. The flag
stays accepted for CLI compatibility and must behave exactly like the
serial default; process-level ``--partition-sampling``
(``sampling/multihost.py``, ``tests/test_multihost.py``) is the ingest
scale-out axis.
"""

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.job import CooccurrenceJob
from tpu_cooccurrence.sampling.reservoir import UserReservoirSampler

from test_pipeline import assert_latest_equal, random_stream, run_production


def test_sample_workers_flag_is_serial_alias():
    kw = dict(window_size=10, seed=0xFA11, item_cut=5, user_cut=4,
              development_mode=True, backend=Backend.ORACLE)
    users, items, ts = random_stream(71, n=800, n_users=23)
    a = run_production(Config(**kw), users, items, ts)
    b = run_production(Config(**kw, sample_workers=4), users, items, ts)
    assert isinstance(b.sampler, UserReservoirSampler)
    assert_latest_equal(a.latest, b.latest)
    assert a.counters.as_dict() == b.counters.as_dict()


def test_sample_workers_cli_flag_still_parses():
    cfg = Config.from_args(["-i", "x.csv", "-ws", "10",
                            "--sample-workers", "8"])
    assert cfg.sample_workers == 8  # parsed, then ignored by the job


def test_sample_workers_allowed_with_sliding_windows():
    # The old thread sampler rejected sliding mode; the retired no-op
    # flag must not.
    cfg = Config(window_size=20, window_slide=10, seed=1, sample_workers=4)
    job = CooccurrenceJob(cfg)
    assert job.sliding
