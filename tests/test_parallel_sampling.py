"""User-partitioned parallel sampling: bit-identical to the serial path.

The partitioned sampler must be indistinguishable from the serial one —
same accept/replace/reject decisions (the RNG hashes global user ids),
same pair multiset, same counters, interchangeable checkpoints.
"""

import numpy as np
import pytest

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.job import CooccurrenceJob
from tpu_cooccurrence.metrics import OBSERVED_COOCCURRENCES

from test_pipeline import assert_latest_equal, random_stream, run_production


@pytest.mark.parametrize("workers", [2, 4])
@pytest.mark.parametrize("overrides", [
    dict(item_cut=5, user_cut=4),
    dict(skip_cuts=True),
    dict(item_cut=500, user_cut=3),  # heavy replace/reject traffic
])
def test_partitioned_sampler_bit_identical_to_serial(workers, overrides):
    kw = dict(window_size=10, seed=0xFA11, development_mode=True,
              backend=Backend.ORACLE)
    kw.update(overrides)
    users, items, ts = random_stream(71, n=800, n_users=23)
    a = run_production(Config(**kw), users, items, ts)
    b = run_production(Config(**kw, sample_workers=workers),
                       users, items, ts)
    assert_latest_equal(a.latest, b.latest)
    assert a.counters.as_dict() == b.counters.as_dict()


def test_partitioned_checkpoint_interchange(tmp_path):
    """Serial checkpoint -> partitioned resume (and the reverse) both
    continue bit-identically: the on-disk layout is worker-count-free."""
    users, items, ts = random_stream(73, n=600, n_users=17)
    half = 300
    for first, second in [(1, 4), (4, 1), (2, 3)]:
        kw = dict(window_size=10, seed=0xCC, item_cut=5, user_cut=3,
                  backend=Backend.ORACLE, development_mode=True,
                  checkpoint_dir=str(tmp_path / f"ck-{first}-{second}"))
        ref = CooccurrenceJob(Config(**kw))
        ref.add_batch(users, items, ts)
        ref.finish()

        a = CooccurrenceJob(Config(**kw, sample_workers=first))
        a.add_batch(users[:half], items[:half], ts[:half])
        a.checkpoint()
        b = CooccurrenceJob(Config(**kw, sample_workers=second))
        b.restore()
        b.add_batch(users[half:], items[half:], ts[half:])
        b.finish()
        assert_latest_equal(ref.latest, b.latest)
        assert ref.counters.as_dict() == b.counters.as_dict()


def test_checkpoint_with_vocab_ahead_of_sampler(tmp_path):
    """The vocab can be ahead of the sampler (users of still-buffered,
    unfired windows); checkpointing then must not truncate or crash."""
    for workers in (1, 4):
        kw = dict(window_size=1000, seed=2, item_cut=5, user_cut=3,
                  backend=Backend.ORACLE, sample_workers=workers,
                  checkpoint_dir=str(tmp_path / f"ck-{workers}"))
        users, items, ts = random_stream(75, n=400, n_users=40)
        a = CooccurrenceJob(Config(**kw))
        # Nothing fires (one giant in-flight window), so the sampler has
        # never seen any user while the vocab holds all of them.
        a.add_batch(users, items, ts)
        assert a.windows_fired == 0
        a.checkpoint()
        b = CooccurrenceJob(Config(**kw))
        b.restore()
        b.finish()
        ref = CooccurrenceJob(Config(**kw))
        ref.add_batch(users, items, ts)
        ref.finish()
        assert_latest_equal(ref.latest, b.latest)


def test_sample_workers_rejected_in_sliding_mode():
    with pytest.raises(ValueError):
        Config(window_size=10, window_slide=5, seed=1, sample_workers=4)


def test_partitioned_counters_accumulate_once():
    users, items, ts = random_stream(74, n=500)
    kw = dict(window_size=10, seed=1, skip_cuts=True, backend=Backend.ORACLE)
    a = run_production(Config(**kw), users, items, ts)
    b = run_production(Config(**kw, sample_workers=3), users, items, ts)
    assert (a.counters.get(OBSERVED_COOCCURRENCES)
            == b.counters.get(OBSERVED_COOCCURRENCES) > 0)
