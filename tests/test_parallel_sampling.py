"""The retired ``--sample-workers`` flag: fully removed, clearly rejected.

The thread-partitioned sampler was removed in round 3 (VERDICT r2, Weak
#6): it measured ~0.9x serial on this image — per-window work is
dominated by small GIL-holding NumPy kernels, and the native serial
kernels (``native/``) had already taken the host-side wins. The flag
spent PRs 3-7 accepted-but-ignored; PR 8 retires it outright: passing it
raises a configuration error that names the reason and the replacement
(process-level ``--partition-sampling``, ``sampling/multihost.py``,
``tests/test_multihost.py`` — the ingest scale-out axis) instead of
argparse's bare "unrecognized arguments".
"""

import pytest

from tpu_cooccurrence.config import Config


def test_sample_workers_flag_rejected_with_retired_error():
    for argv in (
            ["-i", "x.csv", "-ws", "10", "--sample-workers", "8"],
            ["-i", "x.csv", "-ws", "10", "--sample-workers=8"],
    ):
        with pytest.raises(ValueError, match="retired"):
            Config.from_args(argv)
        # The error must carry the replacement, not just the verdict.
        with pytest.raises(ValueError, match="partition-sampling"):
            Config.from_args(argv)


def test_sample_workers_field_removed_from_config():
    import dataclasses

    assert "sample_workers" not in {
        f.name for f in dataclasses.fields(Config)}
