"""Ingest offset codec (ISSUE 18): ``meta["ingest_offsets"]``.

The contracts under test:

* **Registry exactness** — :data:`OFFSET_KEYS` is the canonical list of
  every field either source writes into its offset section (the
  ``ingest-offset-registry`` cooclint rule points here); the sections
  the real sources produce carry exactly these keys, no more, no less.
* **Round-trip** — a section committed by ``job.checkpoint(source=...)``
  rides the npz meta (and the incremental delta header) verbatim, and a
  fresh job + source restored from it reproduce the identical section —
  across cell dtypes, wire formats and StateStores.
* **Legacy fallback** — a checkpoint written before the offset section
  existed restores from the cursor markers with the documented warning.
* **Rescale merge** — :func:`checkpoint.merge_ingest_offsets` keeps the
  owner's copy under agreement, takes the conservative minimum (loudly)
  under disagreement, and resets the rotation cursor when writers
  disagree on it.
"""

import json
import logging
import os

import numpy as np
import pytest

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.io.partitioned import PartitionedLogSource
from tpu_cooccurrence.io.source import FileMonitorSource
from tpu_cooccurrence.job import CooccurrenceJob
from tpu_cooccurrence.state import checkpoint as ckpt
from tpu_cooccurrence.state import delta as deltalog

from test_pipeline import random_stream

#: Canonical ingest-offset codec: every string key either source writes
#: into its ``offsets_state()`` section. The baseline-free cooclint rule
#: ``ingest-offset-registry`` (analysis/rules_ingest.py) requires each
#: key to appear under tests/ — this list is that reference, and
#: test_offset_key_registry_is_exact pins it against the real sections.
OFFSET_KEYS = [
    # section envelope (both formats)
    "v", "format",
    # files format: FileMonitorSource's in-flight rewrite guard
    "in_flight", "path", "mtime", "size", "head_hash",
    # partitioned format: per-partition cursors + the rotation cursor
    "partitions", "byte_offset", "records", "quarantined",
    "rr_part", "rr_remaining",
]

#: StateStore selection via Config knobs (the test_state_store trio).
STORES = {
    "direct": {},
    "tiered": dict(spill_threshold_windows=2, spill_target_hbm_frac=0.0),
    "sharded": dict(num_shards=2),
}


def cfg(tmp_path, subdir="ckpt", incremental=False, **kw):
    kw.setdefault("backend", Backend.SPARSE)
    kw.setdefault("window_size", 10)
    kw.setdefault("seed", 0xABCD)
    kw.setdefault("item_cut", 5)
    kw.setdefault("user_cut", 3)
    kw.setdefault("development_mode", True)
    return Config(checkpoint_dir=str(tmp_path / subdir),
                  checkpoint_incremental=incremental, **kw)


def feed(job, users, items, ts, chunk=97):
    for lo in range(0, len(users), chunk):
        job.add_batch(users[lo:lo + chunk], items[lo:lo + chunk],
                      ts[lo:lo + chunk])


def write_partitions(root, counts=(40, 40, 40)):
    root.mkdir()
    for p, n in enumerate(counts):
        (root / f"part-{p:03d}").write_text(
            "".join(f"p{p}:{i}\n" for i in range(n)))
    return str(root)


def consume(source, k):
    it = source.lines()
    return [next(it) for _ in range(k)], it


def section_keys(section):
    """Every codec key a section carries (partition NAMES are data, not
    codec keys — descend into the per-partition entries only)."""
    out = set(section)
    if isinstance(section.get("in_flight"), dict):
        out |= set(section["in_flight"])
    for entry in (section.get("partitions") or {}).values():
        out |= set(entry)
    return out


# -- registry exactness ------------------------------------------------


def test_offset_key_registry_is_exact(tmp_path):
    """OFFSET_KEYS == exactly the keys the real sources produce: a new
    field must land here (and in a reader — the cooclint rule checks
    that end) in the same PR."""
    f = tmp_path / "events.csv"
    f.write_text("".join(f"{i},{i},{i}\n" for i in range(10)))
    files_src = FileMonitorSource(str(f))
    consume(files_src, 4)  # mid-file, so the in-flight guard is armed
    files_section = files_src.offsets_state()
    assert files_section["format"] == "files"
    assert files_section["in_flight"] is not None

    part_src = PartitionedLogSource(
        write_partitions(tmp_path / "plog"), turn_records=4)
    consume(part_src, 9)  # mid-turn, so the rotation cursor is armed
    part_section = part_src.offsets_state()
    assert part_section["format"] == "partitioned"

    produced = section_keys(files_section) | section_keys(part_section)
    assert len(OFFSET_KEYS) == len(set(OFFSET_KEYS))
    assert produced == set(OFFSET_KEYS), produced ^ set(OFFSET_KEYS)


# -- checkpoint round-trips --------------------------------------------


def _newest_meta(directory):
    gen, path = ckpt.generations(directory, "")[0]
    data = ckpt._load_verified(path)
    return gen, path, json.loads(bytes(data["meta_json"]).decode())


@pytest.mark.parametrize("store", sorted(STORES))
@pytest.mark.parametrize("cell_dtype,wire_format", [
    ("int32", "raw"),
    ("int16", "packed"),
])
def test_partitioned_offsets_round_trip(tmp_path, store, cell_dtype,
                                        wire_format):
    """The committed section rides the npz meta verbatim and a restored
    source reproduces it bit-for-bit — across stores, cell dtypes and
    wire formats (the offset section must be codec-independent)."""
    kw = dict(STORES[store], cell_dtype=cell_dtype,
              wire_format=wire_format)
    plog = write_partitions(tmp_path / "plog")
    src = PartitionedLogSource(plog, turn_records=7)
    consume(src, 53)  # 7 full turns + 4 into the 8th: mid-turn cursor
    users, items, ts = random_stream(51, n=300, n_items=40, n_users=16)
    job = CooccurrenceJob(cfg(tmp_path, **kw))
    feed(job, users, items, ts)
    job.checkpoint(source=src)
    committed = src.offsets_state()
    assert committed["rr_remaining"] not in (0, 7)  # genuinely mid-turn

    _, _, meta = _newest_meta(job.config.checkpoint_dir)
    assert meta["ingest_offsets"] == committed

    job2 = CooccurrenceJob(cfg(tmp_path, **kw))
    src2 = PartitionedLogSource(plog, turn_records=7)
    job2.restore(source=src2)
    src2._discover()
    assert src2.offsets_state() == committed


def test_files_offsets_round_trip(tmp_path):
    f = tmp_path / "events.csv"
    f.write_text("".join(f"{i},{i},{i}\n" for i in range(20)))
    src = FileMonitorSource(str(f))
    consume(src, 7)
    users, items, ts = random_stream(52, n=120, n_items=20, n_users=10)
    job = CooccurrenceJob(cfg(tmp_path))
    feed(job, users, items, ts)
    job.checkpoint(source=src)
    committed = src.offsets_state()
    assert committed["in_flight"]["path"] == str(f)
    assert committed["in_flight"]["size"] == f.stat().st_size

    _, _, meta = _newest_meta(job.config.checkpoint_dir)
    assert meta["ingest_offsets"] == committed

    job2 = CooccurrenceJob(cfg(tmp_path))
    src2 = FileMonitorSource(str(f))
    job2.restore(source=src2)
    assert src2.offsets_state() == committed


def test_incremental_chain_carries_offsets(tmp_path):
    """Every delta generation's header carries the offsets committed at
    its boundary (the replica/catch-up feed sees the wire position),
    and a chain restore lands the NEWEST section."""
    plog = write_partitions(tmp_path / "plog")
    src = PartitionedLogSource(plog, turn_records=5)
    it = src.lines()
    users, items, ts = random_stream(53, n=600, n_items=50, n_users=20)
    job = CooccurrenceJob(cfg(tmp_path, incremental=True))
    feed(job, users[:300], items[:300], ts[:300])
    for _ in range(31):
        next(it)
    job.checkpoint(source=src)
    first = src.offsets_state()
    feed(job, users[300:], items[300:], ts[300:])
    for _ in range(40):
        next(it)
    job.checkpoint(source=src)
    second = src.offsets_state()
    assert second != first

    directory = job.config.checkpoint_dir
    gens = deltalog.delta_generations(directory, "")
    assert gens, "incremental run wrote no delta generations"
    d = deltalog.read_delta_file(
        os.path.join(directory, f"delta.{gens[-1]}.bin"))
    assert d.ingest_offsets == second
    _, _, meta = _newest_meta(directory)
    assert meta["ingest_offsets"] == second

    job2 = CooccurrenceJob(cfg(tmp_path, incremental=True))
    src2 = PartitionedLogSource(plog, turn_records=5)
    job2.restore(source=src2)
    src2._discover()
    assert src2.offsets_state() == second


# -- legacy fallback ---------------------------------------------------


def test_legacy_checkpoint_without_offsets_warns(tmp_path, caplog):
    """A pre-offset-section checkpoint (doctored npz: section removed,
    digest recomputed) restores from the cursor markers with the
    documented warning — marker-exact, but unguarded."""
    f = tmp_path / "events.csv"
    f.write_text("".join(f"{i},{i},{i}\n" for i in range(20)))
    src = FileMonitorSource(str(f))
    consume(src, 6)
    users, items, ts = random_stream(54, n=120, n_items=20, n_users=10)
    job = CooccurrenceJob(cfg(tmp_path))
    feed(job, users, items, ts)
    job.checkpoint(source=src)

    _, path, meta = _newest_meta(job.config.checkpoint_dir)
    assert "ingest_offsets" in meta
    arrays = dict(ckpt._load_verified(path))
    del meta["ingest_offsets"]
    arrays["meta_json"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8)
    arrays["digest_sha256"] = np.frombuffer(
        ckpt.compute_digest(arrays).encode(), dtype=np.uint8)
    np.savez(path, **arrays)

    job2 = CooccurrenceJob(cfg(tmp_path))
    src2 = FileMonitorSource(str(f))
    with caplog.at_level(logging.WARNING,
                         logger="tpu_cooccurrence.checkpoint"):
        job2.restore(source=src2)
    assert "offsets absent, replaying from source markers" in caplog.text
    # The markers still landed; only the rewrite guard is gone.
    assert src2._current_line == 6
    assert src2._in_flight_guard is None


# -- format / version guards -------------------------------------------


def test_format_mismatch_is_a_launch_error(tmp_path):
    src = FileMonitorSource(str(tmp_path / "f"))
    with pytest.raises(ValueError, match="--source-format files"):
        src.restore_offsets({"v": 1, "format": "partitioned"})
    psrc = PartitionedLogSource(write_partitions(tmp_path / "plog"))
    psrc.restore_offsets({"v": 1, "format": "files", "in_flight": None})
    with pytest.raises(ValueError, match="--source-format partitioned"):
        psrc._discover()


def test_format_mismatch_through_full_restore_path(tmp_path):
    """The SAME clean error through ``job.restore``: the offsets-format
    guard must fire before the legacy marker restore, which would
    otherwise choke on the foreign marker shape (KeyError on
    ``global_modification_time``) instead of naming the flag."""
    plog = write_partitions(tmp_path / "plog")
    src = PartitionedLogSource(plog, turn_records=7)
    consume(src, 20)
    users, items, ts = random_stream(53, n=120, n_items=20, n_users=10)
    job = CooccurrenceJob(cfg(tmp_path))
    feed(job, users, items, ts)
    job.checkpoint(source=src)

    job2 = CooccurrenceJob(cfg(tmp_path))
    src2 = FileMonitorSource(str(tmp_path / "plog"))
    with pytest.raises(ValueError, match="--source-format files"):
        job2.restore(source=src2)


def test_newer_section_version_warns_best_effort(tmp_path, caplog):
    f = tmp_path / "events.csv"
    f.write_text("a,b,1\n")
    src = FileMonitorSource(str(f))
    with caplog.at_level(logging.WARNING):
        src.restore_offsets({"v": 2, "format": "files",
                             "in_flight": None})
    assert "newer than this reader" in caplog.text


# -- rescale merge -----------------------------------------------------


def _section(offs, rr_part="part-000", rr_remaining=3):
    partitions = {
        name: {"byte_offset": b, "records": r, "head_hash": f"h{name}",
               "quarantined": False}
        for name, (b, r) in offs.items()}
    return {"v": 1, "format": "partitioned", "partitions": partitions,
            "rr_part": rr_part, "rr_remaining": rr_remaining}


def test_merge_agreement_passes_through():
    s = _section({"part-000": (10, 2), "part-001": (20, 4)})
    replica = json.loads(json.dumps(s))
    assert ckpt.merge_ingest_offsets([s, replica], 2) == s


def test_merge_disagreement_takes_conservative_minimum(caplog):
    a = _section({"part-000": (10, 2)})
    b = _section({"part-000": (8, 1)})
    with caplog.at_level(logging.WARNING,
                         logger="tpu_cooccurrence.checkpoint"):
        merged = ckpt.merge_ingest_offsets([a, b], 2)
    assert merged["partitions"]["part-000"]["byte_offset"] == 8
    assert merged["partitions"]["part-000"]["records"] == 1
    assert "disagree" in caplog.text


def test_merge_rr_cursor_disagreement_resets_rotation(caplog):
    a = _section({"part-000": (10, 2)}, rr_remaining=3)
    b = _section({"part-000": (10, 2)}, rr_remaining=1)
    with caplog.at_level(logging.WARNING,
                         logger="tpu_cooccurrence.checkpoint"):
        merged = ckpt.merge_ingest_offsets([a, b], 2)
    assert merged["rr_part"] is None
    assert merged["rr_remaining"] == 0
    # The partition offsets themselves were NOT disturbed.
    assert merged["partitions"]["part-000"]["byte_offset"] == 10


def test_merge_takes_union_of_partitions():
    a = _section({"part-000": (10, 2)})
    b = _section({"part-000": (10, 2), "part-001": (20, 4)})
    merged = ckpt.merge_ingest_offsets([a, b], 2)
    assert set(merged["partitions"]) == {"part-000", "part-001"}
    assert merged["partitions"]["part-001"]["byte_offset"] == 20


def test_merge_files_format_is_writer0_copy():
    a = {"v": 1, "format": "files", "in_flight": {"path": "x"}}
    b = {"v": 1, "format": "files", "in_flight": {"path": "y"}}
    assert ckpt.merge_ingest_offsets([a, b], 2) == a


def test_merge_empty_sections_is_none():
    assert ckpt.merge_ingest_offsets([], 2) is None
    assert ckpt.merge_ingest_offsets([None, {}], 2) is None
