"""Narrow cell dtypes (int16/int8 slabs with wide-promotion) and the
packed wire format: the PR-7 acceptance matrix.

* int16 overflow-promotion exercised by counts crossing 32767, output
  bit-identical to the int32 slab;
* sparse top-K vs the host oracle with compression on (Config default:
  cell int16 + packed wire) at pipeline depths 0 and 2;
* restore from both checkpoint generations (pre-codec raw layout and
  the delta+varint packed layout), across cell dtypes.
"""

import numpy as np
import pytest

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.observability import LEDGER
from tpu_cooccurrence.sampling.reservoir import PairDeltaBatch
from tpu_cooccurrence.state.sparse_scorer import SparseDeviceScorer

from test_pipeline import (assert_latest_close, random_stream,
                           run_production)


def _feed_hot_pair(sc, windows=14, hot_delta=5000):
    """Windows carrying a hot pair whose counts cross 32767 plus
    background noise; returns every emitted batch (incl. final flush)."""
    outs = []
    rng = np.random.default_rng(7)
    for w in range(windows):
        src = np.concatenate([[0, 1], rng.integers(2, 40, 30)])
        dst = np.concatenate([[1, 0], rng.integers(2, 40, 30)])
        src, dst = src.astype(np.int64), dst.astype(np.int64)
        keep = src != dst
        delta = np.concatenate(
            [[hot_delta, hot_delta], np.ones(30, np.int64)])[keep]
        outs.append(sc.process_window(
            w * 10, PairDeltaBatch(src[keep], dst[keep],
                                   delta.astype(np.int32))))
    outs.append(sc.flush())
    return outs


def _assert_batches_equal(oa, ob):
    for x, y in zip(oa, ob):
        ox, oy = np.argsort(x.rows), np.argsort(y.rows)
        np.testing.assert_array_equal(x.rows[ox], y.rows[oy])
        np.testing.assert_array_equal(x.vals[ox], y.vals[oy])
        np.testing.assert_array_equal(x.idx[ox], y.idx[oy])


def _scorer(cell, wire="packed", **kw):
    kw.setdefault("development_mode", True)
    kw.setdefault("capacity", 64)
    kw.setdefault("items_capacity", 8)
    kw.setdefault("compact_min_heap", 256)
    return SparseDeviceScorer(5, cell_dtype=cell, wire_format=wire, **kw)


@pytest.mark.parametrize("cell", ["int16", "int8"])
def test_promotion_crossing_dtype_max_bit_identical(cell):
    """The acceptance test: counts cross 32767 (and 127), rows promote
    to the wide side-table BEFORE saturation, and every emitted batch is
    bit-identical to the int32 slab's."""
    ref = _scorer("int32", wire="raw")
    nar = _scorer(cell)
    oa, ob = _feed_hot_pair(ref), _feed_hot_pair(nar)
    assert int(nar.wide_rows.sum()) >= 2, "promotion never fired"
    assert int(nar.row_sums_host.max()) > 32767
    # The hot rows really live in the wide side-table...
    assert nar.index_w.heap_end > 0
    # ...and the dev-mode row-sum check ran over both residencies.
    _assert_batches_equal(oa, ob)


def test_promotion_before_first_cell():
    """A row whose FIRST window already exceeds the bound: promoted with
    no narrow cells to move (the empty row_cells path)."""
    ref = _scorer("int32", wire="raw")
    nar = _scorer("int16")
    batch = PairDeltaBatch(np.asarray([0, 1], np.int64),
                           np.asarray([1, 0], np.int64),
                           np.asarray([40000, 40000], np.int32))
    a = [ref.process_window(0, batch), ref.flush()]
    b = [nar.process_window(0, batch), nar.flush()]
    assert nar.wide_rows[:2].all()
    _assert_batches_equal(a, b)


@pytest.mark.parametrize("depth", [0, 2])
def test_sparse_compression_on_matches_oracle(depth):
    """Config-default compression (auto -> int16 cells + packed wire)
    vs the exact host oracle, at pipeline depths 0 and 2."""
    kw = dict(window_size=10, seed=0xBEEF, item_cut=5, user_cut=4,
              development_mode=True)
    users, items, ts = random_stream(31, n=2500)
    a = run_production(Config(**kw, backend=Backend.ORACLE), users, items,
                       ts)
    LEDGER.reset()
    b = run_production(Config(**kw, backend=Backend.SPARSE,
                              pipeline_depth=depth), users, items, ts)
    assert_latest_close(a.latest, b.latest)
    snap = LEDGER.snapshot()
    # Compression actually engaged and actually cut wire bytes >= 2x.
    assert snap["uplink_enc_bytes"] > 0
    assert snap["uplink_raw_bytes"] >= 2 * snap["uplink_enc_bytes"]


def test_explicit_flags_reach_scorer():
    from tpu_cooccurrence.job import CooccurrenceJob

    cfg = Config(window_size=10, seed=1, backend=Backend.SPARSE,
                 cell_dtype="int8", wire_format="raw")
    job = CooccurrenceJob(cfg)
    assert job.scorer.cell_dtype == "int8"
    assert not job.scorer.wire_packed
    cfg2 = Config(window_size=10, seed=1, backend=Backend.DEVICE)
    job2 = CooccurrenceJob(cfg2)  # auto degrades to int32/raw elsewhere
    assert not hasattr(job2.scorer, "wire_packed")


def test_narrow_flags_rejected_off_sparse():
    with pytest.raises(ValueError, match="cell-dtype"):
        Config(window_size=10, seed=1, backend=Backend.DEVICE,
               cell_dtype="int16")
    with pytest.raises(ValueError, match="wire-format"):
        Config(window_size=10, seed=1, backend=Backend.DEVICE,
               wire_format="packed")
    # Single-controller sharded sparse carries the wide side-table and
    # the packed uplink (PR 16) — narrow cells are accepted there; only
    # multi-controller runs still reject an explicit narrow request.
    Config(window_size=10, seed=1, backend=Backend.SPARSE,
           num_shards=2, cell_dtype="int16")
    with pytest.raises(ValueError, match="cell-dtype"):
        Config(window_size=10, seed=1, backend=Backend.SPARSE,
               num_shards=2, coordinator="127.0.0.1:9999",
               num_processes=2, process_id=0, cell_dtype="int16")


@pytest.mark.parametrize("wire_a,wire_b", [
    ("raw", "auto"),    # old-format checkpoint restored by codec build
    ("auto", "raw"),    # packed checkpoint restored by raw-config build
    ("auto", "auto"),   # packed end to end
])
def test_checkpoint_format_interchange(tmp_path, wire_a, wire_b):
    """Both checkpoint generations restore, both directions, with the
    run continuing bit-compatibly (the old-format fixture is simply a
    --wire-format raw save)."""
    from tpu_cooccurrence.job import CooccurrenceJob

    users, items, ts = random_stream(33, n=500)
    half = 220
    kw = dict(window_size=10, seed=4, item_cut=5, user_cut=3,
              backend=Backend.SPARSE,
              checkpoint_dir=str(tmp_path / "ck"),
              development_mode=True)

    ref = CooccurrenceJob(Config(**kw, wire_format=wire_b))
    ref.add_batch(users, items, ts)
    ref.finish()

    a = CooccurrenceJob(Config(**kw, wire_format=wire_a))
    a.add_batch(users[:half], items[:half], ts[:half])
    a.checkpoint()
    import glob

    import numpy as np_mod

    path = sorted(glob.glob(str(tmp_path / "ck" / "state.*.npz")))[-1]
    with np_mod.load(path) as data:
        packed_names = [k for k in data.files if k.endswith("__packed")]
    if wire_a == "raw":
        assert packed_names == []  # the pre-codec generation layout
    else:
        assert any("rows_key" in k for k in packed_names)
    b = CooccurrenceJob(Config(**kw, wire_format=wire_b))
    b.restore()
    b.add_batch(users[half:], items[half:], ts[half:])
    b.finish()
    assert_latest_close(ref.latest, b.latest, rtol=1e-6, atol=1e-6)


def test_checkpoint_interchange_across_cell_dtypes(tmp_path):
    """A checkpoint written by an int32 slab restores onto an int16 one
    (and back) — residency is an in-memory layout, not a format."""
    from tpu_cooccurrence.job import CooccurrenceJob

    users, items, ts = random_stream(35, n=500)
    half = 240
    for first, second in [("int32", "int16"), ("int16", "int32"),
                          ("int16", "int8")]:
        kw = dict(window_size=10, seed=9, item_cut=5, user_cut=3,
                  backend=Backend.SPARSE,
                  checkpoint_dir=str(tmp_path / f"ck-{first}-{second}"),
                  development_mode=True)
        ref = CooccurrenceJob(Config(**kw, cell_dtype=second))
        ref.add_batch(users, items, ts)
        ref.finish()
        a = CooccurrenceJob(Config(**kw, cell_dtype=first))
        a.add_batch(users[:half], items[:half], ts[:half])
        a.checkpoint()
        b = CooccurrenceJob(Config(**kw, cell_dtype=second))
        b.restore()
        b.add_batch(users[half:], items[half:], ts[half:])
        b.finish()
        assert_latest_close(ref.latest, b.latest, rtol=1e-6, atol=1e-6)


def test_restore_with_promoted_rows(tmp_path):
    """Checkpoint taken AFTER promotion: the restoring scorer re-splits
    rows by threshold and continues bit-identically."""
    sc = _scorer("int16")
    _feed_hot_pair(sc, windows=10)
    assert int(sc.wide_rows.sum()) >= 2
    st = sc.checkpoint_state()
    fresh = _scorer("int16")
    fresh.restore_state(st)
    assert int(fresh.wide_rows.sum()) >= 2
    np.testing.assert_array_equal(fresh.row_sums_host, sc.row_sums_host)
    # Continue both: identical batches.
    more = PairDeltaBatch(np.asarray([0, 2], np.int64),
                          np.asarray([2, 0], np.int64),
                          np.asarray([3, 3], np.int32))
    a = [sc.process_window(500, more), sc.flush()]
    b = [fresh.process_window(500, more), fresh.flush()]
    _assert_batches_equal(a, b)


def test_state_gauges_populate():
    from tpu_cooccurrence.observability.registry import REGISTRY

    REGISTRY.reset()
    sc = _scorer("int16")
    _feed_hot_pair(sc, windows=3)
    assert REGISTRY.gauge("cooc_host_index_rss_bytes").get() > 0
    assert REGISTRY.gauge("cooc_slab_device_bytes").get() > 0
    assert REGISTRY.gauge("cooc_slab_live_cells").get() == sc.live_cells
    assert sc.live_cells > 0
