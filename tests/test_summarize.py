"""Machine-generated on-chip summary + the guard name-shadowing fix."""

import json

from tpu_cooccurrence.bench import summarize, tpu_round2


def _write_jsonl(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


def test_latest_by_name_maps_historic_config4_rows():
    rows = [
        {"name": "zipfian-1M-items", "ok": True, "backend": "hybrid",
         "pairs_per_sec": 32098.6},
        {"name": "zipfian-1M-items", "ok": True, "backend": "sparse",
         "pairs_per_sec": 71862.0},
        {"name": "config4-sparse", "ok": False, "error": "dead"},
        {"name": "ml25m-full", "ok": True, "seconds": 181.5},
    ]
    latest = summarize.latest_by_name(rows)
    assert latest["config4-sparse"]["pairs_per_sec"] == 71862.0
    assert latest["config4-hybrid"]["pairs_per_sec"] == 32098.6
    assert latest["ml25m-full"]["seconds"] == 181.5


def test_latest_by_name_rejects_non_tpu_platform_rows():
    """An ok row tagged jax_platform=cpu (smoke run whose OUT override
    was lost) must never become the latest on-chip number; untagged
    historic rows and tpu-tagged rows pass."""
    rows = [
        {"name": "config4-headline", "ok": True, "pairs_per_sec": 1.0,
         "jax_platform": "tpu"},
        {"name": "config4-headline", "ok": True, "pairs_per_sec": 9e9,
         "jax_platform": "cpu"},
        {"name": "ml25m-full", "ok": True, "seconds": 181.5},  # historic
    ]
    latest = summarize.latest_by_name(rows)
    assert latest["config4-headline"]["pairs_per_sec"] == 1.0
    assert latest["ml25m-full"]["seconds"] == 181.5


def test_render_sharded_overhead_line(tmp_path, monkeypatch):
    r2 = tmp_path / "rounds.jsonl"
    _write_jsonl(r2, [
        {"name": "sharded-pallas-1chip", "ok": True,
         "jax_platform": "tpu", "ts": "2026-08-01 00:05:00",
         "sharded_dense_int16": {"scores_allclose": True},
         "sharded_sparse": {"scores_allclose": True},
         "step_ms_per_window_unsharded": 10.0,
         "step_ms_per_window_sharded_1dev": 11.2,
         "sharded_overhead_ms_per_window": 1.2,
         "overhead_vocab": 59_047},
    ])
    monkeypatch.setattr(summarize, "ROUND2_PATH", str(r2))
    monkeypatch.setattr(summarize, "HISTORY_PATH",
                        str(tmp_path / "none.jsonl"))
    text = summarize.render()
    assert "1.2 ms/window" in text
    assert "59047-item row sums" in text
    assert "measured point estimate" in text


def test_render_targets_and_regeneration(tmp_path, monkeypatch):
    r2 = tmp_path / "rounds.jsonl"
    hist = tmp_path / "hist.jsonl"
    _write_jsonl(r2, [
        {"name": "config4-sparse", "ok": True, "pairs_per_sec": 500_000,
         "ts": "2026-08-01 00:00:00"},
        {"name": "ml25m-sparse", "ok": True, "seconds": 42.0,
         "ts": "2026-08-01 00:10:00"},
        {"name": "tunnel-probe", "ok": True, "sync_ms_per_dispatch": 3.5,
         "enqueue_ms_per_dispatch": 0.2, "upload_1024kb_ms": 9.0,
         "ts": "2026-08-01 00:01:00"},
    ])
    _write_jsonl(hist, [
        {"ts": "2026-08-01 00:20:00", "pairs_per_sec": 3_000_000,
         "vs_baseline": 25.9, "backend": "tpu"},
    ])
    monkeypatch.setattr(tpu_round2, "OUT", str(r2))
    monkeypatch.setattr(summarize, "ROUND2_PATH", str(r2))
    monkeypatch.setattr(summarize, "HISTORY_PATH", str(hist))
    text = summarize.render()
    assert "25.9x host oracle" in text and text.count("**MET**") >= 3
    assert "500,000 pairs/s" in text
    assert "42.0 s single-chip** (**MET**)" in text
    assert "3.5 ms" in text


def test_render_config4_headline_and_upload_ab(tmp_path, monkeypatch):
    """A short grant landing only the headline rows still reaches the
    summary; the upload A/B renders a verdict only on comparable rows
    (same event count) and flags mixed provenance instead."""
    r2 = tmp_path / "rounds.jsonl"
    rows = [
        {"name": "config4-headline", "ok": True, "pairs_per_sec": 480_000,
         "events": 1_000_000, "mode": "L16/fixed",
         "ts": "2026-08-01 00:00:00"},
        {"name": "config4-chunked", "ok": True, "pairs_per_sec": 700_000,
         "events": 1_000_000, "mode": "L16/fixed/chunks4",
         "ts": "2026-08-01 00:05:00"},
    ]
    _write_jsonl(r2, rows)
    monkeypatch.setattr(tpu_round2, "OUT", str(r2))
    monkeypatch.setattr(summarize, "ROUND2_PATH", str(r2))
    monkeypatch.setattr(summarize, "HISTORY_PATH",
                        str(tmp_path / "none.jsonl"))
    text = summarize.render()
    assert "700,000 pairs/s** (config4-chunked" in text
    assert "**MET**" in text            # 700k >= 458k target
    assert "chunked upload WINS" in text
    # Mixed provenance: a --quick chunked row must not decide the flip.
    rows[1] = dict(rows[1], events=200_000)
    _write_jsonl(r2, rows)
    text = summarize.render()
    assert "INCOMPARABLE" in text
    assert "WINS" not in text
    # Full-size rows outrank a faster quick row for the target line.
    assert "480,000 pairs/s** (config4-headline" in text


def test_guard_preserves_pass_name(tmp_path, monkeypatch):
    out = tmp_path / "out.jsonl"
    monkeypatch.setattr(tpu_round2, "OUT", str(out))

    @tpu_round2.guard("my-pass")
    def fake(quick):
        return {"name": "inner-bench-result", "value": 7}

    fake(False)
    row = json.loads(out.read_text().strip())
    assert row["name"] == "my-pass"
    assert row["config"] == "inner-bench-result"
    assert row["value"] == 7 and row["ok"] is True
