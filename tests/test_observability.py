"""The observability plane: step timing, the transfer ledger, metrics
registry (histograms/gauges/Prometheus text), the run journal, the
scrape endpoint, and the XLA trace wrapper."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from tpu_cooccurrence.metrics import (CANONICAL_COUNTERS, Counters,
                                      OBSERVED_COOCCURRENCES)
from tpu_cooccurrence.observability import (StepTimer, TransferLedger,
                                            WindowStats, clock, xla_trace)
from tpu_cooccurrence.observability.journal import (VERSION, RunJournal,
                                                    read_records, tail,
                                                    validate_record)
from tpu_cooccurrence.observability.registry import (Histogram,
                                                     MetricsRegistry,
                                                     log_buckets)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")


def stats(ts, sample, score, events=10, pairs=20, rows=5):
    return WindowStats(timestamp=ts, events=events, pairs=pairs,
                       rows_scored=rows, sample_seconds=sample,
                       score_seconds=score)


def test_step_timer_summary_aggregates():
    t = StepTimer()
    t.record(stats(0, 0.25, 0.75, events=100, pairs=1000))
    t.record(stats(1, 0.5, 0.5, events=50, pairs=500))
    s = t.summary()
    assert s["windows"] == 2 and s["events"] == 150 and s["pairs"] == 1500
    assert s["sample_seconds"] == pytest.approx(0.75)
    assert s["score_seconds"] == pytest.approx(1.25)
    assert s["pairs_per_sec"] == pytest.approx(750.0)
    assert StepTimer().summary()["pairs_per_sec"] == 0.0  # no div-by-zero


def test_step_timer_slowest_ranks_and_ring_bounds():
    t = StepTimer(keep=4)
    for i, dur in enumerate([0.1, 0.9, 0.2, 0.8, 0.3]):  # 0.1 evicted
        t.record(stats(i, dur, 0.0))
    slow = t.slowest(2)
    assert [w.timestamp for w in slow] == [1, 3]
    assert t.total_windows == 5 and len(t.windows) == 4


def test_xla_trace_writes_profile(tmp_path):
    """--profile-dir produces an on-disk trace consumable by TensorBoard."""
    import jax.numpy as jnp

    out = str(tmp_path / "trace")
    with xla_trace(out):
        jnp.arange(8).sum().block_until_ready()
    found = [os.path.join(r, f) for r, _, fs in os.walk(out) for f in fs]
    assert found, "no trace files written"


def test_xla_trace_none_is_noop():
    with xla_trace(None):
        pass


def test_clock_measures():
    import time

    with clock() as c:
        time.sleep(0.01)
    assert c.seconds >= 0.009


def test_job_records_step_timing():
    from tpu_cooccurrence.config import Backend, Config
    from tpu_cooccurrence.job import CooccurrenceJob

    rng = np.random.default_rng(3)
    users = rng.integers(0, 10, 500).astype(np.int64)
    items = rng.integers(0, 30, 500).astype(np.int64)
    ts = np.cumsum(rng.integers(0, 2, 500)).astype(np.int64)
    job = CooccurrenceJob(Config(window_size=20, seed=1,
                                 backend=Backend.ORACLE))
    job.add_batch(users, items, ts)
    job.finish()
    s = job.step_timer.summary()
    assert s["windows"] == job.windows_fired > 0
    assert s["pairs"] > 0
    assert job.step_timer.slowest(1)


def test_window_stats_as_dict_json_round_trips():
    w = stats(7, 0.25, 0.5)
    d = json.loads(json.dumps(w.as_dict()))
    assert d["timestamp"] == 7 and d["events"] == 10 and d["pairs"] == 20
    assert d["seconds"] == pytest.approx(0.75)
    t = StepTimer()
    t.record(w)
    assert json.loads(json.dumps(t.slowest_as_dicts()))[0] == d


# ---------------------------------------------------------------------------
# metrics registry: fixed-log-bucket histograms + Prometheus exposition


def test_log_buckets_cover_and_ascend():
    b = log_buckets(0.001, 10, base=2.0)
    assert b[0] >= 0.001 and b[0] / 2 < 0.001  # tightest first bound
    assert b[-1] >= 10
    assert all(y == 2 * x for x, y in zip(b, b[1:]))
    with pytest.raises(ValueError):
        log_buckets(0, 1)


def test_histogram_bucket_assignment_and_stats():
    h = Histogram("h", [1.0, 2.0, 4.0, 8.0])
    for v in (0.5, 1.0, 1.5, 3.0, 100.0):  # 1.0 lands in le=1 (inclusive)
        h.observe(v)
    assert h._counts == [2, 1, 1, 0, 1]  # last = +Inf overflow
    assert h.count == 5
    assert h.sum == pytest.approx(106.0)
    assert h.min == 0.5 and h.max == 100.0
    assert h.cumulative_counts() == [2, 3, 4, 4, 5]


def test_histogram_percentiles_bucket_resolved():
    h = Histogram("h", [1.0, 2.0, 4.0, 8.0, 16.0])
    # 100 observations: 50 in (1,2], 45 in (2,4], 5 in (8,16].
    for _ in range(50):
        h.observe(1.5)
    for _ in range(45):
        h.observe(3.0)
    for _ in range(5):
        h.observe(9.0)
    assert h.percentile(50) == 2.0   # rank 50 -> le=2 bucket
    assert h.percentile(95) == 4.0   # rank 95 -> le=4 bucket
    assert h.percentile(99) == 9.0   # rank 99 -> le=16, capped at max seen
    s = h.summary()
    assert (s["p50"], s["p95"], s["p99"]) == (2.0, 4.0, 9.0)
    assert Histogram("e", [1.0]).percentile(99) == 0.0  # empty: no crash


def test_histogram_percentile_exact_within_one_bucket():
    """The pXX error bound the registry promises: at most one bucket step
    (base 2 = a factor of two) above the true quantile."""
    h = Histogram("h", log_buckets(1e-4, 100.0))
    rng = np.random.default_rng(0)
    vals = rng.lognormal(mean=-2.0, sigma=1.0, size=2000)
    for v in vals:
        h.observe(v)
    for p in (50, 95, 99):
        true = float(np.quantile(vals, p / 100.0))
        got = h.percentile(p)
        assert true <= got <= 2.0 * true + 1e-12


def test_histogram_concurrent_observe_exact_totals():
    h = Histogram("h", log_buckets(1e-3, 10.0))

    def hammer():
        for _ in range(5000):
            h.observe(0.01)

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.count == 20_000
    assert h.sum == pytest.approx(200.0)


def test_registry_get_or_create_and_bounds_conflict():
    r = MetricsRegistry()
    h1 = r.histogram("x", [1.0, 2.0])
    assert r.histogram("x") is h1  # no bounds -> existing instance
    with pytest.raises(ValueError, match="different"):
        r.histogram("x", [1.0, 3.0])
    g = r.gauge("g")
    g.set(2)
    g.add(0.5)
    assert r.gauge("g").get() == pytest.approx(2.5)
    r.reset()
    assert r.gauge("g").get() == 0.0


def test_render_prometheus_format_and_canonical_counters():
    r = MetricsRegistry()
    r.gauge("cooc_windows_fired", help="fired").set(3)
    h = r.histogram("cooc_window_score_seconds", [0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    c = Counters()
    c.add(OBSERVED_COOCCURRENCES, 41)
    led = TransferLedger()
    led.up("t", np.zeros(4, np.int32))
    text = r.render_prometheus(c, led)
    # Every reference-named counter appears, incremented or not.
    for name in CANONICAL_COUNTERS:
        assert f"\n{name} " in "\n" + text
    assert f"{OBSERVED_COOCCURRENCES} 41" in text
    assert "cooc_transfer_h2d_bytes_total 16" in text
    assert "cooc_windows_fired 3" in text
    assert 'cooc_window_score_seconds_bucket{le="0.1"} 1' in text
    assert 'cooc_window_score_seconds_bucket{le="+Inf"} 2' in text
    assert "cooc_window_score_seconds_count 2" in text
    assert "cooc_window_score_seconds_p50 0.1" in text
    assert "cooc_window_score_seconds_p99 0.5" in text
    # Text-format sanity: every sample line is "name[{labels}] value".
    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        name, value = line.rsplit(" ", 1)
        float(value)
        assert name and " " not in name.replace('{le="', "").replace('"}', "")


# ---------------------------------------------------------------------------
# transfer ledger / counters thread-safety (the PR-1 pipelined-mode race)


def test_ledger_concurrent_updates_exact():
    led = TransferLedger()
    buf = np.zeros(256, np.int8)  # 256 bytes

    def up():
        for _ in range(2000):
            led.up("u", buf)

    def down():
        for _ in range(2000):
            led.down("d", buf)

    threads = [threading.Thread(target=f) for f in (up, up, down, down)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = led.snapshot()
    assert snap["h2d_bytes"] == 4000 * 256 and snap["h2d_calls"] == 4000
    assert snap["d2h_bytes"] == 4000 * 256 and snap["d2h_calls"] == 4000
    assert led.summary() == snap


def test_counters_merge_and_snapshot_and_diff():
    a, b = Counters(), Counters()
    a.add("x", 1)
    b.add("x", 2)
    b.add("y", 5)
    a.merge(b)
    assert a.get("x") == 3 and a.get("y") == 5
    snap, diff = a.snapshot_and_diff({})
    assert snap == {"x": 3, "y": 5} and diff == snap
    a.add("y", 1)
    snap2, diff2 = a.snapshot_and_diff(snap)
    assert diff2 == {"y": 1}
    _, diff3 = a.snapshot_and_diff(snap2)
    assert diff3 == {}


def test_counters_concurrent_merge_consistent():
    dst = Counters()
    src = Counters()
    src.add("k", 1)
    stop = threading.Event()

    def mutate():
        while not stop.is_set():
            src.add("k", 1)

    t = threading.Thread(target=mutate)
    t.start()
    try:
        for _ in range(200):
            dst.merge(src)
    finally:
        stop.set()
        t.join()
    assert dst.get("k") > 0  # no deadlock, no exception, values sane


# ---------------------------------------------------------------------------
# run journal: schema round-trip, torn tails, serial/pipelined parity


def _journal_record(seq=1, ts=100, **over):
    rec = {"v": VERSION, "seq": seq, "ts": ts, "events": 5, "pairs": 3,
           "rows_scored": 2, "sample_seconds": 0.01, "score_seconds": 0.02,
           "ring_depth": 0, "stall_seconds": 0.0, "wall_unix": 1.5,
           "counters": {"X": 1}, "wire": {"h2d_bytes": 10}}
    rec.update(over)
    return rec


def test_journal_round_trip_and_validation(tmp_path):
    path = str(tmp_path / "j.jsonl")
    with RunJournal(path) as j:
        j.record(_journal_record(seq=1))
        j.record(_journal_record(seq=2, ts=200))
    got = list(read_records(path))
    assert [r["seq"] for r in got] == [1, 2]
    for r in got:
        validate_record(r)
    for bad, match in [
            ({k: v for k, v in _journal_record().items() if k != "ts"},
             "missing"),
            (_journal_record(ts="100"), "type"),
            (_journal_record(extra=1), "unknown"),
            (_journal_record(v=99), "version"),
    ]:
        with pytest.raises(ValueError, match=match):
            validate_record(bad)


def test_journal_append_resumes_and_torn_tail_skipped(tmp_path):
    path = str(tmp_path / "j.jsonl")
    with RunJournal(path) as j:
        j.record(_journal_record(seq=1))
    with open(path, "a") as f:
        f.write('{"v": 1, "seq": 2, "ts"')  # SIGKILL mid-write
    assert [r["seq"] for r in read_records(path)] == [1]
    assert tail(path, n=5)[-1]["seq"] == 1
    # A restarted attempt appends past the torn line.
    with RunJournal(path) as j:
        j.record(_journal_record(seq=2, ts=200))
    assert [r["seq"] for r in read_records(path)] == [1, 2]
    assert tail(str(tmp_path / "missing.jsonl")) == []


def _run_journaled_job(tmp_path, name, pipeline_depth, backend="oracle"):
    from tpu_cooccurrence.config import Backend, Config
    from tpu_cooccurrence.job import CooccurrenceJob

    rng = np.random.default_rng(11)
    n = 4000
    users = rng.integers(0, 40, n).astype(np.int64)
    items = rng.integers(0, 60, n).astype(np.int64)
    ts = np.cumsum(rng.integers(0, 2, n)).astype(np.int64)
    path = str(tmp_path / f"{name}.jsonl")
    job = CooccurrenceJob(Config(window_size=50, seed=5, item_cut=20,
                                 user_cut=10, backend=Backend(backend),
                                 journal=path,
                                 pipeline_depth=pipeline_depth))
    job.add_batch(users, items, ts)
    job.finish()
    return job, list(read_records(path))


def test_journal_matches_job_and_schema(tmp_path):
    job, recs = _run_journaled_job(tmp_path, "serial", 0)
    assert len(recs) == job.windows_fired > 5
    for r in recs:
        validate_record(r)
    assert [r["seq"] for r in recs] == list(range(1, len(recs) + 1))
    # Counter deltas tie out: summing every window's delta reproduces the
    # job's final totals for every counter that moved during windows.
    totals = {}
    for r in recs:
        for k, v in r["counters"].items():
            totals[k] = totals.get(k, 0) + v
    assert totals[OBSERVED_COOCCURRENCES] == \
        job.counters.get(OBSERVED_COOCCURRENCES)
    s = job.step_timer.summary()
    assert sum(r["events"] for r in recs) == s["events"]
    assert sum(r["pairs"] for r in recs) == s["pairs"]


def test_journal_parity_serial_vs_pipelined(tmp_path):
    """Depth 0 and depth 2 journals are identical on every logical field
    (the per-window timings and ring occupancy legitimately differ)."""
    _, serial = _run_journaled_job(tmp_path, "d0", 0)
    _, piped = _run_journaled_job(tmp_path, "d2", 2)
    assert len(serial) == len(piped) > 5
    logical = ("seq", "ts", "events", "pairs", "rows_scored")
    for a, b in zip(serial, piped):
        assert {k: a[k] for k in logical} == {k: b[k] for k in logical}


# ---------------------------------------------------------------------------
# scrape endpoint


def _get(url):
    from urllib.request import urlopen

    with urlopen(url, timeout=10) as r:
        return r.status, r.read().decode()


def test_metrics_server_serves_metrics_and_healthz():
    from tpu_cooccurrence.observability.http import MetricsServer

    reg = MetricsRegistry()
    reg.histogram("cooc_window_score_seconds").observe(0.01)
    reg.gauge("cooc_windows_fired").set(4)
    reg.gauge("cooc_last_window_unix_seconds").set(time.time())
    c = Counters()
    c.add(OBSERVED_COOCCURRENCES, 9)
    srv = MetricsServer(reg, counters=c, ledger=TransferLedger(), port=0,
                        stale_after_s=120.0).start()
    try:
        assert srv.port > 0
        code, text = _get(f"http://127.0.0.1:{srv.port}/metrics")
        assert code == 200
        assert f"{OBSERVED_COOCCURRENCES} 9" in text
        assert 'cooc_window_score_seconds_bucket{le="+Inf"} 1' in text
        code, body = _get(f"http://127.0.0.1:{srv.port}/healthz")
        hz = json.loads(body)
        assert code == 200 and hz["status"] == "ok"
        assert hz["windows_fired"] == 4
        from urllib.error import HTTPError

        with pytest.raises(HTTPError) as e:
            _get(f"http://127.0.0.1:{srv.port}/nope")
        assert e.value.code == 404
        # Stale: last window an hour ago -> 503.
        reg.gauge("cooc_last_window_unix_seconds").set(time.time() - 3600)
        with pytest.raises(HTTPError) as e:
            _get(f"http://127.0.0.1:{srv.port}/healthz")
        assert e.value.code == 503
        assert json.loads(e.value.read().decode())["status"] == "stale"
    finally:
        srv.stop()


def test_metrics_server_healthz_grace_before_first_window():
    from tpu_cooccurrence.observability.http import MetricsServer

    srv = MetricsServer(MetricsRegistry(), stale_after_s=300.0)
    try:
        payload, healthy = srv.health()
        assert healthy and payload["status"] == "starting"
        srv._started_unix -= 301  # grace expired, still no window
        payload, healthy = srv.health()
        assert not healthy and payload["status"] == "stale"
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# CLI end-to-end smoke: --journal + --metrics-port 0 on a live run


def test_cli_journal_and_metrics_endpoint_smoke(tmp_path):
    """The operator path: run the CLI with the flight recorder and an
    ephemeral scrape port, validate every journal line against the
    schema, and scrape /metrics + /healthz while the job is live."""
    import re

    from test_cli import write_stream

    f = tmp_path / "in.csv"
    write_stream(f, n=2000)
    jpath = tmp_path / "journal.jsonl"
    cmd = [sys.executable, "-m", "tpu_cooccurrence.cli",
           "-i", str(f), "-ws", "50", "-ic", "20", "-uc", "10",
           "-s", "0xC0FFEE", "--backend", "oracle",
           "--journal", str(jpath), "--metrics-port", "0",
           # Continuous mode keeps the process (and the endpoint) alive
           # after the file is consumed so the scrape below can't race
           # process exit.
           "--process-continuously", "--buffer-timeout", "10"]
    proc = subprocess.Popen(cmd, env=ENV, cwd=REPO,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.PIPE, text=True)
    stderr_lines = []

    def pump():
        for line in proc.stderr:
            stderr_lines.append(line)

    reader = threading.Thread(target=pump, daemon=True)
    reader.start()
    try:
        port = None
        deadline = time.time() + 120
        while time.time() < deadline and port is None:
            for line in list(stderr_lines):
                m = re.search(r"serving /metrics and /healthz on "
                              r"http://127\.0\.0\.1:(\d+)", line)
                if m:
                    port = int(m.group(1))
            if proc.poll() is not None:
                raise AssertionError(
                    "CLI exited early:\n" + "".join(stderr_lines)[-2000:])
            time.sleep(0.05)
        assert port, "metrics port never logged:\n" + "".join(stderr_lines)
        while time.time() < deadline:  # at least one fired window
            if jpath.exists() and list(read_records(str(jpath))):
                break
            time.sleep(0.1)
        code, text = _get(f"http://127.0.0.1:{port}/metrics")
        assert code == 200
        for name in CANONICAL_COUNTERS:  # all reference-named counters
            assert f"\n{name} " in "\n" + text
        for hist in ("cooc_window_sample_seconds",
                     "cooc_window_score_seconds",
                     "cooc_window_total_seconds"):
            assert f"{hist}_count" in text
            for q in ("p50", "p95", "p99"):
                assert f"{hist}_{q} " in text
        code, body = _get(f"http://127.0.0.1:{port}/healthz")
        assert code == 200
        assert json.loads(body)["status"] in ("ok", "starting")
    finally:
        proc.terminate()
        proc.wait(timeout=30)
    recs = list(read_records(str(jpath)))
    assert recs, "no journal records written"
    for r in recs:
        validate_record(r)
    assert [r["seq"] for r in recs] == list(range(1, len(recs) + 1))


def test_metrics_server_healthz_carries_supervisor_info():
    """Restart forensics from the supervising parent surface on
    /healthz as last_restart (cli.py passes the env payload through)."""
    from tpu_cooccurrence.observability.http import MetricsServer

    info = {"restarts": 2, "last_rc": -9, "backoff_ms": 150,
            "last_restart_unix": 1234.5, "stepped_back": False}
    srv = MetricsServer(MetricsRegistry(), stale_after_s=300.0,
                        supervisor_info=info)
    try:
        payload, healthy = srv.health()
        assert healthy
        assert payload["last_restart"] == info
    finally:
        srv.stop()

    srv = MetricsServer(MetricsRegistry(), stale_after_s=300.0)
    try:
        payload, _ = srv.health()
        assert "last_restart" not in payload
    finally:
        srv.stop()
