"""Step timing, slow-window ranking, and the XLA trace wrapper."""

import os

import numpy as np
import pytest

from tpu_cooccurrence.observability import (StepTimer, WindowStats, clock,
                                            xla_trace)


def stats(ts, sample, score, events=10, pairs=20, rows=5):
    return WindowStats(timestamp=ts, events=events, pairs=pairs,
                       rows_scored=rows, sample_seconds=sample,
                       score_seconds=score)


def test_step_timer_summary_aggregates():
    t = StepTimer()
    t.record(stats(0, 0.25, 0.75, events=100, pairs=1000))
    t.record(stats(1, 0.5, 0.5, events=50, pairs=500))
    s = t.summary()
    assert s["windows"] == 2 and s["events"] == 150 and s["pairs"] == 1500
    assert s["sample_seconds"] == pytest.approx(0.75)
    assert s["score_seconds"] == pytest.approx(1.25)
    assert s["pairs_per_sec"] == pytest.approx(750.0)
    assert StepTimer().summary()["pairs_per_sec"] == 0.0  # no div-by-zero


def test_step_timer_slowest_ranks_and_ring_bounds():
    t = StepTimer(keep=4)
    for i, dur in enumerate([0.1, 0.9, 0.2, 0.8, 0.3]):  # 0.1 evicted
        t.record(stats(i, dur, 0.0))
    slow = t.slowest(2)
    assert [w.timestamp for w in slow] == [1, 3]
    assert t.total_windows == 5 and len(t.windows) == 4


def test_xla_trace_writes_profile(tmp_path):
    """--profile-dir produces an on-disk trace consumable by TensorBoard."""
    import jax.numpy as jnp

    out = str(tmp_path / "trace")
    with xla_trace(out):
        jnp.arange(8).sum().block_until_ready()
    found = [os.path.join(r, f) for r, _, fs in os.walk(out) for f in fs]
    assert found, "no trace files written"


def test_xla_trace_none_is_noop():
    with xla_trace(None):
        pass


def test_clock_measures():
    import time

    with clock() as c:
        time.sleep(0.01)
    assert c.seconds >= 0.009


def test_job_records_step_timing():
    from tpu_cooccurrence.config import Backend, Config
    from tpu_cooccurrence.job import CooccurrenceJob

    rng = np.random.default_rng(3)
    users = rng.integers(0, 10, 500).astype(np.int64)
    items = rng.integers(0, 30, 500).astype(np.int64)
    ts = np.cumsum(rng.integers(0, 2, 500)).astype(np.int64)
    job = CooccurrenceJob(Config(window_size=20, seed=1,
                                 backend=Backend.ORACLE))
    job.add_batch(users, items, ts)
    job.finish()
    s = job.step_timer.summary()
    assert s["windows"] == job.windows_fired > 0
    assert s["pairs"] > 0
    assert job.step_timer.slowest(1)
