"""Fused Pallas sparse-rectangle scorer vs the XLA `_score_rect` path.

Interpret mode on CPU (the standard way to validate Pallas TPU kernels
without hardware). The kernel must be a drop-in for
``state/sparse_scorer._score_rect``: same packed [2, S, K] wire format
(ids as int32 bitcast), same tie semantics (earliest slab slot wins),
same zero-cell masking. (VERDICT r3, Next #2 — reference hot loop 4:
ItemRowRescorerTwoInputStreamOperator.java:158-228.)
"""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_cooccurrence.ops.pallas_score import (pallas_score_rect,
                                               rect_supported, rect_tile)
from tpu_cooccurrence.sampling.reservoir import PairDeltaBatch
from tpu_cooccurrence.state.sparse_scorer import (SparseDeviceScorer,
                                                  _score_rect)

# Interpret-mode Pallas across meshes: minutes of wall-clock. Slow lane
# (deselected by default; TPU_COOC_FULL_SUITE=1 selects it back in).
pytestmark = pytest.mark.slow


def _random_slab(rng, n_rows, num_items, R, zero_frac=0.1,
                 count_hi=50):
    """Synthetic slab: ``n_rows`` rows with random lens in [0, R],
    contiguous starts, random partner ids / counts (some zero =
    cancelled cells), plus 3 all-padding meta rows (len 0)."""
    lens = rng.integers(0, R + 1, n_rows).astype(np.int32)
    starts = np.concatenate([[0], np.cumsum(lens)[:-1]]).astype(np.int32)
    cap = int(lens.sum()) + 8
    cnt = rng.integers(1, count_hi, cap).astype(np.int32)
    cnt[rng.random(cap) < zero_frac] = 0
    dst = rng.integers(0, num_items, cap).astype(np.int32)
    rowids = rng.choice(num_items, n_rows, replace=False).astype(np.int32)
    meta = np.zeros((3, n_rows + 3), dtype=np.int32)  # 3 padding rows
    meta[0, :n_rows] = rowids
    meta[1, :n_rows] = starts
    meta[2, :n_rows] = lens
    row_sums = rng.integers(1, 1 << 16, num_items).astype(np.int32)
    observed = np.float32(1e7)
    return cnt, dst, row_sums, meta, observed


def _unpack(packed, s):
    host = np.asarray(packed)
    return host[0, :s], host[1, :s].view(np.int32)


@pytest.mark.parametrize("seed,R,n_rows", [
    (0, 256, 13),    # single column tile, non-multiple-of-8 rows
    (1, 512, 24),    # tile == R
    (2, 4096, 9),    # two column tiles: running merge across tiles
])
def test_rect_kernel_matches_score_rect(seed, R, n_rows):
    rng = np.random.default_rng(seed)
    num_items = 2048
    top_k = 10
    cnt, dst, row_sums, meta, observed = _random_slab(
        rng, n_rows, num_items, R)

    ref = _score_rect(jnp.asarray(cnt), jnp.asarray(dst),
                      jnp.asarray(row_sums), jnp.asarray(meta), observed,
                      top_k, R)
    got = pallas_score_rect(jnp.asarray(cnt), jnp.asarray(dst),
                            jnp.asarray(row_sums), jnp.asarray(meta),
                            observed, top_k=top_k, R=R, interpret=True)
    s = meta.shape[1]
    ref_vals, ref_idx = _unpack(ref, s)
    got_vals, got_idx = _unpack(got, s)
    np.testing.assert_allclose(got_vals, ref_vals, rtol=1e-5, atol=1e-5)
    # Ids must agree exactly wherever the score is not tied (ties keep
    # set equality — checked via the score match above plus the
    # untied-position identity here).
    for r in range(s):
        for k in range(top_k):
            if not np.isfinite(ref_vals[r, k]):
                continue
            if np.isclose(ref_vals[r], ref_vals[r, k]).sum() == 1:
                assert got_idx[r, k] == ref_idx[r, k], (r, k)


def test_rect_kernel_tie_prefers_earliest_slot():
    """Equal scores: the earliest-inserted slab cell (lowest slot) wins,
    matching lax.top_k in _score_rect and the reference heap's
    keep-earlier rule (IntDoublePriorityQueue.java:146-150)."""
    num_items = 512
    R = 256
    top_k = 4
    # One row, 6 live cells; partners chosen with IDENTICAL row sums and
    # counts so all six scores tie exactly.
    lens = np.asarray([6], dtype=np.int32)
    meta = np.zeros((3, 8), dtype=np.int32)
    meta[0, 0] = 7
    meta[1, 0] = 0
    meta[2, 0] = lens[0]
    cnt = np.zeros(R, dtype=np.int32)
    cnt[:6] = 5
    dst = np.zeros(R, dtype=np.int32)
    partners = np.asarray([40, 30, 20, 10, 50, 60], dtype=np.int32)
    dst[:6] = partners
    row_sums = np.full(num_items, 1000, dtype=np.int32)
    observed = np.float32(1e6)

    ref = _score_rect(jnp.asarray(cnt), jnp.asarray(dst),
                      jnp.asarray(row_sums), jnp.asarray(meta), observed,
                      top_k, R)
    got = pallas_score_rect(jnp.asarray(cnt), jnp.asarray(dst),
                            jnp.asarray(row_sums), jnp.asarray(meta),
                            observed, top_k=top_k, R=R, interpret=True)
    _, ref_idx = _unpack(ref, 1)
    _, got_idx = _unpack(got, 1)
    # Both keep slot order among the all-tied cells: first 4 partners.
    np.testing.assert_array_equal(ref_idx[0], partners[:top_k])
    np.testing.assert_array_equal(got_idx[0], partners[:top_k])


def test_rect_supported_gating():
    assert rect_supported(256, 10)
    assert rect_supported(1024, 10)
    assert not rect_supported(64, 10)       # narrow: XLA carries it
    assert not rect_supported(16, 10)
    assert not rect_supported(256, 200)     # top_k beyond lane width
    assert rect_tile(4096) == 2048  # wide tiles amortize the merge
    assert rect_tile(256) == 256
    with pytest.raises(ValueError, match="rect_supported"):
        pallas_score_rect(jnp.zeros(8, jnp.int32), jnp.zeros(8, jnp.int32),
                          jnp.zeros(16, jnp.int32),
                          jnp.zeros((3, 4), jnp.int32), np.float32(0),
                          top_k=10, R=64, interpret=True)


def test_rect_rejects_vocab_beyond_float32_exact():
    import functools

    import jax

    big = (1 << 24) + 128
    with pytest.raises(ValueError, match="2\\^24"):
        jax.eval_shape(
            functools.partial(pallas_score_rect, top_k=5, R=256,
                              interpret=True),
            jax.ShapeDtypeStruct((1024,), jnp.int32),
            jax.ShapeDtypeStruct((1024,), jnp.int32),
            jax.ShapeDtypeStruct((big,), jnp.int32),
            jax.ShapeDtypeStruct((3, 8), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.float32))


def _dense_stream(seed=11, n=60_000, items=512):
    rng = np.random.default_rng(seed)
    src = rng.integers(0, items, n).astype(np.int64)
    dst = rng.integers(0, items, n).astype(np.int64)
    keep = src != dst
    return PairDeltaBatch(src[keep], dst[keep],
                          np.ones(int(keep.sum()), dtype=np.int32))


def _assert_topk_match(out_on, out_off):
    """Kernel vs XLA result dicts {row: (vals, idx)} under the shared
    parity contract (ops/pallas_score.topk_parity — the same check the
    on-chip bench rows run)."""
    from tpu_cooccurrence.ops.pallas_score import topk_parity

    assert set(out_on) == set(out_off) and out_on
    rows = sorted(out_on)
    ok, mism = topk_parity(
        np.stack([out_off[r][0] for r in rows]),
        np.stack([out_off[r][1] for r in rows]),
        np.stack([out_on[r][0] for r in rows]),
        np.stack([out_on[r][1] for r in rows]))
    assert ok, "scores diverge between the kernel and XLA paths"
    assert mism == 0, f"{mism} untied positions carry different ids"


@pytest.mark.parametrize("mode", ["pipelined", "deferred-fixed"])
def test_sparse_scorer_pallas_end_to_end(mode):
    """SparseDeviceScorer --pallas on matches off, through both dispatch
    forms. The dense random stream pushes rows past 64 partners so the
    R=256 bucket (kernel-carried) is actually exercised."""
    pairs = _dense_stream()
    out = {}
    for pl in ("on", "off"):
        kw = (dict(defer_results=True, fixed_shapes=True)
              if mode == "deferred-fixed" else dict(defer_results=False))
        sc = SparseDeviceScorer(10, use_pallas=pl, **kw)
        sc.process_window(0, pairs)
        batches = [sc.flush()]
        if mode == "pipelined":
            batches.append(sc.flush())  # drain the one-window pipeline
        got = {int(r): (v.copy(), i.copy())
               for b in batches
               for r, i, v in zip(b.rows, b.idx, b.vals)}
        out[pl] = got
        # Sanity: the kernel path actually carried a wide bucket.
        if pl == "on":
            assert sc._rect_pallas(256), "R=256 bucket should be kernel-carried"
    _assert_topk_match(out["on"], out["off"])


def test_sharded_sparse_pallas_matches_xla():
    """ShardedSparseScorer --pallas on == off over the virtual 8-device
    mesh: the rectangle kernel runs per shard inside shard_map."""
    from tpu_cooccurrence.parallel.sharded_sparse import ShardedSparseScorer

    pairs = _dense_stream(seed=13, n=40_000, items=384)
    out = {}
    for pl in ("on", "off"):
        sc = ShardedSparseScorer(10, num_shards=8, defer_results=True,
                                 fixed_shapes=True, use_pallas=pl)
        # Small fixed rectangles: interpret-mode pallas across 8 shards
        # is minutes at the default budget, seconds at this one.
        sc.FIXED_BUDGET = 1 << 13
        sc.FIXED_ROW_CAP = 32
        sc.process_window(0, pairs)
        b = sc.flush()
        out[pl] = {int(r): (v.copy(), i.copy())
                   for r, i, v in zip(b.rows, b.idx, b.vals)}
        if pl == "on":
            assert sc._rect_pallas(256)
    _assert_topk_match(out["on"], out["off"])


def test_sharded_dense_pallas_matches_xla():
    """ShardedScorer --pallas on == off over the virtual 8-device mesh
    (the dense kernel gathers from each shard's local row block against
    the replicated row sums). Small tile keeps interpret mode fast."""
    from tpu_cooccurrence.parallel.sharded import ShardedScorer

    class SmallTile(ShardedScorer):
        PALLAS_TILE = 128

    pairs = _dense_stream(seed=17, n=20_000, items=250)
    out = {}
    for pl in ("on", "off"):
        sc = SmallTile(250, 10, num_shards=8, use_pallas=pl,
                       count_dtype="int16")
        sc.process_window(0, pairs)
        b = sc.flush()
        out[pl] = {int(r): (v.copy(), i.copy())
                   for r, i, v in zip(b.rows, b.idx, b.vals)}
    _assert_topk_match(out["on"], out["off"])


def test_sparse_scorer_rejects_bad_pallas_value():
    with pytest.raises(ValueError, match="auto|on|off"):
        SparseDeviceScorer(10, use_pallas="yes")


def test_sparse_pallas_auto_defaults_off_on_cpu():
    """auto resolves OFF for the int32 slab (measured: XLA wins dense
    int32 ~5x; the sparse-pallas tpu_round2 row re-decides on chip)."""
    sc = SparseDeviceScorer(10, use_pallas="auto")
    assert sc.use_pallas is False
    assert not sc._rect_pallas(1024)


def test_sharded_dense_pallas_checkpoint_cross_padding(tmp_path):
    """A checkpoint written WITHOUT pallas (vocab padded to n_shards
    only) restores into a pallas-enabled scorer (vocab padded to a
    kernel-tile multiple) and vice versa — both directions continue to
    identical results."""
    from tpu_cooccurrence.parallel.sharded import ShardedScorer

    class SmallTile(ShardedScorer):
        PALLAS_TILE = 128

    pairs1 = _dense_stream(seed=21, n=8_000, items=250)
    pairs2 = _dense_stream(seed=22, n=8_000, items=250)

    def run(pl_first, pl_second):
        a = SmallTile(250, 10, num_shards=8, count_dtype="int16",
                      use_pallas=pl_first)
        a.process_window(0, pairs1)
        a.flush()
        st = a.checkpoint_state()
        b = SmallTile(250, 10, num_shards=8, count_dtype="int16",
                      use_pallas=pl_second)
        b.restore_state(st)
        b.process_window(10, pairs2)
        batch = b.flush()
        return {int(r): (v.copy(), i.copy())
                for r, i, v in zip(batch.rows, batch.idx, batch.vals)}

    ref = run("off", "off")
    for combo in (("off", "on"), ("on", "off"), ("on", "on")):
        _assert_topk_match(run(*combo), ref)
