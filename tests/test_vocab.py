"""IdMap: dense-table fast path vs sorted general path equivalence."""

import numpy as np
import pytest

from tpu_cooccurrence.state.vocab import IdMap


def sorted_only_map() -> IdMap:
    m = IdMap()
    m._leave_table_mode()  # force the general path from the start
    return m


def test_first_appearance_order():
    m = IdMap()
    out = m.map_batch(np.array([50, 3, 50, 7, 3, 1]))
    np.testing.assert_array_equal(out, [0, 1, 0, 2, 1, 3])
    assert [m.to_external(i) for i in range(4)] == [50, 3, 7, 1]


def test_table_and_sorted_paths_agree():
    rng = np.random.default_rng(11)
    a, b = IdMap(), sorted_only_map()
    assert a._table is not None and b._table is None
    for _ in range(8):
        ids = rng.integers(0, 5000, int(rng.integers(1, 4000)))
        np.testing.assert_array_equal(a.map_batch(ids), b.map_batch(ids))
    assert a._table is not None  # stayed on the fast path
    assert len(a) == len(b)


def test_switch_to_sorted_on_large_id_keeps_mapping():
    m = IdMap()
    first = m.map_batch(np.array([9, 4, 9, 2]))
    # An id past the table cap permanently switches regimes …
    big = IdMap._TABLE_CAP + 5
    out = m.map_batch(np.array([4, big, 9, big, 2]))
    assert m._table is None
    # … preserving every previously assigned dense id.
    np.testing.assert_array_equal(out, [first[1], 3, first[0], 3, first[3]])
    again = m.map_batch(np.array([big, 4]))
    np.testing.assert_array_equal(again, [3, first[1]])


def test_switch_to_sorted_on_negative_id():
    m = IdMap()
    m.map_batch(np.array([1, 2]))
    out = m.map_batch(np.array([-7, 1]))
    assert m._table is None
    np.testing.assert_array_equal(out, [2, 0])


@pytest.mark.parametrize("make", [IdMap, sorted_only_map])
def test_restore_roundtrip_continues_mapping(make):
    m = make()
    m.map_batch(np.array([100, 7, 42]))
    state = m.checkpoint_state()
    m2 = IdMap()
    m2.restore_state(state)
    np.testing.assert_array_equal(m2.map_batch(np.array([42, 100, 7])),
                                  [2, 0, 1])
    # New ids continue after the restored vocab.
    np.testing.assert_array_equal(m2.map_batch(np.array([5, 42])), [3, 2])
    assert m2.to_dense(7) == 1 and m2.to_dense(999) is None


def test_restore_large_ids_lands_in_sorted_mode():
    m = IdMap()
    rev = np.array([IdMap._TABLE_CAP + 9, 3])
    m.restore_state(rev)
    assert m._table is None
    np.testing.assert_array_equal(
        m.map_batch(np.array([3, IdMap._TABLE_CAP + 9])), [1, 0])


def test_table_dedup_matches_sorted_first_appearance():
    """The sort-free reversed-scatter dedup in _map_table must assign
    dense ids in exact first-appearance order — differentially checked
    against a naive scan over many random duplicate-heavy batches."""
    import numpy as np

    from tpu_cooccurrence.state.vocab import IdMap

    rng = np.random.default_rng(0xDED)
    for _trial in range(30):
        v = IdMap()
        naive = {}
        for _batch in range(rng.integers(1, 5)):
            ids = rng.integers(0, 200, rng.integers(1, 400))
            got = v.map_batch(ids)
            for ext in ids.tolist():
                naive.setdefault(ext, len(naive))
            expect = np.asarray([naive[e] for e in ids.tolist()])
            np.testing.assert_array_equal(got, expect)
        # Reverse mapping agrees.
        for ext, dense in naive.items():
            assert v.to_external(dense) == ext
