"""cooclint (tpu_cooccurrence.analysis): the tier-1 enforcement run plus
fixture-driven proof that each rule pack catches its seeded violation.

The enforcement test runs the analyzer over the whole checkout and
expects zero non-baseline findings — this is the commit-time gate the
analyzer exists for. The fixture tests feed bad-code snippets through
``analyze_source`` impersonating the file each rule watches, including
a regression fixture reproducing the PR-2 ``TransferLedger`` race
pattern (the unlocked ``+=`` on the ledger's byte totals from a worker
module) that motivated the lock-discipline pack.

This file's raw text necessarily quotes the bad fault-site patterns the
text-scanning rules hunt (the deleted PR-3 test excluded itself for the
same reason), so it opts out of that one rule file-wide:
# cooclint: disable-file=fault-site
"""

import json
import os
import subprocess
import sys

import pytest

from tpu_cooccurrence.analysis import (
    Analyzer,
    Finding,
    RULES,
    analyze_source,
    load_baseline,
)
from tpu_cooccurrence.analysis.core import save_baseline

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: Tier-1 runtime budget for the whole-repo pass (ISSUE 4 satellite:
#: the analyzer must stay under this or fail loudly here, in review).
RUNTIME_BUDGET_S = 10.0


def _rules(f):
    return sorted({x.rule for x in f})


# -- the tier-1 gate ---------------------------------------------------


def test_repo_is_clean_under_budget():
    """Whole-repo pass: no new findings, runtime within the tier-1
    budget (recorded in the run summary and asserted here)."""
    result = Analyzer(REPO, baseline=load_baseline()).run()
    assert not result.findings, "\n".join(map(str, result.findings))
    assert not result.stale_baseline, (
        f"stale baseline entries (run --prune-baseline): "
        f"{result.stale_baseline}")
    assert result.files_scanned > 50  # sanity: the walker saw the repo
    print(f"cooclint runtime: {result.elapsed_seconds:.2f}s "
          f"over {result.files_scanned} files")
    assert result.elapsed_seconds < RUNTIME_BUDGET_S


def test_runner_json_schema_and_exit_code():
    """``python -m tpu_cooccurrence.analysis --format json`` under
    JAX_PLATFORMS=cpu (the tier-1 environment): exit 0 on the clean
    repo, schema round-trips through Finding.from_dict, runtime is in
    the summary."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_cooccurrence.analysis",
         "--root", REPO, "--format", "json"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["schema"] == "cooclint-findings/2"
    assert payload["exit_code"] == 0
    assert payload["files_scanned"] > 50
    assert payload["elapsed_seconds"] < RUNTIME_BUDGET_S
    # Round-trip: every finding dict reconstructs losslessly.
    for d in payload["findings"]:
        assert Finding.from_dict(d).to_dict() == d


# -- rule pack 1: lock discipline --------------------------------------

PR2_RACE_FIXTURE = '''
class PipelineWorker:
    def record_upload(self, ledger, arrays):
        n = sum(int(a.nbytes) for a in arrays)
        ledger.h2d_bytes += n
        ledger.h2d_calls += 1
'''


def test_lock_discipline_catches_pr2_ledger_race():
    """The PR-2 regression shape: an unlocked read-modify-write on the
    TransferLedger byte totals from a worker module."""
    findings = analyze_source(
        PR2_RACE_FIXTURE, path="tpu_cooccurrence/pipeline.py",
        rules=["lock-discipline"])
    assert len(findings) == 2
    assert {f.line for f in findings} == {5, 6}
    assert all(f.rule == "lock-discipline" for f in findings)


def test_lock_discipline_allows_locked_and_owner_access():
    locked = '''
class PipelineWorker:
    def record_upload(self, ledger, n):
        with ledger._lock:
            ledger.h2d_bytes += n
'''
    owner = '''
class TransferLedger:
    def up(self, n):
        with self._lock:
            self.h2d_bytes += n
'''
    assert analyze_source(locked, rules=["lock-discipline"]) == []
    assert analyze_source(owner, rules=["lock-discipline"]) == []


def test_lock_discipline_counters_and_results_state():
    bad = '''
def merge_fast(counters, other):
    for k, v in other._counters.items():
        counters._counters[k] += v
'''
    findings = analyze_source(bad, rules=["lock-discipline"])
    # one access per line: the iteration read and the augmented write
    assert {f.line for f in findings} == {3, 4}
    bad_results = "def poke(latest):\n    return latest._ptr_batch[0]\n"
    assert _rules(analyze_source(
        bad_results, rules=["lock-discipline"])) == ["lock-discipline"]


def test_lock_annotation_required_in_worker_modules():
    bad = "import threading\nLOCK = threading.Lock()\n"
    findings = analyze_source(
        bad, path="tpu_cooccurrence/pipeline.py",
        rules=["lock-annotation"])
    assert _rules(findings) == ["lock-annotation"]
    good = ("import threading\n"
            "# lock-ordering: leaf lock, never held across registry "
            "locks\n"
            "LOCK = threading.Lock()\n")
    assert analyze_source(good, path="tpu_cooccurrence/pipeline.py",
                          rules=["lock-annotation"]) == []
    # Outside the two-thread worker modules a bare lock is fine.
    assert analyze_source(bad, path="tpu_cooccurrence/io/source.py",
                          rules=["lock-annotation"]) == []


def test_lock_discipline_is_object_sensitive_inside_owner():
    """The PR-2 Counters.merge race, reintroduced INSIDE the owning
    class: self's lock over *other*'s dict must still be a finding —
    the owner exemption covers `self` only."""
    bad = '''
class Counters:
    def merge(self, other):
        with self._lock:
            for k, v in other._counters.items():
                self._counters[k] += v
'''
    findings = analyze_source(bad, rules=["lock-discipline"])
    assert len(findings) == 1
    assert "other" in findings[0].message and findings[0].line == 5


def test_lock_discipline_wrong_objects_lock_does_not_cover():
    bad = '''
def record(a, b, n):
    with a._lock:
        b.h2d_bytes += n
'''
    findings = analyze_source(bad, rules=["lock-discipline"])
    assert _rules(findings) == ["lock-discipline"]
    good = bad.replace("with a._lock:", "with b._lock:")
    assert analyze_source(good, rules=["lock-discipline"]) == []


# -- rule pack 2: jit / device hygiene ---------------------------------


def test_jit_purity_flags_host_syncs():
    bad = '''
import jax
import numpy as np

@jax.jit
def score(c, x):
    y = np.asarray(x)
    print("debug", y)
    return float(x)
'''
    findings = analyze_source(bad, rules=["jit-purity"])
    msgs = " | ".join(f.message for f in findings)
    assert len(findings) == 3
    assert "np.asarray" in msgs and "print" in msgs and "float(x)" in msgs


def test_jit_purity_static_args_and_plain_functions_exempt():
    src = '''
import functools
import jax
import numpy as np

@functools.partial(jax.jit, static_argnames=("k",))
def topk(vals, k):
    return int(k) + vals.sum()

def host_helper(x):
    return float(np.asarray(x).sum())
'''
    assert analyze_source(src, rules=["jit-purity"]) == []


def test_jit_purity_block_until_ready_and_rng():
    bad = '''
import jax
import numpy as np

@jax.jit
def noisy(x):
    x.sum().block_until_ready()
    return x + np.random.rand()
'''
    findings = analyze_source(bad, rules=["jit-purity"])
    msgs = " | ".join(f.message for f in findings)
    assert "block_until_ready" in msgs and "host RNG" in msgs


def test_jit_purity_transitive_closure_any_module():
    """A helper reached from a jitted entry is hot-path in *every*
    module — the old rule special-cased one hop inside ops/ and missed
    everything else."""
    src = '''
import jax
import numpy as np

def helper(x):
    return np.asarray(x)

@jax.jit
def entry(x):
    return helper(x)
'''
    findings = analyze_source(src, path="tpu_cooccurrence/ops/llr.py",
                              rules=["jit-purity"])
    assert _rules(findings) == ["jit-purity"]
    # Same bug outside ops/ — the graph pass does not care which module
    # the trace walks through.
    job = analyze_source(src, path="tpu_cooccurrence/job.py",
                         rules=["jit-purity"])
    assert _rules(job) == ["jit-purity"]
    assert "traced from `entry`" in job[0].message


def test_jit_purity_two_hops_below_entry():
    """Host RNG two calls below the jit entry — provably invisible to
    the old one-hop rule, caught by call-graph reachability."""
    src = '''
import jax
import numpy as np

def noise(shape):
    return np.random.standard_normal(shape)

def helper(x):
    return x + noise(x.shape)

@jax.jit
def entry(x):
    return helper(x)
'''
    findings = analyze_source(src, path="tpu_cooccurrence/job.py",
                              rules=["jit-purity"])
    assert _rules(findings) == ["jit-purity"]
    f = findings[0]
    assert "host RNG" in f.message
    assert "entry -> helper -> noise" in f.message


def test_jit_purity_uncalled_helper_not_flagged():
    """Reachability, not co-location: a host-sync helper in the same
    file that no jitted code calls stays silent."""
    src = '''
import jax
import numpy as np

def orchestrate(x):
    return np.asarray(x)

@jax.jit
def entry(x):
    return x * 2
'''
    assert analyze_source(src, path="tpu_cooccurrence/job.py",
                          rules=["jit-purity"]) == []


DONATION_FIXTURE = '''
import functools
import jax
from ..ops.donation import donate_argnums

@functools.partial(jax.jit, donate_argnums=donate_argnums(0))
def update(c, d):
    return c + d

class Scorer:
    def step(self, d):
        out = update(self.cnt, d)
        return self.cnt.sum()
'''


def test_donation_reuse_flags_use_after_donate():
    findings = analyze_source(DONATION_FIXTURE, rules=["donation-reuse"])
    assert _rules(findings) == ["donation-reuse"]
    assert "self.cnt" in findings[0].message


def test_donation_reuse_allows_same_statement_rebind():
    good = DONATION_FIXTURE.replace(
        "        out = update(self.cnt, d)\n        return self.cnt.sum()",
        "        self.cnt = update(self.cnt, d)\n        return self.cnt.sum()")
    assert analyze_source(good, rules=["donation-reuse"]) == []


# -- rule pack 3: registry drift ---------------------------------------


def test_metric_name_rule():
    bad = ('from .registry import REGISTRY\n'
           'g = REGISTRY.gauge("cooc_bogus_thing", help="x")\n')
    findings = analyze_source(bad, rules=["metric-name"])
    assert _rules(findings) == ["metric-name"]
    assert "cooc_bogus_thing" in findings[0].message
    good = bad.replace("cooc_bogus_thing", "cooc_windows_fired")
    assert analyze_source(good, rules=["metric-name"]) == []


def test_metric_name_rule_counter_literals():
    bad = ('class J:\n'
           '    def f(self):\n'
           '        self.counters.add("TotallyMadeUpCounter", 1)\n')
    findings = analyze_source(bad, rules=["metric-name"])
    assert _rules(findings) == ["metric-name"]
    good = bad.replace("TotallyMadeUpCounter",
                       "ItemInteractionCounterLateElements")
    assert analyze_source(good, rules=["metric-name"]) == []


def test_fault_site_rule_fire_and_spec_strings():
    bad = ('def f(plan):\n'
           '    plan.fire("not_a_site", seq=1)\n'
           '    spec = "not_a_site:3:crash"\n')
    findings = analyze_source(bad, rules=["fault-site"])
    # The AST and raw-text scans overlap deliberately (each covers
    # shapes the other cannot); both anchor the same two lines.
    assert {f.line for f in findings} == {2, 3}
    good = bad.replace("not_a_site", "window_fire")
    assert analyze_source(good, rules=["fault-site"]) == []


def test_fault_site_rule_argv_pairs_without_kind():
    """CLI-test argv shape: the site rides a separate literal with no
    kind suffix — the text scan must still validate it (coverage the
    deleted PR-3 test had)."""
    bad = 'cmd = ["--inject-fault", "windw_fire:3"]\n'  # cooclint: disable=fault-site
    findings = analyze_source(bad, rules=["fault-site"])
    assert _rules(findings) == ["fault-site"]
    assert "windw_fire" in findings[0].message
    good = 'cmd = ["--inject-fault", "window_fire:3"]\n'
    assert analyze_source(good, rules=["fault-site"]) == []


def test_metric_name_reverse_check_flags_dead_canonical_entries(
        tmp_path):
    """A CANONICAL_METRICS entry nothing in the package emits is a dead
    registry row (mirrors the fault-site dead-entry check)."""
    from tpu_cooccurrence.observability.registry import CANONICAL_METRICS

    pkg = tmp_path / "tpu_cooccurrence" / "observability"
    pkg.mkdir(parents=True)
    (pkg / "registry.py").write_text(
        'G = REGISTRY.gauge("cooc_windows_fired")\n')
    result = Analyzer(str(tmp_path), rules=[RULES["metric-name"]]).run()
    dead = {f.message.split("'")[1] for f in result.findings}
    assert dead == CANONICAL_METRICS - {"cooc_windows_fired"}


def test_fault_site_rule_midstring_and_bare_fire():
    """Coverage parity with the deleted PR-3 scan: a quoted spec
    embedded mid-docstring and a bare imported fire() call must both
    be validated."""
    doc = ('def f():\n'
           '    """Example: pass "typo_site:3:crash" to the CLI."""\n')
    findings = analyze_source(doc, rules=["fault-site"])
    assert _rules(findings) == ["fault-site"]
    assert "typo_site" in findings[0].message
    bare = ('from tpu_cooccurrence.robustness.faults import fire\n'
            'fire("typo_site", seq=1)\n')
    findings = analyze_source(bare, rules=["fault-site"])
    assert _rules(findings) == ["fault-site"]
    # Quoted spec in a doc line (no --inject-fault token on the line).
    md = 'pass "typo_site:2:torn_write" to the child\n'
    findings = analyze_source(md, path="docs/RUNBOOK.md",
                              rules=["fault-site"])
    assert _rules(findings) == ["fault-site"]


def test_metric_name_reverse_check_ignores_definition_literals(
        tmp_path):
    """The CANONICAL_METRICS assignment itself is not an emission: a
    dead entry must be flagged even though it textually appears at its
    own definition site."""
    from tpu_cooccurrence.observability.registry import CANONICAL_METRICS

    pkg = tmp_path / "tpu_cooccurrence" / "observability"
    pkg.mkdir(parents=True)
    names = ",\n    ".join(f'"{n}"' for n in sorted(CANONICAL_METRICS))
    (pkg / "registry.py").write_text(
        "CANONICAL_METRICS = frozenset({\n    " + names + ",\n})\n"
        'G = REGISTRY.gauge("cooc_windows_fired")\n')
    result = Analyzer(str(tmp_path), rules=[RULES["metric-name"]]).run()
    dead = {f.message.split("'")[1] for f in result.findings}
    assert dead == CANONICAL_METRICS - {"cooc_windows_fired"}


def test_cli_flag_rule_on_a_mini_repo(tmp_path):
    pkg = tmp_path / "tpu_cooccurrence"
    pkg.mkdir()
    (pkg / "config.py").write_text(
        "import argparse\n"
        "import dataclasses\n\n\n"
        "@dataclasses.dataclass\n"
        "class Config:\n"
        "    top_k: int = 10\n\n\n"
        "def from_args():\n"
        "    p = argparse.ArgumentParser()\n"
        '    p.add_argument("--top-k", type=int, dest="top_k")\n'
        '    p.add_argument("--mystery-flag", type=int, dest="mystery")\n'
        "    return p\n")
    (tmp_path / "README.md").write_text("Flags: `--top-k`.\n")
    result = Analyzer(str(tmp_path), rules=[RULES["cli-flag"]]).run()
    msgs = " | ".join(f.message for f in result.findings)
    assert len(result.findings) == 2  # undocumented + orphaned dest
    assert "--mystery-flag" in msgs and "mystery" in msgs
    assert "--top-k" not in msgs


# -- rule pack 4: native / fold dtype ----------------------------------


def test_native_dtype_rule():
    bad = ('import numpy as np\n'
           'def call(x):\n'
           '    lib.kernel(_ptr64(x), 3)\n')
    findings = analyze_source(
        bad, path="tpu_cooccurrence/native/__init__.py",
        rules=["native-dtype"])
    assert _rules(findings) == ["native-dtype"]
    good_contig = ('import numpy as np\n'
                   'def call(x):\n'
                   '    x = np.ascontiguousarray(x, dtype=np.int64)\n'
                   '    lib.kernel(_ptr64(x), 3)\n')
    good_assert = ('import numpy as np\n'
                   'def call(scratch):\n'
                   '    assert scratch.buf.dtype == np.int32\n'
                   '    lib.kernel(_ptr32(scratch.buf), 1)\n')
    for good in (good_contig, good_assert):
        assert analyze_source(
            good, path="tpu_cooccurrence/native/__init__.py",
            rules=["native-dtype"]) == []


def test_fold_dtype_guard_rule():
    bad = ('import numpy as np\n'
           'def aggregate_window_coo(src, dst, delta, return_key=False):\n'
           '    return src, dst, delta\n')
    findings = analyze_source(
        bad, path="tpu_cooccurrence/ops/aggregate.py",
        rules=["fold-dtype-guard"])
    assert _rules(findings) == ["fold-dtype-guard"]
    good = ('import numpy as np\n'
            'def aggregate_window_coo(src, dst, delta, return_key=False):\n'
            '    if not np.issubdtype(delta.dtype, np.integer):\n'
            '        raise TypeError("delta dtype")\n'
            '    return src, dst, delta\n')
    assert analyze_source(
        good, path="tpu_cooccurrence/ops/aggregate.py",
        rules=["fold-dtype-guard"]) == []


# -- suppressions ------------------------------------------------------


def test_suppression_exact_line_named_rule():
    src = PR2_RACE_FIXTURE.replace(
        "ledger.h2d_bytes += n",
        "ledger.h2d_bytes += n  # cooclint: disable=lock-discipline")
    findings = analyze_source(src, path="tpu_cooccurrence/pipeline.py",
                              rules=["lock-discipline"])
    assert {f.line for f in findings} == {6}  # only the unsuppressed line


def test_suppression_bare_disables_all_rules_on_line():
    src = PR2_RACE_FIXTURE.replace(
        "ledger.h2d_calls += 1",
        "ledger.h2d_calls += 1  # cooclint: disable")
    findings = analyze_source(src, path="tpu_cooccurrence/pipeline.py",
                              rules=["lock-discipline"])
    assert {f.line for f in findings} == {5}


def test_suppression_file_level_named_rule():
    """`# cooclint: disable-file=rule` opts the whole file out of one
    rule (the fixture-holder escape hatch) without touching others."""
    src = ('# cooclint: disable-file=fault-site\n'
           'def f(plan, ledger, n):\n'
           '    plan.fire("typo_site")\n'
           '    ledger.h2d_bytes += n\n')
    assert analyze_source(src, rules=["fault-site"]) == []
    # Other rules still fire in the same file.
    assert _rules(analyze_source(
        src, rules=["lock-discipline"])) == ["lock-discipline"]


def test_suppression_wrong_rule_name_does_not_silence():
    src = PR2_RACE_FIXTURE.replace(
        "ledger.h2d_bytes += n",
        "ledger.h2d_bytes += n  # cooclint: disable=metric-name")
    findings = analyze_source(src, path="tpu_cooccurrence/pipeline.py",
                              rules=["lock-discipline"])
    assert {f.line for f in findings} == {5, 6}


# -- baseline ----------------------------------------------------------


def _mini_repo_with_race(tmp_path):
    pkg = tmp_path / "tpu_cooccurrence"
    pkg.mkdir()
    (pkg / "pipeline.py").write_text(PR2_RACE_FIXTURE)
    return tmp_path


def test_baseline_grandfathers_and_reports_stale(tmp_path):
    root = _mini_repo_with_race(tmp_path)
    baseline = [
        {"rule": "lock-discipline", "file": "tpu_cooccurrence/pipeline.py",
         "line": 5, "justification": "grandfathered for the test"},
        {"rule": "lock-discipline", "file": "tpu_cooccurrence/gone.py",
         "line": 1, "justification": "stale entry"},
    ]
    result = Analyzer(str(root), rules=[RULES["lock-discipline"]],
                      baseline=baseline).run()
    assert {f.line for f in result.findings} == {6}  # line 5 baselined
    assert len(result.baselined) == 1
    assert [e["file"] for e in result.stale_baseline] == [
        "tpu_cooccurrence/gone.py"]


def test_prune_baseline_rewrites_file(tmp_path):
    from tpu_cooccurrence.analysis.__main__ import main

    root = _mini_repo_with_race(tmp_path)
    bl_path = str(tmp_path / "baseline.json")
    save_baseline([
        {"rule": "lock-discipline", "file": "tpu_cooccurrence/pipeline.py",
         "line": 5, "justification": "kept"},
        {"rule": "lock-discipline", "file": "tpu_cooccurrence/pipeline.py",
         "line": 6, "justification": "kept"},
        {"rule": "lock-discipline", "file": "tpu_cooccurrence/gone.py",
         "line": 1, "justification": "stale"},
    ], bl_path)
    rc = main(["--root", str(root), "--baseline", bl_path,
               "--prune-baseline"])
    assert rc == 0  # everything real is baselined, stale was pruned
    kept = load_baseline(bl_path)
    assert len(kept) == 2
    assert all(e["file"] == "tpu_cooccurrence/pipeline.py" for e in kept)
    # A second run sees no stale entries.
    result = Analyzer(str(root), rules=[RULES["lock-discipline"]],
                      baseline=kept).run()
    assert not result.findings and not result.stale_baseline


def test_explicit_missing_baseline_path_is_usage_error(tmp_path):
    """A typo'd --baseline must not silently run with an empty baseline
    (full re-report); it is exit 2. The DEFAULT path staying optional
    is separate (a clean repo has an empty baseline file anyway)."""
    from tpu_cooccurrence.analysis.__main__ import main

    root = _mini_repo_with_race(tmp_path)
    rc = main(["--root", str(root),
               "--baseline", str(tmp_path / "nope.json")])
    assert rc == 2


def test_malformed_baseline_rejected(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text('{"findings": [{"rule": "x"}]}')
    with pytest.raises(ValueError, match="malformed baseline entry"):
        load_baseline(str(p))


def test_finding_json_round_trip():
    f = Finding(rule="lock-discipline", file="a/b.py", line=7,
                message="msg")
    assert Finding.from_dict(json.loads(json.dumps(f.to_dict()))) == f


# ---------------------------------------------------------------------------
# degrade-registry rule (ISSUE 5)

_DEGRADE_OK = '''
import enum

class DegradationLevel(enum.IntEnum):
    NORMAL = 0
    SHED_SAMPLING = 1

TRANSITION_RULES = {
    "NORMAL": "healthy",
    "SHED_SAMPLING": "overloaded",
}
LEVEL_EVENTS = {
    "NORMAL": "degrade/enter_normal",
    "SHED_SAMPLING": "degrade/enter_shed_sampling",
}
'''


def test_degrade_registry_clean_fixture_passes():
    assert analyze_source(
        _DEGRADE_OK, path="tpu_cooccurrence/robustness/degrade.py",
        rules=["degrade-registry"]) == []


def test_degrade_registry_flags_member_missing_from_tables():
    bad = _DEGRADE_OK.replace('    "SHED_SAMPLING": "overloaded",\n', "")
    findings = analyze_source(
        bad, path="tpu_cooccurrence/robustness/degrade.py",
        rules=["degrade-registry"])
    assert _rules(findings) == ["degrade-registry"]
    assert "TRANSITION_RULES" in findings[0].message
    assert "SHED_SAMPLING" in findings[0].message


def test_degrade_registry_flags_dead_table_row():
    # A key naming no member must be flagged. (Scope note: the rule
    # reads dict-LITERAL keys only — a row added later via subscript
    # assignment is outside its reach, like every registry rule here.)
    bad = _DEGRADE_OK.replace(
        '    "SHED_SAMPLING": "degrade/enter_shed_sampling",\n',
        '    "SHED_SAMPLING": "degrade/enter_shed_sampling",\n'
        '    "GONE": "degrade/enter_gone",\n')
    findings = analyze_source(
        bad, path="tpu_cooccurrence/robustness/degrade.py",
        rules=["degrade-registry"])
    assert _rules(findings) == ["degrade-registry"]
    assert "dead registry row" in findings[0].message


def test_degrade_registry_flags_removed_table():
    bad = _DEGRADE_OK.replace("TRANSITION_RULES", "RENAMED_TABLE")
    findings = analyze_source(
        bad, path="tpu_cooccurrence/robustness/degrade.py",
        rules=["degrade-registry"])
    assert any("TRANSITION_RULES dict literal not found" in f.message
               for f in findings)


def test_degrade_registry_requires_architecture_mention(tmp_path):
    """With docs/ARCHITECTURE.md present but missing a level name, the
    rule flags it — the level table is part of the registry."""
    root = tmp_path / "repo"
    pkg = root / "tpu_cooccurrence" / "robustness"
    pkg.mkdir(parents=True)
    (root / "docs").mkdir()
    (pkg / "degrade.py").write_text(_DEGRADE_OK)
    (root / "docs" / "ARCHITECTURE.md").write_text(
        "# arch\n\nonly NORMAL is documented here\n")
    result = Analyzer(str(root), rules=[RULES["degrade-registry"]],
                      baseline=[]).run()
    assert [f.rule for f in result.findings] == ["degrade-registry"]
    assert "SHED_SAMPLING" in result.findings[0].message
    assert "ARCHITECTURE" in result.findings[0].message


# -- rule pack 6: pallas kernel registry --------------------------------


def _mini_pallas_repo(tmp_path, *, test_body, arch_body):
    """A minimal repo for the pallas-kernel-registry rule: one kernel
    core issuing pallas_call plus a public wrapper calling it."""
    root = tmp_path / "repo"
    ops = root / "tpu_cooccurrence" / "ops"
    ops.mkdir(parents=True)
    (ops / "pallas_score.py").write_text(
        "from jax.experimental import pallas as pl\n\n\n"
        "def _my_kernel_core(x):\n"
        "    return pl.pallas_call(None)(x)\n\n\n"
        "def my_kernel_wrapper(x):\n"
        "    return _my_kernel_core(x)\n")
    (root / "tests").mkdir()
    (root / "tests" / "test_parity_fixture.py").write_text(test_body)
    (root / "docs").mkdir()
    (root / "docs" / "ARCHITECTURE.md").write_text(arch_body)
    return root


def test_pallas_kernel_registry_wrapper_coverage_passes(tmp_path):
    """A parity test referencing the public WRAPPER covers the private
    kernel core (one call hop — the surface tests actually drive)."""
    root = _mini_pallas_repo(
        tmp_path,
        test_body="def test_parity():\n    assert my_kernel_wrapper\n",
        arch_body="| `_my_kernel_core` | streaming thing |\n")
    result = Analyzer(str(root), rules=[RULES["pallas-kernel-registry"]],
                      baseline=[]).run()
    assert result.findings == []


def test_pallas_kernel_registry_flags_untested_kernel(tmp_path):
    root = _mini_pallas_repo(
        tmp_path,
        test_body="def test_nothing():\n    pass\n",
        arch_body="| `_my_kernel_core` | streaming thing |\n")
    result = Analyzer(str(root), rules=[RULES["pallas-kernel-registry"]],
                      baseline=[]).run()
    assert [f.rule for f in result.findings] == ["pallas-kernel-registry"]
    assert "no registered parity test" in result.findings[0].message
    assert "_my_kernel_core" in result.findings[0].message


def test_pallas_kernel_registry_flags_missing_arch_row(tmp_path):
    root = _mini_pallas_repo(
        tmp_path,
        test_body="def test_parity():\n    assert my_kernel_wrapper\n",
        arch_body="# arch\n\nno kernel table here\n")
    result = Analyzer(str(root), rules=[RULES["pallas-kernel-registry"]],
                      baseline=[]).run()
    assert [f.rule for f in result.findings] == ["pallas-kernel-registry"]
    assert "Pallas kernel table" in result.findings[0].message


def test_pallas_kernel_registry_flags_empty_registry(tmp_path):
    """ops/pallas_score.py with every pallas_call gone = the registry
    this rule guards no longer exists; that is a finding, not silence."""
    root = _mini_pallas_repo(
        tmp_path,
        test_body="def test_parity():\n    assert my_kernel_wrapper\n",
        arch_body="| `_my_kernel_core` |\n")
    (root / "tpu_cooccurrence" / "ops" / "pallas_score.py").write_text(
        "def plain(x):\n    return x\n")
    result = Analyzer(str(root), rules=[RULES["pallas-kernel-registry"]],
                      baseline=[]).run()
    assert [f.rule for f in result.findings] == ["pallas-kernel-registry"]
    assert "no pallas_call entry points" in result.findings[0].message


def test_pallas_kernel_registry_scans_beyond_pallas_score(tmp_path):
    """The rule's scope is the whole package: a fused-sparse kernel that
    grew inside state/ (not ops/pallas_score.py) needs the same parity
    surface + ARCHITECTURE row — uncovered, it is two findings anchored
    at ITS file."""
    root = _mini_pallas_repo(
        tmp_path,
        test_body="def test_parity():\n    assert my_kernel_wrapper\n",
        arch_body="| `_my_kernel_core` | streaming thing |\n")
    state = root / "tpu_cooccurrence" / "state"
    state.mkdir()
    (state / "fused_sparse.py").write_text(
        "from jax.experimental import pallas as pl\n\n\n"
        "def _slab_decode_kernel(x):\n"
        "    return pl.pallas_call(None)(x)\n")
    result = Analyzer(str(root), rules=[RULES["pallas-kernel-registry"]],
                      baseline=[]).run()
    assert sorted(f.message.split("'")[1] for f in result.findings) == \
        ["_slab_decode_kernel", "_slab_decode_kernel"]
    assert all(f.file.endswith("state/fused_sparse.py")
               for f in result.findings)


def test_pallas_kernel_registry_survives_missing_anchor_file(tmp_path):
    """A vanished ops/pallas_score.py must not silently waive the rule:
    kernels elsewhere in the package are still checked, and a repo with
    no kernels at all yields the registry-gone finding."""
    root = _mini_pallas_repo(
        tmp_path,
        test_body="def test_nothing():\n    pass\n",
        arch_body="# arch\n")
    (root / "tpu_cooccurrence" / "ops" / "pallas_score.py").unlink()
    state = root / "tpu_cooccurrence" / "state"
    state.mkdir()
    (state / "fused_sparse.py").write_text(
        "from jax.experimental import pallas as pl\n\n\n"
        "def _slab_decode_kernel(x):\n"
        "    return pl.pallas_call(None)(x)\n")
    result = Analyzer(str(root), rules=[RULES["pallas-kernel-registry"]],
                      baseline=[]).run()
    assert len(result.findings) == 2  # untested + un-documented
    assert all("_slab_decode_kernel" in f.message for f in result.findings)
    # With that kernel gone too there is nothing to guard — and no
    # anchor file, so fixture repos for OTHER rules stay silent here
    # (the registry-gone finding needs ops/pallas_score.py to exist).
    (state / "fused_sparse.py").write_text("def plain(x):\n    return x\n")
    result = Analyzer(str(root), rules=[RULES["pallas-kernel-registry"]],
                      baseline=[]).run()
    assert result.findings == []


def test_pallas_kernel_registry_covers_out_of_tree_kernel_via_wrapper(
        tmp_path):
    """Same out-of-ops kernel, but with a same-module wrapper referenced
    from tests/ and an ARCHITECTURE row: clean — the one-hop wrapper
    contract applies uniformly across the package."""
    root = _mini_pallas_repo(
        tmp_path,
        test_body="def test_parity():\n    assert my_kernel_wrapper\n"
                  "def test_slab():\n    assert slab_decode\n",
        arch_body="| `_my_kernel_core` | x |\n| `_slab_decode_kernel` |\n")
    state = root / "tpu_cooccurrence" / "state"
    state.mkdir()
    (state / "fused_sparse.py").write_text(
        "from jax.experimental import pallas as pl\n\n\n"
        "def _slab_decode_kernel(x):\n"
        "    return pl.pallas_call(None)(x)\n\n\n"
        "def slab_decode(x):\n"
        "    return _slab_decode_kernel(x)\n")
    result = Analyzer(str(root), rules=[RULES["pallas-kernel-registry"]],
                      baseline=[]).run()
    assert result.findings == []


# -- rule pack 6b: fused fallback-reason registry -----------------------


def _mini_fallback_repo(tmp_path, *, scorer_body, arch_body, test_body):
    """A minimal repo for the fused-fallback-registry rule: the sharded
    scorer with _fallback_chained call sites, the ARCHITECTURE fallback
    table, and a test asserting the reason literals."""
    root = tmp_path / "repo"
    par = root / "tpu_cooccurrence" / "parallel"
    par.mkdir(parents=True)
    (par / "sharded_sparse.py").write_text(scorer_body)
    (root / "docs").mkdir()
    (root / "docs" / "ARCHITECTURE.md").write_text(arch_body)
    (root / "tests").mkdir()
    (root / "tests" / "test_fallback_fixture.py").write_text(test_body)
    return root


_FALLBACK_SCORER = (
    "class S:\n"
    "    def _fallback_chained(self, reason):\n"
    "        self.last_fallback_reason = reason\n\n"
    "    def window(self, cold):\n"
    "        if cold:\n"
    "            self._fallback_chained('plan-rebuild')\n")


def test_fused_fallback_registry_documented_and_tested_passes(tmp_path):
    root = _mini_fallback_repo(
        tmp_path,
        scorer_body=_FALLBACK_SCORER,
        arch_body="| `plan-rebuild` | cold plans |\n",
        test_body="def test_cold():\n"
                  "    assert reason == 'plan-rebuild'\n")
    result = Analyzer(str(root), rules=[RULES["fused-fallback-registry"]],
                      baseline=[]).run()
    assert result.findings == []


def test_fused_fallback_registry_flags_undocumented_reason(tmp_path):
    """A reason absent from the ARCHITECTURE fallback table is a
    finding; prose mentioning the bare word does not count — the table
    quotes reasons backticked."""
    root = _mini_fallback_repo(
        tmp_path,
        scorer_body=_FALLBACK_SCORER,
        arch_body="plans rebuild after a plan-rebuild window\n",  # prose
        test_body="def test_cold():\n"
                  "    assert reason == 'plan-rebuild'\n")
    result = Analyzer(str(root), rules=[RULES["fused-fallback-registry"]],
                      baseline=[]).run()
    assert [f.rule for f in result.findings] == ["fused-fallback-registry"]
    assert "fallback table" in result.findings[0].message
    assert "plan-rebuild" in result.findings[0].message


def test_fused_fallback_registry_flags_untested_reason(tmp_path):
    root = _mini_fallback_repo(
        tmp_path,
        scorer_body=_FALLBACK_SCORER,
        arch_body="| `plan-rebuild` | cold plans |\n",
        test_body="def test_nothing():\n    pass\n")
    result = Analyzer(str(root), rules=[RULES["fused-fallback-registry"]],
                      baseline=[]).run()
    assert [f.rule for f in result.findings] == ["fused-fallback-registry"]
    assert "never asserted under tests/" in result.findings[0].message


def test_fused_fallback_registry_flags_dynamic_reason(tmp_path):
    """A non-literal reason defeats static registry checking and is a
    finding at the call site."""
    root = _mini_fallback_repo(
        tmp_path,
        scorer_body=("class S:\n"
                     "    def window(self, why):\n"
                     "        self._fallback_chained(why)\n"),
        arch_body="| `plan-rebuild` |\n",
        test_body="def test_nothing():\n    pass\n")
    result = Analyzer(str(root), rules=[RULES["fused-fallback-registry"]],
                      baseline=[]).run()
    assert [f.rule for f in result.findings] == ["fused-fallback-registry"]
    assert "not a string literal" in result.findings[0].message


def test_fused_fallback_registry_flags_gone_registry(tmp_path):
    """The sharded scorer defining _fallback_chained with zero call
    sites = the fallback taxonomy this rule guards is gone; other
    fixture repos (no sharded_sparse.py) stay silent."""
    root = _mini_fallback_repo(
        tmp_path,
        scorer_body=("class S:\n"
                     "    def _fallback_chained(self, reason):\n"
                     "        pass\n"),
        arch_body="| `plan-rebuild` |\n",
        test_body="def test_nothing():\n    pass\n")
    result = Analyzer(str(root), rules=[RULES["fused-fallback-registry"]],
                      baseline=[]).run()
    assert [f.rule for f in result.findings] == ["fused-fallback-registry"]
    assert "registry this rule guards is gone" in result.findings[0].message
    # No _fallback_chained anywhere at all -> silence (fixture repos for
    # other rules are not fallback registries).
    (root / "tpu_cooccurrence" / "parallel" / "sharded_sparse.py"
     ).write_text("def plain(x):\n    return x\n")
    result = Analyzer(str(root), rules=[RULES["fused-fallback-registry"]],
                      baseline=[]).run()
    assert result.findings == []


def test_fused_fallback_registry_flags_missing_arch(tmp_path):
    """A vanished ARCHITECTURE.md is a finding, not a silent waiver of
    the doc half of the registry."""
    root = _mini_fallback_repo(
        tmp_path,
        scorer_body=_FALLBACK_SCORER,
        arch_body="| `plan-rebuild` |\n",
        test_body="def test_cold():\n"
                  "    assert reason == 'plan-rebuild'\n")
    (root / "docs" / "ARCHITECTURE.md").unlink()
    result = Analyzer(str(root), rules=[RULES["fused-fallback-registry"]],
                      baseline=[]).run()
    assert [f.rule for f in result.findings] == ["fused-fallback-registry"]
    assert "not found" in result.findings[0].message


# -- rule pack 8: serving route registry --------------------------------


def _mini_serving_repo(tmp_path, *, http_body, readme_body, test_body):
    """A minimal repo for the serving-route rule: the http module with a
    ROUTE_METRICS table plus README and tests/ to reference routes."""
    root = tmp_path / "repo"
    obs = root / "tpu_cooccurrence" / "observability"
    obs.mkdir(parents=True)
    (obs / "http.py").write_text(http_body)
    (root / "README.md").write_text(readme_body)
    (root / "tests").mkdir()
    (root / "tests" / "test_routes_fixture.py").write_text(test_body)
    return root


_GOOD_HTTP = (
    'ROUTE_METRICS = {\n'
    '    "/metrics": "cooc_scrape_seconds",\n'
    '    "/healthz": "cooc_healthz_seconds",\n'
    '    "/recommend": "cooc_query_seconds",\n'
    '}\n')


def test_serving_route_clean_repo_passes(tmp_path):
    root = _mini_serving_repo(
        tmp_path, http_body=_GOOD_HTTP,
        readme_body="curl /metrics /healthz /recommend\n",
        test_body='ROUTES = ["/metrics", "/healthz", "/recommend"]\n')
    result = Analyzer(str(root), rules=[RULES["serving-route"]],
                      baseline=[]).run()
    assert result.findings == []


def test_serving_route_flags_unregistered_metric_and_missing_refs(tmp_path):
    http = (
        'ROUTE_METRICS = {\n'
        '    "/newroute": "cooc_bogus_seconds",\n'
        '}\n')
    root = _mini_serving_repo(
        tmp_path, http_body=http,
        readme_body="nothing here\n",
        test_body="def test_nothing():\n    pass\n")
    result = Analyzer(str(root), rules=[RULES["serving-route"]],
                      baseline=[]).run()
    msgs = [f.message for f in result.findings]
    assert any("cooc_bogus_seconds" in m and "CANONICAL_METRICS" in m
               for m in msgs)
    assert any("README" in m for m in msgs)
    assert any("tests/ reference" in m for m in msgs)


def test_serving_route_flags_unlisted_route_literal(tmp_path):
    http = _GOOD_HTTP + (
        '\n\ndef do_GET(path):\n'
        '    if path == "/secret":\n'
        '        return "ok"\n')
    root = _mini_serving_repo(
        tmp_path, http_body=http,
        readme_body="/metrics /healthz /recommend\n",
        test_body='R = ["/metrics", "/healthz", "/recommend"]\n')
    result = Analyzer(str(root), rules=[RULES["serving-route"]],
                      baseline=[]).run()
    assert [f.rule for f in result.findings] == ["serving-route"]
    assert "/secret" in result.findings[0].message


def test_serving_route_flags_vanished_table(tmp_path):
    root = _mini_serving_repo(
        tmp_path, http_body="def handler():\n    return 404\n",
        readme_body="x\n", test_body="y = 1\n")
    result = Analyzer(str(root), rules=[RULES["serving-route"]],
                      baseline=[]).run()
    assert [f.rule for f in result.findings] == ["serving-route"]
    assert "ROUTE_METRICS" in result.findings[0].message


# -- rule pack 9: state-store registry ----------------------------------


def _mini_store_repo(tmp_path, *, test_body, arch_body):
    """A minimal repo for the state-store-registry rule: the base class
    plus one direct subclass and one transitive subclass."""
    root = tmp_path / "repo"
    state = root / "tpu_cooccurrence" / "state"
    state.mkdir(parents=True)
    (state / "store.py").write_text(
        "class StateStore:\n"
        "    def checkpoint_state(self):\n"
        "        raise NotImplementedError\n\n\n"
        "class MyDirectStore(StateStore):\n"
        "    pass\n\n\n"
        "class MyTieredStore(MyDirectStore):\n"
        "    pass\n")
    (root / "tests").mkdir()
    (root / "tests" / "test_store_fixture.py").write_text(test_body)
    (root / "docs").mkdir()
    (root / "docs" / "ARCHITECTURE.md").write_text(arch_body)
    return root


def test_state_store_registry_clean_fixture_passes(tmp_path):
    """Both stores referenced from tests/ (the transitive subclass
    counts as an implementation too) and in the ARCHITECTURE table."""
    root = _mini_store_repo(
        tmp_path,
        test_body=("def test_round_trip():\n"
                   "    assert MyDirectStore and MyTieredStore\n"),
        arch_body=("| `MyDirectStore` | direct |\n"
                   "| `MyTieredStore` | tiered |\n"))
    result = Analyzer(str(root), rules=[RULES["state-store-registry"]],
                      baseline=[]).run()
    assert result.findings == []


def test_state_store_registry_flags_untested_store(tmp_path):
    root = _mini_store_repo(
        tmp_path,
        test_body="def test_round_trip():\n    assert MyDirectStore\n",
        arch_body=("| `MyDirectStore` | direct |\n"
                   "| `MyTieredStore` | tiered |\n"))
    result = Analyzer(str(root), rules=[RULES["state-store-registry"]],
                      baseline=[]).run()
    assert [f.rule for f in result.findings] == ["state-store-registry"]
    assert "MyTieredStore" in result.findings[0].message
    assert "round-trip" in result.findings[0].message


def test_state_store_registry_flags_missing_arch_row(tmp_path):
    root = _mini_store_repo(
        tmp_path,
        test_body=("def test_round_trip():\n"
                   "    assert MyDirectStore and MyTieredStore\n"),
        arch_body="# arch\n\nno state-store table here\n")
    result = Analyzer(str(root), rules=[RULES["state-store-registry"]],
                      baseline=[]).run()
    assert sorted(f.rule for f in result.findings) == [
        "state-store-registry", "state-store-registry"]
    assert all("state-store table" in f.message for f in result.findings)


def test_state_store_registry_flags_vanished_arch_doc(tmp_path):
    """A missing docs/ARCHITECTURE.md is a finding in its own right,
    not a silent waiver of the doc requirement for every store (same
    posture as the serving rule's vanished ROUTE_METRICS table)."""
    root = _mini_store_repo(
        tmp_path,
        test_body=("def test_round_trip():\n"
                   "    assert MyDirectStore and MyTieredStore\n"),
        arch_body="x\n")
    os.remove(root / "docs" / "ARCHITECTURE.md")
    result = Analyzer(str(root), rules=[RULES["state-store-registry"]],
                      baseline=[]).run()
    assert [f.rule for f in result.findings] == ["state-store-registry"]
    assert "ARCHITECTURE.md not found" in result.findings[0].message


def test_state_store_registry_flags_empty_registry(tmp_path):
    """state/store.py with every implementation gone = the registry this
    rule guards no longer exists; that is a finding, not silence."""
    root = _mini_store_repo(
        tmp_path, test_body="x = 1\n", arch_body="# arch\n")
    (root / "tpu_cooccurrence" / "state" / "store.py").write_text(
        "class StateStore:\n    pass\n")
    result = Analyzer(str(root), rules=[RULES["state-store-registry"]],
                      baseline=[]).run()
    assert [f.rule for f in result.findings] == ["state-store-registry"]
    assert "registry" in result.findings[0].message


# -- rule pack 10: checkpoint-format round trip -------------------------


_OK_DELTA_BODY = ("def encode():\n"
                  "    header = {\"gen\": 1}\n\n\n"
                  "def decode(header):\n"
                  "    return header[\"gen\"]\n")


def _mini_ckpt_repo(tmp_path, *, ckpt_body, delta_body=_OK_DELTA_BODY,
                    test_body="x = 1\n"):
    root = tmp_path / "repo"
    state = root / "tpu_cooccurrence" / "state"
    state.mkdir(parents=True)
    (state / "checkpoint.py").write_text(ckpt_body)
    (state / "delta.py").write_text(delta_body)
    (root / "tests").mkdir()
    (root / "tests" / "test_fmt_fixture.py").write_text(test_body)
    return root


def test_ckpt_format_clean_fixture_passes(tmp_path):
    root = _mini_ckpt_repo(
        tmp_path,
        ckpt_body=("def save():\n"
                   "    meta = {\"windows\": 1}\n"
                   "    meta[\"extra\"] = 2\n\n\n"
                   "def restore(meta):\n"
                   "    return meta[\"windows\"], meta.get(\"extra\")\n"),
        delta_body=("def encode():\n"
                    "    header = {\"gen\": 1}\n\n\n"
                    "def decode(header):\n"
                    "    return header[\"gen\"]\n"),
        test_body=("KEYS = {\"windows\", \"extra\", \"gen\"}\n"))
    result = Analyzer(str(root), rules=[RULES["ckpt-format-roundtrip"]],
                      baseline=[]).run()
    assert result.findings == []


def test_ckpt_format_flags_writer_only_field(tmp_path):
    """A meta key with no restore-side read is silent format drift."""
    root = _mini_ckpt_repo(
        tmp_path,
        ckpt_body=("def save():\n"
                   "    meta = {\"windows\": 1, \"orphan\": 2}\n\n\n"
                   "def restore(meta):\n"
                   "    return meta[\"windows\"]\n"),
        test_body="KEYS = {\"windows\", \"orphan\", \"gen\"}\n")
    result = Analyzer(str(root), rules=[RULES["ckpt-format-roundtrip"]],
                      baseline=[]).run()
    assert [f.rule for f in result.findings] == ["ckpt-format-roundtrip"]
    assert "'orphan'" in result.findings[0].message
    assert "never read back" in result.findings[0].message


def test_ckpt_format_flags_untested_field(tmp_path):
    root = _mini_ckpt_repo(
        tmp_path,
        ckpt_body=("def save():\n"
                   "    meta = {\"windows\": 1}\n\n\n"
                   "def restore(meta):\n"
                   "    return meta[\"windows\"]\n"),
        test_body="KEYS = {\"gen\"}\n")
    result = Analyzer(str(root), rules=[RULES["ckpt-format-roundtrip"]],
                      baseline=[]).run()
    assert [f.rule for f in result.findings] == ["ckpt-format-roundtrip"]
    assert "round-trip reference" in result.findings[0].message


def test_ckpt_format_flags_vanished_module(tmp_path):
    """A format module going missing is a finding in its own right, not
    a silent waiver (same posture as the other registry rules)."""
    root = _mini_ckpt_repo(
        tmp_path,
        ckpt_body=("def save():\n"
                   "    meta = {\"windows\": 1}\n\n\n"
                   "def restore(meta):\n"
                   "    return meta[\"windows\"]\n"),
        test_body="KEYS = {\"windows\"}\n")
    os.remove(root / "tpu_cooccurrence" / "state" / "delta.py")
    result = Analyzer(str(root), rules=[RULES["ckpt-format-roundtrip"]],
                      baseline=[]).run()
    msgs = [f.message for f in result.findings
            if f.rule == "ckpt-format-roundtrip"]
    assert any("missing" in m for m in msgs)


def test_ckpt_format_flags_empty_key_registry(tmp_path):
    """A checkpoint.py that no longer builds a meta dict means the
    registry this rule guards moved — finding, not silence."""
    root = _mini_ckpt_repo(
        tmp_path, ckpt_body="def save():\n    pass\n",
        delta_body=("def encode():\n"
                    "    header = {\"gen\": 1}\n\n\n"
                    "def decode(header):\n"
                    "    return header[\"gen\"]\n"),
        test_body="KEYS = {\"gen\"}\n")
    result = Analyzer(str(root), rules=[RULES["ckpt-format-roundtrip"]],
                      baseline=[]).run()
    assert [f.rule for f in result.findings] == ["ckpt-format-roundtrip"]
    assert "no format keys" in result.findings[0].message


def test_ckpt_format_rule_clean_on_repo():
    """The real repo is clean under the rule (baseline-free contract)."""
    result = Analyzer(REPO, rules=[RULES["ckpt-format-roundtrip"]],
                      baseline=[]).run()
    assert result.findings == []


# -- collective-watchdog / gang-fault-sites (rules_gang) ----------------


def test_collective_watchdog_flags_raw_collectives():
    bad = '''
from jax.experimental import multihost_utils

def exchange(vec):
    lens = multihost_utils.process_allgather(vec)
    multihost_utils.sync_global_devices("x")
    return lens
'''
    findings = analyze_source(
        bad, path="tpu_cooccurrence/sampling/multihost.py",
        rules=["collective-watchdog"])
    assert _rules(findings) == ["collective-watchdog"]
    assert {f.line for f in findings} == {5, 6}


def test_collective_watchdog_flags_bare_imported_call():
    bad = ('from jax.experimental.multihost_utils import '
           'process_allgather\n'
           'def f(v):\n'
           '    return process_allgather(v)\n')
    findings = analyze_source(
        bad, path="tpu_cooccurrence/parallel/sharded.py",
        rules=["collective-watchdog"])
    assert _rules(findings) == ["collective-watchdog"]


def test_collective_watchdog_allows_wrappers_and_wrapper_module():
    good = '''
from tpu_cooccurrence.parallel.distributed import (
    gang_barrier, guarded_allgather)

def exchange(vec):
    gang_barrier("x")
    return guarded_allgather(vec)
'''
    assert analyze_source(
        good, path="tpu_cooccurrence/sampling/multihost.py",
        rules=["collective-watchdog"]) == []
    # The wrapper module itself is the one allowed caller.
    raw = ('from jax.experimental import multihost_utils\n'
           'def g(a):\n'
           '    return multihost_utils.process_allgather(a)\n')
    assert analyze_source(
        raw, path="tpu_cooccurrence/parallel/distributed.py",
        rules=["collective-watchdog"]) == []


def test_gang_fault_sites_rule_clean_on_repo():
    result = Analyzer(REPO, rules=[RULES["gang-fault-sites"]],
                      baseline=[]).run()
    assert result.findings == []


def test_gang_fault_sites_flags_unfired_site(tmp_path):
    """A faults.py present but no package code firing a GANG_SITES
    member = a finding (the chaos specs can no longer trigger)."""
    root = tmp_path / "repo"
    pkg = root / "tpu_cooccurrence" / "robustness"
    pkg.mkdir(parents=True)
    (pkg / "faults.py").write_text("SITES = {}\n")
    result = Analyzer(str(root), rules=[RULES["gang-fault-sites"]],
                      baseline=[]).run()
    # Every gang site is unplugged in this mini-repo.
    from tpu_cooccurrence.robustness.gang import GANG_SITES

    assert len(result.findings) == len(GANG_SITES)
    assert all(f.rule == "gang-fault-sites" for f in result.findings)


# -- rule pack: serving fleet (replica routes + generation tag) --------


def _mini_fleet_repo(tmp_path, replica_body, http_body=None):
    """Mini repo with a registered route table, its docs/tests
    obligations satisfied, and a replica module under test."""
    obs = tmp_path / "tpu_cooccurrence" / "observability"
    obs.mkdir(parents=True)
    (obs / "http.py").write_text(
        http_body if http_body is not None else
        'ROUTE_METRICS = {"/metrics": "cooc_scrape_seconds"}\n\n\n'
        "class MetricsServer:\n"
        "    def recommend(self, query):\n"
        '        return 200, {"generation": 1}\n')
    serving = tmp_path / "tpu_cooccurrence" / "serving"
    serving.mkdir()
    (serving / "replica.py").write_text(replica_body)
    (tmp_path / "README.md").write_text("Routes: /metrics\n")
    tests = tmp_path / "tests"
    tests.mkdir()
    (tests / "test_routes.py").write_text('URL = "/metrics"\n')
    return tmp_path


def test_serving_route_rule_flags_replica_only_route(tmp_path):
    """A route-shaped literal the replica module quotes that is not in
    observability/http.py ROUTE_METRICS is an unmeasured endpoint."""
    root = _mini_fleet_repo(
        tmp_path,
        "from ..observability.http import MetricsServer\n\n\n"
        "class ReplicaServer(MetricsServer):\n"
        "    pass\n\n\n"
        "def sneaky(handler):\n"
        '    handler.route("/sneaky")\n')
    result = Analyzer(str(root), rules=[RULES["serving-route"]],
                      baseline=[]).run()
    assert len(result.findings) == 1
    f = result.findings[0]
    assert f.file.endswith("serving/replica.py")
    assert "/sneaky" in f.message and "ROUTE_METRICS" in f.message
    # Registered routes quoted by the replica are fine.
    root2 = _mini_fleet_repo(
        tmp_path / "clean",
        "from ..observability.http import MetricsServer\n\n\n"
        "class ReplicaServer(MetricsServer):\n"
        "    pass\n\n\n"
        'PROBE = "/metrics"\n')
    result = Analyzer(str(root2), rules=[RULES["serving-route"]],
                      baseline=[]).run()
    assert result.findings == []


def test_replica_generation_tag_inherited_body_is_clean():
    src = ("from ..observability.http import MetricsServer\n\n\n"
           "class ReplicaServer(MetricsServer):\n"
           "    pass\n")
    assert analyze_source(
        src, path="tpu_cooccurrence/serving/replica.py",
        rules=["replica-generation-tag"]) == []


def test_replica_generation_tag_flags_untagged_override():
    src = ("from ..observability.http import MetricsServer\n\n\n"
           "class ReplicaServer(MetricsServer):\n"
           "    def recommend(self, query):\n"
           '        return 200, {"items": []}\n')
    found = analyze_source(
        src, path="tpu_cooccurrence/serving/replica.py",
        rules=["replica-generation-tag"])
    assert len(found) == 1
    assert "generation" in found[0].message
    # The same override carrying the tag is clean.
    src_ok = src.replace('{"items": []}',
                         '{"items": [], "generation": 1}')
    assert analyze_source(
        src_ok, path="tpu_cooccurrence/serving/replica.py",
        rules=["replica-generation-tag"]) == []


def test_replica_generation_tag_requires_metricsserver_subclass():
    src = ("class LoneServer:\n"
           "    def recommend(self, query):\n"
           '        return 200, {"generation": 1}\n')
    found = analyze_source(
        src, path="tpu_cooccurrence/serving/replica.py",
        rules=["replica-generation-tag"])
    assert len(found) == 1
    assert "MetricsServer subclass" in found[0].message


def test_replica_generation_tag_flags_untagged_inherited_body(tmp_path):
    """No override: the obligation lands on the inherited
    observability/http.py recommend body."""
    root = _mini_fleet_repo(
        tmp_path,
        "from ..observability.http import MetricsServer\n\n\n"
        "class ReplicaServer(MetricsServer):\n"
        "    pass\n",
        http_body=(
            'ROUTE_METRICS = {"/metrics": "cooc_scrape_seconds"}\n\n\n'
            "class MetricsServer:\n"
            "    def recommend(self, query):\n"
            '        return 200, {"items": []}\n'))
    result = Analyzer(str(root), rules=[RULES["replica-generation-tag"]],
                      baseline=[]).run()
    assert len(result.findings) == 1
    assert result.findings[0].file.endswith("observability/http.py")
    assert "generation" in result.findings[0].message


def test_replica_generation_tag_silent_without_replica_module():
    """Fixture repos for other rules (no serving/replica.py) must not
    trip this rule."""
    assert analyze_source(
        "X = 1\n", path="tpu_cooccurrence/other.py",
        rules=["replica-generation-tag"]) == []


# -- rule pack 12: scale-policy registry ---------------------------------


def _mini_policy_repo(tmp_path, *, test_body, arch_body):
    """A minimal repo for the scale-policy-registry rule: the base
    class plus one direct subclass and one transitive subclass."""
    root = tmp_path / "repo"
    rob = root / "tpu_cooccurrence" / "robustness"
    rob.mkdir(parents=True)
    (rob / "autoscale.py").write_text(
        "class ScalePolicy:\n"
        "    def decide(self, *a):\n"
        "        raise NotImplementedError\n\n\n"
        "class MyLadderPolicy(ScalePolicy):\n"
        "    pass\n\n\n"
        "class MySteppedPolicy(MyLadderPolicy):\n"
        "    pass\n")
    (root / "tests").mkdir()
    (root / "tests" / "test_policy_fixture.py").write_text(test_body)
    (root / "docs").mkdir()
    (root / "docs" / "ARCHITECTURE.md").write_text(arch_body)
    return root


def test_scale_policy_registry_clean_fixture_passes(tmp_path):
    root = _mini_policy_repo(
        tmp_path,
        test_body=("def test_hysteresis():\n"
                   "    assert MyLadderPolicy and MySteppedPolicy\n"),
        arch_body=("| `MyLadderPolicy` | ladder |\n"
                   "| `MySteppedPolicy` | stepped |\n"))
    result = Analyzer(str(root), rules=[RULES["scale-policy-registry"]],
                      baseline=[]).run()
    assert result.findings == []


def test_scale_policy_registry_flags_untested_policy(tmp_path):
    root = _mini_policy_repo(
        tmp_path,
        test_body="def test_hysteresis():\n    assert MyLadderPolicy\n",
        arch_body=("| `MyLadderPolicy` | ladder |\n"
                   "| `MySteppedPolicy` | stepped |\n"))
    result = Analyzer(str(root), rules=[RULES["scale-policy-registry"]],
                      baseline=[]).run()
    assert [f.rule for f in result.findings] == ["scale-policy-registry"]
    assert "MySteppedPolicy" in result.findings[0].message
    assert "hysteresis" in result.findings[0].message


def test_scale_policy_registry_flags_missing_arch_row(tmp_path):
    root = _mini_policy_repo(
        tmp_path,
        test_body=("def test_hysteresis():\n"
                   "    assert MyLadderPolicy and MySteppedPolicy\n"),
        arch_body="# arch\n\nno scale-policy table here\n")
    result = Analyzer(str(root), rules=[RULES["scale-policy-registry"]],
                      baseline=[]).run()
    assert sorted(f.rule for f in result.findings) == [
        "scale-policy-registry", "scale-policy-registry"]
    assert all("scale-policy table" in f.message
               for f in result.findings)


def test_scale_policy_registry_flags_vanished_arch_doc(tmp_path):
    root = _mini_policy_repo(
        tmp_path,
        test_body=("def test_hysteresis():\n"
                   "    assert MyLadderPolicy and MySteppedPolicy\n"),
        arch_body="x\n")
    os.remove(root / "docs" / "ARCHITECTURE.md")
    result = Analyzer(str(root), rules=[RULES["scale-policy-registry"]],
                      baseline=[]).run()
    assert [f.rule for f in result.findings] == ["scale-policy-registry"]
    assert "ARCHITECTURE.md not found" in result.findings[0].message


def test_scale_policy_registry_flags_empty_registry(tmp_path):
    root = _mini_policy_repo(
        tmp_path, test_body="x = 1\n", arch_body="x\n")
    (root / "tpu_cooccurrence" / "robustness" / "autoscale.py"
     ).write_text("class ScalePolicy:\n    pass\n")
    result = Analyzer(str(root), rules=[RULES["scale-policy-registry"]],
                      baseline=[]).run()
    assert [f.rule for f in result.findings] == ["scale-policy-registry"]
    assert "registry this rule guards is gone" in result.findings[0].message


def test_scale_policy_registry_silent_without_autoscale_module():
    """Fixture repos for other rules must not trip this rule."""
    assert analyze_source(
        "X = 1\n", path="tpu_cooccurrence/other.py",
        rules=["scale-policy-registry"]) == []


# ---------------------------------------------------------------------------
# journal-schema-registry (ISSUE 17): every journal-emitted key must be
# in the schema tables, the ARCHITECTURE journal table, and tests/


def test_journal_registry_flags_unregistered_key():
    src = (
        "class J:\n"
        "    def emit(self):\n"
        "        self.journal.record({'v': 1, 'seq': 1,\n"
        "                             'warp_factor': 9})\n"
    )
    findings = analyze_source(src, path="tpu_cooccurrence/fixmod.py",
                              rules=["journal-schema-registry"])
    assert [f.rule for f in findings] == ["journal-schema-registry"]
    assert "warp_factor" in findings[0].message
    assert "*_SCHEMA" in findings[0].message


def test_journal_registry_sees_through_stamp_and_name_args():
    """The writers pass dict literals through a stamping wrapper or
    build the record incrementally (``rec = {...}; rec["k"] = ...``) —
    the collector must see every shape."""
    wrapped = (
        "class J:\n"
        "    def emit(self):\n"
        "        self.journal.record(self._stamp({'v': 1,\n"
        "                                         'bogus_a': 1}))\n"
    )
    findings = analyze_source(wrapped, path="tpu_cooccurrence/fm.py",
                              rules=["journal-schema-registry"])
    assert ["bogus_a" in f.message for f in findings] == [True]
    built = (
        "class J:\n"
        "    def emit(self):\n"
        "        rec = {'v': 1, 'seq': 1}\n"
        "        rec['bogus_b'] = 2\n"
        "        self.journal.record(self._stamp(rec))\n"
    )
    findings = analyze_source(built, path="tpu_cooccurrence/fm.py",
                              rules=["journal-schema-registry"])
    assert ["bogus_b" in f.message for f in findings] == [True]


def test_journal_registry_docs_and_tests_legs(tmp_path):
    """With docs/ and tests/ trees present, a registered-but-
    undocumented / untested key is flagged on those legs too."""
    root = tmp_path / "repo"
    (root / "tpu_cooccurrence").mkdir(parents=True)
    (root / "docs").mkdir()
    (root / "tests").mkdir()
    (root / "tpu_cooccurrence" / "writer.py").write_text(
        "class J:\n"
        "    def emit(self):\n"
        "        self.journal.record({'v': 1, 'seq': 1})\n")
    # `v` documented + tested; `seq` neither.
    (root / "docs" / "ARCHITECTURE.md").write_text(
        "| `v` | version |\n")
    (root / "tests" / "test_x.py").write_text("K = 'v'\n")
    result = Analyzer(str(root),
                      rules=[RULES["journal-schema-registry"]],
                      baseline=[]).run()
    msgs = sorted(f.message for f in result.findings)
    assert len(msgs) == 2
    assert all("'seq'" in m for m in msgs)
    assert any("undocumented" in m for m in msgs)
    assert any("no tests/ reference" in m for m in msgs)


def test_journal_registry_silent_without_writers():
    """Fixture repos for other rules must not trip this rule."""
    assert analyze_source(
        "X = 1\n", path="tpu_cooccurrence/other.py",
        rules=["journal-schema-registry"]) == []


def test_journal_registry_clean_on_repo():
    """The real writers, schema tables, ARCHITECTURE journal table and
    tests/ registry are in sync right now."""
    result = Analyzer(REPO, rules=[RULES["journal-schema-registry"]],
                      baseline=[]).run()
    assert result.findings == []


# -- rule pack: ingest offset-codec registry (ISSUE 18) -----------------


_OK_FILES_SRC = ("def offsets_state(self):\n"
                 "    in_flight = {\"path\": self.p}\n"
                 "    offsets = {\"v\": 1, \"in_flight\": in_flight}\n"
                 "    return offsets\n\n\n"
                 "def restore_offsets(self, state):\n"
                 "    self.v = state.get(\"v\")\n"
                 "    guard = state.get(\"in_flight\")\n"
                 "    self.p = guard[\"path\"]\n")

_OK_PART_SRC = ("def offsets_state(self):\n"
                "    partitions = {}\n"
                "    partitions[name] = {\"byte_offset\": 0}\n"
                "    offsets = {\"v\": 1, \"partitions\": partitions}\n"
                "    return offsets\n\n\n"
                "def restore_offsets(self, state):\n"
                "    self.v = state.get(\"v\")\n"
                "    for e in state[\"partitions\"].values():\n"
                "        self.b = e[\"byte_offset\"]\n")


def _mini_ingest_repo(tmp_path, *, files_src=_OK_FILES_SRC,
                      part_src=_OK_PART_SRC, test_body="x = 1\n"):
    root = tmp_path / "repo"
    io_dir = root / "tpu_cooccurrence" / "io"
    io_dir.mkdir(parents=True)
    (io_dir / "source.py").write_text(files_src)
    (io_dir / "partitioned.py").write_text(part_src)
    (root / "tests").mkdir()
    (root / "tests" / "test_ingest_fixture.py").write_text(test_body)
    return root


def test_ingest_registry_clean_fixture_passes(tmp_path):
    root = _mini_ingest_repo(
        tmp_path,
        test_body=("KEYS = {\"v\", \"in_flight\", \"path\", "
                   "\"partitions\", \"byte_offset\"}\n"))
    result = Analyzer(str(root), rules=[RULES["ingest-offset-registry"]],
                      baseline=[]).run()
    assert result.findings == []


def test_ingest_registry_flags_writer_only_key(tmp_path):
    """An offset field with no restore-side reader silently stops
    steering where the wire resumes — the drift this rule exists for."""
    root = _mini_ingest_repo(
        tmp_path,
        files_src=("def offsets_state(self):\n"
                   "    offsets = {\"v\": 1, \"orphan\": 2}\n"
                   "    return offsets\n\n\n"
                   "def restore_offsets(self, state):\n"
                   "    self.v = state.get(\"v\")\n"),
        test_body="KEYS = {\"v\", \"orphan\", \"partitions\", "
                  "\"byte_offset\"}\n")
    msgs = [f.message for f in Analyzer(
        str(root), rules=[RULES["ingest-offset-registry"]],
        baseline=[]).run().findings]
    assert any("'orphan'" in m and "never read back" in m for m in msgs)
    # The healthy partitioned module contributed no findings.
    assert not any("byte_offset" in m for m in msgs)


def test_ingest_registry_flags_untested_key(tmp_path):
    root = _mini_ingest_repo(
        tmp_path,
        test_body="KEYS = {\"v\", \"in_flight\", \"path\", "
                  "\"partitions\"}\n")  # byte_offset missing
    msgs = [f.message for f in Analyzer(
        str(root), rules=[RULES["ingest-offset-registry"]],
        baseline=[]).run().findings]
    assert len(msgs) == 1
    assert "'byte_offset'" in msgs[0]
    assert "round-trip reference" in msgs[0]
    assert "test_ingest_offsets.py" in msgs[0]


def test_ingest_registry_flags_vanished_module(tmp_path):
    """One end of the codec going missing is a finding (the other
    module is still present, so the scope guard does not waive it)."""
    root = _mini_ingest_repo(
        tmp_path,
        test_body=("KEYS = {\"v\", \"in_flight\", \"path\", "
                   "\"partitions\", \"byte_offset\"}\n"))
    os.remove(root / "tpu_cooccurrence" / "io" / "partitioned.py")
    msgs = [f.message for f in Analyzer(
        str(root), rules=[RULES["ingest-offset-registry"]],
        baseline=[]).run().findings]
    assert any("missing" in m for m in msgs)


def test_ingest_registry_silent_without_ingest_modules():
    """Fixture repos for other rules must not trip this rule."""
    assert analyze_source(
        "offsets = {\"v\": 1}\n", path="tpu_cooccurrence/other.py",
        rules=["ingest-offset-registry"]) == []


def test_ingest_registry_clean_on_repo():
    """The real sources, their restore paths and the
    tests/test_ingest_offsets.py registry are in sync right now."""
    result = Analyzer(REPO, rules=[RULES["ingest-offset-registry"]],
                      baseline=[]).run()
    assert result.findings == []


# ---------------------------------------------------------------------------
# thread-ownership rule (whole-program graph, PR 19)

PR2_THREAD_RACE = '''
import threading

class TransferLedger:
    def __init__(self):
        self.h2d_bytes = 0
        self.h2d_calls = 0

    def add(self, n):
        self.h2d_bytes += n
        self.h2d_calls += 1

def scorer_worker(ledger):
    ledger.h2d_bytes += 4

def main():
    ledger = TransferLedger()
    threading.Thread(target=scorer_worker, name="scorer").start()
    ledger.add(3)
'''


def test_thread_ownership_rediscovers_pr2_ledger_race():
    """The pre-fix PR-2 shape, no class list involved: the spawned
    scorer worker and the main thread both write the ledger's byte
    totals with no lock — derived purely from the call graph's thread
    roots."""
    findings = analyze_source(PR2_THREAD_RACE,
                              rules=["thread-ownership"])
    assert len(findings) == 1
    f = findings[0]
    assert "TransferLedger.h2d_bytes" in f.message
    assert "scorer" in f.message and "main" in f.message
    # Anchored on the spawned-writer side (the actionable site).
    assert f.line == 14


PR2_COUNTERS_RACE = '''
import threading

class Counters:
    def __init__(self):
        self._counts = {}

    def increment(self, key):
        self._counts[key] = self._counts.get(key, 0) + 1

    def merge(self, other):
        for k, v in other._counts.items():
            self._counts[k] = self._counts.get(k, 0) + v

def scorer_worker(counters):
    counters.increment("windows_scored")

def main():
    counters = Counters()
    threading.Thread(target=scorer_worker).start()
    counters.merge(Counters())
'''


def test_thread_ownership_rediscovers_pr2_counters_race():
    """The second PR-2 race: the worker folds counts into the shared
    Counters while the main thread's merge rewrites the same dict."""
    findings = analyze_source(PR2_COUNTERS_RACE,
                              rules=["thread-ownership"])
    assert len(findings) == 1
    assert "Counters._counts" in findings[0].message


def test_thread_ownership_lock_and_annotation_exempt():
    locked = PR2_THREAD_RACE.replace(
        "    ledger.h2d_bytes += 4",
        "    with ledger._lock:\n        ledger.h2d_bytes += 4").replace(
        "        self.h2d_bytes += n\n        self.h2d_calls += 1",
        "        with self._lock:\n"
        "            self.h2d_bytes += n\n"
        "            self.h2d_calls += 1")
    assert analyze_source(locked, rules=["thread-ownership"]) == []
    annotated = PR2_THREAD_RACE.replace(
        "    ledger.h2d_bytes += 4",
        "    # thread-owner: handoff precedes the scorer's first write\n"
        "    ledger.h2d_bytes += 4")
    assert analyze_source(annotated, rules=["thread-ownership"]) == []


def test_thread_ownership_mode_dependent_sharing_is_clean():
    """job.py's shape: one write site reachable from main (serial mode)
    AND the pipeline worker (pipelined mode). The root sets are equal,
    not mutually exclusive — no single run has two threads in that
    write, so it must not flag."""
    src = '''
import threading

class Ledger:
    def __init__(self):
        self.h2d_bytes = 0

def step(ledger):
    ledger.h2d_bytes += 1

def worker():
    step(Ledger())

def main():
    threading.Thread(target=worker).start()
    step(Ledger())
'''
    assert analyze_source(src, rules=["thread-ownership"]) == []


def test_thread_ownership_flags_self_concurrent_handler():
    """An HTTP handler runs one thread per request: a single unlocked
    write inside do_* races with itself, no second site needed."""
    src = '''
import http.server

class MetricsHandler(http.server.BaseHTTPRequestHandler):
    def do_GET(self):
        self.hits = getattr(self, "hits", 0) + 1
'''
    findings = analyze_source(src, rules=["thread-ownership"])
    assert len(findings) == 1
    assert "self-concurrent" in findings[0].message
    assert "MetricsHandler.hits" in findings[0].message


def test_thread_ownership_clean_on_repo():
    result = Analyzer(REPO, rules=[RULES["thread-ownership"]],
                      baseline=[]).run()
    assert result.findings == []


# ---------------------------------------------------------------------------
# tuning registry (PR 19 tentpole: tpu_cooccurrence/tuning.py + rules)

def test_tuning_registry_flags_unregistered_knob():
    src = ('import os\n'
           'budget = os.environ.get("TPU_COOC_NOT_A_KNOB", "0")\n')
    findings = analyze_source(src, rules=["tuning-registry"])
    msgs = [f.message for f in findings]
    assert any("not a registered" in m for m in msgs)
    assert any("tuning.env_read" in m for m in msgs)


def test_tuning_registry_flags_direct_read_of_registered_knob():
    """Even a registered knob must be read via tuning.env_read (the
    registry has to see the live read surface)."""
    for src in (
            'import os\nrid = os.environ.get("TPU_COOC_RUN_ID")\n',
            'import os\nrid = os.getenv("TPU_COOC_RUN_ID")\n',
            'import os\nrid = os.environ["TPU_COOC_RUN_ID"]\n',
            # an aliased module-level constant is seen through
            'import os\nK = "TPU_COOC_RUN_ID"\nrid = os.environ.get(K)\n'):
        findings = analyze_source(src, rules=["tuning-registry"])
        assert len(findings) == 1, src
        assert "tuning.env_read" in findings[0].message


def test_tuning_registry_env_read_is_clean():
    src = ('from tpu_cooccurrence import tuning\n'
           'rid = tuning.env_read("TPU_COOC_RUN_ID")\n')
    assert analyze_source(src, rules=["tuning-registry"]) == []


def test_tuning_env_read_rejects_unregistered_at_runtime():
    from tpu_cooccurrence import tuning
    with pytest.raises(KeyError, match="TPU_COOC_BOGUS"):
        tuning.env_read("TPU_COOC_BOGUS")
    assert tuning.env_read("TPU_COOC_RUN_ID",
                           environ={"TPU_COOC_RUN_ID": "r7"}) == "r7"


def test_tuning_parameter_validate_bounds_and_choices():
    from tpu_cooccurrence import tuning
    tuning.get("pipeline_depth").validate(2)
    with pytest.raises(ValueError, match="pipeline_depth"):
        tuning.get("pipeline_depth").validate(3)
    with pytest.raises(ValueError, match="wire_format"):
        tuning.get("wire_format").validate("gzip")
    assert tuning.bounds("score_ladder") == (2, None)


def test_tuning_magic_number_flags_inlined_default():
    src = ('def plan(rows):\n'
           '    if rows < 256:\n'
           '        return None\n'
           '    return rows\n')
    findings = analyze_source(src, path="tpu_cooccurrence/ops/plan.py",
                              rules=["tuning-magic-number"])
    assert len(findings) == 1
    assert findings[0].severity == "warning"
    assert "256" in findings[0].message
    # Outside the hot-path prefixes the same literal is style, not perf.
    assert analyze_source(src, path="tpu_cooccurrence/config.py",
                          rules=["tuning-magic-number"]) == []


def test_every_env_knob_in_package_is_registered():
    """Acceptance: every TPU_COOC_* token in package source resolves
    through the registry (grep-level, independent of the analyzer)."""
    import re
    from tpu_cooccurrence import tuning
    registered = set(tuning.by_env())
    pkg = os.path.join(REPO, "tpu_cooccurrence")
    offenders = []
    for dirpath, dirnames, files in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fname in files:
            if not fname.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fname),
                      encoding="utf-8") as fh:
                for tok in set(re.findall(r"TPU_COOC_[A-Z0-9_]+",
                                          fh.read())):
                    if tok not in registered:
                        offenders.append((fname, tok))
    assert not offenders


def test_readme_tuning_table_is_generated_and_pinned():
    """The README "Tuning parameters" table is the literal output of
    tuning.markdown_table() — docs cannot drift from the registry."""
    from tpu_cooccurrence import tuning
    with open(os.path.join(REPO, "README.md"), encoding="utf-8") as fh:
        readme = fh.read()
    assert tuning.markdown_table("perf") in readme
    assert tuning.markdown_table("infra") in readme


def test_config_reads_defaults_from_registry():
    """config.py field defaults come from tuning.default(...) — the
    registry is the single source of truth for knob defaults."""
    from tpu_cooccurrence import config as cfg
    from tpu_cooccurrence import tuning
    c = cfg.Config()
    assert c.pipeline_depth == tuning.default("pipeline_depth")
    assert c.checkpoint_compact_ratio == tuning.default(
        "checkpoint_compact_ratio")


# ---------------------------------------------------------------------------
# fingerprints + --changed (PR 19 satellites)

def test_findings_carry_symbol_severity_and_rule_doc():
    findings = analyze_source(
        PR2_RACE_FIXTURE, path="tpu_cooccurrence/pipeline.py",
        rules=["lock-discipline"])
    f = findings[0]
    assert f.symbol == "PipelineWorker.record_upload"
    assert f.severity == "error"
    assert f.rule_doc == RULES["lock-discipline"].description
    d = f.to_dict()
    assert d["symbol"] and d["severity"] and d["rule_doc"]


def test_baseline_symbol_fingerprint_survives_line_drift(tmp_path):
    """A {rule, file, symbol} baseline entry keeps matching after lines
    above the finding shift (the legacy line form would go stale)."""
    root = _mini_repo_with_race(tmp_path)
    baseline = [{"rule": "lock-discipline",
                 "file": "tpu_cooccurrence/pipeline.py",
                 "symbol": "PipelineWorker.record_upload",
                 "justification": "fingerprint form"}]
    result = Analyzer(str(root), rules=[RULES["lock-discipline"]],
                      baseline=baseline).run()
    assert not result.findings and not result.stale_baseline
    assert len(result.baselined) == 2
    # Same entry still matches with ten blank lines pushed above it.
    (root / "tpu_cooccurrence" / "pipeline.py").write_text(
        "\n" * 10 + PR2_RACE_FIXTURE)
    result = Analyzer(str(root), rules=[RULES["lock-discipline"]],
                      baseline=baseline).run()
    assert not result.findings and not result.stale_baseline


def test_prune_baseline_upgrades_legacy_entries_to_fingerprints(tmp_path):
    """--prune-baseline rewrites matched legacy {rule, file, line}
    entries into the stable {rule, file, symbol} form."""
    from tpu_cooccurrence.analysis.__main__ import main

    root = _mini_repo_with_race(tmp_path)
    bl_path = str(tmp_path / "baseline.json")
    save_baseline([
        {"rule": "lock-discipline",
         "file": "tpu_cooccurrence/pipeline.py", "line": 5,
         "justification": "kept"},
        {"rule": "lock-discipline",
         "file": "tpu_cooccurrence/pipeline.py", "line": 6,
         "justification": "kept"},
    ], bl_path)
    rc = main(["--root", str(root), "--baseline", bl_path,
               "--prune-baseline"])
    assert rc == 0
    kept = load_baseline(bl_path)
    assert all(e.get("symbol") == "PipelineWorker.record_upload"
               and "line" not in e for e in kept)
    assert all(e["justification"] == "kept" for e in kept)


def test_changed_mode_falls_back_to_full_run_without_git(tmp_path):
    from tpu_cooccurrence.analysis.__main__ import main

    root = _mini_repo_with_race(tmp_path)
    rc = main(["--root", str(root), "--changed"])
    assert rc == 1  # no git: full-run fallback still sees the race


def test_changed_mode_scopes_and_caches_on_real_repo():
    """--changed on the checkout: exits 0 (clean repo), reports its
    scope, and persists the sha-keyed pass-1 cache for the next run."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_cooccurrence.analysis",
         "--root", REPO, "--changed"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    if "changed)" in proc.stdout:  # git + main ref available
        cache = os.path.join(REPO, ".cooclint-cache.json")
        assert os.path.exists(cache)
        with open(cache, encoding="utf-8") as fh:
            data = json.load(fh)
        assert data["schema"] == "cooclint-pass1/1"
        assert data["modules"]
