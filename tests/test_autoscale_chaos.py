"""Autoscale chaos capstone (ISSUE 15): the real CLI in gang mode.

A 2-process CPU multi-controller sparse gang with ``--autoscale on``:

* **scale-before-shed, bit-identical** — injected load (delay faults
  billed into the window wall) forces a 2→4 rescale; the idle tail
  decays 4→2; final stdout is bit-identical to the same stream run at
  a FIXED 2-worker topology. The journals prove the precedence claim:
  the degradation ladder (armed, trip within reach) never leaves
  NORMAL — the pressure became capacity, not shed work — and carry the
  AUTOSCALE grow/shrink records.

* **crash inside the rescale seam** — ``rescale_drain@1:crash`` kills
  worker 1 after the drain checkpoint committed but before its
  voluntary exit. The gang restarts (one billed attempt), relaunches
  at the pending target, and the topology-aware restore vote merges
  the 2-writer generation onto the 4-worker gang — stdout still
  bit-identical to the fixed-topology reference.

**The comparator.** A sparse restore canonicalizes within-row slab
order (``rebuild_from_keys`` is key-sorted), and equal-score top-K
tie-breaks are slot-ordered — so ANY restored run differs from a
never-restored one at exactly the tied scores, whatever the topology.
Same precedent as the PR-12 gang chaos: the bit-exact comparator is a
fixed-topology run *recovered at the same window boundaries*, not an
uninterrupted one. The supervisor's beacon-driven decisions make the
drain windows timing-dependent, so the test is two-phase: run the
elastic gang, read its drain windows from the journal's AUTOSCALE
records, then run the fixed 2-worker reference with a crash injected
at each drain-successor window (``--checkpoint-every-windows 1``
guarantees a committed generation at every boundary) — both runs then
restore-canonicalize at the identical windows, and everything else is
pure rescale topology, which is bit-free by the PR-9 contract.

Timing levers: ``--degrade-window-wall-s 2`` makes a 2500 ms injected
delay an overloaded window and anything under 500 ms an idle one —
margins wide enough for a contended CI box. Only worker 0 is delayed
(``@0``); the gang-max vote spreads the signal.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(os.environ, JAX_PLATFORMS="cpu",
           XLA_FLAGS="--xla_force_host_platform_device_count=1",
           PALLAS_AXON_POOL_IPS="")


@pytest.fixture(scope="module")
def stream(tmp_path_factory):
    path = tmp_path_factory.mktemp("autoscale") / "in.csv"
    with open(path, "w") as fh:
        # 520 events = 20 windows at ws 250: pressure at windows 3..5,
        # grow drain ~5; the policy's cooldown (2) plus FRESH idle
        # evidence (clear 3) put the shrink drain ~10-13, leaving a
        # several-window tail at 2 workers before the final dump.
        for i in range(520):
            fh.write(f"{i % 13},{i % 17},{i * 10}\n")
    return str(path)


#: Reference stdout cache keyed by the drain-window tuple: the two
#: tier-1 chaos runs usually drain at the same windows, and a
#: fixed-topology reference is a whole extra gang run — reuse it when
#: the boundaries match (correctness never depends on the reuse).
_REFERENCE_CACHE = {}


def _args(stream, ck_dir, extra):
    return [sys.executable, "-m", "tpu_cooccurrence.cli",
            "-i", stream, "-ws", "250", "-ic", "8", "-uc", "5",
            "-s", "0xC0FFEE", "--backend", "sparse",
            "--num-shards", "2",
            "--checkpoint-dir", ck_dir,
            "--checkpoint-every-windows", "1",
            "--checkpoint-retain", "100",
            "--gang-workers", "2", "--gang-heartbeat-s", "1",
            "--collective-timeout-s", "60",
            "--restart-delay-ms", "0"] + extra


#: The load script: worker 0's windows 3..5 each stall 2.5 s inside
#: the sample clock — consecutive overloaded windows under a 2 s wall
#: threshold (the gang-max vote makes them gang-wide), then nothing:
#: the tail is idle. Fired-once markers survive the rescale relaunches,
#: so the pressure never returns at 4 workers.
_LOAD = ["--inject-fault", "window_fire@0:3:delay_ms:2500",
         "--inject-fault", "window_fire@0:4:delay_ms:2500",
         "--inject-fault", "window_fire@0:5:delay_ms:2500"]

_AUTOSCALE = ["--degrade", "--degrade-window-wall-s", "2.0",
              "--degrade-trip-windows", "3",
              "--autoscale", "on",
              "--autoscale-min-workers", "2",
              "--autoscale-max-workers", "4",
              "--autoscale-trip-windows", "2",
              "--autoscale-clear-windows", "3",
              "--autoscale-cooldown-windows", "2"]


def _run(stream, ck_dir, extra, timeout=420):
    return subprocess.run(_args(stream, ck_dir, extra),
                          capture_output=True, text=True, env=ENV,
                          cwd=REPO, timeout=timeout)


def _journal_records(jpath, pid):
    with open(f"{jpath}.p{pid}") as f:
        return [json.loads(line) for line in f if line.strip()]


def _fixed_topology_reference(stream, tmp_path, drain_windows,
                              last_window):
    """The bit-exact comparator: the same stream on a FIXED 2-worker
    gang, crash-recovered at exactly the elastic run's drain windows
    (see the module docstring for why an uninterrupted run cannot be
    the comparator). A crash at window W+1 fires before sampling, so
    the restore lands on the generation committed at W — the same
    boundary the drain checkpoint committed. A drain at the FINAL
    window needs no reference crash at all: the relaunched gang
    processes zero windows before the dump, and the dump prints the
    restored ``latest`` — exactly the rows the reference's own
    final-window checkpoint held, with nothing written post-restore to
    canonicalize differently."""
    replay = [w for w in drain_windows if w < last_window]
    key = tuple(replay)
    if key in _REFERENCE_CACHE:
        return _REFERENCE_CACHE[key]
    ck = str(tmp_path / "ck-ref")
    extra = ["--restart-on-failure", str(len(replay))]
    for w in replay:
        # Built by concatenation, not an f-string: the fault-site text
        # scan must see the site name at the spec's head.
        extra += ["--inject-fault",
                  "window_fire@0:" + str(w + 1) + ":crash"]
    extra += ["--fault-state-dir", str(tmp_path / "faults-ref")]
    proc = _run(stream, ck, extra)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert proc.stdout, "reference run produced no output"
    assert proc.stderr.count("gang-restarting") == len(replay)
    _REFERENCE_CACHE[key] = proc.stdout
    return proc.stdout


@pytest.fixture(scope="module")
def elastic(stream, tmp_path_factory):
    """THE capstone run: load forces 2→4, idle decays 4→2, with a ZERO
    restart budget — every relaunch must be a voluntary drain."""
    tmp_path = tmp_path_factory.mktemp("autoscale-elastic")
    ck = str(tmp_path / "ck")
    jpath = str(tmp_path / "journal.jsonl")
    proc = _run(stream, ck,
                _AUTOSCALE + _LOAD
                + ["--journal", jpath,
                   "--fault-state-dir", str(tmp_path / "faults")])
    assert proc.returncode == 0, proc.stderr[-3000:]
    recs = _journal_records(jpath, 0)
    return proc, recs, ck


def test_autoscale_grow_shrink_bit_identical(tmp_path, stream, elastic):
    proc, recs, ck = elastic
    scale = [r for r in recs if "autoscale" in r]
    assert [(r["autoscale"], r["from"], r["to"]) for r in scale] == [
        ("grow", 2, 4), ("shrink", 4, 2)]
    assert scale[0]["trigger"] == "pressure"
    assert scale[1]["trigger"] == "idle"
    assert "autoscale decision: grow 2 -> 4" in proc.stderr
    assert "autoscale decision: shrink 4 -> 2" in proc.stderr
    assert "gang rescale 1" in proc.stderr
    assert "gang rescale 2" in proc.stderr
    # No billed restarts: the gang ran with a ZERO restart budget, so
    # completing at all proves both rescale exits were free.
    assert "gang-restarting" not in proc.stderr
    # The 2→4 seam restored across topologies (merge + re-bucket).
    assert "rescale restore: generation" in proc.stderr
    # Scale-before-shed in the transition sequence: --degrade was armed
    # with its trip within reach (3 consecutive overloaded windows
    # existed), yet the ladder never left NORMAL — the pressure became
    # capacity, not shed work.
    windows = [r for r in recs if "seq" in r]
    assert windows, "no window records journaled"
    assert all(r.get("degradation_level") == 0 for r in windows), \
        "the ladder left NORMAL during a successful scale-up"
    assert not any(r.get("degrade_events") for r in windows)
    # Drain generations committed at BOTH topologies (2- and 4-writer
    # marker sets) — the rescale-tagged commit trail.
    from tpu_cooccurrence.state import checkpoint as ckpt

    topos = {w for _g, w in ckpt.topology_committed_generations(ck)}
    assert topos == {2, 4}
    # Bit-identity vs the fixed topology, recovered at the same
    # boundaries (module docstring): the elastic run destroyed and
    # rebuilt the gang twice and still produced the reference stream.
    ref = _fixed_topology_reference(
        stream, tmp_path, [r["window"] for r in scale],
        max(r["seq"] for r in windows))
    assert proc.stdout == ref


@pytest.mark.slow
def test_crash_inside_rescale_seam_recovers_via_vote(tmp_path, stream):
    """rescale_drain@1:crash: worker 1 dies AFTER the drain commit and
    BEFORE its voluntary exit. The crash bills one restart, the gang
    relaunches at the pending target (4), the topology-aware vote
    restores the 2-writer generation onto 4 workers, and the idle tail
    still decays back to 2 — with NO lost or duplicated windows: the
    journal's window-record seqs across every attempt are exactly
    1..N, each once (the drain committed before the crash, so the
    resumed gang continues at the very next window)."""
    ck = str(tmp_path / "ck")
    jpath = str(tmp_path / "journal.jsonl")
    proc = _run(stream, ck,
                _AUTOSCALE + _LOAD
                + ["--restart-on-failure", "2",
                   "--journal", jpath,
                   "--inject-fault", "rescale_drain@1:crash",
                   "--fault-state-dir", str(tmp_path / "faults")])
    assert proc.returncode == 0, proc.stderr[-3000:]
    # The seam crash was a REAL failure (billed restart)...
    assert "gang-restarting" in proc.stderr
    # ...that still relaunched at the pending target and crossed the
    # topology on restore.
    assert "rescale restore: generation" in proc.stderr
    fired = sorted(os.listdir(tmp_path / "faults"))
    assert "fault3.p1.fired" in fired  # the seam crash, worker 1 only
    recs = _journal_records(jpath, 0)
    scale = [r for r in recs if "autoscale" in r]
    assert [(r["from"], r["to"]) for r in scale] == [(2, 4), (4, 2)]
    # No lost or duplicated windows, across the crash and both seams.
    seqs = [r["seq"] for r in recs if "seq" in r]
    assert sorted(seqs) == list(range(1, max(seqs) + 1))
    assert len(seqs) == len(set(seqs))
    assert proc.stdout, "recovered gang produced no output"


@pytest.mark.slow
def test_autoscale_incremental_chain_crosses_the_seam(tmp_path, stream):
    """Slow lane: the same grow/shrink capstone with
    --checkpoint-incremental — the drain commit is a delta generation,
    the cross-topology restore resolves each writer's chain, and the
    first post-rescale save is forced to a full base (a delta against
    the old shard layout would be mis-keyed). The comparator is the
    full-checkpoint fixed topology recovered at the same boundaries —
    delta-chain restore is byte-equivalent to full restore (PR 12)."""
    ck = str(tmp_path / "ck")
    jpath = str(tmp_path / "journal.jsonl")
    proc = _run(stream, ck,
                _AUTOSCALE + _LOAD
                + ["--checkpoint-incremental",
                   "--checkpoint-compact-ratio", "10",
                   "--journal", jpath,
                   "--fault-state-dir", str(tmp_path / "faults")])
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "rescale restore: generation" in proc.stderr
    recs = _journal_records(jpath, 0)
    scale = [r for r in recs if "autoscale" in r]
    assert [(r["from"], r["to"]) for r in scale] == [(2, 4), (4, 2)]
    ref = _fixed_topology_reference(
        stream, tmp_path, [r["window"] for r in scale],
        max(r["seq"] for r in recs if "seq" in r))
    assert proc.stdout == ref
