"""The tracing plane: span-structured journal records, fleet-wide
correlation (run_id / process_id / attempt), the cooc-trace offline
analyzer (waterfall, reconciliation, freshness, seams, Chrome export),
the /healthz last_window block, and supervisor run-id threading.

``JOURNAL_SCHEMA_KEYS`` below is the canonical tests/ registry the
``journal-schema-registry`` cooclint rule points at: every key any
journal writer emits must appear here (and in the schema tables and the
ARCHITECTURE journal table) or the analyzer fails tier-1.
"""

import json
import os
import sys

import numpy as np
import pytest

from tpu_cooccurrence.observability import journal as jn
from tpu_cooccurrence.observability import trace
from tpu_cooccurrence.observability.journal import (
    REPLICA_SPAN_STAGES, SPAN_STAGES, VERSION, RunJournal, mint_run_id,
    run_context, validate_record)

# The journal key registry (see module docstring). Kept as literals on
# purpose — the lint rule scans tests/ for the emitted key *strings*.
JOURNAL_SCHEMA_KEYS = [
    # window records (SCHEMA)
    "v", "seq", "ts", "events", "pairs", "rows_scored",
    "sample_seconds", "score_seconds", "ring_depth", "stall_seconds",
    "wall_unix", "counters", "wire", "degradation_level",
    "degrade_events", "breaker_state", "fused", "fused_compiles",
    "fallback_reason", "snapshot_generation", "snapshot_rows", "epoch",
    "run_id", "process_id", "attempt", "spans",
    "ingest_offsets", "ingest_lag",
    # event records (EVENT_SCHEMA)
    "event", "window_seq",
    # checkpoint records (CKPT_SCHEMA)
    "checkpoint", "kind", "bytes", "seconds", "chain_len", "generation",
    # autoscale records (AUTOSCALE_SCHEMA)
    "autoscale", "from", "to", "trigger", "window", "cooldown",
    # replica records (REPLICA_SCHEMA)
    "replica", "rows", "topk_rows", "lag", "resyncs",
]


def test_schema_key_registry_is_exact():
    """The literal registry above matches the schema tables exactly —
    a new journal field must be added to both (plus the ARCHITECTURE
    table) in the same PR."""
    tables = (jn.SCHEMA, jn.EVENT_SCHEMA, jn.CKPT_SCHEMA,
              jn.AUTOSCALE_SCHEMA, jn.REPLICA_SCHEMA)
    union = set()
    for t in tables:
        union |= set(t)
    assert set(JOURNAL_SCHEMA_KEYS) == union
    assert len(JOURNAL_SCHEMA_KEYS) == len(set(JOURNAL_SCHEMA_KEYS))


# ---------------------------------------------------------------------------
# record builders (every fixture is validated — schema-true by
# construction, so these tests can never drift from the writers)


def _spans(sample_s, score_s):
    """Core spans partitioning sample+score exactly, the job contract."""
    admit = 0.25 * sample_s
    parts = [("ingest-admission", admit), ("sample", sample_s - admit),
             ("uplink-encode", 0.3 * score_s),
             ("dispatch", 0.5 * score_s), ("rescore", 0.2 * score_s)]
    off, out = 0.0, []
    for stage, secs in parts:
        out.append([stage, round(off, 9), round(secs, 9)])
        off += secs
    return out


def _win(seq, run_id="r1", pid=0, attempt=0, wall=100.0, sample_s=0.4,
         score_s=0.6, **over):
    rec = {"v": VERSION, "seq": seq, "ts": seq * 10, "events": 5,
           "pairs": 3, "rows_scored": 2, "sample_seconds": sample_s,
           "score_seconds": score_s, "ring_depth": 0,
           "stall_seconds": 0.0, "wall_unix": wall, "counters": {},
           "wire": {}, "run_id": run_id, "process_id": pid,
           "attempt": attempt, "spans": _spans(sample_s, score_s)}
    rec.update(over)
    validate_record(rec)
    return rec


def _ckpt(gen, window_seq, run_id="r1", pid=0, attempt=0, wall=100.0):
    rec = {"v": VERSION, "checkpoint": gen, "kind": "delta", "bytes": 10,
           "seconds": 0.01, "chain_len": 1, "wall_unix": wall,
           "window_seq": window_seq, "generation": gen, "run_id": run_id,
           "process_id": pid, "attempt": attempt}
    validate_record(rec)
    return rec


def _replica(gen, run_id="r1", pid=0, attempt=0, wall=100.0, lag=0,
             resyncs=0):
    rec = {"v": VERSION, "replica": gen, "rows": 4, "topk_rows": 2,
           "lag": lag, "resyncs": resyncs, "wall_unix": wall,
           "generation": gen, "run_id": run_id, "process_id": pid,
           "attempt": attempt,
           "spans": [["delta-apply", 0.0, 0.002],
                     ["publish", 0.002, 0.001]]}
    validate_record(rec)
    return rec


def _write(path, records):
    with RunJournal(str(path)) as j:
        for rec in records:
            j.record(rec)
    return str(path)


# ---------------------------------------------------------------------------
# span schema validation


def test_span_validation_rejects_malformed():
    validate_record(_win(1))  # canonical order passes
    with pytest.raises(ValueError, match="not in"):
        validate_record(_win(1, spans=[["warp-core", 0.0, 0.1]]))
    with pytest.raises(ValueError, match="out of order"):
        validate_record(_win(1, spans=[["sample", 0.0, 0.1],
                                       ["ingest-admission", 0.1, 0.1]]))
    with pytest.raises(ValueError, match="not \\[stage"):
        validate_record(_win(1, spans=[["sample", 0.0]]))
    with pytest.raises(ValueError, match="not in"):
        # Replica stages are a different table: a window stage on a
        # replica record is a writer bug, not a new stage.
        validate_record(_replica(1, run_id="r")
                        | {"spans": [["sample", 0.0, 0.1]]})


def test_span_stage_tables():
    assert SPAN_STAGES[:5] == ("ingest-admission", "sample",
                               "uplink-encode", "dispatch", "rescore")
    assert SPAN_STAGES[5:] == ("snapshot-publish", "checkpoint-commit")
    assert REPLICA_SPAN_STAGES == ("delta-apply", "publish")


def test_run_context_inherits_env(monkeypatch):
    monkeypatch.setenv(jn.RUN_ID_ENV, "abc123")
    monkeypatch.setenv(jn.ATTEMPT_ENV, "4")
    assert run_context() == ("abc123", 4)
    monkeypatch.delenv(jn.RUN_ID_ENV)
    monkeypatch.delenv(jn.ATTEMPT_ENV)
    run_id, attempt = run_context()
    assert len(run_id) == 12 and attempt == 0
    assert mint_run_id() != mint_run_id()


# ---------------------------------------------------------------------------
# the real writers: a journaled job run carries correlation + spans
# that reconcile with its own wall-seconds fields


def _run_job(tmp_path, name, pipeline_depth=0, run_id="tracerun12ab"):
    from tpu_cooccurrence.config import Backend, Config
    from tpu_cooccurrence.job import CooccurrenceJob

    rng = np.random.default_rng(11)
    n = 4000
    users = rng.integers(0, 40, n).astype(np.int64)
    items = rng.integers(0, 60, n).astype(np.int64)
    ts = np.cumsum(rng.integers(0, 2, n)).astype(np.int64)
    path = str(tmp_path / f"{name}.jsonl")
    job = CooccurrenceJob(Config(window_size=50, seed=5, item_cut=20,
                                 user_cut=10, backend=Backend("oracle"),
                                 journal=path, run_id=run_id,
                                 pipeline_depth=pipeline_depth))
    job.add_batch(users, items, ts)
    job.finish()
    return job, path


@pytest.mark.parametrize("depth", [0, 2])
def test_job_records_spans_that_reconcile(tmp_path, depth):
    job, path = _run_job(tmp_path, f"d{depth}", pipeline_depth=depth)
    recs = [r for r in jn.read_records(path) if "seq" in r]
    assert len(recs) == job.windows_fired > 5
    for r in recs:
        validate_record(r)
        assert r["run_id"] == "tracerun12ab"
        assert r["process_id"] == 0 and r["attempt"] == 0
        stages = [s[0] for s in r["spans"]]
        assert stages[:5] == list(SPAN_STAGES[:5])
        # The core contract: the five core spans partition
        # sample_seconds + score_seconds (to field rounding).
        core = sum(s[2] for s in r["spans"] if s[0] in SPAN_STAGES[:5])
        assert core == pytest.approx(
            r["sample_seconds"] + r["score_seconds"], abs=2e-6)
        # Offsets are contiguous: each span starts where the prior ended.
        off = 0.0
        for _stage, start, secs in r["spans"]:
            assert start == pytest.approx(off, abs=2e-6)
            off += secs
    rep = trace.reconcile(recs)
    assert rep["ok"], rep
    assert job.last_window_health is not None
    assert job.last_window_health["window_seq"] == job.windows_fired
    assert set(job.last_window_health["stages"]) <= set(SPAN_STAGES)


def test_healthz_carries_last_window_block():
    from tpu_cooccurrence.observability.http import MetricsServer
    from tpu_cooccurrence.observability.registry import MetricsRegistry

    block = {"window_seq": 7, "seconds": 0.25, "fused": True,
             "stages": {"sample": 0.1, "dispatch": 0.15}}
    srv = MetricsServer(MetricsRegistry(), stale_after_s=300.0,
                        last_window=lambda: block)
    try:
        payload, _healthy = srv.health()
        assert payload["last_window"] == block
    finally:
        srv.stop()
    # Absent callback (or a job with no window yet): no block, no crash.
    srv = MetricsServer(MetricsRegistry(), stale_after_s=300.0,
                        last_window=lambda: None)
    try:
        payload, _healthy = srv.health()
        assert "last_window" not in payload
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# cooc-trace: merge, dedup, waterfall, reconciliation, freshness


def test_classify_and_discover(tmp_path):
    assert trace.classify(_win(1)) == "window"
    assert trace.classify(_ckpt(1, 1)) == "checkpoint"
    assert trace.classify(_replica(1)) == "replica"
    assert trace.classify({"v": 1, "event": "x",
                           "wall_unix": 1.0}) == "event"
    assert trace.classify({"not": "a record"}) is None
    _write(tmp_path / "journal.jsonl.p0", [_win(1)])
    _write(tmp_path / "replica.jsonl", [_replica(1)])
    (tmp_path / "ckpt.bin").write_bytes(b"\x00")  # ignored: not jsonl
    files = trace.discover([str(tmp_path)])
    assert [os.path.basename(f) for f in files] == [
        "journal.jsonl.p0", "replica.jsonl"]


def test_dedup_keeps_highest_attempt():
    a0 = [_win(s, attempt=0, wall=100.0 + s) for s in (1, 2, 3)]
    a1 = [_win(s, attempt=1, wall=200.0 + s) for s in (2, 3, 4)]
    kept, dropped = trace.dedup_windows(a0 + a1)
    assert dropped == 2
    by_seq = {r["seq"]: r["attempt"] for r in kept}
    assert by_seq == {1: 0, 2: 1, 3: 1, 4: 1}


def test_waterfall_covers_both_planes():
    wf = trace.waterfall([_win(1), _win(2)], [_replica(1)])
    assert wf["sample"]["count"] == 2
    assert wf["delta-apply"]["count"] == 1
    assert wf["sample"]["max"] == pytest.approx(0.3)
    assert "checkpoint-commit" not in wf  # no boundary spans emitted


def test_reconcile_flags_torn_partition():
    good = _win(1)
    bad = _win(2, spans=[["sample", 0.0, 0.1]])  # 0.1 != 1.0 wall
    rep = trace.reconcile([good, bad])
    assert rep["windows_checked"] == 2
    assert rep["violations"] == 1 and not rep["ok"]
    # Sub-millisecond windows are skipped (field rounding dominates).
    tiny = _win(3, sample_s=1e-5, score_s=1e-5)
    assert trace.reconcile([tiny])["windows_checked"] == 0


def test_freshness_joins_window_to_replica_via_generation():
    windows = [_win(1, wall=100.0), _win(2, wall=110.0)]
    ckpts = [_ckpt(3, window_seq=2, wall=110.5)]
    replicas = [_replica(3, run_id="r1", pid=0, wall=112.3, lag=0)]
    fr = trace.freshness(windows, ckpts, replicas)
    # Anchored at the *window* wall (110.0), not the commit (110.5).
    assert fr["count"] == 1 and fr["joined"] == 1
    assert fr["max"] == pytest.approx(2.3)
    assert "cross_run_join" not in fr
    # A separately launched replica (own run id) still joins on the
    # generation over the shared state dir — flagged, not dropped.
    other = [_replica(3, run_id="other", wall=115.0)]
    fr = trace.freshness(windows, ckpts, other)
    assert fr["joined"] == 1 and fr["cross_run_join"] is True
    # Unknown generation: counted as unjoined, never guessed.
    fr = trace.freshness(windows, ckpts, [_replica(99, wall=120.0)])
    assert fr["joined"] == 0 and fr["unjoined_replica_records"] == 1


# ---------------------------------------------------------------------------
# chaos: gang crash + restart, replica resync mid-tail (ISSUE 17
# satellite — the merged timeline must stay coherent through both)


def _gang_dir(tmp_path):
    """Two workers; p0 crashes after seq 4 and its restart (attempt 1)
    replays seq 3-6 into the SAME journal file (append mode)."""
    run = "gangrun00001"
    p0 = [_win(s, run_id=run, pid=0, attempt=0, wall=100.0 + s)
          for s in (1, 2, 3, 4)]
    p0 += [_win(s, run_id=run, pid=0, attempt=1, wall=150.0 + s)
           for s in (3, 4, 5, 6)]
    p0 += [_ckpt(1, window_seq=6, run_id=run, pid=0, attempt=1,
                 wall=157.0)]
    p1 = [_win(s, run_id=run, pid=1, attempt=0, wall=100.0 + s)
          for s in (1, 2, 3, 4, 5, 6)]
    _write(tmp_path / "journal.jsonl.p0", p0)
    _write(tmp_path / "journal.jsonl.p1", p1)
    reps = [_replica(1, run_id=run, pid=0, wall=158.0)]
    _write(tmp_path / "replica.jsonl.p0", reps)
    return run, str(tmp_path)


def test_chaos_gang_crash_restart_merges_cleanly(tmp_path):
    run, root = _gang_dir(tmp_path)
    analysis = trace.analyze(trace.discover([root]))
    an = analysis["annotations"]
    assert an["restarts"] == 1
    assert an["dropped_duplicate_windows"] == 2  # seq 3, 4 replayed
    assert analysis["reconcile"]["ok"]
    assert analysis["freshness"]["joined"] == 1
    assert sorted(analysis["processes"]) == [f"{run}/p0", f"{run}/p1"]
    # The merged Chrome timeline carries each (pid, window_seq, stage)
    # span exactly once — the dedup dropped the pre-crash attempts.
    ct = trace.chrome_trace(trace.discover([root]))
    seen = set()
    for ev in ct["traceEvents"]:
        if ev["ph"] == "X" and ev.get("cat") == "window":
            key = (ev["pid"], ev["args"]["window_seq"], ev["name"])
            assert key not in seen, f"duplicate span {key}"
            seen.add(key)
    # p0 fired 1-6 (surviving attempts), p1 fired 1-6: 12 windows x 5
    # core spans.
    assert len(seen) == 12 * 5


def test_chaos_replica_resync_mid_tail():
    """A replica that hits DeltaCorrupt mid-tail resyncs FORWARD from
    the newest checkpoint: its generation stream may skip but must
    never step back."""
    reps = [_replica(g, wall=100.0 + g, resyncs=0) for g in (1, 2, 3)]
    # resync: bootstrap jumps over 4-6 straight to 7
    reps += [_replica(g, wall=110.0 + g, resyncs=1) for g in (7, 8)]
    an = trace.annotations([], [], [], reps, 0)
    assert an["replica_resyncs"] == 1
    assert an["replica_generation_monotone"] is True
    # A genuinely backwards stream (corrupt merge, clock skew) flags.
    bad = reps + [_replica(2, wall=130.0, resyncs=1)]
    an = trace.annotations([], [], [], bad, 0)
    assert an["replica_generation_monotone"] is False


def test_annotations_count_seams():
    windows = [_win(1, fused=1), _win(2, fused=0,
                                      fallback_reason="width_overflow"),
               _win(3, fused=1, degrade_events=["shed_k_on"])]
    events = [{"v": VERSION, "event": "pause_on", "wall_unix": 104.0,
               "window_seq": 3, "run_id": "r1", "process_id": 0,
               "attempt": 0}]
    autos = [{"v": VERSION, "autoscale": "grow", "from": 2, "to": 4,
              "trigger": "pressure", "window": 3, "cooldown": 6,
              "wall_unix": 105.0, "run_id": "r1", "process_id": 0,
              "attempt": 0}]
    for rec in events + autos:
        validate_record(rec)
    an = trace.annotations(windows, events, autos, [], 1)
    assert an["fused_windows"] == 2 and an["chained_windows"] == 1
    assert an["fallback_reasons"] == {"width_overflow": 1}
    assert an["degrade_transitions"] == 2  # 1 in-window + 1 o-o-b event
    assert an["autoscale_drains"] == [
        {"decision": "grow", "from": 2, "to": 4, "trigger": "pressure",
         "window": 3}]
    assert an["dropped_duplicate_windows"] == 1


# ---------------------------------------------------------------------------
# Chrome-trace export + CLI


def test_chrome_trace_structure(tmp_path):
    _, root = _gang_dir(tmp_path)
    ct = trace.chrome_trace(trace.discover([root]))
    assert ct["displayTimeUnit"] == "ms"
    evs = ct["traceEvents"]
    assert {e["ph"] for e in evs} <= {"M", "X", "i"}
    # Metadata names every process/thread track before its spans.
    names = [e for e in evs if e["ph"] == "M"]
    assert any(e["name"] == "process_name"
               and e["args"]["name"].startswith("worker p0")
               for e in names)
    assert any(e["name"] == "process_name"
               and e["args"]["name"].startswith("replica p0")
               for e in names)
    assert any(e["name"] == "thread_name"
               and e["args"]["name"] == "attempt 1" for e in names)
    # Replicas live on their own pid plane; worker pids stay raw.
    pids = {e["pid"] for e in evs if e.get("cat") == "replica"}
    assert pids == {1000}
    xs = [e for e in evs if e["ph"] == "X"]
    assert all(e["dur"] >= 0 and e["ts"] > 0 for e in xs)
    # Spans within one record are laid back-to-back (contiguous).
    one = sorted((e for e in xs if e.get("cat") == "window"
                  and e["pid"] == 1 and e["args"]["window_seq"] == 1),
                 key=lambda e: e["ts"])
    for a, b in zip(one, one[1:]):
        assert b["ts"] == pytest.approx(a["ts"] + a["dur"], abs=1.0)
    # The instant events mark the out-of-band records.
    assert any(e["ph"] == "i" and e["name"].startswith("checkpoint gen")
               for e in evs)
    # Stream is time-sorted and JSON-serializable (Perfetto's loader).
    ts = [e.get("ts", 0.0) for e in evs]
    assert ts == sorted(ts)
    json.dumps(ct)


def test_trace_cli_formats(tmp_path, capsys):
    _, root = _gang_dir(tmp_path)
    assert trace.main([root, "--format", "text"]) == 0
    out = capsys.readouterr().out
    assert "stage waterfall" in out and "restarts=1" in out
    assert "dropped-dup-windows=2" in out
    jpath = str(tmp_path / "analysis.json")
    assert trace.main(["--gang-dir", root, "--format", "json",
                       "--out", jpath]) == 0
    with open(jpath) as f:
        analysis = json.load(f)
    assert analysis["reconcile"]["ok"]
    cpath = str(tmp_path / "trace.chrome.json")
    assert trace.main(["--state-dir", root, "--format", "chrome",
                       "--out", cpath]) == 0
    with open(cpath) as f:
        assert json.load(f)["traceEvents"]
    with pytest.raises(SystemExit):  # no inputs at all
        trace.main(["--format", "text"])


def test_trace_module_runs_jax_free(tmp_path):
    """cooc-trace is an offline tool: it must import and run with jax
    imports poisoned (journals are analyzed on laptops, not TPU VMs)."""
    _, root = _gang_dir(tmp_path)
    code = (
        "import sys\n"
        "sys.modules['jax'] = None  # import jax -> TypeError\n"
        "from tpu_cooccurrence.observability import trace\n"
        f"rc = trace.main([{root!r}, '--format', 'text'])\n"
        "sys.exit(rc)\n"
    )
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run([sys.executable, "-c", code], cwd=repo,
                          env=env, capture_output=True, text=True,
                          timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "stage waterfall" in proc.stdout


# ---------------------------------------------------------------------------
# supervisor run-id threading (restart children link to the prior
# attempt instead of starting an unrelated trace)


class _Sink:
    def __init__(self):
        self.text = ""

    def write(self, s):
        self.text += s


def test_supervisor_threads_run_id_and_attempt(tmp_path, monkeypatch):
    from tpu_cooccurrence.supervisor import supervise

    monkeypatch.delenv(jn.RUN_ID_ENV, raising=False)
    monkeypatch.delenv(jn.ATTEMPT_ENV, raising=False)
    log = tmp_path / "env.log"
    code = (
        "import os, sys\n"
        f"p = {str(log)!r}\n"
        "with open(p, 'a') as f:\n"
        f"    f.write(os.environ['{jn.RUN_ID_ENV}'] + ' '\n"
        f"            + os.environ['{jn.ATTEMPT_ENV}'] + chr(10))\n"
        "n = sum(1 for _ in open(p))\n"
        "sys.exit(0 if n > 1 else 5)\n"  # crash the first attempt
    )
    rc = supervise([sys.executable, "-c", code], attempts=2, delay_s=0,
                   stdout=_Sink())
    assert rc == 0
    lines = log.read_text().splitlines()
    assert len(lines) == 2
    (run0, att0), (run1, att1) = (ln.split() for ln in lines)
    assert run0 == run1 and len(run0) == 12
    assert (att0, att1) == ("0", "1")


def test_gang_supervisor_spawn_env_carries_identity(tmp_path, monkeypatch):
    """GangSupervisor stamps every worker's env with the shared run id
    and the gang-wide attempt ordinal (the chaos-merge tests above rely
    on the children inheriting both)."""
    from tpu_cooccurrence.robustness.gang import GangSupervisor

    monkeypatch.delenv(jn.RUN_ID_ENV, raising=False)
    captured = []

    class FakeProc:
        pid = 4242

        def poll(self):
            return 0

    def fake_popen(cmd, **kw):
        captured.append(kw.get("env") or {})
        return FakeProc()

    monkeypatch.setattr(
        "tpu_cooccurrence.robustness.gang.subprocess.Popen", fake_popen)
    sup = GangSupervisor(["-i", "x.csv", "-ws", "10"], num_workers=2,
                         attempts=0, gang_dir=str(tmp_path))
    sup._spawn(restarts=1, last_rc=0, backoff_s=0.0)
    assert len(captured) == 2
    assert {env[jn.RUN_ID_ENV] for env in captured} == {sup.run_id}
    assert all(env[jn.ATTEMPT_ENV] == "1" for env in captured)
    state = json.loads(captured[0]["TPU_COOC_SUPERVISOR_STATE"])
    assert state["run_id"] == sup.run_id and state["attempt"] == 1
