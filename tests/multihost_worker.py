"""Subprocess worker for ``tests/test_multihost.py``.

Runs one multi-controller process of a 2-process sharded job on the CPU
backend (virtual local devices; the parent controls JAX_PLATFORMS /
XLA_FLAGS via the environment). Invoked as:

    python multihost_worker.py <spec.json> <out.json>

``spec`` fields: stream (npz path with users/items/ts), window_size, seed,
item_cut, user_cut, num_items, coordinator, num_processes, process_id,
phase ("full" | "first-half" | "resume"), half, checkpoint_dir.
"""

import json
import sys


def main() -> None:
    with open(sys.argv[1]) as f:
        spec = json.load(f)
    import numpy as np

    from tpu_cooccurrence.config import Backend, Config
    from tpu_cooccurrence.job import CooccurrenceJob

    data = np.load(spec["stream"])
    users, items, ts = data["users"], data["items"], data["ts"]
    backend = Backend(spec.get("backend", "sharded"))
    cfg = Config(
        window_size=spec["window_size"], seed=spec["seed"],
        window_slide=spec.get("window_slide"),
        item_cut=spec["item_cut"], user_cut=spec["user_cut"],
        backend=backend, num_items=spec["num_items"],
        num_shards=spec.get("num_shards", 1) if backend == Backend.SPARSE
        else 1,
        checkpoint_dir=spec.get("checkpoint_dir"),
        partition_sampling=spec.get("partition_sampling", False),
        # Gang-robustness knobs (ISSUE 10): pipelined multi-host
        # execution and lockstep degradation are exercisable here too.
        pipeline_depth=spec.get("pipeline_depth", 0),
        degrade=spec.get("degrade", False),
        journal=spec.get("journal"),
        coordinator=spec["coordinator"],
        num_processes=spec["num_processes"],
        process_id=spec["process_id"])
    job = CooccurrenceJob(cfg)
    half = spec.get("half", len(users))
    phase = spec["phase"]
    if phase == "full":
        job.add_batch(users, items, ts)
        job.finish()
    elif phase == "first-half":
        job.add_batch(users[:half], items[:half], ts[:half])
        job.checkpoint()
    elif phase == "resume":
        job.restore()
        job.add_batch(users[half:], items[half:], ts[half:])
        job.finish()
    else:
        raise ValueError(f"unknown phase {phase}")

    out = {
        "process_id": spec["process_id"],
        "counters": job.counters.as_dict(),
        "latest": {str(item): job.latest[item] for item in job.latest},
    }
    with open(sys.argv[2], "w") as f:
        json.dump(out, f)


if __name__ == "__main__":
    main()
