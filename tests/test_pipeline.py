"""End-to-end equivalence tests.

The production pipeline (vectorized window engine + item cut + reservoir +
backend scorer) must reproduce the record-at-a-time OracleJob exactly on the
oracle backend (same float64 math, same hash-RNG), and to float32 tolerance
on the device backend.

User RNG keys: OracleJob draws with raw user ids, the production job with
dense first-appearance indices — test streams are relabeled so they
coincide.
"""

import numpy as np
import pytest

from tpu_cooccurrence.config import Backend, Config
from tpu_cooccurrence.job import CooccurrenceJob
from tpu_cooccurrence.metrics import (
    ITEM_LATE_ELEMENTS,
    OBSERVED_COOCCURRENCES,
    RESCORED_ITEMS,
    ROW_SUM_PROCESS_WINDOW,
)
from tpu_cooccurrence.oracle import OracleJob


def relabel_first_appearance(ids):
    mapping = {}
    out = []
    for x in ids:
        out.append(mapping.setdefault(x, len(mapping)))
    return np.asarray(out, dtype=np.int64)


def random_stream(seed, n=600, n_users=12, n_items=25, max_dt=3):
    rng = np.random.default_rng(seed)
    users = relabel_first_appearance(rng.integers(0, n_users, n))
    items = relabel_first_appearance(rng.integers(0, n_items, n))
    ts = np.cumsum(rng.integers(0, max_dt, n)).astype(np.int64)
    return users, items, ts


def run_oracle(cfg, users, items, ts):
    job = OracleJob(cfg)
    for u, i, t in zip(users.tolist(), items.tolist(), ts.tolist()):
        job.process(u, i, t)
    job.finish()
    return job


def run_production(cfg, users, items, ts, chunk=97):
    job = CooccurrenceJob(cfg)
    for lo in range(0, len(users), chunk):
        job.add_batch(users[lo:lo + chunk], items[lo:lo + chunk], ts[lo:lo + chunk])
    job.finish()
    return job


def assert_latest_equal(oracle_latest, prod_latest, tol=None):
    assert set(oracle_latest) == set(prod_latest)
    for item in oracle_latest:
        o = oracle_latest[item]
        p = prod_latest[item]
        assert len(o) == len(p), f"row {item}: {o} vs {p}"
        o_scores = np.array([s for _, s in o])
        p_scores = np.array([s for _, s in p])
        if tol is None:
            np.testing.assert_allclose(p_scores, o_scores, rtol=1e-12, atol=1e-12)
            # Tie order among equal scores is implementation-defined (the
            # reference depends on hashmap iteration order); compare
            # canonicalized by (score desc, item).
            assert sorted(o, key=lambda e: (-e[1], e[0])) == \
                sorted(p, key=lambda e: (-e[1], e[0]))
        else:
            np.testing.assert_allclose(p_scores, o_scores, **tol)


def assert_latest_close(a_latest, b_latest, rtol=1e-4, atol=1e-3, gap=1e-2):
    """Tolerance comparison for f32-vs-f64 backends: scores to (rtol, atol),
    and the recommended item ids exactly whenever every score gap in the row
    exceeds ``gap`` (near-ties may legitimately reorder across precisions)."""
    assert set(a_latest) == set(b_latest)
    for item in a_latest:
        o = a_latest[item]
        p = b_latest[item]
        assert len(o) == len(p), f"row {item}: {o} vs {p}"
        o_scores = np.array([s for _, s in o])
        p_scores = np.array([s for _, s in p])
        np.testing.assert_allclose(p_scores, o_scores, rtol=rtol, atol=atol)
        if len(o_scores) > 1 and np.min(np.abs(np.diff(o_scores))) > gap:
            # The final rank stays uncertain even with clean in-list gaps:
            # the unseen K+1'th score may near-tie it across precisions.
            assert [j for j, _ in o][:-1] == [j for j, _ in p][:-1], \
                f"row {item}"


CONFIGS = [
    dict(skip_cuts=True),
    dict(item_cut=5, user_cut=4),
    dict(item_cut=3, user_cut=2, window_size=25),
    dict(item_cut=500, user_cut=3),
]


@pytest.mark.parametrize("overrides", CONFIGS)
def test_production_oracle_backend_matches_oracle_job(overrides):
    kw = dict(window_size=10, seed=0xBEEF, development_mode=True,
              backend=Backend.ORACLE)
    kw.update(overrides)
    cfg = Config(**kw)
    users, items, ts = random_stream(1)
    oracle = run_oracle(cfg, users, items, ts)
    prod = run_production(cfg, users, items, ts)
    assert_latest_equal({i: t for i, t in oracle.latest.items()}, prod.latest)
    for name in (OBSERVED_COOCCURRENCES, ROW_SUM_PROCESS_WINDOW,
                 RESCORED_ITEMS, ITEM_LATE_ELEMENTS):
        assert oracle.counters.get(name) == prod.counters.get(name), name


@pytest.mark.parametrize("overrides", CONFIGS)
def test_device_backend_matches_oracle_job(overrides):
    kw = dict(window_size=10, seed=0xBEEF, development_mode=True,
              backend=Backend.DEVICE, num_items=32)
    kw.update(overrides)
    cfg = Config(**kw)
    users, items, ts = random_stream(2)
    oracle_cfg = Config(**{**kw, "backend": Backend.ORACLE})
    oracle = run_oracle(oracle_cfg, users, items, ts)
    prod = run_production(cfg, users, items, ts)
    # float32 device scores vs float64 oracle: compare score vectors.
    assert set(oracle.latest) == set(prod.latest)
    for item in oracle.latest:
        o_scores = np.array([s for _, s in oracle.latest[item]])
        p_scores = np.array([s for _, s in prod.latest[item]])
        assert len(o_scores) == len(p_scores)
        np.testing.assert_allclose(p_scores, o_scores, rtol=1e-4, atol=1e-3)
        # Top-K member sets may differ only among near-tied scores; require
        # equality when all gaps exceed the tolerance.
        o_items = [j for j, _ in oracle.latest[item]]
        p_items = [j for j, _ in prod.latest[item]]
        if len(o_scores) > 1 and np.min(np.abs(np.diff(o_scores))) > 1e-2:
            assert o_items == p_items


def test_device_backend_chunked_upload_matches(monkeypatch):
    """TPU_COOC_UPLOAD_CHUNKS=K splits the dense packed COO upload into
    K transfers of one dispatch (the tunnel-cliff lever, shared with
    the sparse backend); results, counters, and the ledger's transfer
    pattern all track the monolithic path."""
    import tpu_cooccurrence.ops.device_scorer as ds
    from tpu_cooccurrence.observability import LEDGER

    kw = dict(window_size=10, seed=0xBEEF, development_mode=True,
              backend=Backend.DEVICE, num_items=32)
    users, items, ts = random_stream(2)
    a = run_production(Config(**kw), users, items, ts)

    calls = {"chunked": 0}
    for name in ("_update_coo_chunked", "_update_coo_u16_chunked"):
        orig = getattr(ds, name)

        def counting(*args, _orig=orig, **kwargs):
            calls["chunked"] += 1
            return _orig(*args, **kwargs)

        monkeypatch.setattr(ds, name, counting)
    monkeypatch.setenv("TPU_COOC_UPLOAD_CHUNKS", "4")
    LEDGER.reset()
    b = run_production(Config(**kw), users, items, ts)
    assert calls["chunked"] > 0, "chunked path must actually engage"
    assert set(a.latest) == set(b.latest)
    for item in a.latest:
        np.testing.assert_allclose(
            [s for _, s in b.latest[item]],
            [s for _, s in a.latest[item]], rtol=1e-6, atol=1e-6)
    assert a.counters.as_dict() == b.counters.as_dict()
    up = LEDGER.labels("h2d")
    assert "coo-chunk" in up and "coo" not in up


def test_negative_timestamps_end_to_end():
    """Pre-epoch event times (legal raw longs in the reference CSV)
    flow through windowing, cuts, and scoring identically on the
    oracle and sparse backends — window floors must not truncate
    toward zero when ts < 0."""
    rng = np.random.default_rng(0xAB)
    n = 800
    users = relabel_first_appearance(rng.integers(0, 10, n))
    items = relabel_first_appearance(rng.integers(0, 20, n))
    ts = (np.cumsum(rng.integers(0, 3, n)) - 600).astype(np.int64)
    assert ts[0] < 0 < ts[-1]
    kw = dict(window_size=10, seed=0xBEEF, item_cut=5, user_cut=4,
              development_mode=True)
    a = run_production(Config(**kw, backend=Backend.ORACLE),
                       users, items, ts)
    b = run_production(Config(**kw, backend=Backend.SPARSE),
                       users, items, ts)
    assert a.latest, "negative-ts stream must produce results"
    assert_latest_close(a.latest, b.latest)
    assert a.counters.as_dict() == b.counters.as_dict()


def test_device_backend_counters_match_oracle_backend():
    cfg_o = Config(window_size=10, seed=3, item_cut=4, user_cut=3,
                   backend=Backend.ORACLE)
    cfg_d = Config(window_size=10, seed=3, item_cut=4, user_cut=3,
                   backend=Backend.DEVICE, num_items=32)
    users, items, ts = random_stream(7)
    a = run_production(cfg_o, users, items, ts)
    b = run_production(cfg_d, users, items, ts)
    for name in (OBSERVED_COOCCURRENCES, ROW_SUM_PROCESS_WINDOW, RESCORED_ITEMS):
        assert a.counters.get(name) == b.counters.get(name), name


def test_batch_boundaries_do_not_matter():
    cfg = Config(window_size=10, seed=5, item_cut=4, user_cut=3,
                 backend=Backend.ORACLE)
    users, items, ts = random_stream(9)
    a = run_production(cfg, users, items, ts, chunk=1)
    cfg2 = Config(window_size=10, seed=5, item_cut=4, user_cut=3,
                  backend=Backend.ORACLE)
    b = run_production(cfg2, users, items, ts, chunk=600)
    assert_latest_equal(a.latest, b.latest)


def test_device_backend_auto_derives_vocab():
    """num_items == 0: the dense backend grows C from the data (the
    config.py promise) and matches the oracle across growth events."""
    from tpu_cooccurrence.ops.device_scorer import DeviceScorer

    users, items, ts = random_stream(51, n=900, n_items=60)
    kw = dict(window_size=10, seed=0xA0, item_cut=6, user_cut=4,
              development_mode=True)
    a = run_production(Config(**kw, backend=Backend.ORACLE), users, items, ts)
    cfg = Config(**kw, backend=Backend.DEVICE)  # num_items defaults to 0
    job = CooccurrenceJob(cfg)
    # Start tiny so the stream forces several doublings.
    job.scorer = DeviceScorer(0, cfg.top_k, job.counters)
    job.scorer.num_items = job.scorer.num_items_logical = 16
    job.scorer.C = job.scorer.C[:16, :16]
    job.scorer.row_sums = job.scorer.row_sums[:16]
    for lo in range(0, len(users), 97):
        job.add_batch(users[lo:lo + 97], items[lo:lo + 97], ts[lo:lo + 97])
    job.finish()
    assert job.scorer.num_items >= 60  # grew past the stream's vocab
    assert_latest_close(a.latest, job.latest)


def test_device_backend_auto_derive_checkpoint_roundtrip(tmp_path):
    kw = dict(window_size=10, seed=7, item_cut=5, user_cut=3,
              backend=Backend.DEVICE, checkpoint_dir=str(tmp_path / "ck"))
    users, items, ts = random_stream(52, n=400)
    half = 200
    ref = CooccurrenceJob(Config(**kw))
    ref.add_batch(users, items, ts)
    ref.finish()
    a = CooccurrenceJob(Config(**kw))
    a.add_batch(users[:half], items[:half], ts[:half])
    a.checkpoint()
    b = CooccurrenceJob(Config(**kw))
    b.restore()
    b.add_batch(users[half:], items[half:], ts[half:])
    b.finish()
    assert_latest_close(ref.latest, b.latest, rtol=1e-6, atol=1e-6)


def test_device_int16_counts_match_oracle():
    """--count-dtype int16 (reference-style short counts) is exact while
    counts stay within int16 range."""
    users, items, ts = random_stream(41)
    kw = dict(window_size=10, seed=0xD0D0, item_cut=6, user_cut=4)
    a = run_production(Config(**kw, backend=Backend.ORACLE), users, items, ts)
    b = run_production(Config(**kw, backend=Backend.DEVICE, num_items=32,
                              count_dtype="int16"), users, items, ts)
    assert_latest_equal(a.latest, b.latest, tol=dict(rtol=1e-4, atol=1e-4))
    assert a.counters.as_dict() == b.counters.as_dict()


def test_sharded_int16_counts_match_oracle():
    users, items, ts = random_stream(42)
    kw = dict(window_size=10, seed=0xD0D1, skip_cuts=True)
    a = run_production(Config(**kw, backend=Backend.ORACLE), users, items, ts)
    b = run_production(Config(**kw, backend=Backend.SHARDED, num_items=32,
                              num_shards=8, count_dtype="int16"),
                       users, items, ts)
    assert_latest_equal(a.latest, b.latest, tol=dict(rtol=1e-4, atol=1e-4))


def test_int16_counts_wrap_like_reference_shorts():
    """--count-dtype int16 reproduces the reference's silent short
    overflow (ItemRowAggregator.java:16 accumulates Java shorts): a cell
    pushed past 32767 wraps negative instead of raising, and the run
    keeps going."""
    from tpu_cooccurrence.ops.device_scorer import DeviceScorer
    from tpu_cooccurrence.sampling.reservoir import PairDeltaBatch

    sc = DeviceScorer(8, top_k=2, count_dtype="int16")
    # 3 windows x 20k on one cell: crosses 32767 -> wraps.
    n = 20_000
    batch = PairDeltaBatch(np.zeros(n, np.int64), np.ones(n, np.int64),
                           np.ones(n, np.int32))
    for ts in range(3):
        sc.process_window(ts, batch)
    sc.flush()
    c = sc.checkpoint_state()["C"]
    assert c.dtype == np.int16
    assert c[0, 1] == 60_000 - 65_536  # wrapped into the negative range
    assert c[0, 1] < 0


def test_device_deferred_matches_pipelined():
    """Dense-backend deferred-results mode (job default without
    --emit-updates) matches the per-window pipeline's final state, for
    both count dtypes and the pallas-on path."""

    from tpu_cooccurrence.job import CooccurrenceJob
    from tpu_cooccurrence.ops.device_scorer import DeviceScorer

    kw = dict(window_size=10, seed=0xD3, item_cut=5, user_cut=4,
              num_items=40, development_mode=True)
    users, items, ts = random_stream(43, n=1200)

    def run(defer, **scorer_kw):
        cfg = Config(**kw, backend=Backend.DEVICE)
        scorer = DeviceScorer(cfg.num_items, cfg.top_k,
                              defer_results=defer, **scorer_kw)
        job = CooccurrenceJob(cfg, scorer=scorer)
        scorer.counters = job.counters
        emitted = []
        job.on_update = lambda batch: emitted.append(len(batch))
        job.add_batch(users, items, ts)
        mid = list(emitted)
        job.finish()
        return job, mid

    piped, mid_p = run(False)
    assert sum(mid_p) > 0
    for scorer_kw in (dict(), dict(count_dtype="int16"),
                      dict(count_dtype="int16", use_pallas="on")):
        deferred, mid_d = run(True, **scorer_kw)
        assert mid_d == []
        assert_latest_close(piped.latest, deferred.latest,
                            rtol=1e-4, atol=1e-4)


def test_device_deferred_auto_capacity_growth():
    """Deferred table survives dense auto-capacity re-allocation
    (--num-items omitted): rows scored before the growth keep their
    entries. Window size 60 (not 10): the growth claim needs the vocab
    to cross the dense capacity MID-stream with scored rows on both
    sides, which ~60 windows prove as well as ~375 did at a sixth of
    the wall time (tier-1 budget)."""
    from tpu_cooccurrence.job import CooccurrenceJob

    kw = dict(window_size=60, seed=0xD4, skip_cuts=True,
              development_mode=True)
    users, items, ts = random_stream(47, n=2500, n_items=1500)
    a = run_production(Config(**kw, backend=Backend.ORACLE),
                       users, items, ts)
    cfg = Config(**kw, backend=Backend.DEVICE)  # num_items=0: derive
    b = CooccurrenceJob(cfg)
    assert b.scorer.defer_results
    for lo in range(0, len(users), 500):
        b.add_batch(users[lo:lo + 500], items[lo:lo + 500],
                    ts[lo:lo + 500])
    b.finish()
    assert b.scorer.num_items > 1024  # growth actually happened
    assert_latest_close(a.latest, b.latest)


def test_vocab_smaller_than_top_k():
    """A vocabulary smaller than K must not crash the dense backends
    (lax.top_k rejects k > axis size; the reference's heap simply holds
    fewer entries). Found by the extended randomized sweep."""
    rng = np.random.default_rng(0x26)
    n = 600
    users = rng.integers(0, 20, n).astype(np.int64)
    items = rng.integers(0, 5, n).astype(np.int64)
    ts = np.cumsum(rng.integers(0, 3, n)).astype(np.int64)
    kw = dict(window_size=20, seed=9, item_cut=8, user_cut=4, top_k=10)
    oracle = run_production(Config(backend=Backend.ORACLE,
                                   development_mode=True, **kw),
                            users, items, ts)
    ref = {i: oracle.latest[i] for i in oracle.latest}
    for backend, extra in (("device", {"num_items": 5}),
                           ("sharded", {"num_items": 5, "num_shards": 4}),
                           ("sparse", {})):
        job = run_production(Config(backend=Backend(backend),
                                    development_mode=True,
                                    **dict(kw, **extra)),
                             users, items, ts)
        assert job.counters.as_dict() == oracle.counters.as_dict(), backend
        assert_latest_close(ref, {i: job.latest[i] for i in job.latest},
                            rtol=2e-4, atol=2e-4)
