"""Pallas fused score/top-K kernel vs the XLA reference path.

Runs in interpreter mode on CPU (the standard way to validate Pallas TPU
kernels without hardware)."""

import numpy as np
import pytest

import jax.numpy as jnp

from tpu_cooccurrence.ops.device_scorer import _score
from tpu_cooccurrence.ops.pallas_score import pallas_score_topk


@pytest.mark.parametrize("seed,num_items,s,top_k", [
    (0, 256, 8, 10),
    (1, 512, 16, 5),
    (2, 256, 32, 16),
])
def test_pallas_matches_xla_score(seed, num_items, s, top_k):
    rng = np.random.default_rng(seed)
    C = np.zeros((num_items, num_items), dtype=np.int32)
    nnz = 4000
    src = rng.integers(0, num_items, nnz)
    dst = rng.integers(0, num_items, nnz)
    np.add.at(C, (src, dst), 1)
    row_sums = C.sum(axis=1).astype(np.int32)
    observed = np.float32(row_sums.sum())
    rows = rng.integers(0, num_items, s).astype(np.int32)

    ref_vals, ref_idx = _score(jnp.asarray(C), jnp.asarray(row_sums),
                               jnp.asarray(rows), observed, top_k=top_k)
    got_vals, got_idx = pallas_score_topk(
        jnp.asarray(C), jnp.asarray(row_sums), jnp.asarray(rows), observed,
        top_k=top_k, tile=128, interpret=True)

    ref_vals = np.asarray(ref_vals)
    got_vals = np.asarray(got_vals)
    np.testing.assert_allclose(got_vals, ref_vals, rtol=1e-5, atol=1e-5)
    # Indices must agree wherever scores are not tied with a neighbor.
    ref_idx = np.asarray(ref_idx)
    got_idx = np.asarray(got_idx)
    for r in range(s):
        for k in range(top_k):
            if not np.isfinite(ref_vals[r, k]):
                continue
            ties = np.isclose(ref_vals[r], ref_vals[r, k]).sum()
            if ties == 1:
                assert got_idx[r, k] == ref_idx[r, k], (r, k)


def test_pallas_empty_rows():
    num_items = 128
    C = jnp.zeros((num_items, num_items), dtype=jnp.int32)
    row_sums = jnp.zeros((num_items,), dtype=jnp.int32)
    rows = jnp.zeros((4,), dtype=jnp.int32)
    vals, idx = pallas_score_topk(C, row_sums, rows, np.float32(0.0),
                                  top_k=10, tile=128, interpret=True)
    assert not np.isfinite(np.asarray(vals)).any()


def test_pallas_rejects_bad_tile():
    C = jnp.zeros((130, 130), dtype=jnp.int32)
    with pytest.raises(ValueError):
        pallas_score_topk(C, jnp.zeros((130,), jnp.int32),
                          jnp.zeros((2,), jnp.int32), np.float32(0),
                          top_k=5, tile=128, interpret=True)


def test_pallas_packed_value_space_decode():
    """packed=True ships idx as float *values* (not a bitcast view).

    The host decode is ``astype(int32)``; a bitcast of the kernel's second
    output miscompiles on real-TPU Mosaic at >=4 row blocks, which is why
    the contract is value-space (see pallas_score.py).
    """
    rng = np.random.default_rng(7)
    num_items, s, top_k = 256, 32, 8
    C = np.zeros((num_items, num_items), dtype=np.int32)
    src = rng.integers(0, num_items, 3000)
    dst = rng.integers(0, num_items, 3000)
    np.add.at(C, (src, dst), 1)
    row_sums = C.sum(axis=1).astype(np.int32)
    observed = np.float32(row_sums.sum())
    rows = rng.integers(0, num_items, s).astype(np.int32)

    vals, idx = pallas_score_topk(
        jnp.asarray(C), jnp.asarray(row_sums), jnp.asarray(rows), observed,
        top_k=top_k, tile=128, interpret=True)
    packed = np.asarray(pallas_score_topk(
        jnp.asarray(C), jnp.asarray(row_sums), jnp.asarray(rows), observed,
        top_k=top_k, tile=128, interpret=True, packed=True))
    np.testing.assert_allclose(packed[0], np.asarray(vals), rtol=1e-6)
    np.testing.assert_array_equal(packed[1].astype(np.int32), np.asarray(idx))


def test_pallas_rejects_vocab_beyond_float32_exact():
    import functools

    import jax

    big = (1 << 24) + 128
    with pytest.raises(ValueError, match="2\\^24"):
        # eval_shape: the guard must fire at trace time, no allocation.
        jax.eval_shape(
            functools.partial(pallas_score_topk, top_k=5, tile=128,
                              interpret=True),
            jax.ShapeDtypeStruct((big, big), jnp.int32),
            jax.ShapeDtypeStruct((big,), jnp.int32),
            jax.ShapeDtypeStruct((2,), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.float32))


@pytest.mark.parametrize("seed,num_items,s,top_k", [
    (3, 256, 8, 10),
    (4, 512, 24, 5),
])
def test_pallas_int16_counts_match_xla(seed, num_items, s, top_k):
    """int16 (reference-style short) counts run with 16-row blocks."""
    rng = np.random.default_rng(seed)
    C = np.zeros((num_items, num_items), dtype=np.int16)
    nnz = 4000
    src = rng.integers(0, num_items, nnz)
    dst = rng.integers(0, num_items, nnz)
    np.add.at(C, (src, dst), 1)
    row_sums = C.sum(axis=1, dtype=np.int64).astype(np.int32)
    observed = np.float32(row_sums.sum())
    rows = rng.integers(0, num_items, s).astype(np.int32)

    ref_vals, ref_idx = _score(jnp.asarray(C), jnp.asarray(row_sums),
                               jnp.asarray(rows), observed, top_k=top_k)
    got_vals, got_idx = pallas_score_topk(
        jnp.asarray(C), jnp.asarray(row_sums), jnp.asarray(rows), observed,
        top_k=top_k, tile=128, interpret=True)
    ref_vals = np.asarray(ref_vals)
    got_vals = np.asarray(got_vals)
    np.testing.assert_allclose(got_vals, ref_vals, rtol=1e-5, atol=1e-5)
    # Tie-aware index check (same protocol as the int32 test above): a
    # col_base/run_idx bug under 16-row blocks must not hide behind
    # correct scores.
    ref_idx = np.asarray(ref_idx)
    got_idx = np.asarray(got_idx)
    for r in range(s):
        for k in range(top_k):
            if not np.isfinite(ref_vals[r, k]):
                continue
            if np.isclose(ref_vals[r], ref_vals[r, k]).sum() == 1:
                assert got_idx[r, k] == ref_idx[r, k], (r, k)


def test_pallas_int16_device_scorer_end_to_end():
    """DeviceScorer accepts --pallas on with --count-dtype int16 and
    matches the XLA path's results."""
    from tpu_cooccurrence.ops.device_scorer import DeviceScorer
    from tpu_cooccurrence.sampling.reservoir import PairDeltaBatch

    rng = np.random.default_rng(9)
    n = 3000
    src = rng.integers(0, 512, n).astype(np.int64)
    dst = rng.integers(0, 512, n).astype(np.int64)
    keep = src != dst
    pairs = PairDeltaBatch(src[keep], dst[keep],
                           np.ones(int(keep.sum()), dtype=np.int32))
    out = {}
    for pallas in ("on", "off"):
        sc = DeviceScorer(512, top_k=10, use_pallas=pallas,
                          count_dtype="int16")
        sc.process_window(0, pairs)
        out[pallas] = sc.flush()
    np.testing.assert_array_equal(out["on"].rows, out["off"].rows)
    np.testing.assert_allclose(out["on"].vals, out["off"].vals,
                               rtol=1e-5, atol=1e-5)
    # Indices agree wherever a row's scores have no ties at the cutoff.
    for r in range(len(out["on"].rows)):
        v = out["off"].vals[r]
        for k in range(v.shape[0]):
            if np.isfinite(v[k]) and np.isclose(v, v[k]).sum() == 1:
                assert out["on"].idx[r, k] == out["off"].idx[r, k]


def test_pallas_auto_rule():
    """--pallas auto: kernel on exactly for int16 counts on a real TPU
    (measured 247x there, ~5x slower at int32 — TPU_ROUND2.jsonl)."""
    from tpu_cooccurrence.ops.device_scorer import DeviceScorer, pallas_auto

    assert pallas_auto(np.dtype(np.int16), "tpu") is True
    assert pallas_auto(np.dtype(np.int32), "tpu") is False
    assert pallas_auto(np.dtype(np.int16), "cpu") is False
    assert pallas_auto(np.dtype(np.int32), "cpu") is False
    # top_k beyond the kernel's 128-lane output width: XLA path, not a
    # crash one window in (pallas_score_topk would reject it).
    assert pallas_auto(np.dtype(np.int16), "tpu", top_k=128) is True
    assert pallas_auto(np.dtype(np.int16), "tpu", top_k=200) is False
    # The constructor must resolve "auto" through the same rule (on the
    # CPU test backend both dtypes give False; on a TPU host int16 gives
    # True — compare against the rule, not a hard-coded value).
    import jax

    for dt in ("int16", "int32"):
        assert (DeviceScorer(64, 5, use_pallas="auto",
                             count_dtype=dt).use_pallas
                is pallas_auto(np.dtype(dt), jax.default_backend(), 5))
