"""Auto-resume supervisor: crash recovery with zero operator action.

The reference delegates failure recovery to Flink's restart strategies
(SURVEY §5); here a parent process respawns the job and the child
resumes from its checkpoint. The headline property (VERDICT r2, Next
#7): SIGKILL the job under the supervisor and the total stdout is
byte-identical to an uninterrupted run."""

import os
import subprocess
import sys

import pytest

from tpu_cooccurrence.supervisor import child_argv, supervise

from test_cli import write_stream

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")


def test_child_argv_strips_supervisor_flags():
    argv = ["-i", "x.csv", "--restart-on-failure", "3", "-ws", "10",
            "--restart-delay-ms=0", "--restart-on-failure=2"]
    assert child_argv(argv) == ["-i", "x.csv", "-ws", "10"]


class _Sink:
    def __init__(self):
        self.text = ""

    def write(self, s):
        self.text += s


def test_supervise_retries_then_succeeds(tmp_path):
    """Two failing attempts (partial output discarded), then success:
    rc 0 and ONLY the successful attempt's stdout comes through."""
    marker = tmp_path / "attempts"
    code = (
        "import os, sys\n"
        f"p = {str(marker)!r}\n"
        "n = int(open(p).read()) if os.path.exists(p) else 0\n"
        "open(p, 'w').write(str(n + 1))\n"
        "if n < 2:\n"
        "    print('partial garbage', flush=True)\n"
        "    sys.exit(3)\n"
        "print('final output')\n"
    )
    sink = _Sink()
    rc = supervise([sys.executable, "-c", code], attempts=2, delay_s=0,
                   stdout=sink)
    assert rc == 0
    assert sink.text == "final output\n"
    assert marker.read_text() == "3"


def test_supervise_exhausts_attempts(tmp_path):
    sink = _Sink()
    rc = supervise([sys.executable, "-c", "import sys; sys.exit(7)"],
                   attempts=2, delay_s=0, stdout=sink)
    assert rc == 7
    assert sink.text == ""


def test_supervise_timeout_counts_as_failed_attempt(tmp_path):
    """A hung attempt (timeout_s) is a failed attempt, not a supervisor
    crash: the child is killed, the retry runs, output comes through."""
    marker = tmp_path / "ran-once"
    code = (
        "import os, sys, time\n"
        f"p = {str(marker)!r}\n"
        "if not os.path.exists(p):\n"
        "    open(p, 'w').close()\n"
        "    time.sleep(600)\n"
        "print('after hang')\n"
    )
    sink = _Sink()
    rc = supervise([sys.executable, "-c", code], attempts=1, delay_s=0,
                   stdout=sink, timeout_s=3)
    assert rc == 0
    assert sink.text == "after hang\n"
    sink2 = _Sink()
    rc = supervise([sys.executable, "-c", "import time; time.sleep(600)"],
                   attempts=0, delay_s=0, stdout=sink2, timeout_s=1)
    assert rc == 124  # exhausted: timeout's conventional exit code
    assert sink2.text == ""


def test_restart_flag_abbreviation_rejected():
    """allow_abbrev=False: `--restart-on` must NOT parse as
    --restart-on-failure (an abbreviation would survive child_argv's
    exact-name strip and nest supervisors indefinitely)."""
    import pytest

    from tpu_cooccurrence.config import Config

    with pytest.raises(SystemExit):
        Config.from_args(["-i", "x.csv", "-ws", "10", "--restart-on", "2"])


def test_restart_rejected_with_process_continuously():
    import pytest

    from tpu_cooccurrence.config import Config

    with pytest.raises(ValueError, match="process-continuously"):
        Config.from_args(["-i", "x.csv", "-ws", "10",
                          "--restart-on-failure", "2",
                          "--process-continuously"])


def test_restart_rejected_with_multihost():
    """A respawned child re-joining the coordinator while surviving peers
    are blocked mid-collective would hang the distributed run; supervise
    multi-host jobs externally instead."""
    import pytest

    from tpu_cooccurrence.config import Config

    with pytest.raises(ValueError, match="multi-host"):
        Config.from_args(["-i", "x.csv", "-ws", "10",
                          "--restart-on-failure", "2",
                          "--coordinator", "127.0.0.1:9999",
                          "--num-processes", "2", "--process-id", "0"])


@pytest.mark.slow
def test_supervise_large_output_spools_to_disk(tmp_path):
    """A multi-hundred-MB child stream must not live in supervisor RAM:
    stdout spools to disk per attempt (VERDICT r3, Weak #3). Output
    integrity is checked end-to-end; RSS growth is bounded well under
    the stream size."""
    import resource

    n_mb = 256
    line = "x" * 1023  # 1 KB with newline
    code = (f"import sys\n"
            f"for _ in range({n_mb * 1024}):\n"
            f"    sys.stdout.write({line!r} + '\\n')\n")
    out_path = tmp_path / "out.txt"
    rss_before = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    with open(out_path, "w") as sink:  # has .buffer → binary fast path
        rc = supervise([sys.executable, "-c", code], attempts=0, delay_s=0,
                       stdout=sink)
    rss_after = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    assert rc == 0
    assert out_path.stat().st_size == n_mb * 1024 * 1024
    with open(out_path) as f:
        first = f.readline()
    assert first == line + "\n"
    # ru_maxrss is KB on Linux; allow 64 MB of slack for the interpreter,
    # far under the 256 MB stream a PIPE buffer would have held.
    assert rss_after - rss_before < 64 * 1024, (
        f"supervisor RSS grew {(rss_after - rss_before) // 1024} MB "
        f"on a {n_mb} MB stream — stdout is being buffered in memory")


def test_supervise_text_sink_multibyte_across_chunks():
    """Text sinks decode incrementally; multi-byte UTF-8 sequences that
    straddle copy-chunk boundaries must survive."""
    # 3-byte chars at 1-byte offset guarantee straddles at any power-of-2
    # chunk size.
    code = ("import sys\n"
            "sys.stdout.write('a' + '\\u20ac' * 100000)\n"
            "sys.stdout.write('x\\r\\ny')\n")
    sink = _Sink()
    rc = supervise([sys.executable, "-c", code], attempts=0, delay_s=0,
                   stdout=sink)
    assert rc == 0
    # \r\n must come through untranslated (byte-identical contract).
    assert sink.text == "a" + "\u20ac" * 100000 + "x\r\ny"


@pytest.mark.slow
def test_sigkill_under_supervisor_output_identical(tmp_path):
    """SIGKILL mid-run (right after the first periodic checkpoint lands);
    the supervisor restarts, the child restores, and total stdout is
    byte-identical to an uninterrupted run — zero operator action."""
    f = tmp_path / "in.csv"
    write_stream(f, n=60_000)
    cli_args = ["-i", str(f), "-ws", "20", "-ic", "8", "-uc", "5",
                "-s", "0xC0FFEE", "--backend", "oracle",
                "--checkpoint-every-windows", "5"]

    clean = subprocess.run(
        [sys.executable, "-m", "tpu_cooccurrence.cli"] + cli_args
        + ["--checkpoint-dir", str(tmp_path / "ck-clean")],
        capture_output=True, text=True, env=ENV, cwd=REPO, timeout=300)
    assert clean.returncode == 0, clean.stderr[-800:]

    ck = tmp_path / "ck"
    worker = os.path.join(REPO, "tests", "supervised_crash_worker.py")
    cmd = [sys.executable, worker, str(ck), str(tmp_path / "crashed-once")]
    cmd += cli_args + ["--checkpoint-dir", str(ck)]
    sink = _Sink()
    rc = supervise(cmd, attempts=2, delay_s=0, stdout=sink)
    assert rc == 0
    assert (tmp_path / "crashed-once").exists(), "crash never injected"
    assert sink.text == clean.stdout


def test_cli_restart_flag_healthy_run(tmp_path, capsys):
    """--restart-on-failure on a healthy run: supervised child executes
    once and the output matches an unsupervised run."""
    f = tmp_path / "in.csv"
    write_stream(f)
    base = ["-i", str(f), "-ws", "50", "--backend", "oracle",
            "-s", "0xC0FFEE"]
    plain = subprocess.run(
        [sys.executable, "-m", "tpu_cooccurrence.cli"] + base,
        capture_output=True, text=True, env=ENV, cwd=REPO, timeout=300)
    assert plain.returncode == 0, plain.stderr[-800:]
    supervised = subprocess.run(
        [sys.executable, "-m", "tpu_cooccurrence.cli"] + base
        + ["--restart-on-failure", "2", "--restart-delay-ms", "0"],
        capture_output=True, text=True, env=ENV, cwd=REPO, timeout=300)
    assert supervised.returncode == 0, supervised.stderr[-800:]
    assert supervised.stdout == plain.stdout
