"""Auto-resume supervisor: crash recovery with zero operator action.

The reference delegates failure recovery to Flink's restart strategies
(SURVEY §5); here a parent process respawns the job and the child
resumes from its checkpoint. The headline property (VERDICT r2, Next
#7): SIGKILL the job under the supervisor and the total stdout is
byte-identical to an uninterrupted run."""

import os
import subprocess
import sys
import time

import pytest

from tpu_cooccurrence.supervisor import child_argv, supervise

from test_cli import write_stream

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")


def test_child_argv_strips_supervisor_flags():
    argv = ["-i", "x.csv", "--restart-on-failure", "3", "-ws", "10",
            "--restart-delay-ms=0", "--restart-on-failure=2"]
    assert child_argv(argv) == ["-i", "x.csv", "-ws", "10"]


class _Sink:
    def __init__(self):
        self.text = ""

    def write(self, s):
        self.text += s


def test_supervise_retries_then_succeeds(tmp_path):
    """Two failing attempts (partial output discarded), then success:
    rc 0 and ONLY the successful attempt's stdout comes through."""
    marker = tmp_path / "attempts"
    code = (
        "import os, sys\n"
        f"p = {str(marker)!r}\n"
        "n = int(open(p).read()) if os.path.exists(p) else 0\n"
        "open(p, 'w').write(str(n + 1))\n"
        "if n < 2:\n"
        "    print('partial garbage', flush=True)\n"
        "    sys.exit(3)\n"
        "print('final output')\n"
    )
    sink = _Sink()
    rc = supervise([sys.executable, "-c", code], attempts=2, delay_s=0,
                   stdout=sink)
    assert rc == 0
    assert sink.text == "final output\n"
    assert marker.read_text() == "3"


def test_supervise_exhausts_attempts(tmp_path):
    sink = _Sink()
    rc = supervise([sys.executable, "-c", "import sys; sys.exit(7)"],
                   attempts=2, delay_s=0, stdout=sink)
    assert rc == 7
    assert sink.text == ""


def test_supervise_timeout_counts_as_failed_attempt(tmp_path):
    """A hung attempt (timeout_s) is a failed attempt, not a supervisor
    crash: the child is killed, the retry runs, output comes through."""
    marker = tmp_path / "ran-once"
    code = (
        "import os, sys, time\n"
        f"p = {str(marker)!r}\n"
        "if not os.path.exists(p):\n"
        "    open(p, 'w').close()\n"
        "    time.sleep(600)\n"
        "print('after hang')\n"
    )
    sink = _Sink()
    rc = supervise([sys.executable, "-c", code], attempts=1, delay_s=0,
                   stdout=sink, timeout_s=3)
    assert rc == 0
    assert sink.text == "after hang\n"
    sink2 = _Sink()
    rc = supervise([sys.executable, "-c", "import time; time.sleep(600)"],
                   attempts=0, delay_s=0, stdout=sink2, timeout_s=1)
    assert rc == 124  # exhausted: timeout's conventional exit code
    assert sink2.text == ""


def test_restart_flag_abbreviation_rejected():
    """allow_abbrev=False: `--restart-on` must NOT parse as
    --restart-on-failure (an abbreviation would survive child_argv's
    exact-name strip and nest supervisors indefinitely)."""
    import pytest

    from tpu_cooccurrence.config import Config

    with pytest.raises(SystemExit):
        Config.from_args(["-i", "x.csv", "-ws", "10", "--restart-on", "2"])


def test_restart_rejected_with_process_continuously():
    import pytest

    from tpu_cooccurrence.config import Config

    with pytest.raises(ValueError, match="process-continuously"):
        Config.from_args(["-i", "x.csv", "-ws", "10",
                          "--restart-on-failure", "2",
                          "--process-continuously"])


def test_restart_rejected_with_multihost():
    """A respawned child re-joining the coordinator while surviving peers
    are blocked mid-collective would hang the distributed run; supervise
    multi-host jobs externally instead."""
    import pytest

    from tpu_cooccurrence.config import Config

    with pytest.raises(ValueError, match="multi-host"):
        Config.from_args(["-i", "x.csv", "-ws", "10",
                          "--restart-on-failure", "2",
                          "--coordinator", "127.0.0.1:9999",
                          "--num-processes", "2", "--process-id", "0"])


@pytest.mark.slow
def test_supervise_large_output_spools_to_disk(tmp_path):
    """A multi-hundred-MB child stream must not live in supervisor RAM:
    stdout spools to disk per attempt (VERDICT r3, Weak #3). Output
    integrity is checked end-to-end; RSS growth is bounded well under
    the stream size."""
    import resource

    n_mb = 256
    line = "x" * 1023  # 1 KB with newline
    code = (f"import sys\n"
            f"for _ in range({n_mb * 1024}):\n"
            f"    sys.stdout.write({line!r} + '\\n')\n")
    out_path = tmp_path / "out.txt"
    rss_before = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    with open(out_path, "w") as sink:  # has .buffer → binary fast path
        rc = supervise([sys.executable, "-c", code], attempts=0, delay_s=0,
                       stdout=sink)
    rss_after = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    assert rc == 0
    assert out_path.stat().st_size == n_mb * 1024 * 1024
    with open(out_path) as f:
        first = f.readline()
    assert first == line + "\n"
    # ru_maxrss is KB on Linux; allow 64 MB of slack for the interpreter,
    # far under the 256 MB stream a PIPE buffer would have held.
    assert rss_after - rss_before < 64 * 1024, (
        f"supervisor RSS grew {(rss_after - rss_before) // 1024} MB "
        f"on a {n_mb} MB stream — stdout is being buffered in memory")


def test_supervise_text_sink_multibyte_across_chunks():
    """Text sinks decode incrementally; multi-byte UTF-8 sequences that
    straddle copy-chunk boundaries must survive."""
    # 3-byte chars at 1-byte offset guarantee straddles at any power-of-2
    # chunk size.
    code = ("import sys\n"
            "sys.stdout.write('a' + '\\u20ac' * 100000)\n"
            "sys.stdout.write('x\\r\\ny')\n")
    sink = _Sink()
    rc = supervise([sys.executable, "-c", code], attempts=0, delay_s=0,
                   stdout=sink)
    assert rc == 0
    # \r\n must come through untranslated (byte-identical contract).
    assert sink.text == "a" + "\u20ac" * 100000 + "x\r\ny"


def test_supervisor_quotes_dead_childs_journal_tail(tmp_path, caplog):
    """A SIGKILLed child's journal survives (including a torn final
    line) and the supervisor's restart log quotes its tail — the crashed
    attempt's last fired windows are not lost with its discarded stdout."""
    import logging

    jpath = tmp_path / "j.jsonl"
    marker = tmp_path / "crashed-once"
    code = (
        "import os, signal, sys\n"
        "sys.path.insert(0, sys.argv[3])\n"
        "from tpu_cooccurrence.observability.journal import RunJournal, VERSION\n"
        "rec = dict(v=VERSION, seq=1, ts=100, events=5, pairs=3,\n"
        "           rows_scored=2, sample_seconds=0.01, score_seconds=0.02,\n"
        "           ring_depth=0, stall_seconds=0.0, wall_unix=1.0,\n"
        "           counters={}, wire={})\n"
        "j = RunJournal(sys.argv[1])\n"
        "if not os.path.exists(sys.argv[2]):\n"
        "    open(sys.argv[2], 'w').close()\n"
        "    j.record(rec)\n"
        "    j.record(dict(rec, seq=2, ts=200))\n"
        "    j._f.write('{\"v\": 1, \"seq\": 3, \"ts\"')  # torn mid-write\n"
        "    j._f.flush()\n"
        "    os.kill(os.getpid(), signal.SIGKILL)\n"
        "j.record(dict(rec, seq=3, ts=300))\n"
        "print('done')\n"
    )
    sink = _Sink()
    with caplog.at_level(logging.WARNING, "tpu_cooccurrence.supervisor"):
        rc = supervise([sys.executable, "-c", code, str(jpath), str(marker),
                        REPO],
                       attempts=1, delay_s=0, stdout=sink,
                       journal_path=str(jpath))
    assert rc == 0 and sink.text == "done\n"
    quoted = [r.message for r in caplog.records if "journal" in r.message]
    assert any("journal tail (2 record(s)" in m for m in quoted), quoted
    # The dead attempt's LAST fired window (seq 2, not the torn seq-3
    # line) is quoted verbatim.
    assert any('"seq": 2' in m and '"ts": 200' in m for m in quoted), quoted
    # The file itself carries both attempts: crash tail + clean rerun.
    from tpu_cooccurrence.observability.journal import read_records

    assert [r["seq"] for r in read_records(str(jpath))] == [1, 2, 3]


def test_supervisor_journal_tail_missing_file_logs_and_continues(tmp_path,
                                                                 caplog):
    import logging

    sink = _Sink()
    with caplog.at_level(logging.WARNING, "tpu_cooccurrence.supervisor"):
        rc = supervise([sys.executable, "-c", "import sys; sys.exit(3)"],
                       attempts=0, delay_s=0, stdout=sink,
                       journal_path=str(tmp_path / "never-written.jsonl"))
    assert rc == 3
    assert any("wrote no journal records" in r.message
               for r in caplog.records)


def test_supervisor_does_not_quote_stale_journal_as_dead_childs(tmp_path,
                                                                caplog):
    """A child that dies before its first window (startup crash) must not
    have an earlier run's journal records quoted as its last act — even
    when opening the journal grew the file by sealing a predecessor's
    torn line (the 1-byte write that defeats a size-only guard)."""
    import logging

    jpath = tmp_path / "j.jsonl"
    # Earlier run's record plus a torn final line (no trailing newline):
    # the child's RunJournal open seals it with "\n" before crashing.
    jpath.write_text('{"v": 1, "seq": 9, "ts": 900}\n{"v": 1, "seq": 10')
    code = ("import sys\n"
            "sys.path.insert(0, sys.argv[2])\n"
            "from tpu_cooccurrence.observability.journal import RunJournal\n"
            "RunJournal(sys.argv[1])\n"
            "sys.exit(5)\n")
    sink = _Sink()
    with caplog.at_level(logging.WARNING, "tpu_cooccurrence.supervisor"):
        rc = supervise([sys.executable, "-c", code, str(jpath), REPO],
                       attempts=0, delay_s=0, stdout=sink,
                       journal_path=str(jpath))
    assert rc == 5
    msgs = [r.message for r in caplog.records]
    assert any("wrote no journal records" in m for m in msgs), msgs
    assert not any('"seq": 9' in m for m in msgs), msgs


@pytest.mark.slow
def test_sigkill_under_supervisor_output_identical(tmp_path):
    """SIGKILL mid-run (right after the first periodic checkpoint lands);
    the supervisor restarts, the child restores, and total stdout is
    byte-identical to an uninterrupted run — zero operator action. The
    run journal survives the kill: every record validates and the
    supervisor quotes the dead attempt's tail."""
    f = tmp_path / "in.csv"
    write_stream(f, n=60_000)
    jpath = tmp_path / "journal.jsonl"
    cli_args = ["-i", str(f), "-ws", "20", "-ic", "8", "-uc", "5",
                "-s", "0xC0FFEE", "--backend", "oracle",
                "--checkpoint-every-windows", "5"]

    clean = subprocess.run(
        [sys.executable, "-m", "tpu_cooccurrence.cli"] + cli_args
        + ["--checkpoint-dir", str(tmp_path / "ck-clean")],
        capture_output=True, text=True, env=ENV, cwd=REPO, timeout=300)
    assert clean.returncode == 0, clean.stderr[-800:]

    ck = tmp_path / "ck"
    worker = os.path.join(REPO, "tests", "supervised_crash_worker.py")
    cmd = [sys.executable, worker, str(ck), str(tmp_path / "crashed-once")]
    cmd += cli_args + ["--checkpoint-dir", str(ck), "--journal", str(jpath)]
    sink = _Sink()
    rc = supervise(cmd, attempts=2, delay_s=0, stdout=sink,
                   journal_path=str(jpath))
    assert rc == 0
    assert (tmp_path / "crashed-once").exists(), "crash never injected"
    assert sink.text == clean.stdout
    # Journal integrity across the kill + restore: every surviving line
    # validates, and the stream replay is deterministic — any window
    # ordinal journaled by both attempts carries identical logical fields.
    from tpu_cooccurrence.observability.journal import (read_records,
                                                        validate_record)

    recs = list(read_records(str(jpath)))
    assert recs, "journal never written"
    by_seq = {}
    for r in recs:
        validate_record(r)
        logical = (r["ts"], r["events"], r["pairs"])
        assert by_seq.setdefault(r["seq"], logical) == logical
    assert max(by_seq) == len(by_seq), "window ordinals must be gapless"


def test_cli_restart_flag_healthy_run(tmp_path, capsys):
    """--restart-on-failure on a healthy run: supervised child executes
    once and the output matches an unsupervised run."""
    f = tmp_path / "in.csv"
    write_stream(f)
    base = ["-i", str(f), "-ws", "50", "--backend", "oracle",
            "-s", "0xC0FFEE"]
    plain = subprocess.run(
        [sys.executable, "-m", "tpu_cooccurrence.cli"] + base,
        capture_output=True, text=True, env=ENV, cwd=REPO, timeout=300)
    assert plain.returncode == 0, plain.stderr[-800:]
    supervised = subprocess.run(
        [sys.executable, "-m", "tpu_cooccurrence.cli"] + base
        + ["--restart-on-failure", "2", "--restart-delay-ms", "0"],
        capture_output=True, text=True, env=ENV, cwd=REPO, timeout=300)
    assert supervised.returncode == 0, supervised.stderr[-800:]
    assert supervised.stdout == plain.stdout


# -- hardened recovery loop (robustness PR) ----------------------------


def _fail_n_times_cmd(marker, n, rc=3, final_line="recovered"):
    """A child that exits ``rc`` its first ``n`` runs, then succeeds."""
    return [sys.executable, "-c", (
        "import os, sys\n"
        f"p = {str(marker)!r}\n"
        "k = int(open(p).read()) if os.path.exists(p) else 0\n"
        "open(p, 'w').write(str(k + 1))\n"
        f"if k < {n}:\n"
        f"    sys.exit({rc})\n"
        f"print({final_line!r})\n")]


def test_permanent_exit_code_not_retried(tmp_path):
    """EX_CONFIG (and argparse's 2) mean a bad flag: restarting cannot
    help, so the supervisor returns immediately without burning
    attempts."""
    from tpu_cooccurrence.supervisor import EX_CONFIG

    marker = tmp_path / "runs"
    code = (
        "import os, sys\n"
        f"p = {str(marker)!r}\n"
        "k = int(open(p).read()) if os.path.exists(p) else 0\n"
        "open(p, 'w').write(str(k + 1))\n"
        f"sys.exit({EX_CONFIG})\n")
    sink = _Sink()
    rc = supervise([sys.executable, "-c", code], attempts=5, delay_s=0,
                   stdout=sink)
    assert rc == EX_CONFIG
    assert marker.read_text() == "1", "a permanent failure must not retry"


def test_cli_config_error_exits_ex_config(tmp_path):
    """cli.main turns a config ValueError into EX_CONFIG (a permanent
    code), instead of an uncaught traceback's generic rc=1."""
    from tpu_cooccurrence.supervisor import EX_CONFIG

    f = tmp_path / "in.csv"
    write_stream(f, n=20)
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_cooccurrence.cli", "-i", str(f),
         "-ws", "10", "--checkpoint-retain", "0"],
        capture_output=True, text=True, env=ENV, cwd=REPO, timeout=300)
    assert proc.returncode == EX_CONFIG, proc.stderr[-500:]
    assert "checkpoint-retain" in proc.stderr


def test_crash_loop_breaker_steps_back_then_gives_up(tmp_path, caplog):
    """Threshold failures inside the window: the breaker retires the
    newest checkpoint generation once (the poisoned-snapshot
    hypothesis); a re-trip gives up instead of burning every attempt."""
    import logging

    ck = tmp_path / "ck"
    ck.mkdir()
    (ck / "state.1.npz").write_bytes(b"older")
    (ck / "state.2.npz").write_bytes(b"poisoned")
    marker = tmp_path / "runs"
    cmd = _fail_n_times_cmd(marker, n=99)  # never recovers
    sink = _Sink()
    with caplog.at_level(logging.WARNING):
        rc = supervise(cmd, attempts=10, delay_s=0, stdout=sink,
                       crash_loop_threshold=2, crash_loop_window_s=60.0,
                       checkpoint_dir=str(ck))
    assert rc == 3
    assert (ck / "state.2.npz.rolledback").exists()
    assert (ck / "state.1.npz").exists()
    # fail, fail -> step back; fail, fail -> breaker open, give up: the
    # 10 attempts were NOT exhausted.
    assert marker.read_text() == "4"
    assert any("crash-loop breaker open" in r.message
               for r in caplog.records)


def test_breaker_without_checkpoint_keeps_full_attempt_budget(tmp_path):
    """The breaker only trades attempts for a step-back it actually
    performed: with no --checkpoint-dir it must NOT override the
    operator's --restart-on-failure budget."""
    marker = tmp_path / "runs"
    sink = _Sink()
    rc = supervise(_fail_n_times_cmd(marker, n=99), attempts=4,
                   delay_s=0, stdout=sink, crash_loop_threshold=3,
                   crash_loop_window_s=60.0)
    assert rc == 3
    assert marker.read_text() == "5", "all attempts must burn"


def test_breaker_single_generation_warns_and_continues(tmp_path, caplog):
    """A checkpoint dir with only one generation has nothing to fall
    back to: the breaker logs once and the full budget still applies."""
    import logging

    ck = tmp_path / "ck"
    ck.mkdir()
    (ck / "state.1.npz").write_bytes(b"only one")
    marker = tmp_path / "runs"
    sink = _Sink()
    with caplog.at_level(logging.WARNING, "tpu_cooccurrence.supervisor"):
        rc = supervise(_fail_n_times_cmd(marker, n=99), attempts=4,
                       delay_s=0, stdout=sink, crash_loop_threshold=2,
                       crash_loop_window_s=60.0, checkpoint_dir=str(ck))
    assert rc == 3
    assert marker.read_text() == "5"
    assert (ck / "state.1.npz").exists()
    warns = [r for r in caplog.records
             if "no older checkpoint generation" in r.message]
    assert len(warns) == 1, "the no-step-back warning must fire once"


def test_breaker_off_preserves_attempt_exhaustion(tmp_path):
    """crash_loop_threshold=0 disables the breaker: all attempts burn
    (the legacy semantics)."""
    marker = tmp_path / "runs"
    sink = _Sink()
    rc = supervise(_fail_n_times_cmd(marker, n=99), attempts=4,
                   delay_s=0, stdout=sink, crash_loop_threshold=0)
    assert rc == 3
    assert marker.read_text() == "5"


def test_backoff_decorrelated_jitter_bounds(tmp_path, monkeypatch):
    """Backoff draws uniform on [base, prev*3] capped at max — record
    the draw bounds instead of sleeping through them."""
    import random as _random

    draws = []

    def fake_uniform(lo, hi):
        draws.append((round(lo, 6), round(hi, 6)))
        return hi

    monkeypatch.setattr(_random, "uniform", fake_uniform)
    naps = []
    import tpu_cooccurrence.supervisor as sup
    monkeypatch.setattr(sup, "_POLL_S", 0.01)
    real_sleep = time.sleep
    monkeypatch.setattr(
        time, "sleep",
        lambda s: naps.append(s) if s > 0.01 else real_sleep(s))

    marker = tmp_path / "runs"
    sink = _Sink()
    rc = supervise(_fail_n_times_cmd(marker, n=3), attempts=5,
                   delay_s=0, stdout=sink, crash_loop_threshold=0,
                   backoff_base_s=0.05, backoff_max_s=0.2)
    assert rc == 0 and sink.text == "recovered\n"
    assert draws[0] == (0.05, round(0.05 * 3, 6))
    assert draws[1] == (0.05, round(0.15 * 3, 6))
    # Third delay hit the 0.2 cap: min(0.2, uniform(...)=1.35).
    assert naps[:3] == pytest.approx([0.15, 0.2, 0.2])


def test_journal_forensics_failure_does_not_kill_supervisor(
        tmp_path, monkeypatch, caplog):
    """A garbled/unreadable journal must cost the restart log its quote,
    never the restart itself."""
    import logging

    from tpu_cooccurrence.observability import journal as journal_mod

    def boom(*a, **kw):
        raise RuntimeError("journal reader exploded")

    monkeypatch.setattr(journal_mod, "tail", boom)
    marker = tmp_path / "runs"
    jpath = tmp_path / "j.jsonl"
    jpath.write_text("not json at all\n")
    sink = _Sink()
    with caplog.at_level(logging.WARNING, "tpu_cooccurrence.supervisor"):
        rc = supervise(_fail_n_times_cmd(marker, n=1), attempts=2,
                       delay_s=0, stdout=sink, journal_path=str(jpath))
    assert rc == 0 and sink.text == "recovered\n"
    assert any("restarting without the quote" in r.message
               for r in caplog.records)


def test_watchdog_kills_stale_child(tmp_path):
    """A child whose journal stops growing past the staleness threshold
    is killed (SIGTERM->SIGKILL) and counted as a failed attempt."""
    jpath = tmp_path / "j.jsonl"
    code = (
        "import sys, time\n"
        f"f = open({str(jpath)!r}, 'a')\n"
        "f.write('{\"seq\": 1}\\n')\n"
        "f.flush()\n"
        "time.sleep(600)\n")
    sink = _Sink()
    t0 = time.monotonic()
    rc = supervise([sys.executable, "-c", code], attempts=0, delay_s=0,
                   stdout=sink, journal_path=str(jpath),
                   watchdog_stale_after_s=1.0)
    assert rc == 124
    assert sink.text == ""
    assert time.monotonic() - t0 < 30, "watchdog should not wait the hang out"


def test_watchdog_start_grace_survives_torn_tail_seal(tmp_path,
                                                      monkeypatch):
    """A restarted child seals a predecessor's torn journal line with a
    single newline the moment it opens the journal — before restore.
    That 1-byte growth must NOT count as progress, or the startup grace
    collapses to the steady-state threshold and a healthy recovering
    child is killed mid-restore."""
    import tpu_cooccurrence.supervisor as sup

    monkeypatch.setattr(sup, "WATCHDOG_START_GRACE_S", 4.0)
    jpath = tmp_path / "j.jsonl"
    jpath.write_text('{"seq": 1}\n{"torn": tru')  # predecessor's torn tail
    code = (
        "import time\n"
        f"f = open({str(jpath)!r}, 'a')\n"
        "f.write('\\n')\n"  # the seal, written at journal open
        "f.flush()\n"
        "time.sleep(600)\n")  # "restore/replay" that never progresses
    sink = _Sink()
    t0 = time.monotonic()
    rc = supervise([sys.executable, "-c", code], attempts=0, delay_s=0,
                   stdout=sink, journal_path=str(jpath),
                   watchdog_stale_after_s=1.0)
    elapsed = time.monotonic() - t0
    assert rc == 124
    # Killed on the 4s startup grace, not 1s after the seal byte.
    assert elapsed > 3.0, (
        f"seal byte collapsed the startup grace (killed after "
        f"{elapsed:.1f}s)")


def test_supervisor_state_env_reaches_child(tmp_path):
    """The child of a restarted attempt sees restart count/backoff in
    TPU_COOC_SUPERVISOR_STATE (the scrape plane's input)."""
    import json as _json

    from tpu_cooccurrence.supervisor import SUPERVISOR_STATE_ENV

    marker = tmp_path / "runs"
    code = (
        "import json, os, sys\n"
        f"p = {str(marker)!r}\n"
        "k = int(open(p).read()) if os.path.exists(p) else 0\n"
        "open(p, 'w').write(str(k + 1))\n"
        "if k < 1:\n"
        "    sys.exit(3)\n"
        f"print(os.environ[{SUPERVISOR_STATE_ENV!r}])\n")
    sink = _Sink()
    rc = supervise([sys.executable, "-c", code], attempts=2, delay_s=0.01,
                   stdout=sink)
    assert rc == 0
    state = _json.loads(sink.text)
    assert state["restarts"] == 1
    assert state["last_rc"] == 3
    assert state["backoff_ms"] == 10
    assert state["last_restart_unix"] > 0
